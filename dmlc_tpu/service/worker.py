"""Data-service parse worker: claim splits across jobs, parse, stream.

One worker of the disaggregated ingest fleet (arXiv:2210.14826 §3.2):
it polls the :class:`~dmlc_tpu.service.dispatcher.Dispatcher` for
partitions — **multiplexing every registered job from the one grant
rotation**: each ``next_split`` grant names ``(job, part)``, the job's
dataset spec is fetched lazily at first grant and cached, and the frame
store is keyed per job, so one worker process serves N trainers' corpora
side by side (docs/service.md multi-tenant service). Each granted part
runs the **existing** parser stack
(:func:`dmlc_tpu.data.parsers.create_parser` with the job's
dispatcher-shipped config, optionally fronted by the parse-once
:class:`~dmlc_tpu.data.parsers.BlockCacheIter` when the config carries
``block_cache`` — a relaunched worker then re-serves its parts from the
warm cache instead of re-parsing text, and a part of a job whose
share-by-signature cache was ALREADY published by a sibling job serves
warm without parsing at all: that fleet-wide parse-once is the
cross-job sharing claim, counted as ``service_parts_shared`` vs
``service_parts_parsed`` for actual parses), encodes every RowBlock
into a wire frame at parse time
(:func:`~dmlc_tpu.service.frame.encode_block_frame`, ``service_encode``
spans), and serves job-qualified ``stream``/``find``/``count`` requests
from trainer clients over its own TCP listener (``service_send``
spans). Completed parts tick the job-labeled
``service_job_parts`` registry counter, so the tracker pod table shows
per-job parts served next to per-rank stages (docs/observability.md).

Fleet bootstrap reuses the tracker layer: pass ``tracker=(uri, port)``
and the worker fetches a stable rank from the rabit-protocol tracker
(:class:`~dmlc_tpu.tracker.client.WorkerClient`) — its worker id becomes
``rank<N>`` — and ships its telemetry registry to the tracker over the
PR-6 ``metrics`` command (``start_heartbeat(metrics=True)``), so
per-worker parse/encode/send seconds and ``service_*`` span counts land
in the tracker's merged pod table next to every other rank.

Failure model: :meth:`kill` simulates a crash — listener and client
connections drop mid-frame, the dispatcher is NOT told (clients
``report_lost`` it / heartbeats go stale), and the in-memory frame store
is gone, exactly like a dead process. The dispatcher re-issues the dead
worker's parts and a live worker re-parses them; parsing is
deterministic, so the re-served frames are byte-identical.

Graceful exit model (docs/service.md elastic membership): preemptible
capacity comes with a NOTICE, and wasting it means re-parsing everything
the worker held. :meth:`drain` begins a graceful departure — triggered
by the operator (``LocalFleet.drain_worker``), by SIGTERM
(``handle_sigterm=True``, main-thread processes), by the
``DMLC_TPU_PREEMPTION_NOTICE`` file/env signal, or by the ``preempt``
fault-plan op (chaos harness), the latter two checked every heartbeat
and counted as ``preemption_notices``. The worker tells the dispatcher
to drain it (no new grants; unstarted parts proactively re-issue), marks
any in-progress parse as a *draining* ERROR so clients relocate
immediately instead of waiting for a dead socket, and keeps SERVING its
frame-store-complete parts (ENDs carry a ``draining`` flag so clients
confirm handoffs) until the dispatcher reports the drain complete or the
drain deadline (``DMLC_TPU_DRAIN_DEADLINE``) expires — then exits
cleanly.

Chaos knobs: :meth:`kill` (crash), :meth:`drain` (preemption), and
``straggle_seconds`` — an artificial per-block stall that turns this
worker into a deterministic straggler so the dispatcher's speculative
hedging path is testable without racy scheduling tricks.

Control-plane failure model (docs/service.md control-plane recovery): a
dispatcher-unreachable round trip is a classified retryable fault —
every control RPC runs under the shared
:class:`~dmlc_tpu.io.resilience.RetryPolicy` (backoff + jitter,
``control_plane_retries`` counted per re-attempt). Every dispatcher
response carries a monotonic generation token; a bump means the
dispatcher restarted, so the worker re-attaches
(``worker_reregistrations``): it re-registers and **reclaims** — sends
the new ``reclaim`` command re-announcing the fully-parsed parts still
in its frame store, which the recovered dispatcher adopts
(``parts_reclaimed``) instead of re-issuing them for a fleet-wide
re-parse. Completed parts also ``part_done`` to the dispatcher as they
finish, journaling the completion so a later restart keeps them done.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
from typing import Dict, List, Optional, Tuple

from dmlc_tpu.io import faults as _faults
from dmlc_tpu.io import resilience as _resilience
from dmlc_tpu.service import dispatcher as _dispatch
from dmlc_tpu.service.dispatcher import DEFAULT_JOB
from dmlc_tpu.service.frame import (
    WIRE_CODECS,
    annot_key,
    decode_frame,
    encode_block_frame,
    encode_block_frame_v2,
    encode_end_frame,
    encode_error_frame,
    encode_hello_frame,
    negotiate_codec,
    reframe_v2,
    send_frame,
    send_frame_vectored,
)
from dmlc_tpu.store.manager import publish_owner
from dmlc_tpu.utils import knobs as _knobs
from dmlc_tpu.utils import telemetry as _telemetry
from dmlc_tpu.utils.check import DMLCError
from dmlc_tpu.utils.timer import get_time

logger = logging.getLogger("dmlc_tpu.service")


# the shared packed-snapshot container (docs/service.md snapshot
# sharing): the DMLCSN01 store tier's on-disk home for a part's packed
# snapshot frames — magic, frame count, then length-prefixed wire
# frames. Deliberately trivial: the frames ARE the wire encoding
# (dmlc_tpu.service.frame), so a load is a read + split, no re-pack.
_SNAP_SHARE_MAGIC = b"DMLCSN01"


def _encode_snap_container(frames: List[bytes]) -> bytes:
    import struct

    out = [_SNAP_SHARE_MAGIC, struct.pack("<I", len(frames))]
    for fr in frames:
        out.append(struct.pack("<Q", len(fr)))
        out.append(fr)
    return b"".join(out)


def _decode_snap_container(data: bytes) -> Optional[List[bytes]]:
    """The container's frames, or None on any shape violation — a
    corrupt/foreign file must fall back to a local pack, never crash
    the serve."""
    import struct

    if len(data) < 12 or data[:8] != _SNAP_SHARE_MAGIC:
        return None
    (count,) = struct.unpack_from("<I", data, 8)
    off = 12
    frames: List[bytes] = []
    for _ in range(count):
        if off + 8 > len(data):
            return None
        (ln,) = struct.unpack_from("<Q", data, off)
        off += 8
        if off + ln > len(data):
            return None
        frames.append(data[off:off + ln])
        off += ln
    return frames if off == len(data) else None


class _PartStore:
    """Frames of one claimed part, appended as the parse progresses so a
    client can stream a part that is still being parsed. Held in RAM for
    the worker's life (warm epoch re-serves + O(1) failover resume) —
    the fleet must be sized so each worker's share of the encoded corpus
    fits its host (docs/service.md "Memory model"). ``snap_frames`` is
    the part re-encoded as device-layout snapshot frames (packed on
    first snapshot stream request, once the part is complete — the
    dispatcher's ``snapshot`` geometry decides shape and dtype)."""

    __slots__ = ("frames", "keys", "complete", "error", "snap_frames",
                 "snap_packing", "cache_path", "wire_cache")

    def __init__(self):
        self.frames: List[bytes] = []
        self.keys: List[Optional[str]] = []  # annot_key per block (or None)
        self.complete = False
        self.error: Optional[str] = None
        self.snap_frames: Optional[List[bytes]] = None
        self.snap_packing = False  # one serve thread holds the pack claim
        # the part's published block-cache path (set at parse end when it
        # exists): the v2 HELLO offers it to co-located clients as the
        # mmap fast path (docs/service.md Wire v2)
        self.cache_path: Optional[str] = None
        # lazily compressed v2 frames per negotiated codec: codec ->
        # {block: frame-or-None} (None = measured incompressible, ship
        # identity) — compressed once, re-served to every v2 client
        self.wire_cache: Dict[str, Dict[int, Optional[bytes]]] = {}


class ParseWorker:
    """One tracker-launchable parse worker process/object."""

    def __init__(self, dispatcher: str, worker_id: Optional[str] = None,
                 host: str = "127.0.0.1",
                 tracker: Optional[Tuple[str, int]] = None,
                 tracker_world: int = -1,
                 poll_interval: float = 0.2,
                 heartbeat_interval: float = 2.0,
                 autotune: Optional[bool] = None,
                 drain_deadline: Optional[float] = None,
                 handle_sigterm: bool = False,
                 straggle_seconds: float = 0.0):
        self.dispatcher = dispatcher
        self.poll_interval = float(poll_interval)
        self.heartbeat_interval = float(heartbeat_interval)
        # graceful-drain state (docs/service.md elastic membership):
        # `_draining` flips once and never back; the local deadline is a
        # backstop in case the dispatcher never confirms completion
        self._draining = threading.Event()
        self._drain_deadline = drain_deadline
        self._drain_deadline_at: Optional[float] = None
        self._sigterm_seen = False
        self.drained = False
        # chaos harness: a deterministic straggler — sleep this long
        # before publishing each parsed block, so hedging tests need no
        # scheduler tricks (docs/service.md elastic membership)
        self.straggle_seconds = float(straggle_seconds)
        # control RPCs heal through the shared policy (backoff + jitter,
        # control_plane_retries per re-attempt) — a dispatcher between
        # kill and restart is retryable, not fatal (docs/service.md)
        self._policy = _resilience.default_policy()
        self._gen: Optional[int] = None
        cfg = self._request({"cmd": "config"}, reattach=False)
        # the default job's spec, kept as attributes for the historical
        # one-dataset view (None/0/{} on a dispatcher born empty); jobs
        # beyond the default are fetched lazily at first grant and
        # cached in _job_cfgs (docs/service.md multi-tenant service)
        self.uri = cfg.get("uri")
        self.num_parts = int(cfg.get("num_parts") or 0)
        self._parser_cfg = dict(cfg.get("parser") or {})
        self._job_cfgs: Dict[str, dict] = {}
        if self.uri is not None:
            self._job_cfgs[DEFAULT_JOB] = {
                "uri": self.uri, "num_parts": self.num_parts,
                "parser": self._parser_cfg,
                "plan": dict(cfg.get("plan") or {}),
                "snapshot": dict(cfg.get("snapshot") or {})}
        # per-host parse-tier self-tuning (docs/data.md autotune; the
        # tf.data-service motivation — a heterogeneous fleet cannot share
        # one static parse_workers): each completed part is a clean
        # measurement window, and the measured parallelism efficiency
        # decides the NEXT part's fan-out width within the knob-table
        # caps. Armed by autotune=True or DMLC_TPU_AUTOTUNE=1; block
        # content is engine-width-invariant (the A/B parity suites), so
        # re-served frames stay byte-identical across tier changes.
        self.tier_tuner = None
        from dmlc_tpu.utils import knobs as _knobs

        if _knobs.autotune_enabled(autotune):
            from dmlc_tpu.data.autotune import ParseTierTuner

            self.tier_tuner = ParseTierTuner(
                start=self._parser_cfg.get("parse_workers"))
        # dispatcher-shipped epoch-plan identity, surfaced for clients /
        # operators. Deliberately NOT folded into the worker's own parser
        # builds: frames must stay parse-order — a relaunched worker
        # re-serving a part from an already-published warm cache with a
        # plan armed would serve PLAN order, and the client's
        # failover-resume-at-block-index contract (byte-identity) would
        # break. The seed is the fleet's shared metadata, not a worker
        # serving mode (docs/service.md plan distribution).
        self.plan = dict(cfg.get("plan") or {})
        # dispatcher-shipped snapshot geometry: when set, parts ALSO
        # serve as device-layout snapshot frames — fixed [B, num_col + 2]
        # packed batches in the geometry's x_dtype (bf16 halves the
        # wire), packed lazily per part on first snapshot stream request
        # (docs/service.md snapshot frames)
        self.snapshot = dict(cfg.get("snapshot") or {})
        # data listener first: the tracker/dispatcher registrations carry
        # its port
        self._listen = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listen.bind((host, 0))
        self._listen.listen(64)
        self.host, self.port = self._listen.getsockname()[:2]
        # optional rank bootstrap + pod-telemetry feed via the tracker
        self.rank = -1
        self._tracker_client = None
        try:
            if tracker is not None:
                from dmlc_tpu.tracker.client import WorkerClient

                self._tracker_client = WorkerClient(tracker[0], tracker[1])
                self.rank = self._tracker_client.start(
                    world_size=tracker_world).rank
                self._tracker_client.start_heartbeat(
                    interval=self.heartbeat_interval, metrics=True)
            self.worker_id = worker_id or (
                f"rank{self.rank}" if self.rank >= 0
                else f"{self.host}:{self.port}")
            self._cond = threading.Condition()
            # frame stores are PER JOB: (job, part) -> _PartStore, so N
            # multiplexed jobs' parts never collide (docs/service.md)
            self._store: Dict[Tuple[str, int], _PartStore] = {}
            # every part this worker ever processed, in order — the
            # no-re-parse evidence chaos tests assert on (a reclaimed
            # part must appear exactly once across the fleet); the
            # job-qualified twin rides parts_by_job
            self.parts_parsed: List[int] = []
            self.parts_by_job: Dict[str, List[int]] = {}
            # the cross-job sharing evidence: parts whose supply ran an
            # actual text parse (cold) vs parts that resolved to an
            # already-published share-by-signature block cache (warm —
            # the parse was avoided fleet-wide; docs/store.md)
            self.parts_cold: List[Tuple[str, int]] = []
            self.parts_warm: List[Tuple[str, int]] = []
            # artifact-store pins held for parts this worker serves: a
            # block cache published while parsing a part stays pinned for
            # the worker's life, so a fleet-wide byte-budget squeeze can
            # never evict the tier a relaunched/failed-over worker would
            # re-serve the part from (docs/store.md pin semantics)
            self._artifact_pins: List[str] = []
            self._stop = threading.Event()
            self._dead = False
            self._conns: set = set()
            self._conns_lock = threading.Lock()
            self._register()
            # announce the (empty) frame store: a same-id restart (e.g.
            # rank0 relaunched by the tracker) re-queues any stale parts
            # the dispatcher still maps to this id, immediately instead
            # of waiting for clients to trip over them
            self._reclaim()
        except BaseException:
            # a failed bootstrap must not leak the bound listener or a
            # live heartbeat thread for a worker that never existed
            try:
                self._listen.close()
            except OSError:
                pass
            if self._tracker_client is not None:
                self._tracker_client.close()
                self._tracker_client = None
            raise
        self._threads = [
            threading.Thread(target=self._serve_loop, daemon=True,
                             name=f"service-worker-{self.worker_id}-serve"),
            threading.Thread(target=self._split_loop, daemon=True,
                             name=f"service-worker-{self.worker_id}-parse"),
            threading.Thread(target=self._hb_loop, daemon=True,
                             name=f"service-worker-{self.worker_id}-hb"),
        ]
        for t in self._threads:
            t.start()
        if handle_sigterm:
            self.install_signal_handlers()
        logger.info("parse worker %s serving on %s:%d", self.worker_id,
                    self.host, self.port)

    # ---------------- control plane ----------------

    def _request(self, req: dict, reattach: bool = True) -> dict:
        """One policy-guarded dispatcher round trip: transient faults
        (connection refused while the dispatcher restarts, torn replies)
        back off with jitter and retry under the shared policy, counting
        ``control_plane_retries``. A generation bump in the response
        triggers the re-attach handshake (register + reclaim) unless
        ``reattach=False`` (bootstrap, and the handshake's own RPCs)."""
        resp = self._policy.call(
            lambda: _dispatch.request(self.dispatcher, req),
            op="control_plane", what=self.dispatcher,
            on_retry=lambda: _resilience.record_event(
                "control_plane_retries"))
        if self._note_generation(resp) and reattach:
            self._reattach()
        return resp

    def _note_generation(self, resp: dict) -> bool:
        """Track the dispatcher's generation token; True when it
        advanced past the last one seen (= the dispatcher restarted)."""
        gen = resp.get("gen")
        if gen is None:
            return False
        gen = int(gen)
        changed = self._gen is not None and gen > self._gen
        if self._gen is None or gen > self._gen:
            self._gen = gen
        return changed

    def _register(self) -> None:
        self._request({"cmd": "register", "worker": self.worker_id,
                       "host": self.host, "port": self.port},
                      reattach=False)

    def _reclaim(self) -> None:
        """Re-announce the fully-parsed parts still in the frame store —
        per job — so a restarted dispatcher adopts them instead of
        re-issuing them for a fleet-wide re-parse (counted as
        ``parts_reclaimed``). An empty announce is still useful: it
        re-queues any stale parts the dispatcher maps to this id whose
        frames this incarnation does not hold."""
        held: Dict[str, List[int]] = {}
        with self._cond:
            for (job, part), s in self._store.items():
                if s.complete and s.error is None:
                    held.setdefault(job, []).append(part)
        for parts in held.values():
            parts.sort()
        resp = self._request({"cmd": "reclaim", "worker": self.worker_id,
                              "parts": held}, reattach=False)
        adopted = resp.get("adopted") or {}
        count = (sum(len(ps) for ps in adopted.values())
                 if isinstance(adopted, dict) else len(adopted))
        if count:
            _resilience.record_event("parts_reclaimed", count)
            logger.info("worker %s: dispatcher adopted reclaimed parts %s",
                        self.worker_id, adopted)

    def _reattach(self) -> None:
        """The dispatcher restarted (generation bump) or declared this
        worker dead: re-register and reclaim the frame store
        (docs/service.md control-plane recovery). A DRAINING worker is
        leaving, not rejoining — it re-sends the drain instead, so the
        recovered dispatcher keeps it out of the grant rotation; but if
        the dispatcher no longer knows it at all (declared dead before
        the drain landed), the drain RPC is refused ``unknown`` — then
        it must register + reclaim FIRST, putting its frame-store-
        complete parts back into the serving set, and re-announce the
        drain in the same breath, so it re-enters the fleet as DRAINING,
        never as a grant-eligible ACTIVE."""
        if self._draining.is_set():
            resp = self._announce_drain()
            if resp is not None and resp.get("unknown"):
                try:
                    self._register()
                    self._reclaim()
                except (OSError, DMLCError, ValueError):
                    return  # the next poll retries
                self._announce_drain()
            return
        _resilience.record_event("worker_reregistrations")
        logger.info("worker %s: re-attaching to dispatcher %s (gen %s)",
                    self.worker_id, self.dispatcher, self._gen)
        try:
            self._register()
            self._reclaim()
        except (OSError, DMLCError, ValueError):
            pass  # the next poll retries; dispatcher liveness covers us

    # ---------------- graceful drain ----------------

    def _drain_seconds(self) -> float:
        if self._drain_deadline is not None:
            return float(self._drain_deadline)
        from dmlc_tpu.utils import knobs as _knobs

        return float(_knobs.resolve("drain_deadline"))

    def drain(self, reason: str = "operator",
              deadline: Optional[float] = None) -> None:
        """Begin a graceful departure (docs/service.md elastic
        membership): tell the dispatcher to stop granting and re-issue
        this worker's unstarted parts, abandon any in-progress parse
        (clients get a *draining* ERROR and relocate immediately), and
        keep serving frame-store-complete parts until the dispatcher
        confirms the drain or the deadline expires. Idempotent."""
        if self._stop.is_set():
            return
        if self._draining.is_set():
            # already draining: an explicit deadline may TIGHTEN the
            # window (eviction imminent — drain(deadline=0) means leave
            # now), never loosen it
            if deadline is not None:
                new_at = get_time() + float(deadline)
                if (self._drain_deadline_at is None
                        or new_at < self._drain_deadline_at):
                    self._drain_deadline_at = new_at
                    logger.warning(
                        "worker %s: drain deadline tightened to %.1fs "
                        "(%s)", self.worker_id, float(deadline), reason)
                    self._announce_drain()
            return
        if deadline is not None:
            self._drain_deadline = float(deadline)
        ddl = self._drain_seconds()
        self._draining.set()
        self._drain_deadline_at = get_time() + ddl
        logger.warning("worker %s: draining (%s; deadline %.1fs)",
                       self.worker_id, reason, ddl)
        with self._cond:
            self._cond.notify_all()  # wake streams of the aborted parse
        self._announce_drain()

    def _announce_drain(self) -> Optional[dict]:
        """Send (or RE-send) the idempotent ``drain`` RPC; returns the
        reply, or None when the RPC failed outright. A single
        announcement is not reliable: the RPC can fail, or land while
        the dispatcher transiently considers this worker dead
        (``unknown``) — and a later re-register would heal it back to
        ACTIVE, silently desyncing membership. The split loop therefore
        re-announces (via :meth:`_reattach`) whenever a poll reply shows
        the dispatcher does not have us DRAINING; the local deadline
        backstop bounds it all."""
        remaining = max(0.0, (self._drain_deadline_at or get_time())
                        - get_time())
        try:
            resp = self._request({"cmd": "drain", "worker": self.worker_id,
                                  "deadline": remaining}, reattach=False)
        except (OSError, DMLCError, ValueError) as exc:
            logger.warning("worker %s: drain RPC failed (%s); will "
                           "re-announce from the split loop",
                           self.worker_id, exc)
            return None
        if not resp.get("ok"):
            logger.warning("worker %s: dispatcher refused drain: %s",
                           self.worker_id, resp)
        return resp

    def _check_preemption(self) -> None:
        """The preemption-notice seam, checked every heartbeat: the
        ``DMLC_TPU_PREEMPTION_NOTICE`` env names a notice file (value
        ``1`` means 'notice already served'), and the ``preempt``
        fault-plan op injects notices deterministically — ANY firing,
        whatever its error class, is consumed as the notice. Either
        counts ``preemption_notices`` and begins the drain."""
        if self._draining.is_set() or self._stop.is_set():
            return
        notice = os.environ.get("DMLC_TPU_PREEMPTION_NOTICE", "").strip()
        noticed = bool(notice) and (notice == "1" or os.path.exists(notice))
        why = f"preemption notice {notice!r}"
        if not noticed:
            try:
                _faults.maybe_fail("preempt", self.worker_id)
            except Exception as exc:  # noqa: BLE001 - the raise IS the notice
                noticed = True
                why = f"injected preemption notice ({exc})"
        if noticed:
            _resilience.record_event("preemption_notices")
            self.drain(reason=why)

    def install_signal_handlers(self) -> bool:
        """Route SIGTERM to :meth:`drain` (the k8s/preemptible-VM exit
        contract). Only the main thread may install handlers; returns
        False (and stays signal-free) anywhere else."""
        import signal

        def _on_term(signum, frame):  # noqa: ARG001 - signal contract
            # the handler runs on the user's MAIN thread mid-eviction:
            # it must not block on drain()'s policy-retried dispatcher
            # RPC (an unreachable dispatcher would freeze the training
            # loop for most of the grace window), so the drain protocol
            # runs on a background thread. Orchestrators re-send SIGTERM
            # through the grace period: only the first notice counts
            # (handlers never run concurrently with themselves, so the
            # seen-flag needs no lock; drain() is idempotent besides).
            if (self._sigterm_seen or self._draining.is_set()
                    or self._stop.is_set()):
                return
            self._sigterm_seen = True
            _resilience.record_event("preemption_notices")
            threading.Thread(
                target=self.drain, kwargs={"reason": "SIGTERM"},
                daemon=True,
                name=f"service-worker-{self.worker_id}-drain").start()

        try:
            signal.signal(signal.SIGTERM, _on_term)
            return True
        except ValueError:  # not the main thread
            logger.warning("worker %s: SIGTERM handler needs the main "
                           "thread; rely on DMLC_TPU_PREEMPTION_NOTICE "
                           "or drain() instead", self.worker_id)
            return False

    def _finish_drain(self) -> None:
        """Drain complete (dispatcher confirmed, or the local deadline
        backstop fired): serve out any stream still in flight, then
        leave the fleet cleanly. The dispatcher's completion is keyed on
        handoff confirmations, but handoffs are per PART and clients are
        anonymous — ANOTHER client may still be mid-stream on a part a
        first client already confirmed, and killing its socket here
        would force exactly the ungraceful timeout failover (plus a
        re-parse) the drain protocol exists to prevent. Bounded by what
        remains of the notice window."""
        if self.drained:
            return
        self.drained = True
        deadline = self._drain_deadline_at
        while deadline is not None and get_time() < deadline:
            with self._conns_lock:
                busy = bool(self._conns)
            if not busy:
                break
            self._stop.wait(0.05)
        logger.info("worker %s: drain complete; exiting", self.worker_id)
        self.close()

    # ---------------- parse side ----------------

    def _job_cfg(self, job: str) -> dict:
        """The dataset spec of ``job`` — the cached default/previously
        granted specs, or one lazy ``config`` RPC for a job registered
        after this worker booted (the multiplexing seam)."""
        cfg = self._job_cfgs.get(job)
        if cfg is None:
            cfg = self._request({"cmd": "config", "job": job})
            self._job_cfgs[job] = cfg = {
                "uri": cfg.get("uri"),
                "num_parts": int(cfg.get("num_parts") or 0),
                "parser": dict(cfg.get("parser") or {}),
                "plan": dict(cfg.get("plan") or {}),
                "snapshot": dict(cfg.get("snapshot") or {})}
        return cfg

    def _build_parser(self, job: str, part: int):
        from dmlc_tpu.data.parsers import create_parser

        cfg = self._job_cfg(job)
        kwargs = dict(cfg["parser"])
        type_ = kwargs.pop("format", kwargs.pop("type_", "auto"))
        # plan knobs never reach the worker's parser (see __init__): the
        # frame store must be parse-order for exact-block failover resume
        kwargs.pop("shuffle_seed", None)
        kwargs.pop("shuffle_window", None)
        kwargs.pop("pod_sharding", None)
        if self.tier_tuner is not None:
            # the self-tuned tier overrides the shipped static width
            kwargs["parse_workers"] = self.tier_tuner.workers
        return create_parser(cfg["uri"], part, cfg["num_parts"], type_,
                             **kwargs)

    def _retune_parse_tier(self, parser) -> None:
        """Feed the completed part's measured parallelism efficiency back
        into the tier tuner (grow saturated lanes, shed idle ones) so the
        next part parses at the adjusted width."""
        if self.tier_tuner is None or parser is None:
            return
        stats = None
        fn = getattr(parser, "parallel_stats", None)
        if callable(fn):
            try:
                stats = fn()
            except Exception:  # noqa: BLE001 - a sensor must never kill parse
                stats = None
        self.tier_tuner.decide(
            (stats or {}).get("parse_parallelism_efficiency"),
            workers=(stats or {}).get("parse_workers"))

    def autotune_state(self) -> Optional[dict]:
        """The tier tuner's decision record (None when self-tuning is
        off) — the worker-side analog of stats()['autotune']."""
        return (self.tier_tuner.snapshot()
                if self.tier_tuner is not None else None)

    def _split_loop(self) -> None:
        while not self._stop.is_set():
            if (self._draining.is_set()
                    and self._drain_deadline_at is not None
                    and get_time() >= self._drain_deadline_at):
                # local backstop: the dispatcher never confirmed (or is
                # gone) — the notice window is up, exit anyway
                self._finish_drain()
                return
            gen_before = self._gen
            try:
                resp = self._request(
                    {"cmd": "next_split", "worker": self.worker_id})
            except (OSError, DMLCError, ValueError):
                # the policy's budget is spent and the dispatcher is
                # still unreachable: poll-wait and try a fresh budget
                self._stop.wait(self.poll_interval)
                continue
            if resp.get("drained"):
                # the dispatcher completed our drain (handoffs confirmed
                # or deadline expired): exit cleanly
                self._finish_drain()
                return
            if resp.get("draining"):
                if not self._draining.is_set():
                    # the drain was initiated AT the dispatcher (operator
                    # RPC): adopt it locally so the whole protocol runs —
                    # abandon the in-progress parse with a draining
                    # ERROR, flag ENDs for handoff confirmation, arm the
                    # local deadline backstop. drain() re-sends the RPC,
                    # which is idempotent dispatcher-side.
                    self.drain(reason="dispatcher-initiated drain")
                self._stop.wait(self.poll_interval)
                continue
            if self._draining.is_set():
                # reaching here means the reply carried neither
                # `draining` nor `drained`: the dispatcher does NOT have
                # us DRAINING (it missed the drain RPC, declared us dead,
                # or a restart healed us back to ACTIVE). _reattach
                # re-announces — registering + reclaiming first when
                # we're unknown — and the drain's proactive re-issue
                # re-queues any part this very reply may have granted,
                # which we must not parse.
                self._reattach()
                self._stop.wait(self.poll_interval)
                continue
            if resp.get("register") and self._gen == gen_before:
                # declared dead (zombie) with no restart involved —
                # rejoin AND reclaim, so the frames this incarnation
                # still serves are adopted back instead of re-parsing
                # fleet-wide (a generation bump in the same reply was
                # already handled inside _request)
                self._reattach()
                self._stop.wait(self.poll_interval)
                continue
            part = resp.get("part")
            if part is None:
                self._stop.wait(self.poll_interval)
                continue
            self._parse_part(str(resp.get("job") or DEFAULT_JOB),
                             int(part),
                             _telemetry.trace_context_from_wire(
                                 resp.get("trace")))

    def _parse_part(self, job: str, part: int,
                    ctx: Optional[Tuple[str, str]] = None) -> None:
        # the whole parse — however deep the block-cache/chunk-cache
        # machinery publishes — runs in the job's publish-owner scope,
        # so every artifact lands in the manifest with its owning-job
        # ledger entry (docs/store.md per-job budgets). The grant's
        # trace context (optional `trace` key on the next_split reply)
        # scopes the parse: every service_encode span recorded inside
        # inherits the grant's trace id, parented under the grant span —
        # one (job, part) is one trace (docs/observability.md).
        with publish_owner(job), _telemetry.trace(
                ctx[0] if ctx else None, ctx[1] if ctx else ""):
            t0 = get_time()
            try:
                self._parse_part_owned(job, part)
            finally:
                _telemetry.record_span("service_parse", t0,
                                       get_time() - t0, job=job,
                                       part=part)

    def _parse_part_owned(self, job: str, part: int) -> None:
        store = _PartStore()
        # cache the job's spec BEFORE the store entry becomes visible: a
        # client's snapshot-stream request can arrive the instant the
        # dispatcher's locate names this worker, and the serve path
        # reads the job's geometry from the cfg cache with no RPC — so
        # the cache must be populated first. A failed fetch still
        # publishes the store (with the error), so waiting clients
        # relocate instead of timing out on a missing entry.
        cfg_exc: Optional[BaseException] = None
        try:
            self._job_cfg(job)
        except (OSError, DMLCError, ValueError) as exc:
            cfg_exc = exc
        with self._cond:
            self._store[(job, part)] = store
            self.parts_parsed.append(part)
            self.parts_by_job.setdefault(job, []).append(part)
            self._cond.notify_all()
        parser = None
        warm = False
        release_claim = None
        try:
            if cfg_exc is not None:
                raise cfg_exc
            parser = self._build_parser(job, part)
            # a part whose share-by-signature block cache was already
            # published (by a sibling job over the same corpus, or by
            # this worker's previous incarnation) serves WARM: the parse
            # is avoided fleet-wide (docs/store.md share-by-signature)
            warm = getattr(parser, "cache_state", "cold") == "warm"
            if not warm:
                # single-claim the cold build fleet-wide: a sibling
                # worker mid-cold-pass over the same store signature
                # (a job registered DURING the pass) must not trigger a
                # duplicate parse — wait for its publish instead
                parser, warm, release_claim = self._claim_cold_build(
                    job, part, parser)
            while True:
                if self._stop.is_set():
                    return  # killed mid-parse: the part stays incomplete
                if self._draining.is_set():
                    # the dispatcher already re-issued this part; end the
                    # streams gracefully so clients relocate NOW instead
                    # of waiting out a dead socket (the drain ERROR is
                    # not blamed and costs clients no retry budget)
                    store.error = (f"worker {self.worker_id} draining; "
                                   f"part {part} re-issued")
                    logger.info("worker %s: abandoning part %d mid-parse "
                                "(draining)", self.worker_id, part)
                    return
                block = parser.next_block()
                if block is None:
                    break
                if self.straggle_seconds > 0:
                    # chaos harness: deterministic straggler (docstring)
                    self._stop.wait(self.straggle_seconds)
                annot = getattr(block, "resume_state", None)
                frame = encode_block_frame(block, annot)
                with self._cond:
                    store.frames.append(frame)
                    store.keys.append(
                        annot_key(annot) if annot is not None else None)
                    self._cond.notify_all()
        except Exception as exc:  # noqa: BLE001 - served to clients as ERROR
            store.error = f"{type(exc).__name__}: {exc}"
            logger.warning("worker %s: parse of job %s part %d failed: %s",
                           self.worker_id, job, part, store.error)
        finally:
            if store.error is None:
                # only CLEAN parts are measurement windows: a failed part
                # measures the failure (workers idle behind a dying
                # stream), not the tier — tuning on it would shrink the
                # width the next healthy part needs
                self._retune_parse_tier(parser)
            if store.error is None:
                self._pin_part_artifact(parser)
            cache_path = getattr(parser, "cache_file", None)
            if parser is not None:
                parser.close()
            if release_claim is not None:
                # belt and braces: a clean cold pass already dissolved
                # the claim via its publish; an errored one must not
                # strand it (the waiting sibling would burn its bound)
                release_claim()
            with self._cond:
                if (store.error is None and cache_path
                        and os.path.exists(cache_path)):
                    # the published artifact this part serves from — the
                    # v2 HELLO's co-located mmap fast-path offer
                    store.cache_path = cache_path
                store.complete = True
                self._cond.notify_all()
            if store.error is None:
                # the sharing ledger: an actual parse vs a part resolved
                # from an already-published shared artifact (the bench
                # two-job leg's shared_parse_ratio reads these)
                if warm:
                    self.parts_warm.append((job, part))
                    _resilience.record_event("service_parts_shared")
                else:
                    self.parts_cold.append((job, part))
                    _resilience.record_event("service_parts_parsed")
                # job-labeled parts-served tick for the tracker pod
                # table (docs/observability.md per-job rows)
                _telemetry.REGISTRY.counter(
                    _telemetry.SERVICE_JOB_PARTS_METRIC, job=job).inc()
            if store.error is None and not self._stop.is_set():
                # journal the completion at the dispatcher: a restarted
                # control plane then keeps the part DONE instead of
                # re-queuing it as in-flight. Best-effort — a miss is
                # healed by the reclaim handshake (the response's
                # generation stamp triggers re-attach right here when
                # the dispatcher restarted mid-parse)
                try:
                    self._request({"cmd": "part_done", "part": part,
                                   "worker": self.worker_id, "job": job})
                except (OSError, DMLCError, ValueError):
                    pass
        logger.info("worker %s: job %s part %d %s (%d blocks)",
                    self.worker_id, job, part,
                    "served warm" if warm else "parsed",
                    len(store.frames))

    def _claim_cold_build(self, job: str, part: int, parser):
        """Fleet-wide single-claim of a cold cache build (docs/store.md
        single-claim builds): claim the part's final cache path through
        the PR 11 manifest before parsing. When a DIFFERENT live owner
        already holds the claim, bounded-wait for its publish (the claim
        dissolves with it), rebuild the parser, and serve warm — the
        duplicate cold pass never runs. On timeout / builder death the
        cold pass proceeds anyway (stage_path + atomic rename converge
        on one artifact). Returns ``(parser, warm, release_fn)``."""
        path = getattr(parser, "cache_file", None)
        if not path:
            return parser, False, None
        owner = f"{os.getpid()}:{self.worker_id}"
        try:
            from dmlc_tpu.store import store_for

            store = store_for(path)
        except Exception:  # noqa: BLE001 - claiming must never fail parse
            return parser, False, None

        def release():
            try:
                store.release(path, owner)
            except Exception:  # noqa: BLE001 - teardown is best-effort
                pass

        try:
            if store.claim(path, owner):
                return parser, False, release
        except Exception:  # noqa: BLE001
            return parser, False, None
        _resilience.record_event("service_parse_claim_waits")
        logger.info("worker %s: job %s part %d cold build claimed by %s; "
                    "waiting for its publish", self.worker_id, job, part,
                    store.claimant(path))
        deadline = get_time() + float(_knobs.resolve("claim_wait_deadline"))
        while (get_time() < deadline and not self._stop.is_set()
               and not self._draining.is_set()):
            try:
                if store.claimant(path) is None:
                    break  # published (or the builder died)
            except Exception:  # noqa: BLE001
                break
            self._stop.wait(0.05)
        parser.close()
        parser = self._build_parser(job, part)
        if getattr(parser, "cache_state", "cold") == "warm":
            return parser, True, None
        # builder died or timed out without publishing: take the claim
        # and run the cold pass ourselves
        try:
            if store.claim(path, owner):
                return parser, False, release
        except Exception:  # noqa: BLE001
            pass
        return parser, False, None

    def _pin_part_artifact(self, parser) -> None:
        """Hold the eviction pin on a part's published block cache for
        the worker's life (pins are dropped at close/kill; a REAL crash
        needs no drop — pins of dead pids are ignored at manifest
        replay). ``parser.close()`` releases the reader's own pin, so
        this one is what keeps the artifact resident between serves."""
        path = getattr(parser, "cache_file", None)
        if not path or not os.path.exists(path):
            return
        try:
            from dmlc_tpu.store import store_for

            store_for(path).pin(path)
            self._artifact_pins.append(path)
        except Exception as exc:  # noqa: BLE001 - a pin failure must
            # never fail the part: the artifact just stays evictable
            logger.warning("worker %s: artifact pin of %s failed: %s",
                           self.worker_id, path, exc)

    def _drop_artifact_pins(self) -> None:
        pins, self._artifact_pins = self._artifact_pins, []
        for path in pins:
            try:
                from dmlc_tpu.store import store_for

                store_for(path).drop(path)
            except Exception:  # noqa: BLE001 - teardown is best-effort
                pass

    def _hb_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_interval):
            # preemption notices beat liveness: an eviction window is
            # short, so the drain must start on THIS beat
            self._check_preemption()
            try:
                _dispatch.request(self.dispatcher, {
                    "cmd": "heartbeat", "worker": self.worker_id})
            except (OSError, DMLCError, ValueError):
                pass  # dispatcher gone; the split loop surfaces that

    # ---------------- serve side ----------------

    def _serve_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listen.accept()
            except OSError:
                return  # listener closed (kill/close)
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _wait_store(self, job: str, part: int, timeout: float = 5.0):
        """The store of a (job, part) whose grant may still be in flight
        (the dispatcher answered ``locate`` the instant it assigned the
        part); None when this worker does not serve it. Out-of-range
        parts of a job whose spec is already cached reject instantly —
        a burst of stale locates must not hold handler threads for the
        full wait."""
        if part < 0:
            return None
        cfg = self._job_cfgs.get(job)
        if cfg is not None and part >= int(cfg.get("num_parts") or 0):
            return None
        key = (job, part)
        with self._cond:
            ok = self._cond.wait_for(
                lambda: key in self._store or self._dead, timeout=timeout)
            return self._store.get(key) if ok else None

    def _handle(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(60.0)
            # the request file stays open for the connection's life: a
            # wire-v2 stream keeps reading pipelined fetch lines off it
            # (v1 requests still carry exactly one line)
            with conn.makefile("rb") as f:
                line = f.readline()
                req = json.loads(line) if line else {}
                cmd = req.get("cmd")
                job = str(req.get("job") or DEFAULT_JOB)
                try:
                    part = int(req.get("part", -1))
                except (TypeError, ValueError):
                    part = -1  # "part": null etc — handlers answer ERROR
                try:
                    wire = int(req.get("wire") or 1)
                except (TypeError, ValueError):
                    wire = 1
                # adopt the requester's trace context (optional `trace`
                # key — the part's grant trace, handed to the client by
                # `locate`): every service_send span this stream records
                # joins the same causal chain as the grant and parse
                ctx = _telemetry.trace_context_from_wire(req.get("trace"))
                t0 = get_time()
                with _telemetry.trace(ctx[0] if ctx else None,
                                      ctx[1] if ctx else ""):
                    if cmd == "stream":
                        if req.get("snapshot"):
                            self._serve_stream_snapshot(
                                conn, job, part, int(req.get("start", 0)))
                        elif wire >= 2:
                            self._serve_stream_v2(
                                conn, f, job, part, req.get("accept"),
                                str(req.get("host") or ""))
                        else:
                            self._serve_stream(conn, job, part,
                                               int(req.get("start", 0)))
                    elif cmd == "find":
                        self._serve_find(conn, job, part,
                                         str(req.get("key", "")))
                    elif cmd == "count":
                        self._serve_count(conn, job, part)
                    elif cmd == "trace_dump":
                        # the worker half of the merged pod timeline
                        # (docs/observability.md): span rings +
                        # decisions + a clock stamp, one JSON line
                        conn.sendall(json.dumps(
                            {"snapshot": _telemetry.component_snapshot(
                                self.worker_id)}).encode() + b"\n")
                    elif cmd == "metrics_text":
                        conn.sendall(json.dumps(
                            {"text": _telemetry.render_prometheus(),
                             "content_type": "text/plain; version=0.0.4;"
                                             " charset=utf-8"}
                        ).encode() + b"\n")
                    elif cmd == "decisions":
                        conn.sendall(json.dumps(
                            {"decisions": _telemetry.decisions_snapshot(),
                             "total": _telemetry.decisions_total()}
                        ).encode() + b"\n")
                    else:
                        send_frame(conn, encode_error_frame(
                            f"unknown request {cmd!r}"))
                    _telemetry.record_span(
                        "service_rpc", t0, get_time() - t0,
                        cmd=str(cmd or ""))
        except (OSError, ValueError):
            pass  # client went away / garbage request: nothing to serve
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _serve_stream(self, conn, job: str, part: int, start: int) -> None:
        store = self._wait_store(job, part)
        if store is None:
            send_frame(conn, encode_error_frame(
                f"worker {self.worker_id} does not serve job {job} "
                f"part {part}"))
            return
        i = max(0, int(start))
        while True:
            with self._cond:
                self._cond.wait_for(
                    lambda: i < len(store.frames) or store.complete
                    or self._dead)
                if self._dead:
                    return  # crash simulation: drop mid-stream, no goodbye
                if i < len(store.frames):
                    frame = store.frames[i]
                elif store.error is not None:
                    # mid-drain this is a GRACEFUL notice (the part was
                    # re-issued): the client relocates without blaming
                    frame = encode_error_frame(
                        store.error, draining=self._draining.is_set())
                    send_frame(conn, frame)
                    return
                else:
                    # a draining END asks the client to confirm the
                    # handoff with the dispatcher (docs/service.md)
                    send_frame(conn, encode_end_frame(
                        part, len(store.frames),
                        draining=self._draining.is_set()))
                    return
            send_frame(conn, frame)  # the sendall runs outside the lock
            i += 1

    # ---------------- wire v2 serve side ----------------

    def _negotiate_codec(self, accept) -> Optional[str]:
        """The worker's half of stream-open codec negotiation: the
        operator's mode gates what this end will do, the client's
        ``accept`` list gates what the peer can undo. None = identity."""
        from dmlc_tpu.utils import knobs as _knobs

        mode = _knobs.wire_compression()
        if mode == "off":
            return None
        offered = {str(a) for a in (accept or ())}
        if mode == "auto":
            return negotiate_codec(offered)
        return mode if (mode in WIRE_CODECS and mode in offered) else None

    def _send_block_v2(self, conn, store: _PartStore, i: int,
                       frame: bytes, codec: Optional[str]) -> int:
        """Ship stored v1 frame ``i`` as a v2 frame; returns on-wire
        bytes. With a codec, the compressed form is built once per
        (codec, block) and cached on the store (None = measured
        incompressible — ship identity). The identity path rewrites only
        the header's version byte and hands the stored body to a
        vectored send untouched (:func:`reframe_v2`)."""
        if codec is not None:
            cache = store.wire_cache.setdefault(codec, {})
            v2 = cache.get(i, False)
            if v2 is False:
                _, meta, payload = decode_frame(frame)
                v2 = encode_block_frame_v2(meta, payload, codec)
                cache[i] = v2
            if v2 is not None:
                send_frame(conn, v2)
                return len(v2)
        header, body = reframe_v2(frame)
        return send_frame_vectored(conn, (header, body))

    def _serve_stream_v2(self, conn, rfile, job: str, part: int,
                         accept, client_host: str) -> None:
        """The v2 data plane: reply HELLO (negotiated codec, block count,
        co-located fast-path offer), then serve newline-JSON ``fetch``
        requests FIFO off the same socket — the client keeps
        ``service_pipeline_depth`` fetches in flight so RTT hides behind
        the outstanding window. A fetch past the end of a complete part
        answers END (every in-flight fetch gets one, so the client can
        drain its window); a fetch naming the next part on the same
        connection re-targets the stream (connection reuse when the
        located owner is unchanged). Every served data byte ticks the
        compression ledger (``service_wire_bytes_raw/sent``)."""
        store = self._wait_store(job, part)
        if store is None:
            send_frame(conn, encode_error_frame(
                f"worker {self.worker_id} does not serve job {job} "
                f"part {part}"))
            return
        codec = self._negotiate_codec(accept)
        hello: dict = {"wire": 2, "codec": codec}
        with self._cond:
            complete = store.complete and store.error is None
            blocks = len(store.frames) if complete else None
            cache_path = store.cache_path
        if blocks is not None:
            hello["blocks"] = blocks
        if (client_host and client_host == socket.gethostname()
                and complete and cache_path
                and os.path.exists(cache_path)):
            # co-located peer + published store-pinned cache: offer the
            # mmap fast path — the client maps the artifact directly and
            # skips TCP for the part (pin/byte-identity semantics ride
            # the BlockCacheReader it opens; docs/service.md Wire v2)
            hello["fastpath"] = {"path": cache_path, "blocks": blocks}
        send_frame(conn, encode_hello_frame(hello))
        raw_ctr = _telemetry.REGISTRY.counter(
            _telemetry.SERVICE_WIRE_RAW_METRIC, job=job)
        sent_ctr = _telemetry.REGISTRY.counter(
            _telemetry.SERVICE_WIRE_SENT_METRIC, job=job)
        while True:
            line = rfile.readline()
            if not line:
                return  # client closed (done, or the fast path took over)
            freq = json.loads(line)
            try:
                i = int(freq.get("block", -1))
                p = int(freq.get("part", part))
            except (TypeError, ValueError):
                send_frame(conn, encode_error_frame(
                    f"bad fetch request {line!r}"))
                return
            j = str(freq.get("job") or job)
            if (j, p) != (job, part):
                # connection reuse: the stream re-targets the next part
                # this worker serves without a reconnect
                job, part = j, p
                store = self._wait_store(job, part)
                if store is None:
                    send_frame(conn, encode_error_frame(
                        f"worker {self.worker_id} does not serve job "
                        f"{job} part {part}"))
                    return
                raw_ctr = _telemetry.REGISTRY.counter(
                    _telemetry.SERVICE_WIRE_RAW_METRIC, job=job)
                sent_ctr = _telemetry.REGISTRY.counter(
                    _telemetry.SERVICE_WIRE_SENT_METRIC, job=job)
            with self._cond:
                self._cond.wait_for(
                    lambda: i < len(store.frames) or store.complete
                    or self._dead)
                if self._dead:
                    return  # crash simulation: drop mid-stream
                if i < len(store.frames):
                    frame = store.frames[i]
                elif store.error is not None:
                    send_frame(conn, encode_error_frame(
                        store.error, draining=self._draining.is_set()))
                    return
                else:
                    # fetch past the end: END — and keep reading, the
                    # client's remaining in-flight fetches need theirs
                    send_frame(conn, encode_end_frame(
                        part, len(store.frames),
                        draining=self._draining.is_set()))
                    continue
            sent = self._send_block_v2(conn, store, i, frame, codec)
            raw_ctr.inc(len(frame))
            sent_ctr.inc(sent)

    def _pack_snapshot_frames(self, store: _PartStore,
                              geometry: dict) -> List[bytes]:
        """The part re-encoded as device-layout snapshot frames: decode
        the stored CSR block frames, pack to the job's fixed batch
        geometry, encode once, cache on the store (warm re-serves pay
        nothing). Runs under no lock — only the cached-list publish
        does.

        Contract: a snapshot frame's payload IS the device-decodable
        span — the same ``write_segments`` bytes as an on-disk snapshot
        batch, with meta array offsets payload-relative (base 0) — so a
        ``device_decode=True`` client ships the payload verbatim to HBM
        and decodes it there (``ops/device_decode``). Any change to the
        frame encoding must preserve that byte-level identity."""
        from dmlc_tpu.data.device import pack_dense_batches
        from dmlc_tpu.service.frame import (
            block_from_frame, decode_frame, encode_snapshot_frame,
        )

        B = int(geometry["batch_size"])
        nc = int(geometry["num_col"])
        if geometry.get("x_dtype") == "bfloat16":
            from dmlc_tpu.native import bf16_dtype

            dt = bf16_dtype()
        else:
            dt = None
        blocks = []
        for raw in store.frames:
            _, meta, payload = decode_frame(raw)
            blocks.append(block_from_frame(meta, payload))
        frames = []
        for packed, resume in pack_dense_batches(blocks, B, nc, dtype=dt):
            frames.append(encode_snapshot_frame(
                "dense_packed", (packed,), rows=B, resume=resume))
        return frames

    def _serve_stream_snapshot(self, conn, job: str, part: int,
                               start: int) -> None:
        """Stream a part as snapshot frames (the geometry is the JOB's —
        a bf16-wire trainer and a CSR trainer can share one fleet).
        Packing needs the whole part (fixed batches span block
        boundaries), so this waits for parse completion — the CSR stream
        stays the low-latency path; snapshot frames trade first-byte
        latency for half the wire. Each frame's payload doubles as the
        client's device-decodable span (see
        :meth:`_pack_snapshot_frames`)."""
        store = self._wait_store(job, part)
        # a (job, part) in the store implies the job's cfg was fetched
        # at grant time — the serve path never needs its own RPC
        geometry = (self._job_cfgs.get(job) or {}).get("snapshot") or {}
        if store is None or not geometry:
            send_frame(conn, encode_error_frame(
                f"worker {self.worker_id} does not serve job {job} "
                f"part {part} as snapshot frames"))
            return
        with self._cond:
            self._cond.wait_for(lambda: store.complete or self._dead)
            if self._dead:
                return
            if store.error is not None:
                # mid-drain this is a GRACEFUL notice (the part was
                # re-issued): the client relocates without blaming
                send_frame(conn, encode_error_frame(
                    store.error, draining=self._draining.is_set()))
                return
            # single-packer claim: concurrent first requests must not
            # each decode + repack the whole part — one thread packs,
            # the rest wait on the publish
            self._cond.wait_for(
                lambda: store.snap_frames is not None
                or not store.snap_packing or self._dead)
            if self._dead:
                return
            frames = store.snap_frames
            if frames is None:
                store.snap_packing = True
        if frames is None:
            # cross-job snapshot sharing (docs/service.md snapshot
            # sharing): a sibling job with the SAME geometry over the
            # same corpus signature — or a previous incarnation — may
            # already have published this pack to the DMLCSN01 store
            # tier; load + pin it instead of re-packing
            packed = self._load_shared_snapshot(store, geometry)
            if packed is not None:
                _resilience.record_event("service_parts_shared")
                logger.info("worker %s: job %s part %d snapshot served "
                            "from shared artifact", self.worker_id, job,
                            part)
            else:
                try:
                    packed = self._pack_snapshot_frames(store, geometry)
                except Exception as exc:  # noqa: BLE001 - served as ERROR
                    with self._cond:
                        store.snap_packing = False
                        self._cond.notify_all()
                    send_frame(conn, encode_error_frame(
                        f"snapshot packing failed: {exc}"))
                    return
                self._publish_shared_snapshot(store, geometry, packed,
                                              job)
            with self._cond:
                store.snap_frames = packed
                store.snap_packing = False
                self._cond.notify_all()
                frames = store.snap_frames
        for i in range(max(0, int(start)), len(frames)):
            if self._dead:
                return  # crash simulation: drop mid-stream, no goodbye
            send_frame(conn, frames[i])
        # a draining END asks the client to confirm the handoff with
        # the dispatcher, same as the CSR path (docs/service.md)
        send_frame(conn, encode_end_frame(part, len(frames),
                                          draining=self._draining.is_set()))

    def _snap_share_path(self, store: _PartStore,
                         geometry: dict) -> Optional[str]:
        """The shared on-disk home of this part's packed snapshot
        frames: the part's published (share-by-signature) block-cache
        path + a geometry digest. Sibling jobs over the same corpus
        signature with the same geometry resolve the SAME path, so the
        pack happens once fleet-wide; a job with a private cache still
        shares with its own later incarnations. None when the part has
        no published cache (nothing durable to key on)."""
        cache_path = store.cache_path
        if not cache_path or not geometry:
            return None
        from dmlc_tpu.store import signature_hash

        return f"{cache_path}.g{signature_hash(geometry)}.snap"

    def _load_shared_snapshot(self, store: _PartStore,
                              geometry: dict) -> Optional[List[bytes]]:
        """A previously-published shared snapshot pack for this part +
        geometry, pinned against either tenant's eviction pressure; None
        on miss/corruption (the caller packs locally)."""
        path = self._snap_share_path(store, geometry)
        if not path or not os.path.exists(path):
            return None
        try:
            with open(path, "rb") as f:
                frames = _decode_snap_container(f.read())
        except OSError:
            return None
        if frames is None:
            return None
        try:
            from dmlc_tpu.store import store_for

            store_for(path).pin(path)
            self._artifact_pins.append(path)
        except Exception:  # noqa: BLE001 - a pin failure must never
            pass           # fail the serve; the artifact stays evictable
        return frames

    def _publish_shared_snapshot(self, store: _PartStore, geometry: dict,
                                 frames: List[bytes], job: str) -> None:
        """Publish this part's packed snapshot frames to the DMLCSN01
        store tier (atomic stage + rename — concurrent packers converge
        on one artifact) and pin it for this worker's life. Best-effort:
        a store failure costs only the sharing, never the stream."""
        path = self._snap_share_path(store, geometry)
        if not path:
            return
        try:
            from dmlc_tpu.store import store_for

            st = store_for(path)
            tmp = st.stage_path(path)
            with open(tmp, "wb") as f:
                f.write(_encode_snap_container(frames))
            st.publish_file(
                tmp, path, "snapshot",
                signature={"cache": os.path.basename(store.cache_path),
                           "geometry": geometry},
                job=job)
            st.pin(path)
            self._artifact_pins.append(path)
        except Exception as exc:  # noqa: BLE001 - sharing is an
            # optimization; the local pack already serves this client
            logger.warning("worker %s: shared snapshot publish of %s "
                           "failed: %s", self.worker_id, path, exc)

    def _serve_find(self, conn, job: str, part: int, key: str) -> None:
        """Block index whose resume annotation matches ``key`` — the
        remote half of restoring a parser-chain checkpoint into a fresh
        service client. Scans incrementally so a match early in a part
        still being parsed answers without waiting for completion."""
        store = self._wait_store(job, part)
        found = -1
        interrupted = error = None
        if store is not None:
            i = 0
            with self._cond:
                while True:
                    while i < len(store.keys):
                        if store.keys[i] == key:
                            found = i
                            break
                        i += 1
                    if found >= 0 or store.complete or self._dead:
                        interrupted = self._dead and not store.complete
                        error = store.error
                        break
                    self._cond.wait()
        if found < 0 and (error or interrupted or store is None):
            # a partial scan must not read as an authoritative miss
            resp = {"block": -1,
                    "error": error or f"part {part} not fully served"}
        else:
            resp = {"block": found}
        conn.sendall(json.dumps(resp).encode() + b"\n")

    def _serve_count(self, conn, job: str, part: int) -> None:
        store = self._wait_store(job, part)
        if store is None:
            conn.sendall(json.dumps(
                {"error": f"part {part} not served"}).encode() + b"\n")
            return
        with self._cond:
            self._cond.wait_for(lambda: store.complete or self._dead)
            n = len(store.frames)
            partial = store.error or not store.complete
            error = store.error
        if partial:
            # a truncated count is worse than no count: the client maps
            # delivered-block offsets onto part boundaries with it
            resp = {"error": error or f"part {part} count interrupted"}
        else:
            resp = {"blocks": n}
        conn.sendall(json.dumps(resp).encode() + b"\n")

    # ---------------- lifecycle ----------------

    @property
    def alive(self) -> bool:
        """True while this worker serves: neither killed/closed nor
        drained out."""
        return not self._stop.is_set() and not self.drained

    @property
    def draining(self) -> bool:
        """True once a graceful drain has begun: still serving its
        frame-store-complete parts, but no longer grant-eligible — so
        NOT live capacity (the autoscaler must not count or re-drain
        it)."""
        return self._draining.is_set()

    def _teardown(self) -> None:
        self._stop.set()
        # release artifact pins: close() is a graceful exit, and kill()
        # emulates a dead pid (whose journaled pins replay as ignored) —
        # in-process the explicit drop is the faithful equivalent
        self._drop_artifact_pins()
        with self._cond:
            self._cond.notify_all()
        try:
            self._listen.close()
        except OSError:
            pass
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    def kill(self) -> None:
        """Simulate a crash: every socket drops mid-whatever, the frame
        store is abandoned, and NOBODY is notified — the dispatcher
        learns from client ``report_lost`` / stale heartbeats."""
        self._dead = True
        self._teardown()
        if self._tracker_client is not None:
            # a dead process sends no shutdown; just stop local threads
            self._tracker_client.stop_heartbeat()
            self._tracker_client.close()
            self._tracker_client = None

    def close(self) -> None:
        """Graceful shutdown (end of job)."""
        self._dead = True
        self._teardown()
        if self._tracker_client is not None:
            try:
                if self.rank >= 0:
                    self._tracker_client.shutdown()
                else:
                    self._tracker_client.close()
            except (OSError, AssertionError):
                self._tracker_client.close()
            self._tracker_client = None
