"""Localhost fleet bootstrap: one dispatcher + N parse workers.

The in-process form of the service deployment (tests, ``bench.py
--service``, the docs example): multi-host launches reuse the tracker
backends instead — export ``DMLC_SERVICE_DISPATCHER`` through the
launcher env contract and run one :class:`~dmlc_tpu.service.worker.
ParseWorker` per host (docs/service.md "Deploying").
"""

from __future__ import annotations

import threading
from typing import List, Optional

from dmlc_tpu.service.dispatcher import Dispatcher
from dmlc_tpu.service.worker import ParseWorker
from dmlc_tpu.utils.check import check


class LocalFleet:
    """1 dispatcher + ``num_workers`` workers over localhost TCP.

    ``parser`` is the dispatcher-shipped parse config (see
    :class:`~dmlc_tpu.service.dispatcher.Dispatcher`). With
    ``tracker=True`` a rabit-protocol tracker is started too and every
    worker fetches its rank from it and feeds the pod-telemetry table
    over the ``metrics`` heartbeat (workers then bootstrap in parallel —
    rank assignment is a barrier across the fleet).

    ``journal_path`` arms dispatcher crash recovery and the chaos API:
    :meth:`kill_dispatcher` crash-simulates the control plane,
    :meth:`restart_dispatcher` recovers it from the journal **on the
    same address**, so the live workers and clients ride through
    (docs/service.md control-plane recovery).
    """

    def __init__(self, uri: str, num_parts: int, num_workers: int = 2,
                 parser: Optional[dict] = None, tracker: bool = False,
                 liveness_timeout: float = 10.0,
                 poll_interval: float = 0.05,
                 heartbeat_interval: float = 1.0,
                 plan: Optional[dict] = None,
                 snapshot: Optional[dict] = None,
                 autotune: Optional[bool] = None,
                 journal_path: Optional[str] = None,
                 share_dir: Optional[str] = None):
        self._dispatcher_args = dict(
            uri=uri, num_parts=num_parts, parser=parser,
            liveness_timeout=liveness_timeout, plan=plan,
            snapshot=snapshot, journal_path=journal_path,
            share_dir=share_dir)
        self._worker_args = dict(poll_interval=poll_interval,
                                 heartbeat_interval=heartbeat_interval,
                                 autotune=autotune)
        self.dispatcher = Dispatcher(**self._dispatcher_args)
        self.tracker = None
        tracker_addr = None
        if tracker:
            from dmlc_tpu.tracker.tracker import RabitTracker

            self.tracker = RabitTracker("127.0.0.1", num_workers)
            self.tracker.start(num_workers)
            tracker_addr = ("127.0.0.1", self.tracker.port)
        self.workers: List[ParseWorker] = [None] * num_workers  # type: ignore[list-item]
        errors: List[BaseException] = []

        def boot(slot: int) -> None:
            try:
                self.workers[slot] = ParseWorker(
                    self.dispatcher.address, tracker=tracker_addr,
                    tracker_world=num_workers, poll_interval=poll_interval,
                    heartbeat_interval=heartbeat_interval,
                    autotune=autotune)
            except BaseException as exc:  # noqa: BLE001 - re-raised below
                errors.append(exc)

        stuck = False
        if tracker:
            # rank assignment blocks until every worker joins: boot the
            # fleet concurrently or the first constructor deadlocks
            threads = [threading.Thread(target=boot, args=(i,),
                                        daemon=True)
                       for i in range(num_workers)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60.0)
            if errors and any(t.is_alive() for t in threads):
                # a failed sibling leaves the others blocked inside the
                # rank-assignment barrier forever: break the barrier by
                # closing the tracker, then reap the boot threads
                self.tracker.close()
                for t in threads:
                    t.join(timeout=10.0)
            stuck = any(t.is_alive() for t in threads)
        else:
            for i in range(num_workers):
                boot(i)
        if errors or stuck or any(w is None for w in self.workers):
            # a half-booted fleet must not leak listeners/threads into the
            # caller's process, and the real boot failure rides the raise
            self.close()
            raise RuntimeError("service fleet bootstrap failed") from (
                errors[0] if errors else None)

    @property
    def address(self) -> str:
        """The dispatcher address clients connect to."""
        return self.dispatcher.address

    def register_job(self, job: str, uri: str, num_parts: int,
                     parser: Optional[dict] = None,
                     plan: Optional[dict] = None,
                     snapshot: Optional[dict] = None,
                     priority: Optional[int] = None,
                     weight: Optional[int] = None,
                     slo_wait_frac: Optional[float] = None,
                     max_inflight: Optional[int] = None) -> dict:
        """Register one more job at the running dispatcher
        (docs/service.md multi-tenant service): the live workers pick it
        up at their next grant — no fleet restart, no new fleet. With
        ``share_dir`` set on the fleet, a job over an already-registered
        corpus + config shares its published block caches by signature
        (the corpus parses once fleet-wide). ``priority`` / ``weight`` /
        ``slo_wait_frac`` / ``max_inflight`` declare the job's QoS class
        (docs/service.md Production QoS)."""
        return self.dispatcher.register_job(
            job, uri, num_parts, parser=parser, plan=plan,
            snapshot=snapshot, priority=priority, weight=weight,
            slo_wait_frac=slo_wait_frac, max_inflight=max_inflight)

    def job_qos(self):
        """The registered jobs' QoS classes ({job: {priority, weight,
        ...}}) — the FleetAutoscaler's default SLO/priority source."""
        return self.dispatcher.job_qos()

    def live_workers(self) -> List[ParseWorker]:
        """Workers that are live CAPACITY: not killed/closed/drained,
        and not mid-drain either — a draining worker serves out its
        completed parts but takes no new grants, so counting it would
        let the autoscaler drain a second worker below ``fleet_min``
        (or phantom-re-drain the same one) while the first is still
        exiting."""
        return [w for w in self.workers
                if w is not None and w.alive and not w.draining]

    def autoscale(self, **kwargs) -> "FleetAutoscaler":
        """Attach an input-wait-driven :class:`~dmlc_tpu.service.
        autoscale.FleetAutoscaler` to this fleet (docs/service.md fleet
        autoscaling). ``kwargs`` pass through (``source=``, bounds,
        thresholds, ``start=True`` for the background tick thread)."""
        from dmlc_tpu.service.autoscale import FleetAutoscaler

        return FleetAutoscaler(self, **kwargs)

    def _pull_trace_snapshots(self) -> List[dict]:
        """One ``trace_dump`` round trip per live component — dispatcher
        over the control plane, each worker over its data listener —
        each snapshot tagged with a clock offset estimated from the RPC
        request/reply midpoint (docs/observability.md Distributed
        tracing). A peer that cannot answer is skipped, never fatal."""
        import json
        import socket as _socket

        from dmlc_tpu.service import dispatcher as _dispatch
        from dmlc_tpu.utils.timer import get_time

        peers: List[dict] = []

        def note(snap, t0: float, t1: float) -> None:
            if not isinstance(snap, dict):
                return
            now = snap.get("now")
            offset = ((t0 + t1) / 2.0 - float(now)
                      if isinstance(now, (int, float)) else 0.0)
            peers.append(dict(snap, clock_offset_s=round(offset, 6)))

        try:
            t0 = get_time()
            resp = _dispatch.request(self.address, {"cmd": "trace_dump"})
            note(resp.get("snapshot"), t0, get_time())
        except Exception:  # noqa: BLE001 - a dead dispatcher still dumps
            pass           # the workers' half of the timeline
        for w in self.workers:
            if w is None or not w.alive:
                continue
            try:
                t0 = get_time()
                with _socket.create_connection((w.host, w.port),
                                               timeout=10.0) as s:
                    s.settimeout(10.0)
                    with s.makefile("rwb") as f:
                        f.write(json.dumps(
                            {"cmd": "trace_dump"}).encode() + b"\n")
                        f.flush()
                        line = f.readline()
                note(json.loads(line).get("snapshot") if line else None,
                     t0, get_time())
            except (OSError, ValueError):
                continue
        return peers

    def dump_trace(self, path: str) -> int:
        """Pull every component's span rings + decision ledgers over the
        ``trace_dump`` RPC and export ONE merged Chrome/Perfetto JSON at
        ``path`` (open in ui.perfetto.dev; docs/observability.md). Each
        genuinely remote peer gets its own timeline row with its clock
        offset applied; co-located peers (a LocalFleet is one process,
        so dispatcher and workers share one span-ring set) collapse to a
        single row instead of duplicating every span N times. Returns
        the number of span events written."""
        from dmlc_tpu.utils import telemetry as _telemetry

        unique: List[dict] = []
        by_pid: dict = {}
        for peer in self._pull_trace_snapshots():
            pid = peer.get("pid")
            prior = by_pid.get(pid)
            if pid is not None and prior is not None:
                prior["peer"] = f"{prior['peer']}+{peer.get('peer')}"
                continue
            if pid is not None:
                by_pid[pid] = peer
            unique.append(peer)
        return _telemetry.export_pod_trace(path, unique)

    def kill_worker(self, index: int) -> ParseWorker:
        """Crash-simulate one worker (see :meth:`ParseWorker.kill`)."""
        w = self.workers[index]
        w.kill()
        return w

    def add_worker(self, **kwargs) -> ParseWorker:
        """LIVE JOIN (docs/service.md elastic membership): boot one more
        worker against the running dispatcher mid-epoch — it enters the
        grant rotation and the re-issue serving set immediately
        (journaled ``join`` event, ``worker_joins`` counter). Joined
        workers skip the tracker (rank worlds are fixed at rendezvous;
        elastic capacity is dispatcher-side membership). ``kwargs``
        override the fleet's worker knobs (``straggle_seconds``, ...)."""
        kw = dict(poll_interval=self._worker_args["poll_interval"],
                  heartbeat_interval=self._worker_args[
                      "heartbeat_interval"],
                  autotune=self._worker_args["autotune"])
        kw.update(kwargs)
        w = ParseWorker(self.dispatcher.address, **kw)
        self.workers.append(w)
        return w

    def drain_worker(self, index: int,
                     deadline: Optional[float] = None) -> ParseWorker:
        """Gracefully drain one worker (preemption-notice path, see
        :meth:`ParseWorker.drain`): it stops taking grants, its
        unstarted parts re-issue at the front, and it serves out its
        frame-store-complete parts until clients confirm handoff or the
        deadline (``DMLC_TPU_DRAIN_DEADLINE``) expires — then exits. The
        worker stays in :attr:`workers` (close() is idempotent)."""
        w = self.workers[index]
        w.drain(reason="fleet drain_worker", deadline=deadline)
        return w

    def kill_dispatcher(self) -> Dispatcher:
        """Crash-simulate the dispatcher (``kill -9``): its listener
        drops with no goodbye and the in-memory assignment state is
        abandoned; workers poll a dead socket (classified retryable) and
        clients' locate loops consume stream-failure budget until
        :meth:`restart_dispatcher` recovers the control plane."""
        self.dispatcher.kill()
        return self.dispatcher

    def restart_dispatcher(self) -> Dispatcher:
        """Restart the dispatcher from its journal on the SAME address:
        replay restores the exact assignment state (completed parts stay
        done, in-flight parts re-queue at the front) and the generation
        bump drives the fleet's re-register + reclaim handshake. The old
        dispatcher is killed first if still alive. Requires
        ``journal_path`` — without it the replacement would re-issue
        every part for a fleet-wide re-parse."""
        check(self._dispatcher_args.get("journal_path"),
              "LocalFleet.restart_dispatcher needs journal_path= — "
              "an unjournaled dispatcher cannot recover its assignment "
              "state (docs/service.md control-plane recovery)")
        old = self.dispatcher
        if not old._closed:
            old.kill()
        self.dispatcher = Dispatcher(host=old.host, port=old.port,
                                     **self._dispatcher_args)
        return self.dispatcher

    def close(self) -> None:
        for w in self.workers:
            if w is not None:
                w.close()
        self.dispatcher.close()
        if self.tracker is not None:
            self.tracker.close()
