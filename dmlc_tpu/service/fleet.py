"""Localhost fleet bootstrap: one dispatcher + N parse workers.

The in-process form of the service deployment (tests, ``bench.py
--service``, the docs example): multi-host launches reuse the tracker
backends instead — export ``DMLC_SERVICE_DISPATCHER`` through the
launcher env contract and run one :class:`~dmlc_tpu.service.worker.
ParseWorker` per host (docs/service.md "Deploying").
"""

from __future__ import annotations

import threading
from typing import List, Optional

from dmlc_tpu.service.dispatcher import Dispatcher
from dmlc_tpu.service.worker import ParseWorker


class LocalFleet:
    """1 dispatcher + ``num_workers`` workers over localhost TCP.

    ``parser`` is the dispatcher-shipped parse config (see
    :class:`~dmlc_tpu.service.dispatcher.Dispatcher`). With
    ``tracker=True`` a rabit-protocol tracker is started too and every
    worker fetches its rank from it and feeds the pod-telemetry table
    over the ``metrics`` heartbeat (workers then bootstrap in parallel —
    rank assignment is a barrier across the fleet).
    """

    def __init__(self, uri: str, num_parts: int, num_workers: int = 2,
                 parser: Optional[dict] = None, tracker: bool = False,
                 liveness_timeout: float = 10.0,
                 poll_interval: float = 0.05,
                 heartbeat_interval: float = 1.0,
                 plan: Optional[dict] = None,
                 snapshot: Optional[dict] = None,
                 autotune: Optional[bool] = None):
        self.dispatcher = Dispatcher(uri, num_parts, parser=parser,
                                     liveness_timeout=liveness_timeout,
                                     plan=plan, snapshot=snapshot)
        self.tracker = None
        tracker_addr = None
        if tracker:
            from dmlc_tpu.tracker.tracker import RabitTracker

            self.tracker = RabitTracker("127.0.0.1", num_workers)
            self.tracker.start(num_workers)
            tracker_addr = ("127.0.0.1", self.tracker.port)
        self.workers: List[ParseWorker] = [None] * num_workers  # type: ignore[list-item]
        errors: List[BaseException] = []

        def boot(slot: int) -> None:
            try:
                self.workers[slot] = ParseWorker(
                    self.dispatcher.address, tracker=tracker_addr,
                    tracker_world=num_workers, poll_interval=poll_interval,
                    heartbeat_interval=heartbeat_interval,
                    autotune=autotune)
            except BaseException as exc:  # noqa: BLE001 - re-raised below
                errors.append(exc)

        stuck = False
        if tracker:
            # rank assignment blocks until every worker joins: boot the
            # fleet concurrently or the first constructor deadlocks
            threads = [threading.Thread(target=boot, args=(i,),
                                        daemon=True)
                       for i in range(num_workers)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60.0)
            if errors and any(t.is_alive() for t in threads):
                # a failed sibling leaves the others blocked inside the
                # rank-assignment barrier forever: break the barrier by
                # closing the tracker, then reap the boot threads
                self.tracker.close()
                for t in threads:
                    t.join(timeout=10.0)
            stuck = any(t.is_alive() for t in threads)
        else:
            for i in range(num_workers):
                boot(i)
        if errors or stuck or any(w is None for w in self.workers):
            # a half-booted fleet must not leak listeners/threads into the
            # caller's process, and the real boot failure rides the raise
            self.close()
            raise RuntimeError("service fleet bootstrap failed") from (
                errors[0] if errors else None)

    @property
    def address(self) -> str:
        """The dispatcher address clients connect to."""
        return self.dispatcher.address

    def kill_worker(self, index: int) -> ParseWorker:
        """Crash-simulate one worker (see :meth:`ParseWorker.kill`)."""
        w = self.workers[index]
        w.kill()
        return w

    def close(self) -> None:
        for w in self.workers:
            if w is not None:
                w.close()
        self.dispatcher.close()
        if self.tracker is not None:
            self.tracker.close()
