"""Data-service client: a drop-in RowBlock parser over the wire.

:class:`ServiceParser` implements the :class:`~dmlc_tpu.data.parsers.Parser`
contract against a dispatcher address, so it feeds ``DeviceIter`` (and
``BasicRowIter``) unchanged — selected via
``create_parser(service=...)`` / ``create_row_block_iter(service=...)``
or a ``#service=<host:port>`` URI suffix.

**Job identity** (docs/service.md multi-tenant service): the client
binds to ONE registered job (``job=``, default ``"default"`` — the
dispatcher-constructor dataset), carries it on every control RPC and
stream request, stamps it into checkpoints (a state restored into a
client bound to a different job fails loudly — positions are only
meaningful within one job's part-major order), and labels its
consumer-side input wait with it on the telemetry registry
(``service_job_input_wait_seconds``), which is the per-job signal the
fleet autoscaler aggregates from the tracker pod table
(docs/observability.md). Streams are byte-identical PER JOB: a job's
delivered blocks match its single-job run exactly, whatever other jobs
share the fleet or the underlying cached artifacts.

Delivery order is **part-major**: part 0's blocks, then part 1's, ...
— exactly the stream a single host produces looping
``create_parser(uri, p, num_parts)`` for ``p`` in order with the same
config, so the delivered blocks (arrays AND resume annotations) are
byte-identical to local parsing regardless of which workers parsed what.

Fault tolerance composes the shared :mod:`dmlc_tpu.io.resilience`
machinery: a broken stream (connection loss, torn frame, worker ERROR)
is a classified retryable fault — the client reports the worker lost,
waits for the dispatcher to re-issue the part, reconnects to the new
owner, and resumes **at the exact block index** (``start=`` in the
stream request), counting ``service_retries`` per interruption and
``service_failovers`` when the resume landed on a different worker;
exhausted budgets count ``service_giveups`` and surface as ``DMLCError``.

The control plane is covered too (docs/service.md control-plane
recovery): every dispatcher round trip runs under the shared
``RetryPolicy`` (``control_plane_retries`` per transient re-attempt —
connection refused between a dispatcher kill and its restart, torn
replies), and every dispatcher response carries a monotonic generation
token. A bump means the dispatcher restarted: the client counts a
``dispatcher_restarts`` and simply continues — its ``(part, block)``
cursor is client-owned state, revalidated against the recovered
dispatcher by the very next ``locate``, so the epoch resumes
byte-identically whether the part was reclaimed from a surviving
worker's frame store or re-parsed.

Elastic membership (docs/service.md): a *draining* worker (preemption
notice, SIGTERM, operator drain) hands off gracefully instead of timing
out. The client learns re-assignments from ``moved`` / ``draining``
hints on ``locate`` (it sends the owner it last used as ``have``), a
drain-flagged ERROR frame relocates WITHOUT blaming the worker or
spending retry budget (the part was proactively re-issued), and a
drain-flagged END confirms the handoff back to the dispatcher
(``handoff`` RPC) so the drain can complete before its deadline. Each
graceful move or confirmed handoff counts ``drain_handoffs``.

Checkpoints: ``state_dict()`` is ``(part, block)`` — O(1) to restore
into a **fresh** client/connection. ``load_state`` additionally accepts
the parser chain's annotation states (the ``kind='split'``/``'chunks'``
states a ``DeviceIter`` checkpoint embeds) by asking the serving workers
to ``find`` the annotation in their frame stores — the service analog of
``BlockCacheIter``'s stored-annotation match.
"""

from __future__ import annotations

import os
import socket
import json
import threading
from typing import Dict, Optional

from dmlc_tpu.data.parsers import Parser
from dmlc_tpu.data.row_block import DenseBlock, RowBlock
from dmlc_tpu.io import faults as _faults
from dmlc_tpu.io import resilience as _resilience
from dmlc_tpu.service import dispatcher as _dispatch
from dmlc_tpu.service.dispatcher import DEFAULT_JOB
from dmlc_tpu.utils import knobs as _knobs
from dmlc_tpu.utils import telemetry as _telemetry
from dmlc_tpu.service.frame import (
    KIND_BLOCK,
    KIND_END,
    KIND_ERROR,
    KIND_HELLO,
    KIND_SNAPSHOT,
    WIRE_CODECS,
    ServiceFrameError,
    annot_key,
    attach_trace,
    block_from_frame,
    recv_frame,
    snapshot_from_frame,
)
from dmlc_tpu.utils.check import DMLCError
from dmlc_tpu.utils.timer import get_time

_LOCATE_POLL_S = 0.05


class ServiceUnavailableError(DMLCError):
    """No live worker owns the requested part (yet). Retryable — it
    consumes the client's stream-failure budget like any broken stream,
    so a fleet that never recovers surfaces as a ``service_giveups``."""

    def __init__(self, msg: str):
        super().__init__(msg)
        self.__cause__ = ConnectionError(msg)


class ServiceParser(Parser):
    """RowBlock stream served by a parse-worker fleet (one epoch pass =
    one part-major visitation; ``before_first`` rewinds to part 0 —
    workers re-serve from their frame stores, nothing re-parses)."""

    def __init__(self, service: str, job: str = DEFAULT_JOB,
                 retry_policy: Optional["_resilience.RetryPolicy"] = None,
                 connect_timeout: float = 10.0,
                 stream_timeout: float = 300.0):
        self.service = service
        self.job = str(job)
        self._policy = retry_policy or _resilience.default_policy()
        # consumer-side input wait, labeled by job: every second this
        # client waits on the service's wire is the job's starvation
        # signal — summed fleet-wide by the autoscaler via the tracker
        # pod table (docs/service.md fleet autoscaling)
        self._wait_metric = _telemetry.REGISTRY.counter(
            _telemetry.SERVICE_JOB_WAIT_METRIC, job=self.job)
        self._connect_timeout = float(connect_timeout)
        # idle timeout on an ESTABLISHED stream, deliberately much larger
        # than the policy's attempt timeout: a worker mid-parse (slow
        # remote reads, its own retry backoffs) is slow, not dead —
        # misclassifying it as lost would re-queue all its parts
        self._stream_timeout = float(stream_timeout)
        self._closed = threading.Event()
        # the dispatcher's monotonic generation token: an advance means
        # the control plane restarted (docs/service.md) — counted as
        # dispatcher_restarts, after which the (part, block) cursor is
        # revalidated by the next locate and the epoch rides through
        self._gen: Optional[int] = None
        cfg = self._control({"cmd": "config", "job": self.job})
        self.uri = cfg["uri"]
        self.num_parts = int(cfg["num_parts"])
        self.parser_config = dict(cfg.get("parser") or {})
        # the dispatcher-shipped epoch-plan identity (shuffle_seed /
        # shuffle_window) — the seed the fleet's warm-cache serving is
        # keyed by, surfaced so trainer-side planners agree with the
        # workers on one global shuffle (docs/service.md)
        self.plan = dict(cfg.get("plan") or {})
        self.shuffle_seed = self.plan.get("shuffle_seed")
        # dispatcher-decided snapshot mode: with a geometry shipped, the
        # fleet streams device-layout PACKED batches instead of CSR
        # blocks (bf16 halves the wire bytes) and delivered blocks are
        # exact-batch-size packed DenseBlocks — DeviceIter's zero-work
        # dense_ready fast path (docs/service.md snapshot frames).
        # Checkpoints stay (part, batch) 'service' states: the packed
        # batches carry no parser-chain annotations to match against.
        self.snapshot = dict(cfg.get("snapshot") or {})
        # the dispatcher-declared QoS class (docs/service.md Production
        # QoS): priority/weight shape this job's grant share, an SLO
        # target is republished as a job-labeled gauge so the pod table
        # shows the job's wait beside the contract the autoscaler holds
        self.qos = dict(cfg.get("qos") or {})
        if self.qos.get("slo_wait_frac"):
            _telemetry.REGISTRY.gauge(
                _telemetry.SERVICE_JOB_SLO_METRIC,
                job=self.job).set(float(self.qos["slo_wait_frac"]))
        self._part = 0
        self._pos = 0          # next block index within the current part
        self._delivered = 0    # blocks delivered this epoch (all parts)
        self._sock: Optional[socket.socket] = None
        self._owner: Optional[str] = None
        # the owner the dispatcher last pointed us at, kept across the
        # connect itself: a located worker that refuses the connection is
        # just as dead as one that drops mid-frame and must be reported,
        # or the dispatcher keeps handing it out for the liveness window
        self._pending_owner: Optional[str] = None
        self._failover_from: Optional[str] = None
        # owner already granted one same-owner retry for a torn frame
        # (ServiceFrameError): the first CRC blip re-requests the exact
        # block from the SAME worker; only a repeat escalates to
        # report_lost (which re-queues the worker's whole share)
        self._soft_retry_owner: Optional[str] = None
        # elastic-membership state (docs/service.md): the owner we last
        # located the CURRENT part at (sent as `have` so the dispatcher
        # can hint `moved` when the part was re-assigned), the owner a
        # graceful drain notice moved us off (pending handoff), and a
        # bound on consecutive drain moves (a drain gone wrong must fall
        # back to the normal fault budget, never spin)
        self._last_located: Optional[str] = None
        self._drain_move_from: Optional[str] = None
        self._drain_moves = 0
        # the CURRENT part's trace context — the grant trace the
        # dispatcher hands back on `locate`, re-offered to the worker on
        # the stream request and scoped around this client's recv/decode
        # so one (job, part) renders as one causal trace across all
        # three processes (docs/observability.md Distributed tracing)
        self._trace_ctx: Optional[tuple] = None
        self._stream_failures = 0
        self._bytes = 0
        self._recv_seconds = 0.0
        self._decode_seconds = 0.0
        self._last_annot: Optional[dict] = None
        # ---- wire v2 session state (docs/service.md Wire v2) ----
        # negotiated PER STREAM at open: the client always offers v2 and
        # peeks the first frame — a HELLO means a v2 worker (pipelined
        # newline-JSON fetches, negotiated codec, fast-path offer); any
        # other frame means a v1 worker already pushing, and the peeked
        # frame is stashed so nothing on the wire is lost
        self._pipeline_depth = _knobs.resolve("service_pipeline_depth")
        # what this client OFFERS at stream open (the negotiated result
        # lands in _wire per stream): 2 everywhere, pinned to 1 only as
        # an operational escape hatch / for the compat test matrix
        self._offer_wire = 2
        self._wire = 1
        self._codec: Optional[str] = None
        self._pending: Optional[tuple] = None
        self._inflight = 0          # v2 fetches issued, reply not read
        self._next_fetch = 0        # v2: next block index to fetch
        self._blocks_total: Optional[int] = None  # from HELLO, if complete
        self._fp_reader = None      # co-located mmap fast-path reader
        self._fp_skip = False       # fast path failed: TCP for this part
        self._fastpath_blocks = 0   # blocks served off the mmap, no TCP
        # a finished part's drained, healthy v2 socket parked for reuse:
        # (socket, owner) — adopted by _ensure_stream when the next part
        # locates at the same worker, closed otherwise
        self._held: Optional[tuple] = None

    # ---------------- control plane ----------------

    def _control(self, req: dict) -> dict:
        """One policy-guarded dispatcher round trip: transient
        control-plane faults (connection refused while the dispatcher
        restarts, torn replies — ``dispatcher.request`` classifies them)
        back off and retry under the shared policy, counting
        ``control_plane_retries``; an exhausted budget surfaces as the
        retryable :class:`ServiceUnavailableError` so the stream-fault
        layer above keeps healing. The response's generation stamp is
        inspected, so a dispatcher restart is detected at the next
        control exchange."""
        try:
            resp = self._policy.call(
                lambda: _dispatch.request(self.service, req),
                op="control_plane", what=self.service,
                on_retry=lambda: _resilience.record_event(
                    "control_plane_retries"))
        except DMLCError as exc:
            if _resilience.classify(exc) != _resilience.RETRYABLE:
                raise
            raise ServiceUnavailableError(
                f"service {self.service}: control plane unreachable "
                f"({req.get('cmd')}): {exc}") from exc
        self._note_generation(resp)
        return resp

    def _note_generation(self, resp: dict) -> None:
        gen = resp.get("gen")
        if gen is None:
            return
        gen = int(gen)
        if self._gen is not None and gen > self._gen:
            # the control plane restarted and recovered mid-run: count
            # it; the (part, block) cursor is client-owned, so the next
            # locate against the recovered dispatcher revalidates it and
            # the epoch continues byte-identically
            _resilience.record_event("dispatcher_restarts")
        if self._gen is None or gen > self._gen:
            self._gen = gen

    # ---------------- connection plumbing ----------------

    def _drop_stream(self) -> None:
        sock, self._sock = self._sock, None
        self._owner = None
        # a pending owner is only blameable while ITS connect/stream is in
        # flight: once the stream is dropped (END, epoch reset) a later
        # fault must not report this — by then healthy — worker lost
        self._pending_owner = None
        # v2 session state is per-stream: a reconnect re-negotiates and
        # re-issues the in-flight window from the exact (part, block)
        # cursor — nothing outstanding survives the old socket
        self._wire = 1
        self._codec = None
        self._pending = None
        self._inflight = 0
        self._next_fetch = 0
        self._blocks_total = None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _close_fastpath(self) -> None:
        reader, self._fp_reader = self._fp_reader, None
        if reader is not None:
            try:
                reader.close()
            except OSError:
                pass

    def _drop_held(self) -> None:
        held, self._held = self._held, None
        if held is not None:
            try:
                held[0].close()
            except OSError:
                pass

    def _locate_owner(self) -> dict:
        """Poll the dispatcher until the current part has a live owner.
        Bounded by the policy's attempt timeout — a fleet with no live
        worker must surface, not spin forever. A ``throttled`` reply
        (admission control shedding this job's grants — docs/service.md
        Production QoS) is NOT a dead fleet: back off on the shared
        RetryPolicy's schedule and extend the deadline, so a
        deliberately-queued batch tenant never burns toward a give-up
        while the fleet is healthy."""
        deadline = get_time() + self._policy.attempt_timeout
        throttles = 0
        while not self._closed.is_set():
            req = {"cmd": "locate", "part": self._part, "job": self.job}
            if self._last_located is not None:
                # tell the dispatcher which owner we were on: a draining
                # re-assignment comes back as a `moved` hint, so the
                # failover happens here — not on a dead socket's timeout
                req["have"] = self._last_located
            resp = self._control(req)
            if resp.get("throttled"):
                _resilience.record_event("service_admission_waits")
                pause = self._policy.backoff(throttles)
                throttles += 1
                deadline = get_time() + self._policy.attempt_timeout
                self._closed.wait(pause)
                continue
            if not resp.get("wait"):
                return resp
            if get_time() >= deadline:
                break
            self._closed.wait(_LOCATE_POLL_S)
        raise ServiceUnavailableError(
            f"service {self.service}: no live worker took part "
            f"{self._part} within {self._policy.attempt_timeout:.0f}s")

    def _ensure_stream(self) -> socket.socket:
        if self._sock is not None:
            return self._sock
        owner = self._locate_owner()
        if self._drain_move_from is not None and owner.get("moved"):
            # the dispatcher's `moved` hint: the drain re-issue landed
            # and this part left the owner we were on — the handoff
            # completed before any socket died (docs/service.md)
            _resilience.record_event("drain_handoffs")
            self._drain_move_from = None
        self._last_located = str(owner["worker"])
        self._pending_owner = str(owner["worker"])
        self._trace_ctx = _telemetry.trace_context_from_wire(
            owner.get("trace"))
        # the worker_rpc fault-plan seam: chaos plans break client->
        # worker data-plane connects deterministically (docs/resilience.md)
        # — it fires per part-stream whether the transport reconnects or
        # reuses, so chaos plans see the same schedule either way
        _faults.maybe_fail(
            "worker_rpc", f"{owner['worker']} stream part {self._part}")
        held, self._held = self._held, None
        if held is not None:
            if held[1] == str(owner["worker"]):
                # connection reuse (docs/service.md Wire v2): the next
                # part located at the worker whose drained v2 stream we
                # parked — adopt it; the first fetch line names the new
                # (job, part) and re-targets the stream server-side.
                # No HELLO on a re-target: ENDs close the part, and the
                # fast path waits for the next fresh handshake.
                self._sock, self._owner = held
                self._wire = 2
                self._blocks_total = None
                self._next_fetch = self._pos
                self._inflight = 0
                self._pending = None
                self._failover_from = None
                return self._sock
            try:
                held[0].close()
            except OSError:
                pass
        sock = socket.create_connection(
            (owner["host"], int(owner["port"])),
            timeout=self._connect_timeout)
        try:
            sock.settimeout(self._stream_timeout)
            req = {"cmd": "stream", "part": self._part, "start": self._pos,
                   "job": self.job}
            # re-offer the part's grant trace to the worker (optional
            # key — old workers ignore it): its service_send spans then
            # join the same trace this client's recv/decode record under
            attach_trace(req, self._trace_ctx)
            offer_v2 = not self.snapshot and self._offer_wire >= 2
            if self.snapshot:
                # snapshot streams stay on the v1 push plane: packed
                # batches are already the minimal wire form
                req["snapshot"] = True
            elif offer_v2:
                # offer wire v2 (docs/service.md Wire v2): a v1 worker
                # ignores the unknown keys and pushes v1 frames — the
                # handshake peek below detects which peer answered
                req["wire"] = 2
                req["accept"] = sorted(WIRE_CODECS)
                req["host"] = socket.gethostname()
            sock.sendall(json.dumps(req).encode() + b"\n")
            if offer_v2:
                self._handshake(sock)
        except BaseException:
            try:
                sock.close()
            except OSError:
                pass
            raise
        self._sock = sock
        self._owner = str(owner["worker"])
        if self._failover_from is not None:
            if self._owner != self._failover_from:
                # resumed mid-part on a DIFFERENT worker: the failover
                # the dispatcher's re-issue path exists for
                _resilience.record_event("service_failovers")
            self._failover_from = None
        return sock

    def _handshake(self, sock: socket.socket) -> None:
        """Peek the first frame of a fresh stream. KIND_HELLO: a v2
        worker — record the negotiated codec / shipped block count, arm
        the pipelined fetch cursor at the exact resume position, and take
        a co-located fast-path offer when one rides the HELLO. Anything
        else: a v1 worker already pushing from ``start`` — stash the
        peeked frame so the delivery loop consumes it first."""
        kind, meta, payload = recv_frame(sock)
        if kind != KIND_HELLO:
            self._wire = 1
            self._pending = (kind, meta, payload)
            return
        self._wire = 2
        self._codec = meta.get("codec")
        total = meta.get("blocks")
        self._blocks_total = None if total is None else int(total)
        self._next_fetch = self._pos
        self._inflight = 0
        if not self._fp_skip:
            self._open_fastpath(meta.get("fastpath"))

    def _open_fastpath(self, offer) -> None:
        """Map a co-located worker's published block-cache artifact and
        serve the part off the mmap, skipping TCP entirely. The reader
        pins the artifact against byte-budget eviction for as long as it
        is open (docs/store.md), and the blocks it yields are the same
        cache spans the worker would have framed — byte-identical arrays
        AND resume annotations. Any mismatch falls back to TCP."""
        if not isinstance(offer, dict):
            return
        path = str(offer.get("path") or "")
        if not path or not os.path.exists(path):
            return
        from dmlc_tpu.io.block_cache import BlockCacheReader

        try:
            reader = BlockCacheReader(path)
        except (DMLCError, OSError, ValueError):
            return  # unreadable / torn artifact: TCP serves the part
        blocks = offer.get("blocks")
        if (blocks is not None and int(blocks) != reader.num_blocks) or (
                self._blocks_total is not None
                and self._blocks_total != reader.num_blocks):
            # the artifact on disk disagrees with what the worker serves:
            # trust the wire, not the map
            try:
                reader.close()
            except OSError:
                pass
            return
        self._close_fastpath()
        self._fp_reader = reader

    def _on_stream_fault(self, exc: BaseException) -> None:
        """One broken stream: count it, tell the dispatcher, back off.
        Budget: the shared policy's max_attempts of consecutive faults
        with no delivered block in between."""
        _resilience.record_event("service_retries")
        lost = self._owner or self._pending_owner
        self._pending_owner = None
        self._drop_stream()
        soft = (isinstance(exc, ServiceFrameError) and lost is not None
                and lost != self._soft_retry_owner)
        if soft:
            # a torn frame from a live, talking worker (wire blip): the
            # resume protocol re-requests the exact block — try the same
            # owner once before report_lost re-queues its whole share
            self._soft_retry_owner = lost
            self._failover_from = lost
        elif lost is not None:
            self._failover_from = lost
            try:
                self._control({"cmd": "report_lost", "worker": lost})
            except (OSError, DMLCError, ValueError):
                pass  # dispatcher unreachable too: the locate poll decides
        used = self._stream_failures
        self._stream_failures += 1
        if self._stream_failures >= self._policy.max_attempts:
            _resilience.record_event("service_giveups")
            raise DMLCError(
                f"service {self.service}: part {self._part} stream failed "
                f"{self._stream_failures} times (budget "
                f"{self._policy.max_attempts}): {exc}") from exc
        self._policy.sleep(self._policy.backoff(used))

    def _trace_scope(self):
        """The current part's trace context as a span scope: recv/decode
        spans recorded inside inherit the grant's trace id (or none when
        propagation is off / the dispatcher predates tracing)."""
        ctx = self._trace_ctx
        return _telemetry.trace(ctx[0] if ctx else None,
                                ctx[1] if ctx else "")

    # ---------------- wire v2 engine ----------------

    def _recv_stream(self, sock: socket.socket) -> tuple:
        """One frame off the stream. v1: the worker pushes — just read
        (the handshake's peeked frame first). v2: top the pipelined fetch
        window up to ``service_pipeline_depth`` outstanding requests,
        then read — the worker answers FIFO, so RTT and per-block turn
        around hide behind the in-flight window."""
        if self._pending is not None:
            frame, self._pending = self._pending, None
            return frame
        if self._wire >= 2:
            self._fill_window(sock)
            frame = recv_frame(sock)
            self._inflight -= 1
            return frame
        return recv_frame(sock)

    def _fill_window(self, sock: socket.socket) -> None:
        """Issue fetch lines until ``service_pipeline_depth`` are in
        flight. With the part's block count known (HELLO on a complete
        part) the window stops one PAST the last block, so the final
        fetch elicits the END that closes the part; with the count
        unknown (mid-parse part, re-targeted stream) the window runs
        optimistically and every past-end fetch is answered by an END."""
        depth = max(1, int(self._pipeline_depth))
        lim = None if self._blocks_total is None else self._blocks_total + 1
        while self._inflight < depth:
            if lim is not None and self._next_fetch >= lim:
                break
            sock.sendall(json.dumps(
                {"block": self._next_fetch, "part": self._part,
                 "job": self.job}).encode() + b"\n")
            self._next_fetch += 1
            self._inflight += 1

    def _hold_stream(self) -> None:
        """Close out a finished part's v2 stream for reuse: drain the
        window's trailing ENDs (FIFO — every in-flight fetch past the
        end got one) and park the healthy socket; ``_ensure_stream``
        adopts it when the next part locates at the same worker. Any
        surprise on the drain just drops the socket — reuse is an
        optimization, never a correctness hinge."""
        sock, owner = self._sock, self._owner
        clean = self._wire >= 2 and sock is not None and owner is not None
        while clean and self._inflight > 0:
            try:
                kind, _meta, _payload = recv_frame(sock)
            except (ConnectionError, OSError, ServiceFrameError):
                clean = False
                break
            self._inflight -= 1
            if kind != KIND_END:
                clean = False
        if clean:
            self._sock = None  # detach so _drop_stream cannot close it
            self._drop_stream()
            self._drop_held()
            self._held = (sock, owner)
        else:
            self._drop_stream()

    def _fastpath_next(self, t0: float) -> Optional[RowBlock]:
        """One block off the co-located mmap (docs/service.md Wire v2
        fast path): the same cache span / resume annotation the worker
        would have framed, with zero wire bytes. Returns None when the
        part is finished (cursor advanced, reader closed — its eviction
        pin drops with it) or when the map failed mid-part (falls back
        to TCP at the exact block cursor)."""
        reader = self._fp_reader
        if self._pos >= reader.num_blocks:
            self._close_fastpath()
            self._part += 1
            self._pos = 0
            self._last_located = None
            self._drain_move_from = None
            self._fp_skip = False
            return None
        i = self._pos
        t1 = get_time()
        try:
            segments = reader.load_segments(i)
            block = RowBlock.from_segments(segments, hold=reader.hold)
            block.encoded = reader.block_encoded(i)
            annot = reader.resume(i)
            nbytes = reader.block_nbytes(i)
        except (DMLCError, OSError, ValueError):
            # torn/evicted/corrupt map mid-part: the wire is the source
            # of truth — resume over TCP at this exact block
            self._close_fastpath()
            self._fp_skip = True
            return None
        if annot is not None:
            block.resume_state = annot
        if self._trace_ctx is not None:
            block.trace_ctx = self._trace_ctx
        dt = get_time() - t0
        self._recv_seconds += dt
        self._wait_metric.inc(dt)
        self._decode_seconds += get_time() - t1
        self._bytes += nbytes
        self._pos += 1
        self._delivered += 1
        self._fastpath_blocks += 1
        self._stream_failures = 0
        self._soft_retry_owner = None
        self._drain_moves = 0
        self._last_annot = annot
        return block

    def resize_pipeline_depth(self, depth: int) -> bool:
        """Autotuner seam (docs/data.md feedback controller): the read
        stage climbs ``service_pipeline_depth`` through this, the same
        duck-typed contract as ``resize_prefetch``. Takes effect at the
        next window fill — an oversized in-flight window simply drains
        down. Returns False when nothing changed."""
        depth = int(depth)
        if depth < 1 or depth == self._pipeline_depth:
            return False
        self._pipeline_depth = depth
        return True

    @property
    def pipeline_depth(self) -> int:
        return self._pipeline_depth

    @property
    def fastpath_blocks(self) -> int:
        """Blocks served off the co-located mmap fast path (the bench's
        ``service_wire_fastpath``) — only the client can count these:
        the worker just sees its stream close."""
        return self._fastpath_blocks

    # ---------------- Parser contract ----------------

    def next_block(self) -> Optional[RowBlock]:
        while self._part < self.num_parts:
            t0 = get_time()
            try:
                if self._fp_reader is None:
                    self._ensure_stream()
                if self._fp_reader is not None:
                    # co-located fast path: the part serves off the mmap;
                    # the handshake socket is released (the worker's
                    # fetch-read returns EOF and the handler exits)
                    self._drop_stream()
                    block = self._fastpath_next(t0)
                    if block is None:
                        continue  # part done / fell back: loop re-aims
                    return block
                sock = self._sock
                with self._trace_scope():
                    kind, meta, payload = self._recv_stream(sock)
            except (ConnectionError, OSError,
                    ServiceFrameError, ServiceUnavailableError) as exc:
                # torn dispatcher replies arrive as ConnectionError —
                # dispatcher.request classifies them centrally, so no
                # call-site ValueError special case survives here
                dt = get_time() - t0
                self._recv_seconds += dt
                self._wait_metric.inc(dt)
                self._on_stream_fault(exc)
                continue
            dt = get_time() - t0
            self._recv_seconds += dt
            self._wait_metric.inc(dt)
            if kind == KIND_BLOCK:
                t1 = get_time()
                with self._trace_scope():
                    block = block_from_frame(meta, payload)
                self._decode_seconds += get_time() - t1
                self._bytes += len(payload)
                self._pos += 1
                self._delivered += 1
                self._stream_failures = 0  # progress resets the budget
                self._soft_retry_owner = None
                self._drain_moves = 0
                self._last_annot = meta.get("resume")
                if self._trace_ctx is not None:
                    # ride the trace to the device dispatch: DeviceIter's
                    # dispatch span picks this up whatever thread it runs
                    # on (docs/observability.md)
                    block.trace_ctx = self._trace_ctx
                return block
            if kind == KIND_SNAPSHOT:
                # device-layout packed batch: decode to a packed
                # DenseBlock (zero-copy views over the payload) —
                # DeviceIter serves it through the dense_ready fast path
                t1 = get_time()
                with self._trace_scope():
                    bkind, *arrays = snapshot_from_frame(meta, payload)
                if bkind != "dense_packed":
                    self._on_stream_fault(DMLCError(
                        f"unsupported snapshot frame kind {bkind!r}"))
                    continue
                xp = arrays[0]
                nc = int(self.snapshot["num_col"])
                block = DenseBlock(xp, xp[:, nc], xp[:, nc + 1],
                                   hold=payload, packed=True)
                # the frame payload IS the device-decodable span (same
                # write_segments bytes as an on-disk snapshot batch, meta
                # offsets payload-relative): keep it + its layout beside
                # the host views so a device_decode=True DeviceIter can
                # ship the raw bytes and decode in HBM (ops/device_decode)
                import numpy as _np

                from dmlc_tpu.io.block_cache import span_layout
                block.device_span = (
                    _np.frombuffer(payload, dtype=_np.uint8),
                    span_layout(meta["arrays"], meta.get("shapes"),
                                base=0),
                    bkind)
                resume = meta.get("resume")
                if resume is not None:
                    block.resume_state = resume
                self._decode_seconds += get_time() - t1
                self._bytes += len(payload)
                self._pos += 1
                self._delivered += 1
                self._stream_failures = 0
                self._soft_retry_owner = None
                self._drain_moves = 0
                self._last_annot = resume
                if self._trace_ctx is not None:
                    block.trace_ctx = self._trace_ctx
                return block
            if kind == KIND_END:
                total = meta.get("blocks")
                if total is not None and int(total) != self._pos:
                    # the shipped count is the delivery cross-check: a
                    # worker ending a part early (truncated parse marked
                    # complete) must read as a fault to fail over, never
                    # as a silently short epoch
                    self._on_stream_fault(DMLCError(
                        f"part {self._part} truncated: END after block "
                        f"{self._pos} of {total}"))
                    continue
                if meta.get("draining"):
                    # the part was served out by a DRAINING worker:
                    # confirm the handoff so the drain can complete
                    # before its deadline instead of waiting it out —
                    # and never park its socket (the worker is leaving)
                    self._confirm_handoff(self._part, self._owner)
                    self._drop_stream()
                else:
                    self._hold_stream()
                self._part += 1
                self._pos = 0
                self._last_located = None
                self._drain_move_from = None
                self._fp_skip = False
                continue
            if kind == KIND_ERROR and meta.get("draining"):
                # GRACEFUL drain notice: the worker is leaving and the
                # dispatcher already re-issued this part. Relocate right
                # away — no report_lost (the worker still serves its
                # complete parts), no retry budget, no backoff. Bounded:
                # repeated drain notices with no progress fall through
                # to the normal fault path so a drain gone wrong still
                # consumes budget instead of spinning.
                self._drain_moves += 1
                if self._drain_moves <= 3:
                    mover = self._owner or self._pending_owner
                    self._drop_stream()
                    self._drain_move_from = mover
                    # keep `have` pointing at the drained-off owner so
                    # the relocate's `moved` hint is meaningful
                    self._last_located = mover
                    continue
            # KIND_ERROR (worker reassigned / parse failure): retryable —
            # the dispatcher may have moved the part; ERROR text rides the
            # chained cause for the give-up message
            self._on_stream_fault(DMLCError(
                f"worker error frame: {meta.get('error')}"
                if kind == KIND_ERROR else f"unknown frame kind {kind}"))
        return None

    def _confirm_handoff(self, part: int, worker: Optional[str]) -> None:
        """Best-effort drain-handoff confirmation (``drain_handoffs``):
        tells the dispatcher this client is done streaming ``part`` from
        the draining ``worker``. A miss only delays the drain until its
        deadline — never a correctness problem."""
        if worker is None:
            return
        _resilience.record_event("drain_handoffs")
        try:
            self._control({"cmd": "handoff", "part": int(part),
                           "worker": worker, "job": self.job})
        except (OSError, DMLCError, ValueError):
            pass  # deadline backstop covers it

    def before_first(self) -> None:
        self._drop_stream()
        self._close_fastpath()
        self._drop_held()
        self._fp_skip = False
        self._part = 0
        self._pos = 0
        self._delivered = 0
        self._stream_failures = 0
        self._failover_from = None
        self._soft_retry_owner = None
        self._last_annot = None
        self._last_located = None
        self._drain_move_from = None
        self._drain_moves = 0
        self._trace_ctx = None

    # ---------------- checkpoint / resume ----------------

    def state_dict(self) -> dict:
        """O(1) resume point: the next (part, block) to deliver —
        restorable into a fresh client against the same service AND the
        same job (positions are only meaningful within one job's
        part-major order, so the job rides the state)."""
        return {"kind": "service", "job": self.job, "part": self._part,
                "block": self._pos, "blocks": self._delivered}

    def _part_query(self, part: int, req: dict) -> dict:
        """One JSON request to the worker serving ``part`` (find/count),
        under the shared retry policy with dispatcher-driven relocation.
        The reply socket gets the stream (not attempt) timeout — the
        worker legitimately blocks until the part is fully parsed, and
        slow-mid-parse is not dead."""
        def attempt():
            owner = self._locate_with_part(part)
            sock = socket.create_connection(
                (owner["host"], int(owner["port"])),
                timeout=self._connect_timeout)
            try:
                sock.settimeout(self._stream_timeout)
                sock.sendall(json.dumps(
                    dict(req, part=part, job=self.job)).encode() + b"\n")
                with sock.makefile("rb") as f:
                    line = f.readline()
            finally:
                sock.close()
            if not line:
                raise ConnectionError(f"part {part}: empty reply")
            try:
                resp = json.loads(line)
            except ValueError as exc:
                # a torn worker reply (died mid-response) is the same
                # transient fault as the connection dropping
                raise ConnectionError(
                    f"part {part}: torn reply {line[:64]!r}") from exc
            if "error" in resp:
                # the located worker cannot answer authoritatively (stale
                # assignment, interrupted parse): heal exactly like the
                # stream path — report it, let the dispatcher re-issue,
                # and retry against the new owner. A wrong count/find
                # would silently restore the wrong position.
                try:
                    self._control({"cmd": "report_lost",
                                   "worker": str(owner["worker"])})
                except (OSError, DMLCError, ValueError):
                    pass
                raise ServiceUnavailableError(
                    f"part {part}: {resp['error']}")
            return resp

        return self._policy.call(attempt, op="worker_rpc",
                                 what=f"part {part}")

    def _locate_with_part(self, part: int) -> dict:
        prev, prev_located = self._part, self._last_located
        self._part, self._last_located = part, None
        try:
            return self._locate_owner()
        finally:
            self._part, self._last_located = prev, prev_located

    def _part_counts_until(self, stop_part: int) -> int:
        """Total blocks in parts [0, stop_part) — the global-delivery
        offset a (part, block) position corresponds to."""
        return sum(int(self._part_query(p, {"cmd": "count"})["blocks"])
                   for p in range(stop_part))

    def load_state(self, state: dict) -> None:
        self._drop_stream()
        self._close_fastpath()
        self._drop_held()
        self._fp_skip = False
        self._stream_failures = 0
        self._failover_from = None
        self._soft_retry_owner = None
        self._last_annot = None
        self._last_located = None
        self._drain_move_from = None
        self._drain_moves = 0
        kind = state.get("kind")
        if self.snapshot and kind != "service":
            # per-part batch counts differ from block counts and packed
            # batches carry no parser-chain annotations — a foreign state
            # must fail loudly, not restore a wrong position
            raise DMLCError(
                "snapshot-mode service clients restore (part, batch) "
                f"'service' states only, got kind {kind!r} "
                "(docs/service.md snapshot frames)")
        if kind == "service":
            # legacy job-less states were written against the default
            # job — defaulting to self.job would let them restore into
            # ANY job-bound client and silently serve the wrong data
            state_job = str(state.get("job", DEFAULT_JOB))
            if state_job != self.job:
                # a (part, block) cursor is a position in ONE job's
                # part-major order — restoring it into another job would
                # silently serve the wrong data
                raise DMLCError(
                    f"service checkpoint belongs to job {state_job!r}, "
                    f"this client is bound to job {self.job!r} "
                    f"(docs/service.md multi-tenant service)")
            self._part = int(state["part"])
            self._pos = int(state["block"])
            self._delivered = int(state.get(
                "blocks", state.get("block", 0)))
            return
        if kind == "blocks" or kind == "block_cache":
            # a delivered-block count maps onto the part-major order via
            # the workers' per-part block counts
            n = int(state.get("blocks", state.get("block", 0)))
            part = 0
            while part < self.num_parts:
                c = int(self._part_query(part, {"cmd": "count"})["blocks"])
                if n < c:
                    break
                n -= c
                part += 1
            self._part, self._pos = part, n
            self._delivered = int(state.get("blocks",
                                            state.get("block", 0)))
            return
        if kind in ("split", "chunks"):
            if not state.get("chunks") and not state.get("blocks"):
                self.before_first()  # epoch-start state
                return
            key = annot_key(state)
            for part in range(self.num_parts):
                idx = int(self._part_query(
                    part, {"cmd": "find", "key": key})["block"])
                if idx >= 0:
                    # annotations mark the position AFTER their block
                    self._part = part
                    self._pos = idx + 1
                    self._delivered = (self._part_counts_until(part)
                                       + idx + 1)
                    return
            raise DMLCError(
                f"service {self.service}: no serving worker holds a block "
                f"matching the checkpoint annotation (stale state?)")
        raise DMLCError(f"ServiceParser: unknown state kind {kind!r}")

    # ---------------- metrics ----------------

    def stage_seconds(self) -> Dict[str, float]:
        """Frame recv waits report as the pipeline's ``read`` stage,
        decode as ``parse`` — so ``DeviceIter.stats()`` attributes a
        service-fed pipeline with the same keys as a local one (the
        service-specific twins are the ``service_recv``/``service_decode``
        spans)."""
        return {"read": self._recv_seconds, "parse": self._decode_seconds}

    @property
    def bytes_read(self) -> int:
        return self._bytes

    def close(self) -> None:
        self._closed.set()
        self._drop_stream()
        self._close_fastpath()
        self._drop_held()
