"""Input-wait-driven fleet autoscaler: capacity follows starvation.

The fleet-granularity reuse of the PR 10 feedback-controller pattern
(AUTOTUNE, arXiv:2101.12127 — measure a starvation signal, move ONE knob
one step, hold through hysteresis): where ``DeviceIter``'s controller
moves pipeline knobs toward ``gap_stage == transfer``, this one moves
the **parse-fleet worker count** toward "no trainer waits on input",
which is the tf.data-service scaling thesis (arXiv:2210.14826 §3.3 —
the input tier scales independently of the trainers).

**Signal.** Each :class:`~dmlc_tpu.service.client.ServiceParser` labels
its consumer-side wire wait with its job on the telemetry registry
(``service_job_input_wait_seconds``); worker/trainer ranks ship that to
the tracker over the PR 6 ``metrics`` heartbeat, and
``RabitTracker.pod_job_metrics()`` sums it fleet-wide per job. The
autoscaler's ``source`` callable returns that aggregate —
``{job: cumulative input_wait_seconds}`` — each control tick; the
per-tick delta divided by the tick interval is the job's **wait
fraction** (~1.0 = the job's trainers are fully input-bound, ~0 = the
fleet keeps up).

**Control law** (one decision per tick, docs/service.md fleet
autoscaling):

- *per-job SLO fairness*: each job's wait fraction is measured against
  its OWN target — ``register_job(slo_wait_frac=)`` when declared,
  ``grow_frac`` otherwise — never a mean, so a starved job cannot be
  drowned by a greedy (or idle) sibling averaging it away. Among jobs
  over target, the **highest-priority** one drives the decision
  (docs/service.md Production QoS); because the dispatcher's grant
  scheduling serves higher bands first, capacity added for that job
  actually reaches it.
- *grow*: some job over its target for ``up_ticks`` CONSECUTIVE
  ticks and the live fleet is under ``DMLC_TPU_FLEET_MAX`` -> one
  worker live-joins (``LocalFleet.add_worker()``, the PR 13 join path),
  counted as ``fleet_scale_ups``.
- *shrink*: EVERY job's wait fraction < ``shrink_frac`` for
  ``down_ticks`` consecutive ticks and the live fleet is over
  ``DMLC_TPU_FLEET_MIN`` -> the most recently added worker drains
  gracefully (notice -> no new grants -> serve out -> exit; departure
  is safe by construction, PR 13), counted as ``fleet_scale_downs``.
- *hysteresis*: the consecutive-tick requirements plus a
  ``cooldown_ticks`` freeze after every scale event — capacity changes
  take a while to show in the wait signal, and reacting to a stale
  window is exactly the flapping the bench gate forbids
  (``fleet_scale_events`` must be 0 on a clean run).

Knobs ride the validated knob table (``DMLC_TPU_FLEET_MIN`` /
``DMLC_TPU_FLEET_MAX`` / ``DMLC_TPU_FLEET_SCALE_INTERVAL``,
:mod:`dmlc_tpu.utils.knobs`). The controller itself is deliberately
transport-agnostic and test-drivable: construct with ``start=False``
and call :meth:`step` directly, or ``start=True`` for the background
tick thread a deployment runs.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, List, Optional

from dmlc_tpu.io import resilience as _resilience
from dmlc_tpu.utils import knobs as _knobs
from dmlc_tpu.utils import telemetry as _telemetry
from dmlc_tpu.utils.check import check
from dmlc_tpu.utils.timer import get_time

logger = logging.getLogger("dmlc_tpu.service")

# decision verdicts (the history records one per tick)
GROW = "grow"
SHRINK = "shrink"
HOLD = "hold"

HISTORY_LIMIT = 128


class FleetAutoscaler:
    """Grow/drain a :class:`~dmlc_tpu.service.fleet.LocalFleet` from the
    aggregated per-job input-wait signal.

    ``source`` returns ``{job: cumulative input_wait_seconds}`` (the
    shape of ``RabitTracker.pod_job_metrics()`` flattened to the wait
    values — a tracker is adapted automatically when passed as
    ``tracker=``). ``min_workers`` / ``max_workers`` / ``interval``
    default to the ``fleet_min`` / ``fleet_max`` /
    ``fleet_scale_interval`` knob rows; explicit arguments win (tests
    drive sub-second intervals).
    """

    def __init__(self, fleet,
                 source: Optional[Callable[[], Dict[str, float]]] = None,
                 tracker=None,
                 qos_source: Optional[Callable[[], Dict[str, dict]]] = None,
                 min_workers: Optional[int] = None,
                 max_workers: Optional[int] = None,
                 interval: Optional[float] = None,
                 grow_frac: float = 0.5,
                 shrink_frac: float = 0.1,
                 up_ticks: int = 2,
                 down_ticks: int = 4,
                 cooldown_ticks: int = 2,
                 start: bool = False):
        check(source is not None or tracker is not None
              or getattr(fleet, "tracker", None) is not None,
              "FleetAutoscaler needs an input-wait source: pass "
              "source= (a {job: wait_seconds} callable) or tracker=, "
              "or build the fleet with tracker=True "
              "(docs/service.md fleet autoscaling)")
        self.fleet = fleet
        if source is None:
            trk = tracker if tracker is not None else fleet.tracker

            def source():
                return {job: rec.get("input_wait_seconds", 0.0)
                        for job, rec in trk.pod_job_metrics().items()}
        self._source = source
        # the jobs' QoS classes ({job: {priority, slo_wait_frac, ...}},
        # docs/service.md Production QoS): defaults to the fleet's
        # dispatcher view so registered SLOs steer the controller with
        # zero wiring; a fleet/stub without one degrades to the
        # pre-QoS max-over-jobs law
        if qos_source is None:
            qos_source = getattr(fleet, "job_qos", None)
        self._qos_source = qos_source
        self.min_workers = _knobs.resolve("fleet_min", min_workers)
        self.max_workers = _knobs.resolve("fleet_max", max_workers)
        check(self.min_workers <= self.max_workers,
              f"fleet autoscaler bounds inverted: min {self.min_workers}"
              f" > max {self.max_workers} (check the DMLC_TPU_FLEET_MIN/"
              f"MAX pair)")
        self.interval = (float(interval) if interval is not None
                         else float(_knobs.resolve("fleet_scale_interval")))
        check(self.interval > 0,
              f"fleet autoscaler interval {self.interval} must be > 0")
        self.grow_frac = float(grow_frac)
        self.shrink_frac = float(shrink_frac)
        self.up_ticks = int(up_ticks)
        self.down_ticks = int(down_ticks)
        self.cooldown_ticks = int(cooldown_ticks)
        self._last: Optional[Dict[str, float]] = None
        self._last_t: Optional[float] = None
        self._starved_streak = 0
        self._idle_streak = 0
        self._cooldown = 0
        self.ticks = 0
        self.scale_ups = 0
        self.scale_downs = 0
        # workers this controller added, newest last — shrink drains
        # these first (LIFO), so operator-provisioned baseline capacity
        # outlives elastic capacity
        self._added: List[object] = []
        self.history: List[dict] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="fleet-autoscaler")
            self._thread.start()

    # ---------------- control loop ----------------

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.step()
            except Exception as exc:  # noqa: BLE001 - the controller
                # must never take the fleet down with it: a failed tick
                # (tracker hiccup, fleet mid-close) logs and the next
                # tick retries
                logger.warning("fleet autoscaler: tick failed: %s", exc)

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    # ---------------- one decision ----------------

    def _live_count(self) -> int:
        return len(self.fleet.live_workers())

    def step(self, now: Optional[float] = None) -> dict:
        """One control tick: read the signal, compute per-job wait
        fractions for the window since the last tick, and make at most
        one scale decision. Returns the decision record (also appended
        to :attr:`history`)."""
        now = get_time() if now is None else float(now)
        waits = {str(j): float(v)
                 for j, v in (self._source() or {}).items()}
        if self._last is None or self._last_t is None:
            # first tick primes the window — no decision can be made
            # from a cumulative counter without a delta
            self._last, self._last_t = waits, now
            return self._record(HOLD, {}, "priming window")
        window = max(now - self._last_t, 1e-9)
        fracs = {}
        for job, total in waits.items():
            delta = max(0.0, total - self._last.get(job, 0.0))
            fracs[job] = min(1.0, delta / window)
        self._last, self._last_t = waits, now
        self.ticks += 1
        # one time-series sample per control tick: the bounded
        # metrics-history ring is what answers "what did input_wait
        # look like when the autoscaler grew" after the fact
        # (docs/observability.md Prometheus exposition)
        _telemetry.sample_metrics_history()
        # SLO-aware per-job fairness (docs/service.md Production QoS):
        # each job is measured against its OWN input-wait target
        # (register_job(slo_wait_frac=), default grow_frac), and among
        # the over-target jobs the HIGHEST-PRIORITY one drives the
        # decision (ties broken by relative overage) — capacity grows
        # for the latency-critical tenant breaching its SLO, never for
        # whichever batch job happens to wait hardest. Without QoS
        # specs every target is grow_frac and this degenerates to the
        # historical max-over-jobs law.
        qos = self._job_qos()

        def target(job: str) -> float:
            slo = (qos.get(job) or {}).get("slo_wait_frac")
            return float(slo) if slo else self.grow_frac

        over = [j for j, f in fracs.items() if f > target(j)]
        if over:
            starved_job = max(
                over,
                key=lambda j: (int((qos.get(j) or {}).get("priority", 0)),
                               fracs[j] / target(j)))
        else:
            starved_job = max(fracs, key=fracs.get) if fracs else None
        starved_frac = fracs.get(starved_job, 0.0) if starved_job \
            else 0.0
        if self._cooldown > 0:
            self._cooldown -= 1
            self._starved_streak = 0
            self._idle_streak = 0
            return self._record(HOLD, fracs,
                                f"cooldown ({self._cooldown} left)")
        if over:
            self._starved_streak += 1
            self._idle_streak = 0
        elif fracs and max(fracs.values()) < self.shrink_frac:
            self._idle_streak += 1
            self._starved_streak = 0
        else:
            self._starved_streak = 0
            self._idle_streak = 0
        live = self._live_count()
        if self._starved_streak >= self.up_ticks:
            if live >= self.max_workers:
                return self._record(
                    HOLD, fracs, f"starved (job {starved_job} at "
                    f"{starved_frac:.2f}) but at fleet_max "
                    f"{self.max_workers}")
            return self._grow(fracs, starved_job, starved_frac,
                              target(starved_job) if starved_job
                              else self.grow_frac, live)
        if self._idle_streak >= self.down_ticks:
            if live <= self.min_workers:
                return self._record(
                    HOLD, fracs, f"idle but at fleet_min "
                    f"{self.min_workers}")
            return self._shrink(fracs, live)
        return self._record(HOLD, fracs, "within hysteresis band")

    def _job_qos(self) -> Dict[str, dict]:
        """The jobs' QoS classes from the configured source; a source
        hiccup (dispatcher mid-restart) degrades to no-QoS for the tick
        — the controller never dies on its input."""
        if self._qos_source is None:
            return {}
        try:
            return {str(j): dict(q or {})
                    for j, q in (self._qos_source() or {}).items()}
        except Exception as exc:  # noqa: BLE001
            logger.warning("fleet autoscaler: qos source failed: %s", exc)
            return {}

    def _grow(self, fracs: dict, job: Optional[str], frac: float,
              tgt: float, live: int) -> dict:
        worker = self.fleet.add_worker()
        self._added.append(worker)
        self.scale_ups += 1
        self._starved_streak = 0
        self._cooldown = self.cooldown_ticks
        _resilience.record_event("fleet_scale_ups")
        logger.warning(
            "fleet autoscaler: job %s input-wait frac %.2f > target "
            "%.2f — grew fleet %d -> %d (worker %s live-joined)", job,
            frac, tgt, live, live + 1, worker.worker_id)
        return self._record(GROW, fracs,
                            f"job {job} wait frac {frac:.2f}",
                            worker=worker.worker_id)

    def _shrink(self, fracs: dict, live: int) -> dict:
        # drain elastic capacity LIFO; fall back to the fleet's newest
        # live worker when the controller added none (operator scaled
        # by hand, controller drains back)
        victim = None
        while self._added and victim is None:
            cand = self._added.pop()
            if cand in self.fleet.live_workers():
                victim = cand
        if victim is None:
            victim = self.fleet.live_workers()[-1]
        victim.drain(reason="fleet autoscaler shrink")
        self.scale_downs += 1
        self._idle_streak = 0
        self._cooldown = self.cooldown_ticks
        _resilience.record_event("fleet_scale_downs")
        logger.warning(
            "fleet autoscaler: all jobs idle — draining worker %s "
            "(%d -> %d)", victim.worker_id, live, live - 1)
        return self._record(SHRINK, fracs, "all jobs under "
                            f"{self.shrink_frac:.2f}",
                            worker=victim.worker_id)

    def _record(self, action: str, fracs: dict, why: str,
                worker: Optional[str] = None) -> dict:
        rec = {"action": action,
               "wait_fracs": {j: round(f, 4) for j, f in fracs.items()},
               "fleet_size": self._live_count(),
               "why": why}
        if worker is not None:
            rec["worker"] = worker
        if action != HOLD:
            # scale events land on the audit ledger (HOLD ticks stay in
            # the local history only — one decision event per actual
            # control action, docs/observability.md Decision ledger)
            _telemetry.record_decision(
                "autoscaler", action,
                trigger={"wait_fracs": rec["wait_fracs"],
                         "fleet_size": rec["fleet_size"]},
                outcome=why, worker=worker)
        self.history.append(rec)
        if len(self.history) > HISTORY_LIMIT:
            del self.history[:len(self.history) - HISTORY_LIMIT]
        return rec

    def snapshot(self, history: int = 16) -> dict:
        """The controller's decision record (operators/bench): bounds,
        tick/scale tallies, and the recent decision history."""
        return {
            "min_workers": self.min_workers,
            "max_workers": self.max_workers,
            "interval": self.interval,
            "ticks": self.ticks,
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "fleet_size": self._live_count(),
            "history": list(self.history[-history:]),
        }
