"""Device-side decode: raw container spans -> batches, without the host.

The r05 bench pinned the ingest ceiling at the host: ~544 MB/s
parse/convert against a ~34 GB/s ``device_put`` floor. Even
snapshot-warm epochs still routed every byte through host numpy views
(``read_segments`` -> per-segment ``np.frombuffer`` -> dtype casts)
before transfer. This module is the third tier: the consumer
``device_put``s the container's raw ``[pos, end)`` byte span **verbatim**
(one contiguous u8 transfer — the PR 14 invariant that one segment
materialization feeds host mmap, wire, and now HBM identically) and the
batch is sliced, bitcast, widened, and dequantized **on device**:

- segment slicing from the footer-described offsets (static slices — the
  layout is a hashable compile-time constant, so XLA fuses the whole
  decode into the transfer epilogue);
- ``lax.bitcast_convert_type`` widening for f32/bf16/i32 segments (a
  pure bitcast of the canonical little-endian segment bytes: byte- and
  value-identical to the host ``np.frombuffer`` views by construction);
- the int8 ``q * scale`` dequant generalized into the same path
  (:func:`dequant_q8`, moved here from ``data/device.py``);
- a Pallas byte-stream kernel (:func:`widen_span_pallas`) for the
  fixed-stride 2-D cases — packed dense rows, padded-ELL slabs,
  snapshot frames, the service's DMLCBC01/DMLCSN01 wire spans: byte
  PLANES are peeled outside the kernel (plain strided slices XLA fuses
  into the transfer), and the kernel reassembles the word with
  shift/or + a same-width bitcast. Cross-width ``pltpu.bitcast`` moves
  the SUBLANE dimension on TPU (it does not match C-order byte
  streams), so the kernel only ever bitcasts at equal width.

Everything here runs under ``interpret=True`` / pure-jit fallbacks so
tier-1 exercises the math on the CPU backend; the hardware route is
gated exactly like ``ops/pallas_sparse.py`` (``_on_tpu_backend`` +
Mosaic tile eligibility).

This module is one of the two sanctioned byte-decode homes (with
``io/block_cache.py``) — ``make lint-metrics`` fails any
``np.frombuffer``/``.astype`` creeping back into the warm snapshot
serve path (``io/snapshot.py`` / ``data/device.py``).
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from dmlc_tpu.io.block_cache import _segment_dtype, span_layout  # noqa: F401
from dmlc_tpu.ops.pallas_sparse import _on_tpu_backend
from dmlc_tpu.utils.check import check

# a span layout: ((name, dtype_str, rel_offset, nbytes, shape), ...) —
# hashable, so decode_span can take it as a static jit argument. Built
# by io.block_cache.span_layout from any container's footer/frame-meta
# ``arrays``/``shapes`` mappings (re-exported here for callers).
Layout = Tuple[Tuple[str, str, int, int, Tuple[int, ...]], ...]


# ---------------------------------------------------------------------------
# host-side quantization (the write half of the q8 path)
# ---------------------------------------------------------------------------


def quantize_int8(arr) -> Tuple[np.ndarray, np.ndarray]:
    """Symmetric per-column int8 quantization of a 2-D float batch:
    returns ``(q8, scale)`` with ``scale`` float32 per column
    (``absmax / 127``; zero columns get scale 1.0 so dequant is exact
    zeros). The device dequantizes with one fused multiply
    (:func:`dequant_q8`) — the opt-in that quarters snapshot bytes for
    value ranges that tolerate 8-bit precision. Lives here (not in
    ``io/snapshot.py``) so quantize and dequant are one audited pair:
    the single sanctioned device-side dtype path."""
    a = np.asarray(arr, dtype=np.float32)
    check(a.ndim == 2, "quantize_int8: expected a 2-D [rows, cols] batch")
    scale = np.abs(a).max(axis=0) / 127.0
    scale[scale == 0.0] = 1.0
    q = np.clip(np.rint(a / scale), -127, 127).astype(np.int8)
    return q, scale.astype(np.float32)


# ---------------------------------------------------------------------------
# device-side dtype primitives (the single sanctioned path)
# ---------------------------------------------------------------------------


@jax.jit
def dequant_q8(q, scale):
    """One fused multiply on device: int8 ``q`` widens to f32 lanes and
    scales per column. The [B, C] int8 transfer is what crosses the
    wire/PCIe (a quarter of the f32 bytes); this runs in HBM."""
    return q.astype(jnp.float32) * scale


@jax.jit
def widen_f32(col):
    """Widen a (typically bf16) device column to f32 — the consolidated
    aux-widening jit ``PackedDenseBatch.y``/``.w`` route through (bf16
    aux columns are exactness-checked at pack time, so the widening is
    value-exact)."""
    return col.astype(jnp.float32)


# ---------------------------------------------------------------------------
# Pallas byte-stream kernel (fixed-stride widening)
# ---------------------------------------------------------------------------


def _widen4_kernel(p0_ref, p1_ref, p2_ref, p3_ref, out_ref):
    """Reassemble 4 little-endian byte planes into f32 lanes: widen each
    u8 plane to u32, shift/or the word together, bitcast at EQUAL width
    (the sublane-safe direction — module docstring)."""
    from jax.experimental.pallas import tpu as pltpu

    bits = (p0_ref[...].astype(jnp.uint32)
            | (p1_ref[...].astype(jnp.uint32) << 8)
            | (p2_ref[...].astype(jnp.uint32) << 16)
            | (p3_ref[...].astype(jnp.uint32) << 24))
    out_ref[...] = pltpu.bitcast(bits, jnp.float32)


def _widen2_kernel(p0_ref, p1_ref, out_ref):
    """bf16 planes -> bf16 lanes, exactly: bf16 is truncated f32, so the
    two stored bytes ARE the high half of an f32 word — assemble
    ``(lo << 16) | (hi << 24)``, bitcast to f32, narrow back. The
    narrowing drops only the zero low half (value-exact round trip)."""
    from jax.experimental.pallas import tpu as pltpu

    bits = ((p0_ref[...].astype(jnp.uint32) << 16)
            | (p1_ref[...].astype(jnp.uint32) << 24))
    out_ref[...] = pltpu.bitcast(bits, jnp.float32).astype(jnp.bfloat16)


def _pick_block_r(rows: int) -> int:
    """Largest hardware-valid sublane tile dividing ``rows``: the u8
    plane blocks need (32, 128) tiles on TPU, so the row tile must be a
    multiple of 32; 0 when none exists (the caller routes to the XLA
    bitcast instead of relying on guards)."""
    for bb in (512, 256, 128, 64, 32):
        if rows % bb == 0:
            return bb
    return 0


def _pick_block_r_interpret(rows: int) -> int:
    """Interpret-mode tile pick: any power-of-2 divisor (Mosaic tile
    constraints do not apply off-hardware), so small-shape parity tests
    stay cheap."""
    bb = 1
    while bb * 2 <= min(rows, 256) and rows % (bb * 2) == 0:
        bb *= 2
    return bb


def pallas_decode_eligible(rows: int, cols: int, dtype_str: str) -> bool:
    """Would the HARDWARE byte-plane kernel accept this slab? 2-D f32 or
    bf16 with a lane-aligned column count (cols % 128 == 0 — the plane
    blocks sit full-axis in the lane dimension) and a 32-multiple row
    tile. Shared with the auto-route so eligibility can never diverge
    from what the kernel enforces."""
    dt = _segment_dtype(dtype_str)
    return (dt.name in ("float32", "bfloat16")
            and cols % 128 == 0 and _pick_block_r(rows) != 0)


@functools.partial(jax.jit,
                   static_argnames=("rows", "cols", "dtype_str", "block_r",
                                    "interpret"))
def widen_span_pallas(seg, rows: int, cols: int, dtype_str: str,
                      *, block_r: int = 0, interpret: bool = False):
    """Fixed-stride byte-stream widening: a ``rows * cols * k`` u8
    segment becomes a ``[rows, cols]`` f32/bf16 slab on device. The k
    byte planes are peeled by XLA outside the kernel (strided slices of
    the reshaped span); the kernel reassembles words with shift/or and
    a same-width bitcast. ``block_r=0`` picks a tile (hardware-valid on
    TPU, any power-of-2 divisor in interpret mode)."""
    from jax.experimental import pallas as pl

    dt = jnp.dtype(_segment_dtype(dtype_str))
    k = dt.itemsize
    check(k in (2, 4),
          f"widen_span_pallas: itemsize {k} not a byte-plane case")
    if block_r == 0:
        block_r = (_pick_block_r_interpret(rows) if interpret
                   else _pick_block_r(rows))
        if block_r == 0:
            raise ValueError(
                f"widen_span_pallas: no Mosaic-valid row tile for "
                f"rows={rows} (need rows % 32 == 0) — use the XLA "
                f"bitcast path (decode_span routes there automatically)")
    assert rows % block_r == 0, (rows, block_r)
    # byte planes peeled OUTSIDE the kernel: plain strided slices XLA
    # materializes as contiguous [rows, cols] u8 operands — the kernel
    # never needs a lane-strided access Mosaic would reject
    planes = seg.reshape(rows, cols, k)
    kernel = _widen4_kernel if k == 4 else _widen2_kernel
    out = pl.pallas_call(
        kernel,
        grid=(rows // block_r,),
        in_specs=[pl.BlockSpec((block_r, cols), lambda i: (i, 0))
                  for _ in range(k)],
        out_specs=pl.BlockSpec((block_r, cols), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, cols), dt),
        interpret=interpret,
    )(*[planes[:, :, j] for j in range(k)])
    return out


# ---------------------------------------------------------------------------
# span decode (the tier entry point)
# ---------------------------------------------------------------------------


def _decode_segment(seg, dtype_str: str, shape: Tuple[int, ...],
                    use_pallas: bool, interpret: bool):
    """One footer-described segment (a static u8 slice of the span) to
    its typed array. Pure bitcasts of canonical little-endian bytes —
    byte-identical to the host ``np.frombuffer`` view by construction."""
    dt = jnp.dtype(_segment_dtype(dtype_str))
    k = dt.itemsize
    if k == 1:
        out = (seg if dt == jnp.uint8
               else jax.lax.bitcast_convert_type(seg, dt))
        return out.reshape(shape)
    if (use_pallas and len(shape) == 2
            and np.dtype(dt).name in ("float32", "bfloat16")
            and (interpret or pallas_decode_eligible(shape[0], shape[1],
                                                     dtype_str))):
        return widen_span_pallas(seg, shape[0], shape[1], dtype_str,
                                 interpret=interpret)
    wide = jax.lax.bitcast_convert_type(seg.reshape(-1, k), dt)
    return wide.reshape(shape)


@functools.partial(jax.jit,
                   static_argnames=("layout", "use_pallas", "interpret"))
def _decode_span_jit(span, layout: Layout, use_pallas: bool = False,
                     interpret: bool = False) -> Dict[str, jax.Array]:
    out: Dict[str, jax.Array] = {}
    for name, dtype_str, off, nbytes, shape in layout:
        seg = jax.lax.slice_in_dim(span, off, off + nbytes)
        out[name] = _decode_segment(seg, dtype_str, shape, use_pallas,
                                    interpret)
    return out


def decode_span(span, layout: Layout,
                use_pallas: Optional[bool] = None,
                interpret: bool = False) -> Dict[str, jax.Array]:
    """Decode a raw container span (a u8 HBM array holding one batch's
    ``[pos, end)`` bytes) into {segment name: typed device array} per
    the static ``layout`` (:func:`io.block_cache.span_layout`).

    ``use_pallas=None`` routes fixed-stride f32/bf16 slabs through the
    byte-plane kernel on a TPU backend and the XLA bitcast everywhere
    else (the same auto-route discipline as ``ell_matvec_auto``);
    ``True``/``False`` force either path, and ``interpret=True`` runs
    the kernel's interpreter so tier-1 exercises the kernel math on
    CPU. Everything is jit-fused: the slices, bitcasts, and dequant all
    land in one compiled program per layout."""
    if use_pallas is None:
        use_pallas = _on_tpu_backend()
    return _decode_span_jit(span, layout, use_pallas=bool(use_pallas),
                            interpret=bool(interpret))
