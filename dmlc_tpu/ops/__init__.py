"""Device-side transforms: sparse layouts and kernels.

The reference has no device math (its Row::SDot, data.h:146-161, runs on the
CPU inside linear learners). On TPU the equivalent hot ops are the
CSR->device-layout transforms and the sparse-dense products they feed; these
live here as XLA-first implementations with a Pallas kernel for the ELL
matvec.
"""

from dmlc_tpu.ops.sparse import (
    EllBatch, block_to_bcoo, block_to_dense, block_to_ell,
    ell_matvec, ell_matmul, segment_csr_matvec,
)
from dmlc_tpu.ops.pallas_sparse import ell_matvec_auto, ell_matvec_pallas

__all__ = [
    "EllBatch", "block_to_bcoo", "block_to_dense", "block_to_ell",
    "ell_matvec", "ell_matmul", "segment_csr_matvec",
    "ell_matvec_auto", "ell_matvec_pallas",
]
