"""Sparse batch layouts for TPU + the products over them.

Three device layouts for a parsed RowBlock (host CSR):

- **padded dense** ``[B, D]`` — right for low-dim dense-ish data (HIGGS,
  Criteo after hashing): one bf16/f32 matmul on the MXU beats any sparse
  gather at D up to a few thousand.
- **ELL** ``indices/values [B, K]`` (rows padded to K nonzeros with a
  sentinel) — right for high-dim sparse data (KDD2012): static shapes, XLA
  turns the gather+reduce into vectorized ops; a Pallas kernel covers the
  matvec when K is large.
- **BCOO** (jax.experimental.sparse) — interop layout for downstream jax
  code that wants a real sparse type.

The reference's only sparse op is Row::SDot (data.h:146-161) feeding linear
learners; ``ell_matvec`` is its batched TPU analog.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dmlc_tpu.data.row_block import RowBlock


class EllBatch(NamedTuple):
    """Row-padded sparse batch: the TPU-friendly static-shape layout.

    indices: int32 [B, K] — feature ids, ``D`` (=num_col) marks padding
    values:  float32 [B, K] — zeros at padding
    label:   float32 [B]
    weight:  float32 [B] — ones when the source had no weights
    """

    indices: jax.Array | np.ndarray
    values: jax.Array | np.ndarray
    label: jax.Array | np.ndarray
    weight: jax.Array | np.ndarray

    @property
    def batch_size(self) -> int:
        return self.indices.shape[0]

    @property
    def max_nnz(self) -> int:
        return self.indices.shape[1]


def _row_lengths(block: RowBlock) -> np.ndarray:
    return np.diff(block.offset)


def block_to_ell(
    block: RowBlock,
    num_col: int,
    max_nnz: Optional[int] = None,
    pad_rows_to: Optional[int] = None,
) -> EllBatch:
    """CSR -> ELL with numpy scatter (host side, zero Python loops).

    Rows longer than ``max_nnz`` are truncated (callers pick K as the
    dataset's true max row length to avoid that); short rows pad with
    index=num_col, value=0. ``pad_rows_to`` pads the batch dimension with
    empty zero-weight rows so every batch has one static shape — XLA then
    compiles the downstream step exactly once.
    """
    n = len(block)
    lens = _row_lengths(block)
    k = int(max_nnz if max_nnz is not None else (lens.max() if n else 1))
    k = max(k, 1)
    rows_out = int(pad_rows_to if pad_rows_to is not None else n)
    indices = np.full((rows_out, k), num_col, dtype=np.int32)
    values = np.zeros((rows_out, k), dtype=np.float32)
    if n:
        nnz = len(block.index)
        rows_all = np.repeat(np.arange(n), lens)              # row of each entry
        pos = np.arange(nnz) - np.repeat(block.offset[:-1], lens)  # slot within row
        mask = pos < k                                        # truncate long rows
        vals = block.value if block.value is not None else np.ones(nnz, np.float32)
        indices[rows_all[mask], pos[mask]] = block.index[mask].astype(np.int32)
        values[rows_all[mask], pos[mask]] = vals[mask]
    label = np.zeros(rows_out, np.float32)
    label[:n] = block.label
    weight = np.zeros(rows_out, np.float32)
    weight[:n] = block.weight if block.weight is not None else 1.0
    return EllBatch(indices, values, label, weight)


def block_to_dense(
    block: RowBlock, num_col: int, pad_rows_to: Optional[int] = None,
    copy: bool = True,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """CSR -> padded dense [B, D] (+ label, weight), batch-padded like ELL.

    With ``copy=False`` and dense-in-sparse data whose width equals
    ``num_col`` exactly, ``x`` is returned as a zero-copy reshape view of the
    parser's value array — callers must not mutate it.
    """
    n = len(block)
    rows_out = int(pad_rows_to if pad_rows_to is not None else n)
    x = None
    if n:
        lens = _row_lengths(block)
        vals = block.value if block.value is not None else np.ones(len(block.index), np.float32)
        k = int(lens[0]) if n else 0
        # fast path for dense-in-sparse data (HIGGS/CSV-shaped): every row has
        # the same k features 0..k-1, so the values are already a dense matrix
        if (
            0 < k <= num_col
            and len(block.index) == n * k
            and bool((lens == k).all())
            and bool((block.index.reshape(n, k) == np.arange(k, dtype=block.index.dtype)).all())
        ):
            if (not copy and k == num_col and rows_out == n
                    and vals.dtype == np.float32):
                x = vals.reshape(n, k)
            else:
                x = np.zeros((rows_out, num_col), dtype=np.float32)
                x[:n, :k] = vals.reshape(n, k)
        else:
            x = np.zeros((rows_out, num_col), dtype=np.float32)
            rows = np.repeat(np.arange(n), lens)
            keep = block.index < num_col
            x[rows[keep], block.index[keep].astype(np.int64)] = vals[keep]
    if x is None:
        x = np.zeros((rows_out, num_col), dtype=np.float32)
    label = np.zeros(rows_out, np.float32)
    label[:n] = block.label
    weight = np.zeros(rows_out, np.float32)
    weight[:n] = block.weight if block.weight is not None else 1.0
    return x, label, weight


def block_to_bcoo_host(
    block: RowBlock, num_col: int, pad_rows_to: Optional[int] = None,
    unit_values_as_none: bool = False, pad_nnz_to: Optional[int] = None,
) -> Tuple[np.ndarray, Optional[np.ndarray], np.ndarray, np.ndarray, Tuple[int, int]]:
    """CSR -> host-side COO arrays ``(coords, vals, label, weight, shape)``.

    This is the numpy half of :func:`block_to_bcoo`, split out so a prefetch
    pipeline can run it on a convert thread and keep only the (async)
    device transfer on the consumer thread. Coordinates are int32 whenever
    the shape fits (any realistic corpus: num_col < 2^31): for KDD-shaped
    data the coordinate array dominates transfer bytes, so halving its width
    roughly halves host->HBM traffic for the whole batch. ``pad_rows_to``
    pads the batch dimension (zero-weight empty rows) so every batch shares
    one static shape.

    ``pad_nnz_to`` pads the nnz dimension with OUT-OF-BOUNDS coordinates
    ``(rows_out, num_col)`` — BCOO's canonical padding, masked by every
    sparse op (todense/matvec/matmul drop OOB entries), so the pad values
    are free to be anything and ``unit_values_as_none`` elision composes
    with padding. Quantizing nnz to a bucket multiple keeps the set of
    distinct array shapes small and REPEATING — a fresh shape per batch
    forces a new transfer plan in the runtime and a recompile in any
    downstream jit; on a tunneled device a novel-shape ``device_put``
    measured ~100x the cost of a repeated-shape one.
    """
    n = len(block)
    nnz = len(block.index)
    rows_out = int(pad_rows_to if pad_rows_to is not None else n)
    nnz_out = int(pad_nnz_to) if pad_nnz_to is not None and pad_nnz_to > nnz else nnz
    idx_dtype = np.int32 if max(rows_out + 1, num_col + 1) < (1 << 31) else np.int64
    lens = _row_lengths(block)
    coords = np.empty((nnz_out, 2), idx_dtype)
    coords[:nnz, 0] = np.repeat(np.arange(n, dtype=idx_dtype), lens)
    coords[:nnz, 1] = block.index
    coords[nnz:, 0] = rows_out   # OOB pad: masked by all BCOO ops
    coords[nnz:, 1] = num_col
    vals: Optional[np.ndarray]
    if block.value is None:
        vals = None if unit_values_as_none else np.ones(nnz_out, np.float32)
    else:
        vals = block.value
        if vals.dtype != np.float32:
            vals = vals.astype(np.float32)
        if unit_values_as_none and nnz and bool((vals == 1.0).all()):
            # binary-feature corpora (CTR one-hot rows, libfm ":1" tokens):
            # the consumer synthesizes ones on device, saving 4 B/nnz of
            # host->HBM traffic — the value array is 1/3 of a COO batch
            vals = None
    if vals is not None and nnz_out > len(vals):
        out = np.zeros(nnz_out, np.float32)
        out[:len(vals)] = vals
        vals = out
    label = np.zeros(rows_out, np.float32)
    label[:n] = block.label
    weight = np.zeros(rows_out, np.float32)
    weight[:n] = block.weight if block.weight is not None else 1.0
    return coords, vals, label, weight, (rows_out, num_col)


def block_to_bcoo(block: RowBlock, num_col: int):
    """CSR -> jax.experimental.sparse.BCOO (interop layout)."""
    from jax.experimental import sparse as jsparse

    coords, vals, _, _, shape = block_to_bcoo_host(block, num_col)
    return jsparse.BCOO((jnp.asarray(vals), jnp.asarray(coords)), shape=shape)


# ---------------- products ----------------

def ell_matvec(weights: jax.Array, batch: EllBatch) -> jax.Array:
    """Batched sparse dot: out[b] = sum_k w[idx[b,k]] * val[b,k].

    The TPU analog of Row::SDot (data.h:146-161). ``weights`` is [D+1]; the
    final slot is the padding sink (index=num_col) and must be 0 — callers
    keep a D+1 parameter vector and simply never touch the last slot.
    A 2D table [D+1, C] (multinomial per-class weights) broadcasts the
    values over the class dim and returns [B, C].
    """
    gathered = jnp.take(weights, batch.indices, axis=0)  # [B, K] or [B, K, C]
    vals = batch.values if weights.ndim == 1 else batch.values[..., None]
    return jnp.sum(gathered * vals, axis=1)


def ell_matmul(weights: jax.Array, batch: EllBatch) -> jax.Array:
    """ELL x dense matrix: [B,K] sparse rows times [D+1, H] -> [B, H]."""
    gathered = jnp.take(weights, batch.indices, axis=0)  # [B, K, H]
    return jnp.einsum("bkh,bk->bh", gathered, batch.values)


def segment_csr_matvec(
    weights: jax.Array,
    index: jax.Array,
    value: jax.Array,
    row_ids: jax.Array,
    num_rows: int,
) -> jax.Array:
    """COO-style matvec via segment_sum, for when nnz varies too much for ELL."""
    prod = jnp.take(weights, index, axis=0) * value
    return jax.ops.segment_sum(prod, row_ids, num_segments=num_rows)
