"""Pallas TPU kernel for the ELL sparse matvec.

XLA lowers ``jnp.take`` (ops/sparse.ell_matvec) to an HBM-bound dynamic
gather per batch element. This kernel instead keeps the weight vector
resident in VMEM across the whole batch grid and turns the gather into
one-hot contractions — compare + multiply + reduce, all VPU/MXU-friendly
primitives with static shapes, no HBM gather traffic.

out[b] = sum_k w[idx[b, k]] * val[b, k]

Lowering history (each form rejected by Mosaic with the error quoted):
- r2: statically unrolled K loop over ``[bb, K]`` blocks — IR O(K*D),
  blew up compile for K >= 64 at D = 4096 (SPARSE_TPU_r02).
- r3 draft 1: rolled ``fori_loop`` with ``idx_ref[:, pl.ds(k, 1)]`` —
  dynamic lane-dimension slices fail the alignment proof ("cannot
  statically prove that index in dimension 1 is a multiple of 128").
- r3 draft 2: K as a grid dimension with ``(bb, 1)`` blocks — lane-dim
  block size must be a multiple of 128 or the full axis.

Final form: inputs are fed K-MAJOR (``[K8, B]``, K padded to a multiple
of 8 with zero-valued slots) so the K loop lives in the GRID with
``(8, bb)`` blocks — both block dims satisfy the (8, 128) tiling rule,
every index is static, and the kernel body unrolls exactly 8 compare+
accumulate steps regardless of K. A VMEM scratch holds the one-hot slab
``[D, bb]`` across the sequential k steps (TPU grids iterate the last
dimension innermost); the final k step contracts ``w[1, D] @ slab`` on
the MXU.

Why there is NO pallas kernel for high D (the KDD/1M regime), by
construction rather than by un-tuned accident:
- the one-hot algorithm is O(B*K*D) compare-multiply work — at D = 2^20
  it is arithmetically disqualified regardless of lowering;
- an in-kernel VMEM table gather (O(B*K) work) is not expressible:
  Mosaic's dynamic-gather primitive requires input/indices/output of THE
  SAME 2D shape (per-lane shuffles), i.e. it cannot index a [D] table
  with [B, K] indices ("Only 2D gather is supported" / "Shape mismatch
  in input, indices and output");
- a scalar-core loop over B*K VMEM loads costs ~B*K cycles (~140 us at
  8192x16), ~6x worse than XLA's measured 24 us gather at kdd_like.
So beyond the VMEM slab budget the right lowering IS XLA's native
gather, and :func:`ell_matvec_auto` routes there; the measured A/B lives
in SPARSE_TPU_r03.json.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from dmlc_tpu.ops.sparse import EllBatch, ell_matvec as _xla_ell_matvec

_KTILE = 8  # sublane tile: K is padded to a multiple of this


def _ell_kernel(idx_ref, val_ref, w_ref, out_ref, slab_ref):
    import jax.experimental.pallas as pl

    k = pl.program_id(1)
    num_k = pl.num_programs(1)
    num_d = w_ref.shape[1]
    iota = jax.lax.broadcasted_iota(jnp.int32, (num_d, 1), 0)

    @pl.when(k == 0)
    def _init():
        slab_ref[...] = jnp.zeros_like(slab_ref)

    # 8 static compare+accumulate steps per grid step: padded slots carry
    # value 0, so they add nothing regardless of their index
    slab = slab_ref[...]
    for j in range(_KTILE):
        idx_j = idx_ref[j:j + 1, :]                       # [1, bb], static
        val_j = val_ref[j:j + 1, :]
        slab += val_j * (idx_j == iota).astype(jnp.float32)  # [D, bb]
    slab_ref[...] = slab

    @pl.when(k == num_k - 1)
    def _contract():
        # full-f32 dot: the MXU's default bf16 operands lose ~1e-2 here
        out_ref[...] = jnp.dot(w_ref[...], slab_ref[...],
                               precision=jax.lax.Precision.HIGHEST)  # [1, bb]


def _valid_block_b(num_b: int, num_d: int, bb: int,
                   slab_budget: int = 4 << 20) -> bool:
    """Would the hardware kernel accept this lane tile? The single source
    of truth for the tile constraints — Mosaic lane alignment (bb in
    {128, 256}), B divisibility, and the [D, bb] float32 slab within the
    VMEM budget — shared with the bench grid sweep so its tile list can
    never diverge from what the kernel enforces."""
    return (bb in (256, 128) and num_b % bb == 0
            and bb * max(num_d, 1) * 4 <= slab_budget)


def _pick_block_b(num_b: int, num_d: int, slab_budget: int = 4 << 20) -> int:
    """Largest lane-aligned tile (128 or 256) dividing B whose [D, bb] slab
    fits the VMEM budget; 0 when none exists.

    bb sits in the LANE dimension of the kernel's (8, bb)/(1, bb) blocks,
    and Mosaic requires lane tiles to be multiples of 128 — a smaller bb
    lowers in interpret mode but fails on hardware, so rather than rely on
    caller guards this returns 0 and the entry point refuses loudly."""
    for bb in (256, 128):
        if _valid_block_b(num_b, num_d, bb, slab_budget):
            return bb
    return 0


def _pick_block_b_interpret(num_b: int, num_d: int,
                            slab_budget: int = 4 << 20) -> int:
    """Interpret-mode tile pick: any power-of-2 (Mosaic constraints do not
    apply off-hardware), so small-shape correctness tests stay cheap."""
    limit = max(8, slab_budget // max(num_d * 4, 1))
    bb = 1
    while bb * 2 <= min(num_b, 256, limit) and num_b % (bb * 2) == 0:
        bb *= 2
    return bb


@functools.partial(jax.jit, static_argnames=("block_b", "interpret"))
def ell_matvec_pallas(
    weights: jax.Array,
    indices: jax.Array,
    values: jax.Array,
    *,
    block_b: int = 0,
    interpret: bool = False,
) -> jax.Array:
    """Pallas ELL matvec (one-hot slab). block_b=0 picks a VMEM-sized tile."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if weights.ndim != 1:
        raise ValueError(
            f"ell_matvec_pallas: weights must be a [D] table, got shape "
            f"{weights.shape} — multinomial [D, C] tables route through the "
            f"XLA gather (ell_matvec)")
    num_b, num_k = indices.shape
    num_d = weights.shape[0]
    if block_b == 0:
        block_b = (_pick_block_b_interpret(num_b, num_d) if interpret
                   else _pick_block_b(num_b, num_d))
        if block_b == 0:
            raise ValueError(
                f"ell_matvec_pallas: no Mosaic-lane-aligned tile for "
                f"B={num_b}, D={num_d} (need B % 128 == 0 and a [D, 128] "
                f"slab within VMEM) — use ell_matvec_auto / the XLA gather")
    assert num_b % block_b == 0, (num_b, block_b)
    k8 = -(-num_k // _KTILE) * _KTILE
    # K-major layout, K padded to the sublane tile with zero-valued slots
    idx_t = jnp.zeros((k8, num_b), jnp.int32).at[:num_k].set(
        indices.astype(jnp.int32).T)
    val_t = jnp.zeros((k8, num_b), jnp.float32).at[:num_k].set(values.T)
    grid = (num_b // block_b, k8 // _KTILE)
    out = pl.pallas_call(
        _ell_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((_KTILE, block_b), lambda i, k: (k, i)),
            pl.BlockSpec((_KTILE, block_b), lambda i, k: (k, i)),
            pl.BlockSpec((1, num_d), lambda i, k: (0, 0)),  # resident w
        ],
        out_specs=pl.BlockSpec((1, block_b), lambda i, k: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, num_b), jnp.float32),
        scratch_shapes=[pltpu.VMEM((num_d, block_b), jnp.float32)],
        interpret=interpret,
    )(idx_t, val_t, weights[None, :])
    return out[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _ell_matvec_pallas_ad(weights, indices, values, interpret=False):
    """Differentiable wrapper: pallas forward, XLA backward.

    ``pallas_call`` has a JVP rule but NO transpose rule in current JAX, so
    reverse-mode AD through the raw kernel fails at trace time. The VJP of
    ``out[b] = sum_k w[idx[b,k]] * val[b,k]`` is closed-form: a scatter-add
    for dw and a gather for dval — both standard XLA lowerings, so training
    steps (value_and_grad) can route through the kernel's fast forward.
    """
    return ell_matvec_pallas(weights, indices, values, interpret=interpret)


def _ell_ad_fwd(weights, indices, values, interpret=False):
    return (_ell_matvec_pallas_ad(weights, indices, values, interpret),
            (weights, indices, values))


def _ell_ad_bwd(interpret, res, g):
    weights, indices, values = res
    dw = jnp.zeros_like(weights).at[indices].add(values * g[:, None])
    dval = jnp.take(weights, indices, axis=0) * g[:, None]
    return dw, None, dval


_ell_matvec_pallas_ad.defvjp(_ell_ad_fwd, _ell_ad_bwd)


# the measured pallas win band, inclusive (SPARSE_TPU_r05.json): see
# pallas_band() and the ell_matvec_auto docstring for the evidence
_BAND_D_LO = 512
_BAND_D_HI = 4096


def _on_tpu_backend() -> bool:
    """The auto-route's hardware gate (separate so tests can monkeypatch
    it and exercise the routing wire off-chip in interpret mode)."""
    return jax.default_backend() == "tpu"


def pallas_band(num_b: int, num_d: int, weights_ndim: int = 1) -> bool:
    """True iff (B, D) sits in the pallas kernel's measured win band.

    The band (SPARSE_TPU_r05.json, TPU v5 lite): lane-aligned
    D in [512, 4096] — D a multiple of 128 so the [1, D] weight block and
    the [D, bb] slab tile cleanly — with B lane-aligned and the slab
    within the VMEM budget (``_pick_block_b`` != 0), and a 1-D weight
    table (the kernel is a [D]-table matvec only; multinomial [D, C]
    tables stay on the XLA gather). Everything outside routes to the
    gather: D=28 dense-in-sparse loses (23.7 vs 16.2 us) and high D is
    disqualified by construction (module docstring).
    """
    return (weights_ndim == 1
            and _BAND_D_LO <= num_d <= _BAND_D_HI
            and num_d % 128 == 0
            and _pick_block_b(num_b, num_d) != 0)


def ell_matvec_auto(weights: jax.Array, batch: EllBatch,
                    use_pallas: Optional[bool] = None) -> jax.Array:
    """ELL matvec: routes to the pallas kernel in its measured win band
    on TPU, the XLA gather everywhere else.

    Routing data (r5 on-chip A/B, SPARSE_TPU_r05.json, TPU v5 lite): the
    grid-K kernel WINS at D=512/K=32 (16.1 vs 17.5 us), D=2048/K=64
    (16.1 vs 33.2 us — 2.06x) and D=4096/K=64 (22.3 vs 24.9 us); it
    loses at D=28/K=28 (23.7 vs 16.2 us — dense-in-sparse belongs on the
    gather or a dense matmul) and for high D the XLA gather is the right
    lowering by construction — see the module docstring (confirmed at
    D=1M: 25.9 us). The default (``use_pallas=None``) therefore routes
    to the kernel exactly for lane-aligned D in [512, 4096]
    (:func:`pallas_band`) on a TPU backend. Known in-band anomaly: the
    r5 sweep recorded one loss at D=1024/K=48 (52.1 vs 17.5 us, same
    block_b=256 as the winning shapes); the D x K x lane-tile grid leg
    (bench_sparse_tpu.py with DMLC_SPARSE_GRID=1, in the TPU battery)
    exists to attribute it to shape or tile — if it reproduces as a
    D-effect the band narrows, if it was tile choice the auto-pick
    already avoids it. ``use_pallas=True``/``False`` force either path
    (a forced True off-band still enforces the kernel's shape
    requirements and raises loudly).
    """
    if use_pallas is None:
        use_pallas = (
            pallas_band(batch.indices.shape[0], weights.shape[0],
                        weights.ndim)
            and _on_tpu_backend())
    if not use_pallas:
        return _xla_ell_matvec(weights, batch)
    return _ell_matvec_pallas_ad(
        weights, jnp.asarray(batch.indices), jnp.asarray(batch.values))
