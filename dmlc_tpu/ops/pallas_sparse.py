"""Pallas TPU kernel for the ELL sparse matvec.

XLA lowers ``jnp.take`` (ops/sparse.ell_matvec) to an HBM-bound dynamic
gather per batch element. This kernel instead keeps the weight vector
resident in VMEM across the whole batch grid and turns the gather into
one-hot contractions over D-tiles — compare + multiply + reduce, all
VPU/MXU-friendly primitives with static shapes, no HBM gather traffic.

out[b] = sum_k w[idx[b, k]] * val[b, k]

Grid: batch tiles of ``block_b`` rows. Per step, for each D-tile of
``block_d`` weights: scatter the tile's values into a dense [block_b,
block_d] slab via a one-hot compare against the tile's index range, then
dot with the weight tile. The padding sink (idx == len(w) - 1 slots with
value 0) falls out naturally because the values are 0.

Use :func:`ell_matvec_auto` to pick pallas when supported (TPU, shapes
tile-able) and fall back to the XLA gather otherwise.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from dmlc_tpu.ops.sparse import EllBatch, ell_matvec as _xla_ell_matvec


def _ell_kernel(idx_ref, val_ref, w_ref, out_ref):
    import jax.experimental.pallas as pl

    num_b = idx_ref.shape[0]
    num_k = idx_ref.shape[1]
    num_d = w_ref.shape[0]
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, num_d), 1)

    # accumulate the dense scatter slab one nonzero-slot at a time:
    # slab[b, d] = sum_k val[b, k] * (idx[b, k] == d). Peak VMEM is one
    # [bb, D] slab (the tile size is chosen to keep it ~4MB), not the
    # [bb, K, D] one-hot a fully vectorized form would materialize.
    # K runs through a fori_loop with pl.ds ref reads — r2's statically
    # unrolled K loop blew up the Mosaic lowering for K >= 64 at D = 4096
    # (SPARSE_TPU_r02 boundary_probe compile errors); rolled IR is O(1)
    # in K instead of O(K).
    def body(k, slab):
        idx_k = idx_ref[:, pl.ds(k, 1)]                       # [bb, 1]
        val_k = val_ref[:, pl.ds(k, 1)]
        return slab + val_k * (idx_k == iota).astype(jnp.float32)

    slab = jax.lax.fori_loop(
        0, num_k, body, jnp.zeros((num_b, num_d), jnp.float32))
    # full-f32 dot: the MXU's default bf16 operands lose ~1e-2 here
    out_ref[...] = jnp.dot(slab, w_ref[...][:, None],
                           precision=jax.lax.Precision.HIGHEST)  # [bb, 1]


def _ell_gather_kernel(idx_ref, val_ref, w_ref, out_ref):
    # high-D variant: the weight vector stays RESIDENT in VMEM across the
    # whole batch grid (constant index_map), and the per-element lookup is
    # a VMEM gather — no one-hot scatter work (O(B*K) instead of O(B*K*D))
    # and no HBM random reads, which is what bounds XLA's gather lowering.
    idx = idx_ref[...]                     # [bb, K] int32
    val = val_ref[...]                     # [bb, K] f32
    gathered = jnp.take(w_ref[...], idx, axis=0)  # [bb, K]
    out_ref[...] = jnp.sum(gathered * val, axis=1, keepdims=True)


def _pick_block_b(num_b: int, num_d: int, slab_budget: int = 4 << 20) -> int:
    """Largest power-of-2 tile (<=256) dividing B whose slab fits the budget."""
    limit = max(8, slab_budget // max(num_d * 4, 1))
    bb = 1
    while bb * 2 <= min(num_b, 256, limit) and num_b % (bb * 2) == 0:
        bb *= 2
    return bb


@functools.partial(jax.jit,
                   static_argnames=("block_b", "interpret", "kernel"))
def ell_matvec_pallas(
    weights: jax.Array,
    indices: jax.Array,
    values: jax.Array,
    *,
    block_b: int = 0,
    interpret: bool = False,
    kernel: str = "onehot",
) -> jax.Array:
    """Pallas ELL matvec. block_b=0 picks a VMEM-sized tile automatically.

    kernel='onehot': scatter slab + MXU dot — wins in the mid-D band where
    the slab fits VMEM comfortably. kernel='gather': VMEM-resident weights
    + in-kernel gather — the high-D (KDD-shaped) candidate, O(B*K) work.
    """
    from jax.experimental import pallas as pl

    num_b, _k = indices.shape
    num_d = weights.shape[0]
    if block_b == 0:
        if kernel == "onehot":
            block_b = _pick_block_b(num_b, num_d)
        else:
            # largest power-of-2 tile (<=256) DIVIDING B — no slab budget
            # applies, but the grid still needs exact tiling
            block_b = 1
            while block_b * 2 <= min(num_b, 256) and num_b % (block_b * 2) == 0:
                block_b *= 2
    assert num_b % block_b == 0, (num_b, block_b)
    grid = (num_b // block_b,)
    out = pl.pallas_call(
        _ell_kernel if kernel == "onehot" else _ell_gather_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_b, indices.shape[1]), lambda i: (i, 0)),
            pl.BlockSpec((block_b, values.shape[1]), lambda i: (i, 0)),
            pl.BlockSpec((num_d,), lambda i: (0,)),  # whole w every step
        ],
        out_specs=pl.BlockSpec((block_b, 1), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((num_b, 1), jnp.float32),
        interpret=interpret,
    )(indices.astype(jnp.int32), values, weights)
    return out[:, 0]


def ell_matvec_auto(weights: jax.Array, batch: EllBatch,
                    use_pallas: bool | None = None) -> jax.Array:
    """ELL matvec via pallas on TPU when shapes allow, XLA gather otherwise.

    The one-hot kernel does O(B*K*D) compare-multiply work, so it only pays
    where D is small enough that the HBM gather's latency dominates;
    measured on a v5e chip it beats the XLA gather by 10-33% for D <= 2048
    (SPARSE_TPU_r02.json, e.g. 17.6us vs 23.4us at HIGGS D=28/K=28). r3
    replaced r02's statically-unrolled K loop (which failed to compile for
    K >= 64 at D = 4096) with a rolled fori_loop and added a second
    'gather' kernel (VMEM-resident weights, O(B*K) work) as the high-D
    candidate — the routing gate below still reflects the r02
    measurements and is re-evaluated against SPARSE_TPU_r03 once both
    kernels are timed on hardware.
    """
    num_b = batch.indices.shape[0]
    if use_pallas is None:
        on_tpu = jax.devices()[0].platform == "tpu"
        use_pallas = on_tpu and num_b % 256 == 0 and weights.shape[0] <= 2048
    if not use_pallas:
        return _xla_ell_matvec(weights, batch)
    return ell_matvec_pallas(
        weights, jnp.asarray(batch.indices), jnp.asarray(batch.values))
