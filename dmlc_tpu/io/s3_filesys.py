"""S3 filesystem: AWS Signature V4 client over urllib.

Parity with reference src/io/s3_filesys.cc (1309 LoC curl+openssl client):
- SigV4 request signing (SignSig4, s3_filesys.cc:319) — implemented from the
  published algorithm: canonical request -> string-to-sign -> HMAC chain;
- range-GET read streams with restart-on-seek (CURLReadStreamBase::Read /
  InitRequest ``Range: bytes=N-``, s3_filesys.cc:422-701), built on the
  shared HTTP block reader;
- ListObjectsV2 XML listing (XMLIter, s3_filesys.cc:27);
- multipart-upload write streams (Init/Upload/Finish,
  s3_filesys.cc:768-1010) with per-part retry (:789);
- env config: ``S3_ACCESS_KEY_ID``/``AWS_ACCESS_KEY_ID``,
  ``S3_SECRET_ACCESS_KEY``/``AWS_SECRET_ACCESS_KEY``, ``S3_SESSION_TOKEN``/
  ``AWS_SESSION_TOKEN``, ``S3_ENDPOINT``, ``S3_REGION``, ``S3_VERIFY_SSL``,
  ``DMLC_S3_WRITE_BUFFER_MB`` (s3_filesys.cc:781, 1151-1166).

The endpoint override (``S3_ENDPOINT``) doubles as the test seam: the suite
points it at an in-process fake S3 server, so signing, listing, reading and
multipart writes are exercised without network egress.
"""

from __future__ import annotations

import datetime as _dt
import hashlib
import hmac
import io as _pyio
import os
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
from typing import Dict, List, Optional, Tuple

from dmlc_tpu.io.filesystem import (
    DIR_TYPE, FILE_TYPE, FileInfo, FileSystem, register_filesystem,
)
from dmlc_tpu.io.http_filesys import HttpReadStream
from dmlc_tpu.io.resilience import RetryPolicy, default_policy
from dmlc_tpu.io.uri import URI
from dmlc_tpu.utils.check import DMLCError, check

_EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


# ---------------- SigV4 core (pure functions, golden-tested) ----------------

def _uri_encode(s: str, encode_slash: bool = True) -> str:
    """AWS canonical URI encoding: RFC3986 unreserved chars stay, space is
    %20 (never '+'), '/' optionally preserved."""
    safe = "-._~" + ("" if encode_slash else "/")
    return urllib.parse.quote(s, safe=safe)


def canonical_request(
    method: str,
    path: str,
    query: Dict[str, str],
    headers: Dict[str, str],
    payload_sha256: str,
) -> Tuple[str, str]:
    """Build the canonical request; returns (canonical_request, signed_headers).

    Mirrors the documented algorithm the reference implements in
    SignSig4 (s3_filesys.cc:319).
    """
    cq = "&".join(
        f"{_uri_encode(k)}={_uri_encode(str(v))}"
        for k, v in sorted(query.items())
    )
    lower = {k.lower().strip(): " ".join(str(v).split())
             for k, v in headers.items()}
    signed_headers = ";".join(sorted(lower))
    ch = "".join(f"{k}:{lower[k]}\n" for k in sorted(lower))
    cr = "\n".join([
        method.upper(),
        _uri_encode(path, encode_slash=False) or "/",
        cq,
        ch,
        signed_headers,
        payload_sha256,
    ])
    return cr, signed_headers


def signing_key(secret: str, date: str, region: str, service: str) -> bytes:
    """HMAC chain: kSecret -> kDate -> kRegion -> kService -> kSigning."""
    def h(key: bytes, msg: str) -> bytes:
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    return h(h(h(h(b"AWS4" + secret.encode(), date), region), service),
             "aws4_request")


def sign_v4(
    method: str,
    host: str,
    path: str,
    query: Dict[str, str],
    headers: Dict[str, str],
    payload_sha256: str,
    access_key: str,
    secret_key: str,
    region: str,
    service: str = "s3",
    amz_date: Optional[str] = None,
    session_token: Optional[str] = None,
) -> Dict[str, str]:
    """Return the headers (Authorization + x-amz-*) for a SigV4 request."""
    if amz_date is None:
        amz_date = _dt.datetime.now(_dt.timezone.utc).strftime("%Y%m%dT%H%M%SZ")
    date = amz_date[:8]
    hdrs = dict(headers)
    hdrs["host"] = host
    hdrs["x-amz-date"] = amz_date
    hdrs["x-amz-content-sha256"] = payload_sha256
    if session_token:
        hdrs["x-amz-security-token"] = session_token
    cr, signed_headers = canonical_request(
        method, path, query, hdrs, payload_sha256)
    scope = f"{date}/{region}/{service}/aws4_request"
    sts = "\n".join([
        "AWS4-HMAC-SHA256",
        amz_date,
        scope,
        hashlib.sha256(cr.encode()).hexdigest(),
    ])
    sig = hmac.new(
        signing_key(secret_key, date, region, service),
        sts.encode(), hashlib.sha256).hexdigest()
    hdrs["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={signed_headers}, Signature={sig}"
    )
    del hdrs["host"]  # urllib sets Host itself; it was only needed for signing
    return hdrs


# ---------------- credentials / endpoint config ----------------

class S3Config:
    """Env-sourced credentials and endpoint (s3_filesys.cc:1151-1166)."""

    def __init__(self) -> None:
        env = os.environ
        self.access_key = env.get("S3_ACCESS_KEY_ID") or env.get("AWS_ACCESS_KEY_ID")
        self.secret_key = (env.get("S3_SECRET_ACCESS_KEY")
                           or env.get("AWS_SECRET_ACCESS_KEY"))
        self.session_token = (env.get("S3_SESSION_TOKEN")
                              or env.get("AWS_SESSION_TOKEN"))
        self.region = env.get("S3_REGION") or env.get("AWS_REGION") or "us-east-1"
        self.endpoint = env.get("S3_ENDPOINT")  # e.g. http://127.0.0.1:9999
        self.verify_ssl = env.get("S3_VERIFY_SSL", "1") != "0"
        self.write_buffer_mb = int(env.get("DMLC_S3_WRITE_BUFFER_MB", "8"))

    def require_keys(self) -> None:
        check(
            bool(self.access_key) and bool(self.secret_key),
            "S3 credentials missing: set S3_ACCESS_KEY_ID/S3_SECRET_ACCESS_KEY "
            "(or AWS_*)",
        )

    def url_for(self, bucket: str, key: str) -> Tuple[str, str, str]:
        """(base_url, host_header, canonical_path) for bucket/key.

        The wire URL carries the same %-encoding the signature is computed
        over (S3 recomputes the canonical request from the sent bytes, so
        any mismatch is a SignatureDoesNotMatch)."""
        path = "/" + key.lstrip("/")
        enc_path = _uri_encode(path, encode_slash=False)
        if self.endpoint:
            # path-style addressing against a custom endpoint
            parsed = urllib.parse.urlparse(self.endpoint)
            host = parsed.netloc
            base = f"{self.endpoint.rstrip('/')}/{bucket}{enc_path}"
            return base, host, f"/{bucket}{path}"
        host = f"{bucket}.s3.{self.region}.amazonaws.com"
        return f"https://{host}{enc_path}", host, path


def _parse_s3_uri(uri: URI) -> Tuple[str, str]:
    """s3://bucket/key -> (bucket, key)."""
    return uri.host, uri.name.lstrip("/")


# ---------------- request helper ----------------

def _request(
    cfg: S3Config,
    method: str,
    bucket: str,
    key: str,
    query: Optional[Dict[str, str]] = None,
    headers: Optional[Dict[str, str]] = None,
    body: bytes = b"",
    op: str = "request",
    policy: Optional[RetryPolicy] = None,
    retry: bool = True,
) -> Tuple[int, bytes, Dict[str, str]]:
    """One signed S3 request under the shared retry policy.

    The reference retries 3x per part uniformly (s3_filesys.cc:789) —
    auth failures included; here the shared classifier separates transient
    faults (retried with jittered backoff, re-signed each attempt so the
    x-amz-date stays fresh) from fatal ones (surfaced in one attempt).
    ``retry=False`` runs a single raw attempt for callers that own the
    retry loop (the read stream: its budget lives in ``_fetch_retry``).
    """
    cfg.require_keys()
    query = dict(query or {})
    url, host, path = cfg.url_for(bucket, key)
    if query:
        # same encoding as the canonical query string ('%20', never '+')
        url += "?" + "&".join(
            f"{_uri_encode(k)}={_uri_encode(str(v))}"
            for k, v in sorted(query.items()))
    payload_hash = hashlib.sha256(body).hexdigest() if body else _EMPTY_SHA256
    pol = policy or default_policy()

    def attempt() -> Tuple[int, bytes, Dict[str, str]]:
        hdrs = sign_v4(
            method, host, path, query, dict(headers or {}), payload_hash,
            cfg.access_key, cfg.secret_key, cfg.region,
            session_token=cfg.session_token,
        )
        req = urllib.request.Request(url, data=body or None, method=method,
                                     headers=hdrs)
        try:
            with urllib.request.urlopen(
                    req, timeout=pol.attempt_timeout) as resp:
                # lower-cased keys: HTTP headers are case-insensitive and a
                # proxy/emulator emitting content-length must not read as
                # size 0 (same normalization as azure_filesys._request)
                return resp.status, resp.read(), {
                    k.lower(): v for k, v in resp.headers.items()}
        except urllib.error.HTTPError as exc:
            if exc.code in (404, 403, 416):
                # expected-status pass-through: callers branch on these
                return exc.code, exc.read(), {
                    k.lower(): v for k, v in exc.headers.items()}
            raise

    if not retry:
        return attempt()
    return pol.call(attempt, op=op, what=f"s3://{bucket}/{key}")


# ---------------- streams ----------------

class S3ReadStream(HttpReadStream):
    """Signed range-GET reader (ReadStream, s3_filesys.cc:664-745)."""

    def __init__(self, cfg: S3Config, bucket: str, key: str, size: int):
        self._cfg = cfg
        self._bucket = bucket
        self._key = key
        url, _, _ = cfg.url_for(bucket, key)
        super().__init__(url, size=size)

    def _fetch(self, start: int, end: int) -> bytes:
        status, body, _ = _request(
            self._cfg, "GET", self._bucket, self._key,
            headers={"Range": f"bytes={start}-{end - 1}"},
            retry=False,  # the stream-level _fetch_retry owns the budget
        )
        if status == 416:
            return b""
        if status == 200:
            return body[start:end]  # server ignored Range
        if status == 206:
            return body
        raise DMLCError(f"s3 read failed: {self._bucket}/{self._key}: {status}")


class S3WriteStream(_pyio.RawIOBase):
    """Multipart-upload writer (WriteStream Init/Upload/Finish,
    s3_filesys.cc:768-1010). Parts buffer to ``DMLC_S3_WRITE_BUFFER_MB``;
    short final objects fall back to a single PUT."""

    def __init__(self, cfg: S3Config, bucket: str, key: str):
        super().__init__()
        self._cfg = cfg
        self._bucket = bucket
        self._key = key
        self._buf = bytearray()
        self._part_bytes = cfg.write_buffer_mb << 20
        self._upload_id: Optional[str] = None
        self._etags: List[str] = []
        self._closed = False

    def writable(self) -> bool:
        return True

    def write(self, b) -> int:
        self._buf += bytes(b)
        while len(self._buf) >= self._part_bytes:
            self._upload_part(bytes(self._buf[: self._part_bytes]))
            del self._buf[: self._part_bytes]
        return len(b)

    def _init_multipart(self) -> None:
        status, body, _ = _request(
            self._cfg, "POST", self._bucket, self._key, query={"uploads": ""},
            op="write")
        check(status == 200, f"s3 multipart init failed: {status}")
        root = ET.fromstring(body)
        node = root.find(".//{*}UploadId")
        if node is None:
            node = root.find(".//UploadId")
        check(node is not None and node.text,
              "s3 multipart init: no UploadId in response")
        self._upload_id = node.text

    def _upload_part(self, data: bytes) -> None:
        if self._upload_id is None:
            self._init_multipart()
        part_number = len(self._etags) + 1
        status, _, headers = _request(
            self._cfg, "PUT", self._bucket, self._key,
            query={"partNumber": str(part_number), "uploadId": self._upload_id},
            body=data, op="write",
        )
        check(status == 200, f"s3 part {part_number} upload failed: {status}")
        self._etags.append(headers.get("etag", ""))

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._upload_id is None:
            # small object: single PUT
            status, _, _ = _request(
                self._cfg, "PUT", self._bucket, self._key, body=bytes(self._buf),
                op="write")
            check(status == 200, f"s3 put failed: {status}")
        else:
            if self._buf:
                self._upload_part(bytes(self._buf))
                self._buf.clear()
            parts = "".join(
                f"<Part><PartNumber>{i + 1}</PartNumber><ETag>{etag}</ETag></Part>"
                for i, etag in enumerate(self._etags)
            )
            body = (f"<CompleteMultipartUpload>{parts}"
                    f"</CompleteMultipartUpload>").encode()
            status, _, _ = _request(
                self._cfg, "POST", self._bucket, self._key,
                query={"uploadId": self._upload_id}, body=body, op="write")
            check(status == 200, f"s3 multipart complete failed: {status}")
        super().close()


# ---------------- filesystem ----------------

class S3FileSystem(FileSystem):
    """s3:// FileSystem over the SigV4 client."""

    native_resilience = True  # S3ReadStream resumes via _fetch_retry

    _instance: Optional["S3FileSystem"] = None

    def __init__(self, cfg: Optional[S3Config] = None):
        self.cfg = cfg or S3Config()

    @classmethod
    def instance(cls, uri: Optional[URI] = None) -> "S3FileSystem":
        if cls._instance is None:
            cls._instance = cls()
        else:
            # refresh from env on every lookup: credentials/endpoint may
            # rotate mid-process (env reads are trivia next to any request)
            cls._instance.cfg = S3Config()
        return cls._instance

    def get_path_info(self, path: URI, cfg: Optional[S3Config] = None) -> FileInfo:
        if cfg is None:
            cfg = self.cfg  # snapshot: instance() may swap cfg concurrently
        bucket, key = _parse_s3_uri(path)
        status, _, headers = _request(cfg, "HEAD", bucket, key, op="open")
        if status == 200:
            return FileInfo(path, int(headers.get("content-length", 0)),
                            FILE_TYPE)
        # fall back: prefix listing decides directory-ness (bucket root
        # lists with an empty prefix, not "/")
        prefix = key.rstrip("/") + "/" if key else ""
        entries = self._list(bucket, prefix, max_keys=1, max_total=1, cfg=cfg)
        if entries:
            return FileInfo(path, 0, DIR_TYPE)
        raise DMLCError(f"s3 path not found: {str(path)}")

    def _list(self, bucket: str, prefix: str, max_keys: int = 1000,
              max_total: Optional[int] = None,
              cfg: Optional[S3Config] = None) -> List[Tuple[str, int, str]]:
        """(key, size, type) entries under prefix via ListObjectsV2."""
        cfg = cfg or self.cfg  # one snapshot for every page of the listing
        out: List[Tuple[str, int, str]] = []
        token: Optional[str] = None
        while True:
            query = {
                "list-type": "2",
                "prefix": prefix,
                "delimiter": "/",
                "max-keys": str(max_keys),
            }
            if token:
                query["continuation-token"] = token
            status, body, _ = _request(cfg, "GET", bucket, "", query=query,
                                        op="open")
            check(status == 200, f"s3 list failed: {status}")
            root = ET.fromstring(body)

            def _find_all(tag: str):
                return root.findall(f".//{{*}}{tag}") or root.findall(f".//{tag}")

            def _find(node, tag: str):
                # namespaced first, bare fallback (test servers skip the ns)
                found = node.find(f"{{*}}{tag}")
                return found if found is not None else node.find(tag)

            for node in _find_all("Contents"):
                key_node = _find(node, "Key")
                size_node = _find(node, "Size")
                if key_node is None or not key_node.text:
                    continue
                out.append((key_node.text,
                            int(size_node.text) if size_node is not None else 0,
                            FILE_TYPE))
            for node in _find_all("CommonPrefixes"):
                p = _find(node, "Prefix")
                if p is not None and p.text:
                    out.append((p.text, 0, DIR_TYPE))
            nxt = root.find(".//{*}NextContinuationToken")
            if nxt is None:
                nxt = root.find(".//NextContinuationToken")
            if (nxt is None or not nxt.text
                    or (max_total is not None and len(out) >= max_total)):
                return out
            token = nxt.text

    def list_directory(self, path: URI) -> List[FileInfo]:
        bucket, key = _parse_s3_uri(path)
        prefix = key.rstrip("/") + "/" if key else ""
        infos = []
        for k, size, typ in self._list(bucket, prefix):
            child = URI(f"s3://{bucket}/{k}")
            infos.append(FileInfo(child, size, typ))
        return infos

    def open(self, path: URI, mode: str):
        cfg = self.cfg  # snapshot: stat + stream must share one config
        bucket, key = _parse_s3_uri(path)
        if "r" in mode:
            info = self.get_path_info(path, cfg=cfg)
            check(info.type == FILE_TYPE, f"not a file: {str(path)}")
            raw = S3ReadStream(cfg, bucket, key, info.size)
            return _pyio.BufferedReader(raw)
        if "w" in mode:
            return _pyio.BufferedWriter(S3WriteStream(cfg, bucket, key))
        raise DMLCError(f"unsupported s3 open mode {mode!r}")

    def open_for_read(self, path: URI):
        return self.open(path, "rb")


register_filesystem("s3://", S3FileSystem.instance)
