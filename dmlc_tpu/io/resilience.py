"""Unified fault-tolerance layer for the I/O stack.

The reference dmlc-core hard-codes a 3x-per-part retry in its S3 writer
(s3_filesys.cc:789) and nothing else; this rebuild inherited that unevenly
(two ad-hoc fixed-retry loops, three filesystems with none), so one
transient 5xx mid-epoch killed the whole ``DeviceIter`` pipeline. Input
fault tolerance is a first-class property of a data plane that serves long
TPU runs (tf.data service, arXiv:2210.14826), so it lives HERE, once:

- :func:`classify` — the single error classifier: transient faults
  (5xx/429/408, connection reset, timeout, DNS/unreachable) are
  ``retryable``; everything else (4xx auth, malformed URI, logic errors)
  is ``fatal`` and must surface in one attempt. Walks ``__cause__`` so a
  wrapped DMLCError keeps its cause's class.
- :class:`RetryPolicy` — exponential backoff with FULL jitter (seedable),
  per-attempt timeout, overall deadline, and an ``Retry-After`` floor.
  Every retry loop in the package delegates here; ``make lint-retry``
  fails ad-hoc ``time.sleep``-in-retry-loop patterns anywhere else.
- :class:`ResilientStream` — resumable reads over any reopenable seekable
  stream: a mid-read transient fault reopens the source and resumes at
  the current byte offset (the Range/seek machinery the remote streams
  already have), consuming retry budget instead of failing the epoch.
- module counters (:func:`counters_snapshot`) — retry / resume / giveup
  totals, surfaced by ``DeviceIter.stats()['resilience']`` next to the
  stage attribution and emitted by ``bench.py``. The books live on the
  telemetry metrics registry (:mod:`dmlc_tpu.utils.telemetry`), with every
  event stamped by the recording thread's pipeline scope — so per-pipeline
  slices (``counters_snapshot(pipeline=...)``) stay disjoint between
  concurrent pipelines while the process-wide API stays byte-compatible.
  New events go through :func:`record_event` (``make lint-metrics`` bans
  direct counter mutation elsewhere). See docs/observability.md.

Deterministic fault injection for all of this lives in
:mod:`dmlc_tpu.io.faults`; every guarded attempt calls
``faults.maybe_fail`` so tier-1 tests exercise each retry/resume/give-up
path without a network. See docs/resilience.md.
"""

from __future__ import annotations

import http.client
import io as _pyio
import os
import random
import time
import urllib.error
from typing import Callable, Dict, Optional

from dmlc_tpu.io import faults
from dmlc_tpu.utils import telemetry as _telemetry
from dmlc_tpu.utils.check import CacheCorruptionError, DMLCError
from dmlc_tpu.utils.timer import get_time

RETRYABLE = "retryable"
FATAL = "fatal"

# HTTP statuses that heal with retry: server-side faults, throttling, and
# request timeout. Everything else 4xx (auth, malformed request, not found)
# is deterministic — retrying it only burns budget and hides the bug.
_RETRYABLE_HTTP = frozenset({408, 429, 500, 502, 503, 504})


def classify(exc: BaseException) -> str:
    """``retryable`` or ``fatal`` for an I/O-stack exception.

    Follows the ``__cause__`` chain so a ``DMLCError`` raised ``from`` a
    transient urllib error stays retryable through wrapper layers (the
    stream-level giveup wraps, the pipeline level still wants the class).
    """
    import ssl

    seen = 0
    while exc is not None and seen < 8:
        if isinstance(exc, CacheCorruptionError):
            # cache faults heal: drop the bad cache, re-read/re-parse the
            # source, rewrite — retryable by construction (the retry IS
            # the rebuild), never a fatal structural error
            return RETRYABLE
        # HTTPError subclasses URLError and OSError: check it first
        if isinstance(exc, urllib.error.HTTPError):
            return (RETRYABLE if exc.code in _RETRYABLE_HTTP
                    or exc.code >= 500 else FATAL)
        if isinstance(exc, urllib.error.URLError):
            # urllib wraps transport failures as URLError(reason) where
            # reason is usually an OSError — gaierror for DNS, EHOSTUNREACH
            # / ECONNREFUSED for routing. All transient at this layer; the
            # one deterministic member is a certificate-verification
            # failure (retrying it only re-fails the handshake).
            if isinstance(exc.reason, ssl.SSLCertVerificationError):
                return FATAL
            return RETRYABLE
        if isinstance(exc, (ConnectionError, TimeoutError)):
            return RETRYABLE  # reset/aborted/refused, socket.timeout
        if isinstance(exc, http.client.HTTPException):
            return RETRYABLE  # IncompleteRead, BadStatusLine, ...
        if isinstance(exc, (DMLCError, OSError)) and exc.__cause__ is not None:
            exc = exc.__cause__
            seen += 1
            continue
        return FATAL
    return FATAL


def retry_after_seconds(exc: BaseException) -> float:
    """Backoff floor from a ``Retry-After`` response header, if any.

    Honors the delta-seconds form (the common throttling shape); an
    HTTP-date or garbage value is ignored rather than parsed — the jittered
    backoff still applies, the floor is just 0.
    """
    seen = 0
    while exc is not None and seen < 8:
        headers = getattr(exc, "headers", None)
        if headers is not None:
            try:
                value = headers.get("Retry-After")
            except AttributeError:
                value = None
            if value is not None:
                try:
                    return max(0.0, float(value))
                except (TypeError, ValueError):
                    return 0.0
        exc = exc.__cause__
        seen += 1
    return 0.0


# ---------------- counters ----------------
#
# Since the telemetry PR the books live in the metrics registry
# (dmlc_tpu.utils.telemetry.REGISTRY): every event is ONE registry
# counter under RESILIENCE_METRIC, labeled with the event key and the
# pipeline scope active on the recording thread. The public
# counters_snapshot / counters_delta / reset_counters API is
# byte-compatible (process-wide totals, same keys); the new
# ``pipeline=`` filter is what lets two concurrent DeviceIters keep
# disjoint books (docs/observability.md).

def record_event(key: str, n: int = 1) -> None:
    """Count one resilience event — the ONE sanctioned bump path
    (``make lint-metrics`` fails direct counter mutation elsewhere). The
    active pipeline scope is stamped on automatically, so the event shows
    up both process-wide and under its pipeline's label."""
    _telemetry.REGISTRY.counter(
        _telemetry.RESILIENCE_METRIC, event=key,
        pipeline=_telemetry.current_scope() or "").inc(n)


class _Counters:
    """Resilience event counters (registry facade, thread-safe).

    ``attempts``  guarded attempts issued
    ``retries``   failed attempts that were retried
    ``resumes``   of those, mid-stream reopen-at-offset events
    ``giveups``   operations abandoned with retry budget exhausted
    ``fatal``     operations failed on a non-retryable class (one attempt)
    ``producer_restarts`` / ``producer_giveups``
                  bounded producer restarts in ThreadedIter/OrderedWorkerPool
    ``parse_restarts`` / ``parse_giveups``
                  bounded chunk-source restarts inside the data-parallel
                  parse fan-out (ParallelTextParser's OrderedWorkerPool,
                  which labels its restart counters ``parse``)
    ``cache_corruptions``
                  cache integrity-check failures (CRC mismatch / torn
                  frame) detected while serving a warm cache
    ``cache_invalidations``
                  stale caches dropped at open time (signature mismatch,
                  unreadable/legacy format) — rebuilt from source
    ``cache_rebuilds``
                  healing rebuilds triggered by a mid-stream corruption:
                  the bad cache was dropped, the source re-read/re-parsed,
                  and a fresh cache rewritten
    ``service_retries``
                  data-service client streams interrupted (connection
                  loss, torn frame, worker error) and re-requested at the
                  exact block index
    ``service_failovers``
                  of those, resumes that landed on a DIFFERENT worker
                  after the dispatcher re-issued the dead worker's split
    ``service_giveups``
                  service streams abandoned with the failure budget
                  exhausted (no live worker took the part)
    ``dispatcher_restarts``
                  data-service control-plane restarts a client observed
                  (the dispatcher's generation token advanced mid-run)
    ``worker_reregistrations``
                  parse workers re-attaching to a restarted/recovered
                  dispatcher (generation change or declared-dead zombie)
    ``parts_reclaimed``
                  fully-parsed parts a restarted dispatcher adopted from
                  worker frame stores instead of re-issuing for re-parse
    ``control_plane_retries``
                  dispatcher round trips (register / locate / next_split
                  / reclaim ...) that failed transiently and were
                  retried under the shared policy
    ``worker_drains``
                  graceful worker drains begun (SIGTERM, preemption
                  notice, or operator drain): the dispatcher stopped
                  granting, re-issued the worker's unstarted parts, and
                  the worker served out its frame-store-complete parts
    ``drain_handoffs``
                  parts a client finished streaming off a draining
                  worker gracefully (drain END / moved-hint failover) —
                  handoffs, not socket-timeout failovers
    ``preemption_notices``
                  preemption signals workers observed
                  (``DMLC_TPU_PREEMPTION_NOTICE`` file/env, or the
                  ``preempt`` fault-plan op) — each triggers a drain
    ``speculative_reissues``
                  straggler parts the dispatcher speculatively re-issued
                  to a second worker (stuck past
                  ``DMLC_TPU_HEDGE_FACTOR`` x the fleet median)
    ``speculative_wins``
                  of those, races the speculative worker won
                  (first-complete-wins; the stuck primary's later
                  completion is deduped)
    ``worker_joins``
                  brand-new workers that joined a LIVE fleet mid-epoch
                  (registered after work had already been granted)
    ``service_parts_parsed``
                  parts a service worker supplied by ACTUALLY parsing
                  (cold pass — text ran through a parser somewhere in
                  the fleet)
    ``service_parts_shared``
                  parts a service worker supplied from an
                  already-published block-cache artifact instead of
                  parsing — the cross-job share-by-signature win (a
                  second job over the same corpus, or a relaunched
                  worker re-serving its own publication); the bench
                  two-job leg's ``shared_parse_ratio`` is
                  shared / (parsed + shared)
    ``fleet_scale_ups`` / ``fleet_scale_downs``
                  fleet-autoscaler decisions: workers live-joined under
                  sustained per-job input wait / gracefully drained
                  under sustained idleness (docs/service.md fleet
                  autoscaling) — both zero on a clean bench run
    ``service_throttles``
                  locate requests the dispatcher shed with a retryable
                  ``throttled`` reply because admission control had the
                  job over its ``max_inflight`` budget or the fleet over
                  the ``DMLC_TPU_QOS_MAX_INFLIGHT`` ceiling
                  (docs/service.md Production QoS) — bounded queueing,
                  not failure: a throttled epoch still completes
                  byte-identically
    ``service_admission_waits``
                  client-side backoff sleeps taken on those throttled
                  replies (shared RetryPolicy schedule; each throttle
                  resets the locate deadline, so a deliberately-queued
                  batch tenant never burns toward ``service_giveups``)
    """

    _KEYS = ("attempts", "retries", "resumes", "giveups", "fatal",
             "producer_restarts", "producer_giveups",
             "parse_restarts", "parse_giveups",
             "cache_corruptions", "cache_invalidations", "cache_rebuilds",
             "service_retries", "service_failovers", "service_giveups",
             "dispatcher_restarts", "worker_reregistrations",
             "parts_reclaimed", "control_plane_retries",
             "worker_drains", "drain_handoffs", "preemption_notices",
             "speculative_reissues", "speculative_wins", "worker_joins",
             "service_parts_parsed", "service_parts_shared",
             "fleet_scale_ups", "fleet_scale_downs",
             "service_throttles", "service_admission_waits")

    def bump(self, key: str, n: int = 1) -> None:
        record_event(key, n)

    def snapshot(self, pipeline: Optional[str] = None) -> Dict[str, int]:
        """Totals per event key — process-wide by default, or one
        pipeline's slice with ``pipeline=`` (empty string selects events
        recorded outside any pipeline scope)."""
        label_filter = {} if pipeline is None else {"pipeline": pipeline}
        out = {k: 0 for k in self._KEYS}
        for key, v in _telemetry.REGISTRY.sum_by(
                _telemetry.RESILIENCE_METRIC, "event",
                **label_filter).items():
            if key:
                out[key] = int(round(v))
        return out

    def delta(self, base: Dict[str, int],
              pipeline: Optional[str] = None) -> Dict[str, int]:
        now = self.snapshot(pipeline)
        return {k: now.get(k, 0) - base.get(k, 0) for k in now}

    def reset(self) -> None:
        _telemetry.REGISTRY.clear(_telemetry.RESILIENCE_METRIC)


COUNTERS = _Counters()


def counters_snapshot(pipeline: Optional[str] = None) -> Dict[str, int]:
    return COUNTERS.snapshot(pipeline)


def counters_delta(base: Dict[str, int],
                   pipeline: Optional[str] = None) -> Dict[str, int]:
    return COUNTERS.delta(base, pipeline)


def reset_counters() -> None:
    COUNTERS.reset()


# ---------------- retry policy ----------------

class RetryPolicy:
    """Exponential backoff + full jitter, per-attempt timeout, deadline.

    One instance describes the budget for ONE logical operation (a request,
    a block fetch): ``max_attempts`` total tries, sleeping
    ``uniform(0, min(max_delay, base_delay * 2**retry))`` between them
    (full jitter — herd-safe), never less than a server-sent
    ``Retry-After``. ``deadline`` bounds the whole operation including
    sleeps; ``attempt_timeout`` is what callers should pass to their
    transport (urlopen timeout=).

    Env knobs (read by :func:`from_env` / :func:`default_policy`):

    ======================================  =======  ========================
    ``DMLC_RETRY_MAX_ATTEMPTS``             4        total attempts per op
    ``DMLC_RETRY_BASE_MS``                  50       first backoff cap (ms)
    ``DMLC_RETRY_MAX_MS``                   5000     backoff cap ceiling (ms)
    ``DMLC_RETRY_DEADLINE_S``               0 (off)  per-op wall deadline
    ``DMLC_RETRY_ATTEMPT_TIMEOUT_S``        60       transport timeout
    ``DMLC_RETRY_SEED``                     unset    seed the jitter rng
    ======================================  =======  ========================
    """

    def __init__(
        self,
        max_attempts: int = 4,
        base_delay: float = 0.05,
        max_delay: float = 5.0,
        deadline: Optional[float] = None,
        attempt_timeout: float = 60.0,
        seed: Optional[int] = None,
        sleep_fn: Optional[Callable[[float], None]] = None,
    ):
        self.max_attempts = max(1, int(max_attempts))
        self.base_delay = max(0.0, float(base_delay))
        self.max_delay = max(self.base_delay, float(max_delay))
        self.deadline = float(deadline) if deadline else None
        self.attempt_timeout = float(attempt_timeout)
        self._rng = random.Random(seed)
        self._sleep = sleep_fn or time.sleep

    @classmethod
    def from_env(cls) -> "RetryPolicy":
        env = os.environ
        seed = env.get("DMLC_RETRY_SEED")
        return cls(
            max_attempts=int(env.get("DMLC_RETRY_MAX_ATTEMPTS", "4") or 4),
            base_delay=float(env.get("DMLC_RETRY_BASE_MS", "50") or 50) / 1e3,
            max_delay=float(env.get("DMLC_RETRY_MAX_MS", "5000") or 5000) / 1e3,
            deadline=float(env.get("DMLC_RETRY_DEADLINE_S", "0") or 0) or None,
            attempt_timeout=float(
                env.get("DMLC_RETRY_ATTEMPT_TIMEOUT_S", "60") or 60),
            seed=int(seed) if seed else None,
        )

    @classmethod
    def none(cls) -> "RetryPolicy":
        """Single attempt, no sleeps — for inner layers whose caller owns
        the retry loop (stacked policies would multiply budgets)."""
        return cls(max_attempts=1)

    def backoff(self, retry_index: int, floor: float = 0.0) -> float:
        """Sleep for the (retry_index+1)-th retry: full-jitter exponential,
        floored by a server-sent Retry-After. The honored floor is capped
        at ``max(30s, max_delay)`` — a misbehaving server advertising
        ``Retry-After: 86400`` must not wedge a reader thread for a day."""
        floor = min(floor, max(30.0, self.max_delay))
        cap = min(self.max_delay, self.base_delay * (2 ** retry_index))
        return max(floor, self._rng.uniform(0.0, cap))

    def sleep(self, seconds: float) -> None:
        if seconds > 0:
            self._sleep(seconds)

    def call(
        self,
        fn: Callable[[], object],
        *,
        op: str = "request",
        what: str = "",
        resume_offset: int = 0,
        on_retry: Optional[Callable[[], None]] = None,
    ):
        """Run ``fn`` under this budget.

        Each attempt first passes through the fault-injection seam
        (``faults.maybe_fail`` for the generic ``connect`` op and for
        ``op``), so injected faults flow down the same classify/backoff
        paths as real ones. Fatal-class errors surface immediately (one
        attempt); retryable ones sleep and retry until the budget or
        deadline runs out, then raise a ``DMLCError`` chained to the last
        cause. ``resume_offset > 0`` marks retries as mid-stream resumes
        in the counters; ``on_retry`` runs before each re-attempt (e.g.
        drop a broken inner stream).
        """
        t0 = get_time()
        retries = 0
        while True:
            record_event("attempts")
            try:
                faults.maybe_fail("connect", what)
                faults.maybe_fail(op, what)
                return fn()
            except (KeyboardInterrupt, SystemExit, GeneratorExit):
                raise  # control-flow exceptions must never be rewrapped
            except BaseException as exc:  # noqa: BLE001 - classified below
                if classify(exc) != RETRYABLE:
                    record_event("fatal")
                    if isinstance(exc, DMLCError):
                        raise
                    raise DMLCError(
                        f"{op} {what} failed (non-retryable): {exc}") from exc
                delay = self.backoff(retries, floor=retry_after_seconds(exc))
                out_of_budget = retries + 1 >= self.max_attempts
                past_deadline = (
                    self.deadline is not None
                    and get_time() - t0 + delay > self.deadline)
                if out_of_budget or past_deadline:
                    record_event("giveups")
                    why = ("deadline exceeded" if past_deadline
                           else f"retry budget exhausted "
                                f"({self.max_attempts} attempts)")
                    raise DMLCError(
                        f"{op} {what} failed, {why}: {exc}") from exc
                retries += 1
                record_event("retries")
                if resume_offset > 0:
                    record_event("resumes")
                self.sleep(delay)
                if on_retry is not None:
                    on_retry()


def default_policy() -> RetryPolicy:
    """The env-configured policy (fresh read: knobs may change per test)."""
    return RetryPolicy.from_env()


def restart_verdict(policy: Optional[RetryPolicy], used: int,
                    exc: BaseException) -> str:
    """Shared gate for bounded producer/source/pipeline restarts.

    ``'restart'``   retryable class, budget left — consume one unit
    ``'giveup'``    retryable class, budget (``max_attempts - 1``) spent
    ``'propagate'`` fatal class or restarts disabled (``policy is None``)

    The caller owns its instance counters and the repositioning; pair a
    ``'restart'`` with :func:`restart_backoff` before re-arming.
    """
    if policy is None or classify(exc) != RETRYABLE:
        return "propagate"
    if used >= max(0, policy.max_attempts - 1):
        return "giveup"
    return "restart"


def restart_backoff(policy: RetryPolicy, used: int,
                    exc: BaseException) -> None:
    """Sleep the backoff for the (used+1)-th restart, honoring any
    Retry-After the triggering error carried."""
    policy.sleep(policy.backoff(used, floor=retry_after_seconds(exc)))


NO_RETRY = RetryPolicy.none()


# ---------------- resumable stream wrapper ----------------

class ResilientStream(_pyio.RawIOBase):
    """Resumable read-only stream over a reopenable source.

    ``open_fn()`` returns a fresh readable (and seekable, for mid-stream
    resume) binary stream. On a retryable mid-read failure the broken
    inner stream is dropped, a new one is opened and SEEKED to the current
    byte offset, and the read resumes — the consumer sees an unbroken byte
    sequence. Fatal errors and exhausted budgets surface as ``DMLCError``.

    The five remote filesystems implement the same contract natively (their
    range-GET machinery refetches at the failed offset, see
    ``HttpReadStream._fetch_retry``); this wrapper extends it to any other
    stream — local files on flaky network mounts, third-party filesystems
    registered via ``register_filesystem`` — through
    ``open_stream(uri, resilient=True)``.
    """

    def __init__(self, open_fn: Callable[[], object],
                 policy: Optional[RetryPolicy] = None, what: str = ""):
        super().__init__()
        self._open_fn = open_fn
        self._policy = policy or default_policy()
        self._what = what
        self._inner = None
        self._pos = 0
        self.reopens = 0  # resume events on THIS stream

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def _ensure(self):
        if self._inner is None:
            self._inner = self._open_fn()
            if self._pos:
                self._inner.seek(self._pos)
                self.reopens += 1
        return self._inner

    def _drop_inner(self) -> None:
        inner, self._inner = self._inner, None
        if inner is not None:
            try:
                inner.close()
            except Exception:  # noqa: BLE001 - already broken
                pass

    def seek(self, offset: int, whence: int = 0) -> int:
        def attempt():
            inner = self._ensure()
            return inner.seek(offset, whence)

        self._pos = self._policy.call(
            attempt, op="read", what=self._what,
            resume_offset=self._pos, on_retry=self._drop_inner)
        return self._pos

    def tell(self) -> int:
        return self._pos

    def read(self, n: int = -1) -> bytes:
        def attempt():
            return self._ensure().read(n)

        data = self._policy.call(
            attempt, op="read", what=self._what,
            resume_offset=self._pos, on_retry=self._drop_inner)
        if data:
            self._pos += len(data)
        return data

    def readinto(self, b) -> int:
        data = self.read(len(b))
        b[: len(data)] = data
        return len(data)

    def close(self) -> None:
        self._drop_inner()
        super().close()
