"""Partitioned, record-aware input splitting.

Behavioral equivalent of reference src/io/input_split_base.{h,cc},
line_split.cc, recordio_split.cc, indexed_recordio_split.cc,
single_file_split.h, threaded_input_split.h and input_split_shuffle.h —
rebuilt in Python around byte chunks + memoryview records (the C++ native
core supplies the same contract for the hot path).

The partition invariant (the reference's hardest-won correctness property,
see PR#385/PR#452 citations at input_split_base.cc:196-199, 235-242):

- The logical dataset is the concatenation of all matched files.
- Partition ``k`` of ``n`` owns byte range ``[k*step, (k+1)*step)`` with
  ``step = align(ceil(total/n))`` (ResetPartition, input_split_base.cc:30-64).
- Both range ends are advanced to the next record head by scanning from the
  raw byte offset (``seek_record_begin``) unless they sit exactly on a file
  boundary — file joins are implicit record boundaries.
- A '\\n' is injected at text-file joins so NOEOL files never merge records
  across files (Read, input_split_base.cc:196-199), and at end-of-partition
  when the final record lacks a newline (ReadChunk, input_split_base.cc:235-242).

Every record is therefore owned by exactly one partition: no loss, no
duplication — tested by looping all parts in-process (SURVEY.md §4).
"""

from __future__ import annotations

import os
import random
import struct
from bisect import bisect_right
from typing import BinaryIO, Iterator, List, Optional, Tuple

from dmlc_tpu.io import recordio as rio
from dmlc_tpu.io.filesystem import (
    FileSystem,
    LocalFileSystem,
    get_filesystem,
)
from dmlc_tpu.io.threaded_iter import ThreadedIter
from dmlc_tpu.io.uri import URI, URISpec
from dmlc_tpu.utils.check import DMLCError, check

_EOL = (0x0A, 0x0D)  # '\n', '\r'
DEFAULT_CHUNK_BYTES = 1 << 20


class InputSplit:
    """Abstract input split — analog of dmlc::InputSplit (io.h:190-242)."""

    def next_record(self) -> Optional[memoryview]:
        raise NotImplementedError

    def next_chunk(self) -> Optional[memoryview]:
        raise NotImplementedError

    def before_first(self) -> None:
        raise NotImplementedError

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        raise NotImplementedError

    def hint_chunk_size(self, chunk_size: int) -> None:
        pass

    def close(self) -> None:
        pass

    def iter_records(self) -> Iterator[memoryview]:
        while True:
            rec = self.next_record()
            if rec is None:
                return
            yield rec

    def iter_chunks(self) -> Iterator[memoryview]:
        while True:
            chunk = self.next_chunk()
            if chunk is None:
                return
            yield chunk

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class _Chunk:
    """A loaded chunk being consumed record-by-record.

    ``raw`` is the backing bytes object so searches use C-speed bytes.find;
    a full-span memoryview shares it without a copy, partial views are
    materialized once.
    """

    __slots__ = ("raw", "data", "pos", "resume_state")

    def __init__(self, data):
        if isinstance(data, memoryview):
            if isinstance(data.obj, bytes) and len(data) == len(data.obj):
                data = data.obj
            else:
                data = bytes(data)
        self.raw: bytes = data
        self.data = memoryview(data)
        self.pos = 0

    @property
    def exhausted(self) -> bool:
        return self.pos >= len(self.data)


class InputSplitBase(InputSplit):
    """Core sharding engine — analog of InputSplitBase (input_split_base.cc)."""

    is_text = False
    align_bytes = 1

    def __init__(
        self,
        fs: FileSystem,
        uri: str,
        recurse_directories: bool = False,
    ):
        self.fs = fs
        self.files: List = []
        self._init_file_info(uri, recurse_directories)
        self.file_offset = [0]
        for info in self.files:
            check(
                info.size % self.align_bytes == 0,
                f"file {info.path} does not align by {self.align_bytes} bytes",
            )
            self.file_offset.append(self.file_offset[-1] + info.size)
        self.offset_begin = 0
        self.offset_end = 0
        self.offset_curr = 0
        self.file_ptr = 0
        self._fp: Optional[BinaryIO] = None
        self._overflow = b""
        self._chunk: Optional[_Chunk] = None
        self._chunk_bytes = DEFAULT_CHUNK_BYTES
        self.bytes_read = 0

    # ---------------- file matching ----------------

    def _init_file_info(self, uri: str, recurse: bool) -> None:
        """Expand ';'-separated URIs, directories, and regex basename patterns
        (ConvertToURIs/InitInputFileInfo, input_split_base.cc:96-175)."""
        import re

        for part in uri.split(";"):
            if not part:
                continue
            path = URI(part)
            matched = False
            try:
                info = self.fs.get_path_info(path)
                if info.type == "directory":
                    listing = (
                        self.fs.list_directory_recursive(info.path)
                        if recurse
                        else self.fs.list_directory(info.path)
                    )
                    for f in listing:
                        if f.type == "file" and f.size > 0:
                            self.files.append(f)
                else:
                    if info.size > 0:
                        self.files.append(info)
                matched = True
            except DMLCError:
                pass
            if not matched:
                # regex match over the parent directory's entries
                pos = path.name.rstrip("/").rfind("/")
                if pos <= 0:
                    continue
                dir_uri = URI(path.protocol + path.host + path.name[:pos]
                              if path.protocol != "file://" else path.name[:pos])
                pattern = re.compile(path.name)
                try:
                    listing = self.fs.list_directory(dir_uri)
                except DMLCError:
                    continue
                for f in listing:
                    if f.type != "file" or f.size == 0:
                        continue
                    if pattern.fullmatch(f.path.name.rstrip("/")):
                        self.files.append(f)
        check(len(self.files) > 0, f"Cannot find any files that match the URI pattern {uri!r}")

    # ---------------- subclass contract ----------------

    def seek_record_begin(self, stream: BinaryIO) -> int:
        """Bytes from the stream position to the next record head."""
        raise NotImplementedError

    def find_last_record_begin(self, data: bytes) -> int:
        """Offset of the last record head in ``data`` (0 = none found)."""
        raise NotImplementedError

    def extract_next_record(self, chunk: _Chunk) -> Optional[memoryview]:
        """Pop one record off the chunk; None when exhausted."""
        raise NotImplementedError

    # ---------------- partitioning ----------------

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        """Byte-range partition + record-boundary adjustment
        (ResetPartition, input_split_base.cc:30-64)."""
        check(num_parts >= 1, f"num_parts must be >= 1, got {num_parts}")
        check(0 <= part_index < num_parts,
              f"part_index {part_index} out of range for {num_parts} parts")
        self.part_index = part_index
        self.num_parts = num_parts
        ntotal = self.file_offset[-1]
        nstep = (ntotal + num_parts - 1) // num_parts
        align = self.align_bytes
        nstep = ((nstep + align - 1) // align) * align
        self.offset_begin = min(nstep * part_index, ntotal)
        self.offset_end = min(nstep * (part_index + 1), ntotal)
        self.offset_curr = self.offset_begin
        if self.offset_begin == self.offset_end:
            # empty partition: drop any state from a previous partition too
            self._close_fp()
            self._overflow = b""
            self._chunk = None
            return
        file_ptr = bisect_right(self.file_offset, self.offset_begin) - 1
        file_ptr_end = bisect_right(self.file_offset, self.offset_end) - 1
        # adjust the end: extend to the next record head unless on a file join
        if self.offset_end != self.file_offset[file_ptr_end]:
            check(file_ptr_end < len(self.files), "partition end out of range")
            with self.fs.open_for_read(self.files[file_ptr_end].path) as f:
                f.seek(self.offset_end - self.file_offset[file_ptr_end])
                self.offset_end += self.seek_record_begin(f)
        # adjust the begin the same way
        self.file_ptr = file_ptr
        if self.offset_begin != self.file_offset[file_ptr]:
            with self.fs.open_for_read(self.files[file_ptr].path) as f:
                f.seek(self.offset_begin - self.file_offset[file_ptr])
                self.offset_begin += self.seek_record_begin(f)
        self.before_first()

    def before_first(self) -> None:
        """Seek back to the partition start (BeforeFirst, input_split_base.cc:66-82)."""
        if self.offset_begin >= self.offset_end:
            return
        self.file_ptr = bisect_right(self.file_offset, self.offset_begin) - 1
        self._close_fp()
        self._fp = self.fs.open_for_read(self.files[self.file_ptr].path)
        self._fp.seek(self.offset_begin - self.file_offset[self.file_ptr])
        self.offset_curr = self.offset_begin
        self._overflow = b""
        self._chunk = None

    # ---------------- reading ----------------

    def _read(self, size: int) -> bytes:
        """Read up to ``size`` payload bytes across file joins, injecting '\\n'
        at text-file joins (Read, input_split_base.cc:177-219)."""
        if self._fp is None or self.offset_begin >= self.offset_end:
            return b""
        size = min(size, self.offset_end - self.offset_curr)
        if size <= 0:
            return b""
        out = bytearray()
        nleft = size
        while nleft > 0:
            data = self._fp.read(nleft)
            if data:
                out += data
                nleft -= len(data)
                self.offset_curr += len(data)
                continue
            # file exhausted
            if self.is_text:
                # newline injection at file joins (PR#385)
                out += b"\n"
                nleft -= 1
            check(
                self.offset_curr == self.file_offset[self.file_ptr + 1],
                "file offset not calculated correctly",
            )
            if self.file_ptr + 1 >= len(self.files):
                break
            self.file_ptr += 1
            self._close_fp()
            self._fp = self.fs.open_for_read(self.files[self.file_ptr].path)
        self.bytes_read += len(out)
        return bytes(out)

    def read_chunk(self, max_size: int) -> Optional[bytes]:
        """One chunk of whole records; b'' means grow the buffer; None = EOF
        (ReadChunk, input_split_base.cc:221-258)."""
        if max_size <= len(self._overflow):
            return b""
        olen = len(self._overflow)
        data = self._overflow + self._read(max_size - olen)
        self._overflow = b""
        if len(data) == 0:
            return None
        if self.is_text:
            if len(data) == olen:
                # final record of the partition lacked a newline (PR#452)
                data += b"\n"
        else:
            if len(data) != max_size:
                return data  # EOF tail: records are exactly complete
        cut = self.find_last_record_begin(data)
        self._overflow = data[cut:]
        return data[:cut]

    def _load_chunk(self) -> Optional[_Chunk]:
        """Grow-on-demand chunk load (Chunk::Load, input_split_base.cc:260-277)."""
        size = self._chunk_bytes
        while True:
            data = self.read_chunk(size)
            if data is None:
                return None
            if len(data) == 0:
                size *= 2
                continue
            return _Chunk(data)

    # ---------------- public iteration ----------------

    def next_record(self) -> Optional[memoryview]:
        while True:
            if self._chunk is not None:
                rec = self.extract_next_record(self._chunk)
                if rec is not None:
                    return rec
            self._chunk = self._load_chunk()
            if self._chunk is None:
                return None

    def next_chunk(self) -> Optional[memoryview]:
        # pending chunk tail first (ExtractNextChunk, input_split_base.cc:300-306)
        if self._chunk is not None and not self._chunk.exhausted:
            out = self._chunk.data[self._chunk.pos:]
            self._chunk = None
            return out
        chunk = self._load_chunk()
        if chunk is None:
            return None
        return chunk.data

    def hint_chunk_size(self, chunk_size: int) -> None:
        self._chunk_bytes = max(chunk_size, 4096)

    def records_in_chunk(self, chunk: bytes | memoryview) -> Iterator[memoryview]:
        """Iterate the records inside an already-loaded chunk blob."""
        c = _Chunk(chunk)  # type: ignore[arg-type]
        while True:
            rec = self.extract_next_record(c)
            if rec is None:
                return
            yield rec

    # ---------------- checkpoint / resume ----------------
    #
    # A capability the reference lacks (SURVEY.md §5.4): capture the exact
    # mid-partition read position so a preempted job resumes without
    # re-reading the prefix. State is JSON-friendly.

    @property
    def chunk_resume_state(self) -> Optional[dict]:
        """Resume state positioned just after the chunk most recently
        returned by ``next_chunk``. On an undecorated split the live
        ``state_dict`` IS that position; prefetching decorators override
        this to return the state captured when the chunk was produced."""
        return self.state_dict()

    def state_dict(self) -> dict:
        """Byte-exact resume point: global offset + undelivered buffer tails."""
        pending_chunk = b""
        if self._chunk is not None and not self._chunk.exhausted:
            pending_chunk = bytes(self._chunk.data[self._chunk.pos:])
        return {
            "kind": "byte",
            "offset_curr": self.offset_curr,
            # file_ptr disambiguates a checkpoint taken exactly on a text
            # file join: the reader may still sit at the END of file k (the
            # join '\n' not yet injected) rather than the start of file k+1
            "file_ptr": self.file_ptr,
            "overflow": self._overflow.hex(),
            "chunk": pending_chunk.hex(),
            # partition identity: restore can re-point a split that was
            # constructed for (or last reset to) a different shard
            "part_index": getattr(self, "part_index", None),
            "num_parts": getattr(self, "num_parts", None),
        }

    def load_state(self, state: dict) -> None:
        """Seek to a :meth:`state_dict` position (same URI; the recorded
        partition is re-applied when it differs from the current one)."""
        check(state.get("kind") == "byte", "incompatible split state")
        part, nparts = state.get("part_index"), state.get("num_parts")
        if (nparts is not None and part is not None
                and (part, nparts) != (getattr(self, "part_index", None),
                                       getattr(self, "num_parts", None))):
            self.reset_partition(int(part), int(nparts))
        off = int(state["offset_curr"])
        check(
            self.offset_begin <= off <= self.offset_end,
            f"state offset {off} outside partition "
            f"[{self.offset_begin}, {self.offset_end})",
        )
        self._close_fp()
        self.offset_curr = off
        file_ptr = int(state.get("file_ptr", -1))
        if not (0 <= file_ptr < len(self.files)
                and self.file_offset[file_ptr] <= off
                <= self.file_offset[file_ptr + 1]):
            # legacy/foreign state without a consistent file_ptr
            file_ptr = min(bisect_right(self.file_offset, off) - 1,
                           len(self.files) - 1)
        self.file_ptr = file_ptr
        if off < self.file_offset[-1] or off == self.file_offset[file_ptr + 1]:
            # reopen the recorded file even when off sits on its end: the
            # next _read then performs the pending join-newline injection
            self._fp = self.fs.open_for_read(self.files[file_ptr].path)
            self._fp.seek(off - self.file_offset[file_ptr])
        self._overflow = bytes.fromhex(state["overflow"])
        pending = bytes.fromhex(state["chunk"])
        self._chunk = _Chunk(pending) if pending else None

    def _close_fp(self) -> None:
        if self._fp is not None:
            self._fp.close()
            self._fp = None

    def close(self) -> None:
        self._close_fp()


class LineSplitter(InputSplitBase):
    """Record = line — analog of src/io/line_split.cc.

    EOL handling matches the reference: '\\n' and '\\r' both terminate, runs
    of EOL bytes collapse (so blank lines produce no records), records
    returned exclude the terminator.
    """

    is_text = True
    align_bytes = 1

    def seek_record_begin(self, stream: BinaryIO) -> int:
        """Scan to the first EOL, then past the EOL run (line_split.cc:9-26)."""
        nstep = 0
        # phase 1: find an EOL
        found = False
        while not found:
            block = stream.read(512)
            if not block:
                return nstep
            for i, b in enumerate(block):
                nstep += 1
                if b in _EOL:
                    found = True
                    rest = block[i + 1:]
                    break
        # phase 2: consume the EOL run
        while True:
            for b in rest:
                if b in _EOL:
                    nstep += 1
                else:
                    return nstep
            rest = stream.read(512)
            if not rest:
                return nstep

    def find_last_record_begin(self, data: bytes) -> int:
        """Position after the last EOL (line_split.cc:27-34); 0 if none."""
        pos = max(data.rfind(b"\n"), data.rfind(b"\r"))
        return pos + 1 if pos >= 0 else 0

    def extract_next_record(self, chunk: _Chunk) -> Optional[memoryview]:
        data, pos, end = chunk.data, chunk.pos, len(chunk.data)
        # skip any leading EOL run (blank lines collapse, line_split.cc:36-55)
        while pos < end and data[pos] in _EOL:
            pos += 1
        if pos >= end:
            chunk.pos = end
            return None
        nl = _find_eol(chunk.raw, pos)
        rec = data[pos:nl]
        pos = nl
        while pos < end and data[pos] in _EOL:
            pos += 1
        chunk.pos = pos
        return rec


def _find_eol(raw: bytes, start: int) -> int:
    nl = raw.find(b"\n", start)
    end = nl if nl >= 0 else len(raw)
    # bound the \r search to before the \n so a \r-free chunk is not
    # rescanned end-to-end for every record
    cr = raw.find(b"\r", start, end)
    return cr if cr >= 0 else end


class MmapLineSplit(LineSplitter):
    """Zero-copy chunk reads over plain LOCAL text corpora.

    Chunks are memoryview slices of per-file mmaps, cut at the last record
    boundary inside the chunk budget — each pull costs a tail ``rfind``
    instead of the stream engine's read / concat / slice passes over every
    byte. Built for the data-parallel parse fan-out
    (:class:`dmlc_tpu.data.parsers.ParallelTextParser`), whose design
    constraint is a cheap SERIAL chunk source feeding parallel parse
    workers: with the stream engine the single-threaded pull consumes a
    core's worth of copying per ~500 MB/s and caps the fan-out.

    Partition math (byte ranges + record-boundary adjustment) is inherited
    from the stream engine, so shard boundaries are byte-identical to
    :class:`LineSplitter`'s. Chunk boundaries may differ (a chunk never
    spans a file join; the join newline is implicit in ending the chunk at
    EOF), but the records inside are identical. States keep the stream
    engine's ``kind='byte'`` schema — ``offset_curr`` counts file payload
    bytes — so checkpoints restore across engines; the one stream-engine
    state this class refuses is a mid-record-iteration snapshot with a
    pending-chunk tail (never produced by the chunk-pulling parser chain).
    """

    def __init__(self, fs: FileSystem, uri: str,
                 recurse_directories: bool = False):
        check(isinstance(fs, LocalFileSystem),
              "MmapLineSplit requires local files")
        super().__init__(fs, uri, recurse_directories)
        self._fds: List = [None] * len(self.files)
        self._maps: List = [None] * len(self.files)
        self._views: List = [None] * len(self.files)

    def _map(self, fi: int):
        """Lazy per-file mmap; the listing's size is authoritative — a file
        that shrank since listing fails loudly instead of SIGBUSing."""
        if self._maps[fi] is None:
            import mmap as _mmap

            path = self.files[fi].path.name
            f = open(path, "rb")
            try:
                size = os.fstat(f.fileno()).st_size
                check(size >= self.files[fi].size,
                      f"{path}: shrank since listing "
                      f"({size} < {self.files[fi].size} bytes)")
                mm = _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)
            except BaseException:
                f.close()
                raise
            self._fds[fi] = f
            self._maps[fi] = mm
            self._views[fi] = memoryview(mm)
        return self._maps[fi], self._views[fi]

    def next_chunk(self) -> Optional[memoryview]:
        # pending record-iteration tail first (the base-class contract:
        # a consumer may mix next_record and next_chunk)
        if self._chunk is not None and not self._chunk.exhausted:
            out = self._chunk.data[self._chunk.pos:]
            self._chunk = None
            return out
        self._chunk = None
        # a partition can be EMPTY after record-boundary adjustment
        # (offset_begin advanced to offset_end) while offset_curr still
        # holds the raw un-adjusted position reset_partition started from
        # — the stream engine's _read guards this; without it a mid-record
        # fragment would leak out as a chunk
        if (self.offset_begin >= self.offset_end
                or self.offset_curr >= self.offset_end):
            return None
        pos = max(self.offset_curr, self.offset_begin)
        fi = min(bisect_right(self.file_offset, pos) - 1,
                 len(self.files) - 1)
        self.file_ptr = fi
        fbase = self.file_offset[fi]
        # never span files: a record cannot cross a text-file join (the
        # stream engine injects '\n' there; ending the chunk at EOF is the
        # same record boundary without materializing the byte)
        hard_end = min(self.offset_end, self.file_offset[fi + 1]) - fbase
        lo = pos - fbase
        mm, mv = self._map(fi)
        size = self._chunk_bytes
        while True:
            hi = lo + size
            if hi >= hard_end:
                # partition/file end — record-aligned by reset_partition.
                # An UNTERMINATED final line still becomes its own chunk,
                # mirroring the stream engine (read_chunk cuts at the last
                # EOL and delivers the tail separately): per-chunk parser
                # semantics (indexing_mode=-1 auto-detect, validation)
                # must not depend on which engine grouped the chunks.
                eol = max(mm.rfind(b"\n", lo, hard_end),
                          mm.rfind(b"\r", lo, hard_end))
                cut = (eol + 1 if lo <= eol and eol + 1 < hard_end
                       else hard_end)
                break
            eol = max(mm.rfind(b"\n", lo, hi), mm.rfind(b"\r", lo, hi))
            if eol >= lo:
                cut = eol + 1
                break
            size *= 2  # grow until a whole record fits (Chunk::Load)
        self.offset_curr = fbase + cut
        self.bytes_read += cut - lo
        return mv[lo:cut]

    def next_record(self) -> Optional[memoryview]:
        while True:
            if self._chunk is not None:
                rec = self.extract_next_record(self._chunk)
                if rec is not None:
                    return rec
            nxt = self.next_chunk()
            if nxt is None:
                self._chunk = None
                return None
            self._chunk = _Chunk(nxt)

    def before_first(self) -> None:
        # unlike the stream engine, rewinding an EMPTY partition is safe
        # here (no file handle to manage) — and offset_curr must never be
        # left at reset_partition's raw un-adjusted position
        self.offset_curr = self.offset_begin
        self.file_ptr = min(
            max(bisect_right(self.file_offset, self.offset_begin) - 1, 0),
            len(self.files) - 1)
        self._overflow = b""
        self._chunk = None

    def load_state(self, state: dict) -> None:
        check(state.get("kind") == "byte", "incompatible split state")
        part, nparts = state.get("part_index"), state.get("num_parts")
        if (nparts is not None and part is not None
                and (part, nparts) != (getattr(self, "part_index", None),
                                       getattr(self, "num_parts", None))):
            self.reset_partition(int(part), int(nparts))
        check(not state.get("chunk"),
              "MmapLineSplit cannot restore a mid-record-iteration state "
              "with a pending chunk tail (chunk-pulling consumers never "
              "produce one)")
        # a stream-engine state counts read-but-undelivered overflow bytes
        # in offset_curr; rewind them — overflow never contains a join
        # newline (the injected byte is always an EOL and therefore always
        # a cut point), so the subtraction is exact file-byte arithmetic
        off = int(state["offset_curr"]) - len(bytes.fromhex(
            state.get("overflow", "") or ""))
        check(
            self.offset_begin <= off <= self.offset_end,
            f"state offset {off} outside partition "
            f"[{self.offset_begin}, {self.offset_end})",
        )
        self.offset_curr = off
        self.file_ptr = min(bisect_right(self.file_offset, off) - 1,
                            len(self.files) - 1)
        self._overflow = b""
        self._chunk = None

    def close(self) -> None:
        self._chunk = None
        for i, mm in enumerate(self._maps):
            if mm is None:
                continue
            view, self._views[i] = self._views[i], None
            self._maps[i] = None
            try:
                if view is not None:
                    view.release()
                mm.close()
            except BufferError:
                pass  # exported block views still alive: GC unmaps later
            f, self._fds[i] = self._fds[i], None
            if f is not None:
                f.close()
        super().close()


def create_mmap_text_split(
    uri: str,
    part_index: int,
    num_parts: int,
    chunk_bytes: int = DEFAULT_CHUNK_BYTES,
    recurse_directories: bool = False,
) -> MmapLineSplit:
    """Build the zero-copy local text chunk source (raises DMLCError when
    the URI is not plain local files — callers fall back to
    :func:`create_input_split`)."""
    check(num_parts >= 1, f"num_parts must be >= 1, got {num_parts}")
    check(0 <= part_index < num_parts,
          f"part_index {part_index} out of range for {num_parts} parts")
    fs = get_filesystem(uri)
    split = MmapLineSplit(fs, uri, recurse_directories)
    split.hint_chunk_size(chunk_bytes)
    split.reset_partition(part_index, num_parts)
    return split


class RecordIOSplitter(InputSplitBase):
    """Record = RecordIO frame — analog of src/io/recordio_split.cc."""

    is_text = False
    align_bytes = 4

    def seek_record_begin(self, stream: BinaryIO) -> int:
        """Scan 4-byte cells for a head (magic + cflag 0|1)
        (recordio_split.cc:9-25)."""
        nstep = 0
        while True:
            cell = stream.read(4)
            if len(cell) < 4:
                return nstep
            nstep += 4
            if struct.unpack("<I", cell)[0] == rio.RECORDIO_MAGIC:
                lrec_raw = stream.read(4)
                check(len(lrec_raw) == 4, "invalid recordio format")
                nstep += 4
                lrec = struct.unpack("<I", lrec_raw)[0]
                if rio.decode_flag(lrec) in (0, 1):
                    return nstep - 8

    def find_last_record_begin(self, data: bytes) -> int:
        heads = rio.find_record_heads(data)
        return int(heads[-1]) if len(heads) else 0

    def extract_next_record(self, chunk: _Chunk) -> Optional[memoryview]:
        if chunk.exhausted:
            return None
        rec, chunk.pos = rio.extract_record(chunk.data, chunk.pos, len(chunk.data))
        return rec


class SingleFileSplit(InputSplit):
    """Line reading of a single file or stdin, no partitioning
    (src/io/single_file_split.h).

    Streams in bounded, record-aligned chunks — the reference buffers
    incrementally (single_file_split.h:69-72) rather than slurping, so a
    multi-GB file or stdin feed costs O(chunk_bytes) memory here too.
    stdin is single-pass: a second epoch raises instead of silently
    replaying partial data.
    """

    def __init__(self, path: str, chunk_bytes: int = 4 << 20):
        self.path = path
        self.chunk_bytes = max(4096, int(chunk_bytes))
        self._fp = None
        self._overflow = b""
        self._eof = True
        self._started = False
        self._stdin_consumed = False
        self._records: Iterator[memoryview] = iter(())

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        check(part_index == 0 and num_parts == 1,
              "SingleFileSplit does not support partitioning")
        self.before_first()

    def before_first(self) -> None:
        if self.path == "stdin":
            import sys

            check(not self._stdin_consumed,
                  "SingleFileSplit: stdin is single-pass and cannot restart")
            self._fp = sys.stdin.buffer
        else:
            if self._fp is not None:
                self._fp.close()
            self._fp = get_filesystem(self.path).open_for_read(URI(self.path))
        self._overflow = b""
        self._eof = False
        self._started = True
        self._records = iter(())

    def _read_chunk(self) -> Optional[bytes]:
        """Next record-aligned chunk of ~chunk_bytes, or None at EOF."""
        if self._eof and not self._overflow:
            return None
        parts = [self._overflow]
        got = len(self._overflow)
        self._overflow = b""
        target = self.chunk_bytes
        while True:
            while got < target and not self._eof:
                data = self._fp.read(target - got)
                if not data:
                    self._eof = True
                    break
                if self.path == "stdin":
                    self._stdin_consumed = True
                parts.append(data)
                got += len(data)
            data = b"".join(parts)
            if self._eof:
                return data if data else None
            # cut after the last EOL so the chunk holds whole records
            cut = max(data.rfind(b"\n"), data.rfind(b"\r")) + 1
            if cut > 0:
                self._overflow = data[cut:]
                return data[:cut]
            # a single record longer than the chunk: keep growing
            parts = [data]
            target *= 2

    def next_record(self) -> Optional[memoryview]:
        if not self._started:
            self.before_first()
        rec = next(self._records, None)
        while rec is None:
            chunk = self._read_chunk()
            if chunk is None:
                return None
            mv = memoryview(chunk)
            self._records = iter([mv[s:e] for s, e in _line_spans(chunk)])
            rec = next(self._records, None)
        return rec

    def next_chunk(self) -> Optional[memoryview]:
        """Successive record-aligned chunks, sharing the stream with
        ``next_record`` (like every other InputSplit): records already
        materialized from a partially-consumed chunk are dropped in favor
        of the next chunk from the stream.
        """
        if not self._started:
            self.before_first()
        self._records = iter(())
        chunk = self._read_chunk()
        return memoryview(chunk) if chunk is not None else None

    def close(self) -> None:
        if self._fp is not None and self.path != "stdin":
            self._fp.close()
            self._fp = None


def _line_spans(data: bytes) -> List[Tuple[int, int]]:
    spans = []
    pos, n = 0, len(data)
    while pos < n:
        while pos < n and data[pos] in _EOL:
            pos += 1
        if pos >= n:
            break
        end = data.find(b"\n", pos)
        cr = data.find(b"\r", pos)
        if end < 0 or (0 <= cr < end):
            end = cr
        if end < 0:
            end = n
        spans.append((pos, end))
        pos = end
    return spans


class IndexedRecordIOSplitter(InputSplitBase):
    """Record-count partitioning with an external index + optional shuffle —
    analog of src/io/indexed_recordio_split.cc."""

    is_text = False
    align_bytes = 4
    # state_dict carries the epoch permutation + rng state — far too heavy
    # to snapshot per prefetched chunk (ThreadedInputSplit._produce)
    cheap_chunk_state = False

    def __init__(
        self,
        fs: FileSystem,
        uri: str,
        index_uri: str,
        batch_size: int = 256,
        shuffle: bool = False,
        seed: int = 0,
    ):
        super().__init__(fs, uri)
        with get_filesystem(index_uri).open_for_read(URI(index_uri)) as f:
            self.index = rio.read_index_file(f, self.file_offset[-1])
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.rng = random.Random(seed)
        self.index_begin = 0
        self.index_end = 0
        self.current_index = 0
        self.permutation: List[int] = []

    seek_record_begin = RecordIOSplitter.seek_record_begin
    find_last_record_begin = RecordIOSplitter.find_last_record_begin
    extract_next_record = RecordIOSplitter.extract_next_record

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        """Partition by record count (indexed_recordio_split.cc:12-41)."""
        check(num_parts >= 1, f"num_parts must be >= 1, got {num_parts}")
        check(0 <= part_index < num_parts,
              f"part_index {part_index} out of range for {num_parts} parts")
        self.part_index = part_index
        self.num_parts = num_parts
        ntotal = len(self.index)
        nstep = (ntotal + num_parts - 1) // num_parts
        if part_index * nstep >= ntotal:
            # empty partition: clear all iteration state from any prior part
            self.offset_begin = self.offset_end = 0
            self.index_begin = self.index_end = 0
            self.current_index = 0
            self.permutation = []
            self._overflow = b""
            self._chunk = None
            self._close_fp()
            return
        self.index_begin = part_index * nstep
        self.offset_begin = self.index[self.index_begin][0]
        if (part_index + 1) * nstep < ntotal:
            self.index_end = (part_index + 1) * nstep
            self.offset_end = self.index[self.index_end][0]
        else:
            self.index_end = ntotal
            self.offset_end = self.file_offset[-1]
        self.before_first()

    def before_first(self) -> None:
        if self.shuffle:
            self.permutation = list(range(self.index_begin, self.index_end))
            self.rng.shuffle(self.permutation)
            self.current_index = 0
        else:
            self.current_index = self.index_begin
        super().before_first()

    # -------- checkpoint / resume --------
    #
    # The base class's byte state does not describe this splitter (reads are
    # index-driven; offset_curr never advances), so capture the record cursor
    # and, under shuffle, the epoch permutation + rng state.

    def state_dict(self) -> dict:
        pending_chunk = b""
        if self._chunk is not None and not self._chunk.exhausted:
            pending_chunk = bytes(self._chunk.data[self._chunk.pos:])
        st = {
            "kind": "indexed",
            "current_index": self.current_index,
            "chunk": pending_chunk.hex(),
            "part_index": getattr(self, "part_index", None),
            "num_parts": getattr(self, "num_parts", None),
        }
        if self.shuffle:
            st["permutation"] = list(self.permutation)
            rs = self.rng.getstate()
            st["rng_state"] = [rs[0], list(rs[1]), rs[2]]
        return st

    def load_state(self, state: dict) -> None:
        check(state.get("kind") == "indexed",
              "incompatible indexed-recordio split state")
        part, nparts = state.get("part_index"), state.get("num_parts")
        if (nparts is not None and part is not None
                and (part, nparts) != (getattr(self, "part_index", None),
                                       getattr(self, "num_parts", None))):
            self.reset_partition(int(part), int(nparts))
        self._close_fp()
        self._overflow = b""
        if self.shuffle:
            self.permutation = list(state["permutation"])
            r0, r1, r2 = state["rng_state"]
            self.rng.setstate((r0, tuple(r1), r2))
        self.current_index = int(state["current_index"])
        pending = bytes.fromhex(state["chunk"])
        self._chunk = _Chunk(pending) if pending else None

    def _next_batch_data(self, n_records: int) -> Optional[bytes]:
        """Load the next ``n_records`` as one contiguous buffer
        (NextBatchEx, indexed_recordio_split.cc:159-212)."""
        if self.shuffle:
            parts: List[bytes] = []
            taken = 0
            while taken < n_records and self.current_index < len(self.permutation):
                rec_idx = self.permutation[self.current_index]
                offset, size = self.index[rec_idx]
                parts.append(self._read_span(offset, size))
                self.current_index += 1
                taken += 1
            if not parts:
                return None
            return b"".join(parts)
        if self.current_index >= self.index_end:
            return None
        last = min(self.current_index + n_records, self.index_end)
        begin_off = self.index[self.current_index][0]
        end_off = (
            self.index[last][0] if last < len(self.index) else self.file_offset[-1]
        )
        if last == self.index_end:
            end_off = self.offset_end
        data = self._read_span(begin_off, end_off - begin_off)
        self.current_index = last
        return data

    def _read_span(self, offset: int, size: int) -> bytes:
        """Read an absolute [offset, offset+size) span across files."""
        out = bytearray()
        while size > 0:
            fidx = bisect_right(self.file_offset, offset) - 1
            if fidx >= len(self.files):
                break
            if self.file_ptr != fidx or self._fp is None:
                self._close_fp()
                self.file_ptr = fidx
                self._fp = self.fs.open_for_read(self.files[fidx].path)
            self._fp.seek(offset - self.file_offset[fidx])
            take = min(size, self.file_offset[fidx + 1] - offset)
            data = self._fp.read(take)
            if not data:
                break
            out += data
            offset += len(data)
            size -= len(data)
        self.bytes_read += len(out)
        return bytes(out)

    def next_chunk(self) -> Optional[memoryview]:
        return self.next_batch(self.batch_size)

    def next_batch(self, n_records: int) -> Optional[memoryview]:
        data = self._next_batch_data(n_records)
        return memoryview(data) if data is not None else None

    def next_record(self) -> Optional[memoryview]:
        while True:
            if self._chunk is not None:
                rec = self.extract_next_record(self._chunk)
                if rec is not None:
                    return rec
            data = self._next_batch_data(self.batch_size)
            if data is None:
                self._chunk = None
                return None
            self._chunk = _Chunk(data)


class ThreadedInputSplit(InputSplit):
    """Prefetch decorator: a producer thread loads chunks ahead
    (src/io/threaded_input_split.h; capacity 2 per reference :33-42)."""

    def __init__(self, base: InputSplitBase, capacity: int = 2):
        self.base = base
        self._capacity = capacity
        self._iter = ThreadedIter(self._produce, self._reset_base, max_capacity=capacity)
        self._chunk: Optional[_Chunk] = None
        self._last_chunk_state = None

    def _produce(self, cell):
        chunk = self.base.next_chunk()
        if chunk is None:
            return False, None
        out = _Chunk(chunk)
        # capture the base's position WITH the chunk (the live state runs
        # ahead of consumption once prefetched) — consumers read it back
        # via chunk_resume_state for byte-exact checkpoints. Splitters whose
        # state is heavy (e.g. a shuffled index permutation) opt out via
        # cheap_chunk_state and fall back to count-based resume.
        out.resume_state = None
        if getattr(self.base, "cheap_chunk_state", True):
            try:
                out.resume_state = self.base.state_dict()
            except (AttributeError, DMLCError):
                pass
        return True, out

    def _reset_base(self):
        self.base.before_first()

    def next_chunk(self) -> Optional[memoryview]:
        chunk = self._iter.next()
        if chunk is None:
            return None
        self._last_chunk_state = getattr(chunk, "resume_state", None)
        return chunk.data

    @property
    def chunk_resume_state(self):
        """Base state as of the chunk last handed out (not the prefetched
        live position)."""
        return self._last_chunk_state

    def next_record(self) -> Optional[memoryview]:
        while True:
            if self._chunk is not None:
                rec = self.base.extract_next_record(self._chunk)
                if rec is not None:
                    return rec
            self._chunk = self._iter.next()
            if self._chunk is None:
                return None

    def before_first(self) -> None:
        self._iter.before_first()
        self._chunk = None
        self._last_chunk_state = None  # stale end-of-epoch position otherwise

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        # quiesce the producer, repartition the base, restart
        self._iter.destroy()
        self.base.reset_partition(part_index, num_parts)
        self._iter = ThreadedIter(
            self._produce, self._reset_base, max_capacity=self._capacity
        )
        self._chunk = None
        self._last_chunk_state = None

    def load_state(self, state: dict) -> None:
        """Seek the base to a saved position (a ``chunk_resume_state`` /
        base ``state_dict``) and restart the prefetch from there — the
        producer never re-reads the consumed prefix."""
        self._iter.destroy()
        self.base.load_state(state)
        self._iter = ThreadedIter(
            self._produce, self._reset_base, max_capacity=self._capacity
        )
        self._chunk = None
        self._last_chunk_state = state

    def hint_chunk_size(self, chunk_size: int) -> None:
        self.base.hint_chunk_size(chunk_size)

    def close(self) -> None:
        self._iter.destroy()
        self.base.close()

    @property
    def stall_seconds(self) -> float:
        return self._iter.stall_seconds


class ShuffledInputSplit(InputSplit):
    """Chunk-level global shuffle wrapper —
    analog of include/dmlc/input_split_shuffle.h.

    Splits this rank's partition into ``num_shuffle_parts`` sub-partitions and
    visits them in a shuffled order each epoch (input_split_shuffle.h:19-60).

    Relationship to the epoch planner: this decorator shuffles what gets
    *read*, per epoch, on the parse path — combined with a block cache it
    is superseded by the deterministic epoch plan
    (:mod:`dmlc_tpu.data.epoch`), which shuffles what gets *served* from
    the cache instead; ``create_parser`` maps the legacy
    ``shuffle``/``num_shuffle_parts`` + ``block_cache`` combination onto
    the plan knobs with a one-release deprecation (docs/data.md).
    Uncached parsing keeps this decorator unchanged.
    """

    def __init__(
        self,
        make_base,
        part_index: int,
        num_parts: int,
        num_shuffle_parts: int,
        seed: int = 0,
    ):
        check(num_shuffle_parts > 0, "num_shuffle_parts must be positive")
        self._make_base = make_base
        self.base: InputSplit = make_base()
        self.part_index = part_index
        self.num_parts = num_parts
        self.num_shuffle_parts = num_shuffle_parts
        self.rng = random.Random(seed)
        self._order: List[int] = []
        self._order_pos = 0
        self._active = False
        self.before_first()

    def _sub_parts(self) -> List[int]:
        base = self.part_index * self.num_shuffle_parts
        return [base + i for i in range(self.num_shuffle_parts)]

    def before_first(self) -> None:
        self._order = self._sub_parts()
        self.rng.shuffle(self._order)
        self._order_pos = 0
        self._active = False

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        self.part_index = part_index
        self.num_parts = num_parts
        self.before_first()

    def _advance(self) -> bool:
        if self._order_pos >= len(self._order):
            return False
        sub = self._order[self._order_pos]
        self._order_pos += 1
        self.base.reset_partition(sub, self.num_parts * self.num_shuffle_parts)
        self._active = True
        return True

    def next_record(self) -> Optional[memoryview]:
        while True:
            if self._active:
                rec = self.base.next_record()
                if rec is not None:
                    return rec
                self._active = False
            if not self._advance():
                return None

    def next_chunk(self) -> Optional[memoryview]:
        while True:
            if self._active:
                chunk = self.base.next_chunk()
                if chunk is not None:
                    return chunk
                self._active = False
            if not self._advance():
                return None

    def hint_chunk_size(self, chunk_size: int) -> None:
        self.base.hint_chunk_size(chunk_size)

    def close(self) -> None:
        self.base.close()


def create_input_split(
    uri: str,
    part_index: int,
    num_parts: int,
    type_: str = "text",
    *,
    index_uri: Optional[str] = None,
    shuffle: bool = False,
    seed: int = 0,
    batch_size: int = 256,
    threaded: bool = True,
    recurse_directories: bool = False,
    num_shuffle_parts: int = 0,
    chunk_bytes: int = DEFAULT_CHUNK_BYTES,
) -> InputSplit:
    """Factory — analog of InputSplit::Create (src/io.cc:74-130).

    type_: 'text' (alias 'line'), 'recordio', 'indexed_recordio', 'stdin'.
    Wraps in a prefetch thread by default (src/io.cc:119-124) and in the
    chunk-shuffle decorator when num_shuffle_parts > 0
    (input_split_shuffle.h InputSplit::Create overload).
    """
    check(num_parts >= 1, f"num_parts must be >= 1, got {num_parts}")
    check(0 <= part_index < num_parts,
          f"part_index {part_index} out of range for {num_parts} parts")
    if uri == "stdin" or type_ == "stdin":
        return SingleFileSplit(uri)
    # URI sugar: `real#cachefile` selects the chunk-cache decorator with a
    # partition-qualified cache name (src/io.cc:81-88, 119-123)
    spec = URISpec(uri, part_index, num_parts)
    uri = spec.uri
    cache_file = spec.cache_file
    fs = get_filesystem(uri)
    # hot path: native recordio pipeline (read + framing scan + multi-part
    # reassembly in C++, off the GIL) for plain local .rec corpora
    from dmlc_tpu.io.native_recordio import native_engine_enabled

    if type_ == "recordio" and native_engine_enabled(spec.args):
        from dmlc_tpu.io.native_recordio import (
            NativeRecordIOSplit,
            native_recordio_eligible,
        )

        if native_recordio_eligible(
                uri, threaded, index_uri=index_uri, shuffle=shuffle,
                num_shuffle_parts=num_shuffle_parts, cache_file=cache_file,
                recurse_directories=recurse_directories):
            try:
                return NativeRecordIOSplit(
                    uri, part_index, num_parts,
                    recurse_directories=recurse_directories,
                    chunk_bytes=chunk_bytes)
            except DMLCError:
                pass  # fall through to the Python engine
        else:
            # remote .rec corpora: Python range-reads feed the C++ chunk
            # feeder (framing scan + multi-part reassembly off the GIL)
            from dmlc_tpu.io.native_recordio import (
                NativeFeedRecordIOSplit,
                native_feed_recordio_eligible,
            )

            if native_feed_recordio_eligible(
                    uri, threaded, index_uri=index_uri, shuffle=shuffle,
                    num_shuffle_parts=num_shuffle_parts,
                    cache_file=cache_file):
                try:
                    return NativeFeedRecordIOSplit(
                        uri, part_index, num_parts,
                        recurse_directories=recurse_directories,
                        chunk_bytes=chunk_bytes)
                except DMLCError:
                    pass  # fall through to the Python engine
    # hot path: native indexed-recordio (record-count partitioning, batched
    # reads, per-epoch shuffled seeks in C++) — covers the shuffled-epoch
    # ImageNet .rec case the Python engine served single-threaded before
    if (type_ == "indexed_recordio" and index_uri is not None
            and native_engine_enabled(spec.args)):
        from dmlc_tpu.io.native_recordio import (
            NativeIndexedRecordIOSplit,
            native_indexed_eligible,
        )

        if native_indexed_eligible(
                uri, index_uri, threaded,
                num_shuffle_parts=num_shuffle_parts, cache_file=cache_file):
            try:
                return NativeIndexedRecordIOSplit(
                    uri, index_uri, part_index, num_parts,
                    batch_size=batch_size, shuffle=shuffle, seed=seed,
                    recurse_directories=recurse_directories)
            except DMLCError:
                pass  # fall through to the Python engine

    def make_raw() -> InputSplitBase:
        if type_ in ("text", "line"):
            base = LineSplitter(fs, uri, recurse_directories)
        elif type_ == "recordio":
            base = RecordIOSplitter(fs, uri, recurse_directories)
        elif type_ == "indexed_recordio":
            check(index_uri is not None, "indexed_recordio requires index_uri")
            base = IndexedRecordIOSplitter(
                fs, uri, index_uri, batch_size=batch_size, shuffle=shuffle, seed=seed
            )
        else:
            raise DMLCError(f"unknown input split type {type_!r}")
        base.hint_chunk_size(chunk_bytes)
        return base

    def make_base() -> InputSplit:
        base: InputSplit = make_raw()
        return ThreadedInputSplit(base) if threaded else base

    if num_shuffle_parts > 0:
        check(cache_file is None,
              "cachefile and num_shuffle_parts cannot be combined")
        return ShuffledInputSplit(
            make_base, part_index, num_parts, num_shuffle_parts, seed=seed
        )
    if cache_file is not None:
        from dmlc_tpu.io.cached_split import CachedInputSplit

        def make_partitioned() -> InputSplitBase:
            b = make_raw()
            b.reset_partition(part_index, num_parts)
            return b

        cls = {"text": LineSplitter, "line": LineSplitter,
               "recordio": RecordIOSplitter}.get(type_)
        check(cls is not None, f"cachefile not supported for type {type_!r}")
        return CachedInputSplit(make_partitioned, cache_file, splitter_cls=cls)
    base = make_raw()
    base.reset_partition(part_index, num_parts)
    return ThreadedInputSplit(base) if threaded else base
