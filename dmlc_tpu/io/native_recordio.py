"""Native-backed RecordIO input split: read, framing scan, and multi-part
reassembly all run in C++ off the GIL (native/src/reader.cc format 4/5,
native/src/recordio.cc).

This is the TPU-first hot path for local .rec corpora (BASELINE.md config
#3, ImageNet-shaped): where the reference stacks a prefetch thread over the
RecordIOSplitter's chunk scan (src/io/threaded_input_split.h over
src/io/recordio_split.cc), this class delegates the identical pipeline to
the native core — one GIL-releasing pull per batch of extracted records.

``create_input_split`` routes eligible recordio URIs here (local files,
threaded, no cache/shuffle decorators); everything else takes the Python
engine, which shares partition semantics (both mirror input_split_base.cc +
recordio_split.cc and are A/B-tested row-for-row in
tests/test_native_reader.py).
"""

from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np

from dmlc_tpu.io.filesystem import LocalFileSystem, get_filesystem
from dmlc_tpu.io.input_split import (
    DEFAULT_CHUNK_BYTES,
    InputSplit,
    RecordIOSplitter,
)
from dmlc_tpu.utils import telemetry as _telemetry
from dmlc_tpu.utils.check import DMLCError, check


def native_engine_enabled(args=None) -> bool:
    """Shared native-route opt-out policy: the DMLC_TPU_NO_NATIVE_READER
    env switch and the ``?engine=python`` URI arg, in ONE place for every
    routing site."""
    import os

    if os.environ.get("DMLC_TPU_NO_NATIVE_READER", "0") not in ("", "0"):
        return False
    return (args or {}).get("engine") != "python"


def native_recordio_eligible(uri: str, threaded: bool, *, index_uri=None,
                             shuffle: bool = False, num_shuffle_parts: int = 0,
                             cache_file=None,
                             recurse_directories: bool = False) -> bool:
    """True when create_input_split can route recordio to the native split."""
    from dmlc_tpu import native

    if not threaded or index_uri or shuffle or num_shuffle_parts or cache_file:
        return False
    try:
        fs = get_filesystem(uri)
    except DMLCError:
        return False
    if not isinstance(fs, LocalFileSystem):
        return False
    return native.available()


class _RecordCursorSplit(InputSplit):
    """Shared record cursor over native ``(payload, offsets)`` batches:
    the slicing walk, counters, and bytes accounting used by every native
    recordio-backed split (one implementation, not N copies)."""

    _reader = None

    def _cursor_clear(self) -> None:
        self._payload: Optional[np.ndarray] = None
        self._offsets: Optional[np.ndarray] = None
        self._i = 0
        self._records_out = 0

    def _prepare_records(self) -> None:
        """Hook: put the underlying reader in record mode (lazily)."""

    def _pull_batch(self):
        """Next ``(payload, offsets)`` batch or None at end of stream."""
        raise NotImplementedError

    def next_record(self) -> Optional[memoryview]:
        self._prepare_records()
        while self._offsets is None or self._i >= len(self._offsets) - 1:
            nxt = self._pull_batch()
            if nxt is None:
                return None
            self._payload, self._offsets = nxt
            self._i = 0
        s = int(self._offsets[self._i])
        e = int(self._offsets[self._i + 1])
        self._i += 1
        self._records_out += 1
        return memoryview(self._payload)[s:e]

    @property
    def bytes_read(self) -> int:
        return self._reader.bytes_read if self._reader is not None else 0

    def close(self) -> None:
        if self._reader is not None:
            self._reader.close()
            self._reader = None


class NativeRecordIOSplit(_RecordCursorSplit):
    """InputSplit facade over the native recordio reader.

    Serves either records (extracted payloads, multi-part reassembled) or
    raw record-aligned chunks — whichever the consumer asks for first; the
    two modes map to distinct native stream formats, so mixing them within
    one epoch raises instead of silently skipping data.
    """

    def __init__(self, uri: str, part_index: int, num_parts: int,
                 recurse_directories: bool = False,
                 chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                 queue_depth: int = 4):
        check(num_parts >= 1, f"num_parts must be >= 1, got {num_parts}")
        check(0 <= part_index < num_parts,
              f"part_index {part_index} out of range for {num_parts} parts")
        fs = get_filesystem(uri)
        check(isinstance(fs, LocalFileSystem),
              "native recordio split requires local files")
        # reuse the engine's file matching (';' lists, dirs, regex basenames)
        # AND its 4-byte alignment validation
        lister = RecordIOSplitter(fs, uri, recurse_directories)
        self.paths: List[str] = [info.path.name for info in lister.files]
        self.sizes: List[int] = [info.size for info in lister.files]
        self.part_index = part_index
        self.num_parts = num_parts
        self.chunk_bytes = chunk_bytes
        self.queue_depth = queue_depth
        self._mode: Optional[int] = None  # FMT_RECORDIO | FMT_RECORDIO_CHUNK
        self._reader = None
        self._cursor_clear()

    # ---------------- native reader lifecycle ----------------

    def _ensure_reader(self, fmt: int):
        from dmlc_tpu import native

        if self._reader is None:
            self._mode = fmt
            self._reader = native.Reader(
                self.paths, self.sizes, self.part_index, self.num_parts,
                fmt, chunk_bytes=self.chunk_bytes,
                queue_depth=self.queue_depth)
        elif self._mode != fmt:
            raise DMLCError(
                "native recordio split: next_record and next_chunk cannot "
                "be mixed within one epoch")
        return self._reader

    # ---------------- InputSplit interface ----------------

    def _prepare_records(self) -> None:
        from dmlc_tpu import native

        self._ensure_reader(native.FMT_RECORDIO)

    def _pull_batch(self):
        nxt = self._reader.next()
        return None if nxt is None else nxt[1]

    def next_chunk(self) -> Optional[memoryview]:
        from dmlc_tpu import native

        self._ensure_reader(native.FMT_RECORDIO_CHUNK)
        nxt = self._pull_batch()
        if nxt is None:
            return None
        self._payload, self._offsets = nxt
        self._i = 0
        self._records_out += 1
        return memoryview(self._payload)

    def before_first(self) -> None:
        if self._reader is not None:
            self._reader.before_first()
        self._cursor_clear()
        self._mode = None if self._reader is None else self._mode

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        check(num_parts >= 1, f"num_parts must be >= 1, got {num_parts}")
        check(0 <= part_index < num_parts,
              f"part_index {part_index} out of range for {num_parts} parts")
        self.close()
        self.part_index = part_index
        self.num_parts = num_parts
        self._mode = None
        self._cursor_clear()

    def hint_chunk_size(self, chunk_size: int) -> None:
        if chunk_size > self.chunk_bytes:
            self.chunk_bytes = chunk_size

    # -------- checkpoint / resume (count-based, like NativeStreamParser) ----

    def state_dict(self) -> dict:
        return {"kind": "records", "records": self._records_out,
                "mode": self._mode}

    def load_state(self, state: dict) -> None:
        check(state.get("kind") == "records", "incompatible split state")
        self.before_first()
        n = int(state["records"])
        mode = state.get("mode")
        for _ in range(n):
            got = (self.next_chunk() if mode == _chunk_mode()
                   else self.next_record())
            if got is None:
                break
        self._records_out = n


def _chunk_mode() -> int:
    from dmlc_tpu import native

    return native.FMT_RECORDIO_CHUNK


def native_indexed_eligible(uri: str, index_uri: str, threaded: bool, *,
                            num_shuffle_parts: int = 0, cache_file=None) -> bool:
    """True when create_input_split can route indexed recordio natively
    (shuffle IS supported here, unlike the plain recordio fast path)."""
    from dmlc_tpu import native

    if not threaded or num_shuffle_parts or cache_file:
        return False
    try:
        if not isinstance(get_filesystem(uri), LocalFileSystem):
            return False
        if not isinstance(get_filesystem(index_uri), LocalFileSystem):
            return False
    except DMLCError:
        return False
    return native.available()


class NativeIndexedRecordIOSplit(_RecordCursorSplit):
    """InputSplit facade over the native indexed-recordio reader: record-
    count partitioning, batched contiguous reads, per-epoch shuffled seeks
    all in C++ (reader.cc IndexedReader; indexed_recordio_split.cc:12-233).

    Sequential order matches the Python engine row-for-row; shuffled order
    is deterministic per (seed, epoch) via mt19937 but intentionally not
    identical to the Python engine's random.Random permutation.
    """

    def __init__(self, uri: str, index_uri: str, part_index: int,
                 num_parts: int, batch_size: int = 256,
                 shuffle: bool = False, seed: int = 0,
                 recurse_directories: bool = False, queue_depth: int = 4):
        from dmlc_tpu.io.recordio import read_index_file
        from dmlc_tpu.io.uri import URI

        check(num_parts >= 1, f"num_parts must be >= 1, got {num_parts}")
        check(0 <= part_index < num_parts,
              f"part_index {part_index} out of range for {num_parts} parts")
        fs = get_filesystem(uri)
        check(isinstance(fs, LocalFileSystem),
              "native indexed recordio split requires local files")
        lister = RecordIOSplitter(fs, uri, recurse_directories)
        self.paths: List[str] = [info.path.name for info in lister.files]
        self.sizes: List[int] = [info.size for info in lister.files]
        total = sum(self.sizes)
        with get_filesystem(index_uri).open_for_read(URI(index_uri)) as f:
            self.index = read_index_file(f, total)
        self.part_index = part_index
        self.num_parts = num_parts
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.queue_depth = queue_depth
        self._reader = None
        self._cursor_clear()
        self._epochs = 0

    # target bytes per read batch: bounds producer->consumer buffers to a
    # cache-friendly size — 25 MB batches (256 x 100KB ImageNet records)
    # measured 2x slower than ~3 MB ones on a single-core host
    BATCH_BYTES_TARGET = 4 << 20

    def _effective_batch_records(self) -> int:
        total = sum(size for _, size in self.index)
        avg = max(1, total // max(1, len(self.index)))
        cap = max(1, self.BATCH_BYTES_TARGET // avg)
        return max(1, min(self.batch_size, cap))

    def _ensure_reader(self):
        from dmlc_tpu import native

        if self._reader is None:
            self._reader = native.IndexedReader(
                self.paths, self.sizes, [off for off, _ in self.index],
                self.part_index, self.num_parts,
                batch_records=self._effective_batch_records(),
                shuffle=self.shuffle,
                seed=self.seed, queue_depth=self.queue_depth)
        return self._reader

    def _prepare_records(self) -> None:
        self._ensure_reader()

    def _pull_batch(self):
        return self._reader.next()

    def next_chunk(self) -> Optional[memoryview]:
        raise DMLCError(
            "indexed recordio serves records, not raw chunks "
            "(reference NextChunk is record-batched here too)")

    def before_first(self) -> None:
        if self._reader is not None:
            self._reader.before_first()
            self._epochs += 1
        self._cursor_clear()

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        check(num_parts >= 1, f"num_parts must be >= 1, got {num_parts}")
        check(0 <= part_index < num_parts,
              f"part_index {part_index} out of range for {num_parts} parts")
        self.close()
        self.part_index = part_index
        self.num_parts = num_parts
        self._cursor_clear()
        self._epochs = 0

    def hint_chunk_size(self, chunk_size: int) -> None:
        pass  # batching is record-count based

    # -------- checkpoint / resume --------
    #
    # Shuffled epochs are a deterministic function of (seed, epoch), so the
    # native reader can land on (epoch, record) by pure rng replay + a seek
    # — no prefix bytes are read (dmlc_indexed_reader_skip).

    def state_dict(self) -> dict:
        return {"kind": "indexed_native", "records": self._records_out,
                "epochs": self._epochs}

    def load_state(self, state: dict) -> None:
        check(state.get("kind") == "indexed_native",
              "incompatible indexed-native split state")
        self.close()
        reader = self._ensure_reader()
        epochs = int(state.get("epochs", 0))
        n = int(state["records"])
        reader.skip(epochs, n)
        self._cursor_clear()
        self._epochs = epochs
        self._records_out = n


def native_feed_recordio_eligible(uri: str, threaded: bool, *, index_uri=None,
                                  shuffle: bool = False,
                                  num_shuffle_parts: int = 0,
                                  cache_file=None) -> bool:
    """True when create_input_split can route a REMOTE .rec corpus through
    the push-mode feeder (reader.cc push mode + recordio framing)."""
    from dmlc_tpu import native

    if not threaded or index_uri or shuffle or num_shuffle_parts or cache_file:
        return False
    try:
        fs = get_filesystem(uri)
    except DMLCError:
        return False
    if isinstance(fs, LocalFileSystem):
        return False  # local corpora take the pull-mode reader
    return native.available()


class NativeFeedRecordIOSplit(NativeRecordIOSplit):
    """Remote .rec corpora through the native pipeline: a Python feed
    thread range-reads this partition's bytes through the FileSystem layer
    (S3 / GCS / HTTP / HDFS) and pushes them into the C++ chunk feeder,
    which owns record-aligned chunking, framing scan, and multi-part
    reassembly off the GIL — the reference wraps EVERY source and record
    type in its threaded decorator the same way (src/io.cc:119-124).

    Partitioning (byte ranges, record-boundary adjustment at the 4-byte
    magic alignment) stays with the Python input-split engine, which
    already speaks every filesystem.
    """

    FEED_CHUNK = 1 << 20

    def __init__(self, uri: str, part_index: int, num_parts: int,
                 recurse_directories: bool = False,
                 chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                 queue_depth: int = 4):
        check(num_parts >= 1, f"num_parts must be >= 1, got {num_parts}")
        check(0 <= part_index < num_parts,
              f"part_index {part_index} out of range for {num_parts} parts")
        self.uri = uri
        self.recurse_directories = recurse_directories
        self.part_index = part_index
        self.num_parts = num_parts
        self.chunk_bytes = chunk_bytes
        self.queue_depth = queue_depth
        self._mode: Optional[int] = None
        self._reader = None
        self._payload: Optional[np.ndarray] = None
        self._offsets: Optional[np.ndarray] = None
        self._i = 0
        self._records_out = 0
        self._feed_thread = None

    def _make_split(self) -> RecordIOSplitter:
        split = RecordIOSplitter(get_filesystem(self.uri), self.uri,
                                 self.recurse_directories)
        split.reset_partition(self.part_index, self.num_parts)
        return split

    def _start_feed(self) -> None:
        import threading

        feeder = self._reader
        split = self._make_split()

        def run() -> None:
            try:
                while True:
                    data = split._read(self.FEED_CHUNK)
                    if not data or not feeder.push(data):
                        break
                feeder.finish()
            except Exception as exc:  # noqa: BLE001
                # a mid-stream remote failure must NOT look like EOF
                feeder.fail(f"feed failed: {exc}")
            finally:
                try:
                    split.close()
                except Exception:  # noqa: BLE001
                    pass

        self._feed_thread = threading.Thread(
            target=_telemetry.scoped_target(run),
            name="dmlc-rec-feed", daemon=True)
        self._feed_thread.start()

    def _stop_feed(self) -> None:
        if self._feed_thread is not None:
            if self._reader is not None:
                self._reader.abort()
            self._feed_thread.join()
            self._feed_thread = None

    def _ensure_reader(self, fmt: int):
        from dmlc_tpu import native

        if self._reader is None:
            self._mode = fmt
            self._reader = native.Feeder(
                fmt, chunk_bytes=self.chunk_bytes,
                queue_depth=self.queue_depth)
            self._start_feed()
        elif self._mode != fmt:
            raise DMLCError(
                "native recordio split: next_record and next_chunk cannot "
                "be mixed within one epoch")
        return self._reader

    def before_first(self) -> None:
        if self._reader is not None:
            self._stop_feed()
            self._reader.before_first()
            self._start_feed()
        self._payload = self._offsets = None
        self._i = 0
        self._records_out = 0

    def close(self) -> None:
        self._stop_feed()
        super().close()
