"""Native-backed RecordIO input split: read, framing scan, and multi-part
reassembly all run in C++ off the GIL (native/src/reader.cc format 4/5,
native/src/recordio.cc).

This is the TPU-first hot path for local .rec corpora (BASELINE.md config
#3, ImageNet-shaped): where the reference stacks a prefetch thread over the
RecordIOSplitter's chunk scan (src/io/threaded_input_split.h over
src/io/recordio_split.cc), this class delegates the identical pipeline to
the native core — one GIL-releasing pull per batch of extracted records.

``create_input_split`` routes eligible recordio URIs here (local files,
threaded, no cache/shuffle decorators); everything else takes the Python
engine, which shares partition semantics (both mirror input_split_base.cc +
recordio_split.cc and are A/B-tested row-for-row in
tests/test_native_reader.py).
"""

from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np

from dmlc_tpu.io.filesystem import LocalFileSystem, get_filesystem
from dmlc_tpu.io.input_split import (
    DEFAULT_CHUNK_BYTES,
    InputSplit,
    RecordIOSplitter,
)
from dmlc_tpu.utils.check import DMLCError, check


def native_recordio_eligible(uri: str, threaded: bool, *, index_uri=None,
                             shuffle: bool = False, num_shuffle_parts: int = 0,
                             cache_file=None,
                             recurse_directories: bool = False) -> bool:
    """True when create_input_split can route recordio to the native split."""
    from dmlc_tpu import native

    if not threaded or index_uri or shuffle or num_shuffle_parts or cache_file:
        return False
    try:
        fs = get_filesystem(uri)
    except DMLCError:
        return False
    if not isinstance(fs, LocalFileSystem):
        return False
    return native.available()


class NativeRecordIOSplit(InputSplit):
    """InputSplit facade over the native recordio reader.

    Serves either records (extracted payloads, multi-part reassembled) or
    raw record-aligned chunks — whichever the consumer asks for first; the
    two modes map to distinct native stream formats, so mixing them within
    one epoch raises instead of silently skipping data.
    """

    def __init__(self, uri: str, part_index: int, num_parts: int,
                 recurse_directories: bool = False,
                 chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                 queue_depth: int = 4):
        check(num_parts >= 1, f"num_parts must be >= 1, got {num_parts}")
        check(0 <= part_index < num_parts,
              f"part_index {part_index} out of range for {num_parts} parts")
        fs = get_filesystem(uri)
        check(isinstance(fs, LocalFileSystem),
              "native recordio split requires local files")
        # reuse the engine's file matching (';' lists, dirs, regex basenames)
        # AND its 4-byte alignment validation
        lister = RecordIOSplitter(fs, uri, recurse_directories)
        self.paths: List[str] = [info.path.name for info in lister.files]
        self.sizes: List[int] = [info.size for info in lister.files]
        self.part_index = part_index
        self.num_parts = num_parts
        self.chunk_bytes = chunk_bytes
        self.queue_depth = queue_depth
        self._mode: Optional[int] = None  # FMT_RECORDIO | FMT_RECORDIO_CHUNK
        self._reader = None
        self._payload: Optional[np.ndarray] = None
        self._offsets: Optional[np.ndarray] = None
        self._i = 0
        self._records_out = 0

    # ---------------- native reader lifecycle ----------------

    def _ensure_reader(self, fmt: int):
        from dmlc_tpu import native

        if self._reader is None:
            self._mode = fmt
            self._reader = native.Reader(
                self.paths, self.sizes, self.part_index, self.num_parts,
                fmt, chunk_bytes=self.chunk_bytes,
                queue_depth=self.queue_depth)
        elif self._mode != fmt:
            raise DMLCError(
                "native recordio split: next_record and next_chunk cannot "
                "be mixed within one epoch")
        return self._reader

    def _next_batch(self) -> bool:
        nxt = self._reader.next()
        if nxt is None:
            return False
        _, (payload, offsets) = nxt
        self._payload, self._offsets, self._i = payload, offsets, 0
        return True

    # ---------------- InputSplit interface ----------------

    def next_record(self) -> Optional[memoryview]:
        from dmlc_tpu import native

        self._ensure_reader(native.FMT_RECORDIO)
        while (self._offsets is None
               or self._i >= len(self._offsets) - 1):
            if not self._next_batch():
                return None
        s = int(self._offsets[self._i])
        e = int(self._offsets[self._i + 1])
        self._i += 1
        self._records_out += 1
        return memoryview(self._payload)[s:e]

    def next_chunk(self) -> Optional[memoryview]:
        from dmlc_tpu import native

        self._ensure_reader(native.FMT_RECORDIO_CHUNK)
        if not self._next_batch():
            return None
        self._records_out += 1
        return memoryview(self._payload)

    def before_first(self) -> None:
        if self._reader is not None:
            self._reader.before_first()
        self._payload = self._offsets = None
        self._i = 0
        self._records_out = 0
        self._mode = None if self._reader is None else self._mode

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        check(num_parts >= 1, f"num_parts must be >= 1, got {num_parts}")
        check(0 <= part_index < num_parts,
              f"part_index {part_index} out of range for {num_parts} parts")
        self.close()
        self.part_index = part_index
        self.num_parts = num_parts
        self._mode = None
        self._payload = self._offsets = None
        self._i = 0
        self._records_out = 0

    def hint_chunk_size(self, chunk_size: int) -> None:
        if chunk_size > self.chunk_bytes:
            self.chunk_bytes = chunk_size

    @property
    def bytes_read(self) -> int:
        return self._reader.bytes_read if self._reader is not None else 0

    # -------- checkpoint / resume (count-based, like NativeStreamParser) ----

    def state_dict(self) -> dict:
        return {"kind": "records", "records": self._records_out,
                "mode": self._mode}

    def load_state(self, state: dict) -> None:
        check(state.get("kind") == "records", "incompatible split state")
        self.before_first()
        n = int(state["records"])
        mode = state.get("mode")
        for _ in range(n):
            got = (self.next_chunk() if mode == _chunk_mode()
                   else self.next_record())
            if got is None:
                break
        self._records_out = n

    def close(self) -> None:
        if self._reader is not None:
            self._reader.close()
            self._reader = None


def _chunk_mode() -> int:
    from dmlc_tpu import native

    return native.FMT_RECORDIO_CHUNK
