"""RecordIO: splittable binary record format.

Behavioral equivalent of reference include/dmlc/recordio.h +
src/recordio.cc. Wire format (recordio.h:17-45):

    [magic u32 LE][lrecord u32 LE][data][zero pad to 4-byte alignment]

- ``magic == 0xced7230a`` (recordio.h:45); note ``(magic >> 29) & 7 == 6 > 3``
  so an lrecord can never equal the magic.
- ``lrecord = (cflag << 29) | length`` with ``length < 2**29``
  (EncodeLRec, recordio.h:52-54).
- cflag 0: complete record; 1/2/3: start/middle/end of a multi-part record
  (recordio.h:33-36). Multi-part records arise when the data itself contains
  the magic u32 at a 4-byte-aligned offset: the writer splits the payload at
  each aligned magic cell and drops the cell; the reader re-inserts the magic
  between parts (recordio.cc:22-45, 74-79).

The magic scan is vectorized with numpy instead of the reference's per-cell
char loop (recordio.cc:22-27) — same escape positions, faster in Python.
"""

from __future__ import annotations

import struct
from typing import BinaryIO, Iterator, List, Optional, Tuple

import numpy as np

from dmlc_tpu.utils.check import DMLCError, check

RECORDIO_MAGIC = 0xCED7230A
_MAGIC_BYTES = struct.pack("<I", RECORDIO_MAGIC)
_MAX_LEN = 1 << 29


def encode_lrec(cflag: int, length: int) -> int:
    return (cflag << 29) | length


def decode_flag(lrec: int) -> int:
    return (lrec >> 29) & 7


def decode_length(lrec: int) -> int:
    return lrec & (_MAX_LEN - 1)


def _aligned_magic_positions(data: bytes) -> np.ndarray:
    """4-aligned offsets where the payload contains the magic u32."""
    lower = (len(data) >> 2) << 2
    if lower == 0:
        return np.empty(0, dtype=np.int64)
    cells = np.frombuffer(data, dtype="<u4", count=lower >> 2)
    return np.flatnonzero(cells == RECORDIO_MAGIC).astype(np.int64) << 2


class RecordIOWriter:
    """Analog of dmlc::RecordIOWriter (recordio.cc:11-51)."""

    def __init__(self, stream: BinaryIO):
        self.stream = stream
        self.except_counter = 0  # number of magic-collision escapes performed

    def write_record(self, data: bytes) -> None:
        check(len(data) < _MAX_LEN, "RecordIO only accepts records < 2^29 bytes")
        positions = _aligned_magic_positions(data)
        dptr = 0
        out = self.stream
        for pos in positions:
            pos = int(pos)
            cflag = 1 if dptr == 0 else 2
            out.write(_MAGIC_BYTES)
            out.write(struct.pack("<I", encode_lrec(cflag, pos - dptr)))
            if pos != dptr:
                out.write(data[dptr:pos])
            dptr = pos + 4
            self.except_counter += 1
        cflag = 3 if dptr != 0 else 0
        out.write(_MAGIC_BYTES)
        out.write(struct.pack("<I", encode_lrec(cflag, len(data) - dptr)))
        if len(data) != dptr:
            out.write(data[dptr:])
        pad = (-len(data) + dptr) % 4
        # pad the final part to 4-byte alignment with zeros (recordio.cc:46-50)
        if pad:
            out.write(b"\x00" * pad)

    def tell(self) -> int:
        return self.stream.tell()


class RecordIOReader:
    """Analog of dmlc::RecordIOReader (recordio.cc:53-82)."""

    def __init__(self, stream: BinaryIO):
        self.stream = stream
        self._eos = False

    def next_record(self) -> Optional[bytes]:
        """Next logical record, multi-part frames reassembled; None at EOF."""
        if self._eos:
            return None
        parts: List[bytes] = []
        while True:
            header = self.stream.read(8)
            if len(header) == 0:
                self._eos = True
                return None
            check(len(header) == 8, "Invalid RecordIO File")
            magic, lrec = struct.unpack("<II", header)
            check(magic == RECORDIO_MAGIC, "Invalid RecordIO File")
            cflag = decode_flag(lrec)
            length = decode_length(lrec)
            upper = (length + 3) & ~3
            payload = self.stream.read(upper)
            check(len(payload) == upper, "Invalid RecordIO File (truncated payload)")
            parts.append(payload[:length])
            if cflag in (0, 3):
                break
            parts.append(_MAGIC_BYTES)
        return b"".join(parts)

    def __iter__(self) -> Iterator[bytes]:
        while True:
            rec = self.next_record()
            if rec is None:
                return
            yield rec


def find_record_heads(buf: bytes | memoryview) -> np.ndarray:
    """4-aligned offsets of record heads (magic + cflag in {0,1}) in ``buf``.

    Vectorized analog of FindNextRecordIOHead (recordio.cc:85-99): a head is
    an aligned magic cell whose following lrec cell has cflag 0 or 1.
    """
    mv = memoryview(buf)
    lower = (len(mv) >> 2) << 2
    if lower < 8:
        return np.empty(0, dtype=np.int64)
    cells = np.frombuffer(mv[:lower], dtype="<u4")
    is_magic = cells[:-1] == RECORDIO_MAGIC
    flags = (cells[1:] >> 29) & 7
    heads = np.flatnonzero(is_magic & (flags <= 1)).astype(np.int64) << 2
    return heads


class RecordIOChunkReader:
    """Extract records from one chunk blob, optionally sub-partitioned.

    Analog of dmlc::RecordIOChunkReader (recordio.cc:101-156): used to split
    one chunk across N parser threads (part_index/num_parts sub-partition with
    4-byte-aligned nstep, head-seek at both ends).
    """

    def __init__(self, chunk: bytes | memoryview, part_index: int = 0, num_parts: int = 1):
        chunk = memoryview(chunk)
        size = len(chunk)
        nstep = ((size + num_parts - 1) // num_parts + 3) & ~3
        begin = min(size, nstep * part_index)
        end = min(size, nstep * (part_index + 1))
        self._chunk = chunk
        self._begin = self._seek_head(chunk, begin)
        self._end = self._seek_head(chunk, end)

    @staticmethod
    def _seek_head(chunk: memoryview, start: int) -> int:
        # windowed scan: stop at the first head instead of scanning the whole
        # tail (the reference's FindNextRecordIOHead also stops early)
        n = len(chunk)
        window = 1 << 16
        pos = start
        while pos < n:
            stop = min(pos + window + 8, n)  # +8: catch a head spanning the edge
            heads = find_record_heads(chunk[pos:stop])
            if len(heads):
                return pos + int(heads[0])
            pos += window
        return n

    def next_record(self) -> Optional[memoryview | bytes]:
        """Next record payload; multi-part records are reassembled to bytes."""
        if self._begin >= self._end:
            return None
        rec, self._begin = extract_record(self._chunk, self._begin, self._end)
        return rec

    def __iter__(self):
        while True:
            rec = self.next_record()
            if rec is None:
                return
            yield rec


def extract_record(chunk: memoryview, begin: int, end: int) -> Tuple[memoryview | bytes, int]:
    """Parse one (possibly multi-part) record at ``begin``; return (payload, next).

    Shared by RecordIOChunkReader and the RecordIO input splitter
    (recordio_split.cc:44-82 does the same with in-place memmove; we return
    a zero-copy memoryview for whole records and joined bytes for the rare
    escaped multi-part case).
    """
    check(begin + 8 <= end, "Invalid RecordIO Format")
    magic, lrec = struct.unpack_from("<II", chunk, begin)
    check(magic == RECORDIO_MAGIC, "Invalid RecordIO Format")
    cflag = decode_flag(lrec)
    length = decode_length(lrec)
    payload_end = begin + 8 + length
    cursor = begin + 8 + ((length + 3) & ~3)
    check(cursor <= end, "Invalid RecordIO Format")
    if cflag == 0:
        return chunk[begin + 8: payload_end], cursor
    check(cflag == 1, "Invalid RecordIO Format")
    parts: List[bytes] = [bytes(chunk[begin + 8: payload_end])]
    while cflag != 3:
        check(cursor + 8 <= end, "Invalid RecordIO Format")
        magic, lrec = struct.unpack_from("<II", chunk, cursor)
        check(magic == RECORDIO_MAGIC, "Invalid RecordIO Format")
        cflag = decode_flag(lrec)
        length = decode_length(lrec)
        parts.append(_MAGIC_BYTES)
        parts.append(bytes(chunk[cursor + 8: cursor + 8 + length]))
        cursor += 8 + ((length + 3) & ~3)
    return b"".join(parts), cursor


# ---------------- indexed recordio helpers ----------------

def write_indexed_recordio(data_stream: BinaryIO, index_stream, records) -> int:
    """Write records + a text ``index offset`` index file.

    The index format is whitespace ``index offset`` pairs per line, as read
    by IndexedRecordIOSplitter::ReadIndexFile (indexed_recordio_split.cc:43-62).
    Returns the number of records written.
    """
    writer = RecordIOWriter(data_stream)
    n = 0
    for i, rec in enumerate(records):
        offset = data_stream.tell()
        line = f"{i} {offset}\n"
        try:
            index_stream.write(line.encode())
        except TypeError:  # text-mode index stream
            index_stream.write(line)
        writer.write_record(rec)
        n += 1
    return n


def read_index_file(stream: BinaryIO, total_bytes: int) -> List[Tuple[int, int]]:
    """Parse index file into sorted (offset, size) pairs.

    Mirrors ReadIndexFile (indexed_recordio_split.cc:43-62): offsets are
    sorted; each record's size is the gap to the next offset, the last one
    extends to ``total_bytes``.
    """
    text = stream.read()
    if isinstance(text, bytes):
        text = text.decode()
    offsets: List[int] = []
    tokens = text.split()
    if len(tokens) % 2 != 0:
        raise DMLCError("index file: expected 'index offset' pairs")
    for i in range(1, len(tokens), 2):
        offsets.append(int(tokens[i]))
    if not offsets:
        raise DMLCError("index file: empty")
    offsets.sort()
    out: List[Tuple[int, int]] = []
    for j in range(len(offsets) - 1):
        out.append((offsets[j], offsets[j + 1] - offsets[j]))
    out.append((offsets[-1], total_bytes - offsets[-1]))
    return out
