"""Parse-once columnar RowBlock cache: the on-disk format, writer, reader.

The chunk cache (:mod:`dmlc_tpu.io.cached_split`) caches raw bytes BEFORE
the parser, so warm passes still re-pay the full text-parse cost every
epoch. This module caches AFTER the parser — the highest-leverage point in
the pipeline per tf.data's ``cache()`` study (arXiv:2101.12127 §5) and the
preprocessing/training decoupling argument of the tf.data-service paper
(arXiv:2210.14826): the first (cold) epoch shadow-writes each parsed
block's columnar arrays; warm epochs serve the arrays back as zero-copy
mmap-backed numpy views, bypassing the parser entirely.

This module owns the FORMAT only — it moves named 1-D numpy segments, not
RowBlocks (the RowBlock <-> segments conversion lives in
:meth:`dmlc_tpu.data.row_block.RowBlock.to_segments`, keeping the io layer
free of data-layer imports). The pipeline integration —
``BlockCacheIter`` — lives in :mod:`dmlc_tpu.data.parsers`.

Format v1 (pinned by ``tests/data/blockcache_v1.golden``)::

    [header]   magic "DMLCBC01" (8B) + version u32 LE + 4 zero pad bytes
    [segments] per block, per present array: raw little-endian bytes,
               each array start padded to 64-byte alignment (mmap-friendly
               for numpy views)
    [footer]   utf-8 JSON (sort_keys): {"version", "signature", "num_col",
               "rows", "blocks": [{"pos", "end", "rows", "crc", "resume",
               "arrays": {name: [dtype_str, abs_offset, nbytes]}}, ...]}
    [tail]     u64 footer_offset + u64 footer_len + u32 footer_crc LE
               + magic "DMLCBC01"

Integrity: each block carries a crc32 over its whole ``[pos, end)`` span
(checked on every warm read — zlib crc runs at GB/s, noise next to the
text parse it replaces), the footer carries its own crc, and both file
ends carry the magic so truncation is detected structurally. The writer
streams to a store-allocated staging file and publishes through the
tiered artifact store (:mod:`dmlc_tpu.store`: fsync + atomic rename +
manifest record + byte-budget enforcement) — a crash can never leave a
torn-but-valid-looking cache, and readers pin the cache they serve so
eviction can never take a tier away mid-epoch (docs/store.md).

Staleness: a cache is keyed by a **source signature** (file sizes+mtimes,
partition ``splitN.partK``, parser/format/engine config —
:func:`source_signature`). :func:`open_block_cache` returns ``None`` for a
missing, unreadable, or signature-mismatched cache (dropping the stale
file and counting a ``cache_invalidations`` resilience event), so callers
simply rebuild.
"""

from __future__ import annotations

import json
import mmap
import os
import struct
import zlib
from typing import Dict, List, Optional

import numpy as np

from dmlc_tpu.io import faults
from dmlc_tpu.io import resilience as _resilience
from dmlc_tpu.utils import telemetry as _telemetry
from dmlc_tpu.utils.check import CacheCorruptionError, DMLCError, check
from dmlc_tpu.utils.timer import get_time

BLOCK_CACHE_MAGIC = b"DMLCBC01"
BLOCK_CACHE_VERSION = 1


def _store_manager():
    """Lazy import of the tiered-store manager (it sits above the
    resilience/telemetry layers, so the io formats bind to it at call
    time, never at package init)."""
    from dmlc_tpu.store import manager

    return manager


def _artifact_store(path: str):
    """The :class:`~dmlc_tpu.store.manager.ArtifactStore` owning
    ``path``'s directory."""
    return _store_manager().store_for(path)
_TAIL_FMT = "<QQI"  # footer offset, footer length, footer crc32
_TAIL_LEN = struct.calcsize(_TAIL_FMT) + len(BLOCK_CACHE_MAGIC)
_ALIGN = 64

# canonical segment order (fixed so the golden layout is deterministic);
# optional arrays are simply absent from a block's footer entry
SEGMENT_NAMES = ("offset", "label", "weight", "qid", "field", "index", "value")


def container_header(magic: bytes, version: int) -> bytes:
    """The shared v1 container header: 8-byte magic + u32 LE version +
    4 zero pad bytes — one builder for every DMLC segment container
    (block cache, device-native snapshot)."""
    check(len(magic) == 8, "container magic must be 8 bytes")
    return magic + struct.pack("<I", version) + b"\0" * 4


_HEADER = container_header(BLOCK_CACHE_MAGIC, BLOCK_CACHE_VERSION)


def _pad_to(f, align: int) -> int:
    pos = f.tell()
    rem = pos % align
    if rem:
        f.write(b"\0" * (align - rem))
        pos += align - rem
    return pos


def write_segments(f, segments: Dict[str, Optional[np.ndarray]],
                   crc: int = 0, names=SEGMENT_NAMES) -> tuple:
    """Serialize the present ``names`` arrays (default
    :data:`SEGMENT_NAMES`) at ``f``'s current (already-aligned) position —
    the v1 segment encoding shared by the on-disk cache block, the
    data-service wire frame (:mod:`dmlc_tpu.service.frame`), and the
    device-native snapshot store (:mod:`dmlc_tpu.io.snapshot`, which
    passes its own positional name order): canonical order, each array
    start padded to 64-byte alignment, raw little-endian C-order bytes,
    one crc32 rolling over padding + payload. Returns ``(end, crc,
    arrays)`` with ``arrays`` mapping name ->
    ``[dtype_str, abs_offset, nbytes]`` (the footer/meta schema every
    container stores)."""
    arrays: Dict[str, list] = {}
    for name in names:
        arr = segments.get(name)
        if arr is None:
            continue
        arr = np.ascontiguousarray(arr)
        start = f.tell()
        rem = start % _ALIGN
        if rem:
            padding = b"\0" * (_ALIGN - rem)
            f.write(padding)
            crc = zlib.crc32(padding, crc)
            start += len(padding)
        raw = arr.tobytes()  # canonical C-order little-endian payload
        f.write(raw)
        crc = zlib.crc32(raw, crc)
        # extension dtypes (ml_dtypes bfloat16 in snapshot segments) read
        # as void through .str ('<V2') — their registered NAME round-trips
        # through np.dtype(); standard dtypes keep .str (golden-pinned)
        dtype_str = (arr.dtype.str if arr.dtype.kind != "V"
                     else arr.dtype.name)
        arrays[name] = [dtype_str, start, len(raw)]
    return f.tell(), crc & 0xFFFFFFFF, arrays


def _segment_dtype(dtype_str: str) -> np.dtype:
    """Resolve a stored segment dtype. Extension names ('bfloat16') only
    resolve once ml_dtypes has registered them — a client process that
    never imported jax (e.g. a host-block service consumer decoding bf16
    snapshot frames) must not crash on the lookup."""
    try:
        return np.dtype(dtype_str)
    except TypeError:
        import ml_dtypes  # noqa: F401 - import registers the dtypes

        return np.dtype(dtype_str)


def read_segments(buf, arrays: Dict[str, list]) -> Dict[str, np.ndarray]:
    """Decode a :func:`write_segments` ``arrays`` mapping over ``buf``
    (an mmap or bytes) into {name: zero-copy numpy view} — shared by the
    warm cache reader, the service frame decoder, and the snapshot
    reader."""
    out: Dict[str, np.ndarray] = {}
    for name, (dtype_str, off, nbytes) in arrays.items():
        dt = _segment_dtype(dtype_str)
        out[name] = np.frombuffer(buf, dtype=dt,
                                  count=nbytes // dt.itemsize,
                                  offset=int(off))
    return out


def span_layout(arrays: Dict[str, list], shapes=None, base: int = 0):
    """A batch's footer/frame ``arrays`` (+ optional ``shapes``) mapping
    as a hashable span layout: ``((name, dtype_str, rel_offset, nbytes,
    shape), ...)`` with offsets rebased to ``base`` (the batch's ``pos``
    for an on-disk container span, 0 for a wire-frame payload). The
    compile-time constant :func:`dmlc_tpu.ops.device_decode.decode_span`
    slices and bitcasts a verbatim-transferred u8 span by — built here
    (jax-free, beside the footer schema it reads) so snapshot readers
    and service frame decoders share one definition."""
    entries = []
    for name, (dtype_str, off, nbytes) in arrays.items():
        shape = (shapes or {}).get(name)
        dt = _segment_dtype(dtype_str)
        shape = (tuple(int(d) for d in shape) if shape is not None
                 else (int(nbytes) // dt.itemsize,))
        entries.append((str(name), str(dtype_str), int(off) - int(base),
                        int(nbytes), shape))
    return tuple(entries)


def finish_container(f, tmp_path: str, path: str, footer: dict,
                     magic: bytes) -> None:
    """The shared publish tail: write the crc'd JSON ``footer`` + tail
    record + closing ``magic``, then publish through the artifact store
    (:mod:`dmlc_tpu.store` — fsync + atomic rename + manifest record +
    byte-budget enforcement). One implementation so a crash can never
    leave a torn-but-valid-looking container of either format."""
    payload = json.dumps(footer, sort_keys=True,
                         separators=(",", ":")).encode()
    off = _pad_to(f, _ALIGN)
    f.write(payload)
    f.write(struct.pack(_TAIL_FMT, off, len(payload),
                        zlib.crc32(payload) & 0xFFFFFFFF))
    f.write(magic)
    _artifact_store(path).publish_file(
        tmp_path, path, tier=_store_manager().tier_for_magic(magic),
        signature=footer.get("signature"), fobj=f)


def open_container(path: str, magic: bytes, version: int, what: str):
    """mmap a published container and verify its structure (header magic +
    version, tail magic, footer crc): the shared open half of
    :func:`finish_container`. Returns ``(file, mmap, footer_dict)``;
    raises :class:`DMLCError` — with the file/mmap already closed — on
    any structural problem."""
    header = container_header(magic, version)
    f = mm = None
    try:
        size = os.path.getsize(path)
        check(size >= len(header) + _TAIL_LEN, f"{what}: too short")
        f = open(path, "rb")
        mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
    except (OSError, DMLCError) as exc:
        if mm is not None:
            mm.close()
        if f is not None:
            f.close()  # the fd must not leak when the mmap fails
        raise DMLCError(f"{what}: unreadable: {exc}") from exc
    try:
        head = mm[: len(header)]
        check(head[:8] == magic, f"{what}: bad magic")
        (ver,) = struct.unpack("<I", head[8:12])
        check(ver == version, f"{what}: version {ver} != {version}")
        tail = mm[size - _TAIL_LEN:]
        check(tail[-8:] == magic, f"{what}: truncated (no tail magic)")
        off, length, crc = struct.unpack(
            _TAIL_FMT, tail[: struct.calcsize(_TAIL_FMT)])
        check(off + length <= size - _TAIL_LEN,
              f"{what}: footer out of range")
        with memoryview(mm)[off: off + length] as mv:
            payload_crc = zlib.crc32(mv) & 0xFFFFFFFF
            payload = bytes(mv)  # json needs bytes; footer is small
        check(payload_crc == crc, f"{what}: footer crc mismatch")
        return f, mm, json.loads(payload)
    except Exception:
        try:
            mm.close()
        except BufferError:  # pragma: no cover - no views exported yet
            pass
        f.close()
        raise


class BlockCacheWriter:
    """Streams checksummed columnar block segments to a store-allocated
    staging file; :meth:`finish` writes the footer and publishes through
    the artifact store (fsync + atomic rename + manifest + budget)."""

    def __init__(self, path: str, signature: Optional[dict] = None):
        self.path = path
        self._sig = signature or {}
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        # process-unique staging name from the store: two writers racing
        # the same path (concurrent service workers) can never clobber
        # each other's half-written bytes (docs/store.md)
        self.tmp_path = _artifact_store(path).stage_path(path)
        self._f = open(self.tmp_path, "wb")
        self._f.write(_HEADER)
        self._entries: List[dict] = []
        self._num_col = 0
        self._rows = 0
        self._finished = False

    def add_block(self, segments: Dict[str, Optional[np.ndarray]],
                  rows: int, num_col: int = 0,
                  resume: Optional[dict] = None) -> None:
        """Append one block. ``segments`` maps :data:`SEGMENT_NAMES` to 1-D
        arrays (``None`` = absent); ``resume`` is the block's JSON-friendly
        resume annotation (position just after the block), stored so warm
        epochs can re-attach byte-exact checkpoint states."""
        check(self._f is not None and not self._finished,
              "BlockCacheWriter: writer already finished/aborted")
        t_span = get_time()
        f = self._f
        pos = _pad_to(f, _ALIGN)
        end, crc, arrays = write_segments(f, segments)
        self._append_entry(t_span, pos, end, crc, arrays, rows, num_col,
                           resume)

    def _append_entry(self, t_span, pos, end, crc, arrays, rows, num_col,
                      resume) -> None:
        """Shared bookkeeping tail of both append paths (resume JSON
        normalization, footer entry, totals, cache_write span) — one
        source of truth so the two write paths cannot drift a footer
        apart."""
        # resume annotations round-trip through JSON (tuples -> lists,
        # dict order normalized) so cold- and warm-served states compare
        # equal byte for byte
        resume_json = (json.loads(json.dumps(resume))
                       if resume is not None else None)
        self._entries.append({
            "pos": pos, "end": end, "rows": int(rows),
            "crc": crc, "resume": resume_json,
            "arrays": arrays,
        })
        self._rows += int(rows)
        self._num_col = max(self._num_col, int(num_col))
        # the shadow-write's own cost, visible on the trace timeline next
        # to the parse spans it rides behind (cold-epoch overhead is a
        # real stage even though stats() folds it into supply wall)
        _telemetry.record_span("cache_write", t_span, get_time() - t_span,
                               rows=int(rows))

    def add_block_encoded(self, encoded, resume: Optional[dict] = None) -> None:
        """Append one PRE-ENCODED block span — the zero re-encode cold
        path. ``encoded`` is an
        :class:`~dmlc_tpu.data.batch_parser.EncodedSegments`: the native
        batch parser already materialized the exact ``[pos, end)`` bytes
        this writer would produce (canonical segment order, 64-byte
        alignment, zero gap bytes) plus the span's zlib-compatible crc32
        and the footer ``arrays`` schema, so the tee is ONE buffer write
        and offset translation — no per-array ``tobytes`` copies, no
        Python-side crc pass. Byte-identical output to
        :meth:`add_block` on the same block (golden-pinned)."""
        check(self._f is not None and not self._finished,
              "BlockCacheWriter: writer already finished/aborted")
        t_span = get_time()
        f = self._f
        pos = _pad_to(f, _ALIGN)
        f.write(encoded.data)
        arrays = {name: [dt, pos + int(off), int(nb)]
                  for name, (dt, off, nb) in encoded.arrays.items()}
        self._append_entry(t_span, pos, pos + int(encoded.nbytes),
                           int(encoded.crc), arrays, encoded.rows,
                           encoded.num_col, resume)

    def finish(self) -> None:
        """Write footer + tail, fsync, atomically publish at ``path``."""
        check(self._f is not None and not self._finished,
              "BlockCacheWriter: writer already finished/aborted")
        f = self._f
        footer = {
            "version": BLOCK_CACHE_VERSION,
            "signature": self._sig,
            "num_col": self._num_col,
            "rows": self._rows,
            "blocks": self._entries,
        }
        finish_container(f, self.tmp_path, self.path, footer,
                         BLOCK_CACHE_MAGIC)
        self._f = None
        self._finished = True

    def abort(self) -> None:
        """Drop the partial tmp file (interrupted cold pass)."""
        if self._f is not None:
            self._f.close()
            self._f = None
        try:
            os.remove(self.tmp_path)
        except OSError:
            pass

    def close(self) -> None:
        if not self._finished:
            self.abort()


class BlockCacheReader:
    """mmap-backed reader: blocks decode to zero-copy numpy views.

    Views returned by :meth:`load_segments` alias the mmap — callers keep
    the reader's ``buffer`` (exposed as ``hold``) alive for as long as the
    views are; the mmap itself is closed only by GC once every view died.
    """

    def __init__(self, path: str, signature: Optional[dict] = None,
                 verify: bool = True):
        self.path = path
        self.verify = verify
        self._store_pinned = False
        self._file, self._mm, footer = open_container(
            path, BLOCK_CACHE_MAGIC, BLOCK_CACHE_VERSION,
            f"block cache {path}")
        try:
            self.signature = footer.get("signature") or {}
            self.num_col = int(footer.get("num_col", 0))
            self.rows = int(footer.get("rows", 0))
            self._blocks = footer["blocks"]
            if signature is not None and self.signature != _normalize(
                    signature):
                raise DMLCError(
                    f"block cache {path}: source signature mismatch "
                    f"(stale cache)")
            # pin/refcount (docs/store.md): while this reader serves the
            # cache, a byte-budget squeeze may never evict it — a warm
            # epoch cannot lose its tier mid-epoch. Dropped at close().
            _artifact_store(path).pin(path)
            self._store_pinned = True
        except Exception:
            self.close()
            raise

    # ---------------- accessors ----------------

    @property
    def num_blocks(self) -> int:
        return len(self._blocks)

    @property
    def hold(self):
        """The buffer owner views must pin (the mmap)."""
        return self._mm

    def resume(self, i: int) -> Optional[dict]:
        """The stored resume annotation of block ``i`` (position just
        after it), or None when the producing parser had none."""
        return self._blocks[i]["resume"]

    def block_rows(self, i: int) -> int:
        return int(self._blocks[i]["rows"])

    def block_nbytes(self, i: int) -> int:
        e = self._blocks[i]
        return int(e["end"]) - int(e["pos"])

    def load_segments(self, i: int,
                      copy: bool = False) -> Dict[str, np.ndarray]:
        """Decode block ``i`` to {name: zero-copy read-only numpy view}.

        ``copy=True`` materializes the arrays into process memory instead
        (no ``hold`` needed): plan-ordered warm epochs serve blocks in a
        permuted pattern the OS readahead cannot predict, and the copy
        forces the page faults to land HERE — inside the caller's timed
        ``cache_read`` region — instead of leaking into whichever
        downstream stage first touches the lazy views (the same
        attribution class of bug PR 6 fixed for the serial path).

        Raises :class:`CacheCorruptionError` on a crc mismatch (or when a
        ``cache_read`` fault is injected) — callers heal by dropping the
        cache and re-parsing the source.
        """
        faults.maybe_fail("cache_read", self.path)
        entry = self._blocks[i]
        if self.verify:
            # checksum straight off the page cache: slicing the mmap would
            # memcpy the whole block span; a memoryview slice does not
            with memoryview(self._mm)[
                    int(entry["pos"]): int(entry["end"])] as span:
                ok = zlib.crc32(span) & 0xFFFFFFFF == int(entry["crc"])
            if not ok:
                raise CacheCorruptionError(
                    f"block cache {self.path}: crc mismatch on block {i}")
        segments = read_segments(self._mm, entry["arrays"])
        if copy:
            segments = {k: np.array(v) for k, v in segments.items()}
        return segments

    def block_encoded(self, i: int):
        """Block ``i``'s contiguous segment span as an
        :class:`~dmlc_tpu.data.batch_parser.EncodedSegments` view over
        the mmap — ZERO-COPY span export. A parse worker serving a warm
        cache hands this straight to the wire encoder (the frame payload
        IS the cache span, no per-array ``tobytes`` re-buffering) and a
        vectored send ships the mmap pages themselves. The view aliases
        the mmap via ``hold``; keep the reader open while it lives."""
        from dmlc_tpu.data.batch_parser import EncodedSegments

        entry = self._blocks[i]
        pos, end = int(entry["pos"]), int(entry["end"])
        span = memoryview(self._mm)[pos:end]
        arrays = {name: (dt, int(off) - pos, int(nb))
                  for name, (dt, off, nb) in entry["arrays"].items()}
        return EncodedSegments(
            data=span, arrays=arrays, crc=int(entry["crc"]),
            rows=int(entry["rows"]),
            num_col=self.num_col, hold=self._mm)

    def close(self) -> None:
        # the eviction pin drops first, unconditionally — even when
        # exported views keep the mmap alive (an unlinked-but-mapped file
        # keeps serving on POSIX, so releasing the pin is always safe)
        if getattr(self, "_store_pinned", False):
            self._store_pinned = False
            try:
                _artifact_store(self.path).drop(self.path)
            except OSError:
                pass
        # best-effort: the mmap cannot close while exported views are
        # alive (BufferError) — GC reclaims it once the last view dies
        mm = getattr(self, "_mm", None)
        if mm is not None:
            try:
                mm.close()
                self._mm = None
            except BufferError:
                pass
        f = getattr(self, "_file", None)
        if f is not None:
            self._file = None
            f.close()


# ---------------- cache-key signature + open helper ----------------

def _normalize(obj):
    """JSON round-trip: the stored signature is what JSON preserves."""
    return json.loads(json.dumps(obj, sort_keys=True))


def source_signature(uri: str, part_index: int, num_parts: int,
                     **config) -> dict:
    """The staleness key a block cache is bound to.

    Captures the source file set with sizes and mtimes (local paths; remote
    URIs record sizes via the filesystem layer, mtime ``None``), the
    partition identity, and whatever parser/format/engine ``config`` the
    caller passes — any drift invalidates the cache on open.
    """
    base = uri.split("#", 1)[0].split("?", 1)[0]
    files: List[list] = []
    for part in base.split(";"):
        if not part:
            continue
        local = part[7:] if part.startswith("file://") else (
            part if "://" not in part else None)
        if local is not None:
            if os.path.isdir(local):
                for name in sorted(os.listdir(local)):
                    fp = os.path.join(local, name)
                    if os.path.isfile(fp):
                        st = os.stat(fp)
                        files.append([fp, st.st_size, st.st_mtime_ns])
            elif os.path.exists(local):
                st = os.stat(local)
                files.append([local, st.st_size, st.st_mtime_ns])
            else:
                files.append([part, None, None])
            continue
        try:  # remote: sizes from the filesystem layer, no mtimes
            from dmlc_tpu.io.filesystem import get_filesystem
            from dmlc_tpu.io.uri import URI

            fs = get_filesystem(part)
            info = fs.get_path_info(URI(part))
            if info.type == "directory":
                for f in fs.list_directory(info.path):
                    if f.type == "file":
                        files.append([str(f.path), f.size, None])
            else:
                files.append([str(info.path), info.size, None])
        except Exception:  # noqa: BLE001 - unreachable source: path-only key
            files.append([part, None, None])
    return _normalize({
        "cache_version": BLOCK_CACHE_VERSION,
        "files": files,
        "partition": [int(part_index), int(num_parts)],
        "config": config,
    })


def open_block_cache(path: str, signature: Optional[dict] = None,
                     verify: bool = True) -> Optional[BlockCacheReader]:
    """Open a published cache, or None when it is missing or must be
    rebuilt (unreadable / wrong version / signature mismatch — the stale
    file is dropped via the store and a ``cache_invalidations``
    resilience event counted). A miss on a path the store manifest marks
    as EVICTED counts a ``store_rebuilds_after_eviction`` event — the
    rebuild the caller now runs is the budget's doing (docs/store.md)."""
    if not os.path.exists(path):
        # light probe: only consults the store when the directory already
        # carries a manifest (never creates state for an unmanaged dir)
        _store_manager().note_missing(path)
        return None
    try:
        return BlockCacheReader(path, signature=signature, verify=verify)
    except DMLCError:
        _resilience.record_event("cache_invalidations")
        _artifact_store(path).discard(path)
        return None
