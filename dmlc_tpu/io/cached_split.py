"""Chunk-cache decorator for input splits.

Equivalent of reference src/io/cached_input_split.h: the first pass serves
chunks while writing them to a local cache file (``[u64 size][bytes]``
frames, InitPreprocIter, cached_input_split.h:148-164); later passes stream
straight from the cache (InitCachedIter, cached_input_split.h:166-189),
skipping filesystem/remote reads entirely. Selected by a ``#cachefile`` URI
suffix (src/io.cc:119-123) with the partition-qualified ``.splitN.partK``
name from URISpec.

Improvement over the reference: the cache is written to ``<file>.tmp`` and
renamed on completion, so a crashed first pass can never leave a truncated
cache that later passes would read as valid.
"""

from __future__ import annotations

import os
import struct
from typing import Iterator, Optional

from dmlc_tpu.io.input_split import InputSplit, InputSplitBase, _Chunk
from dmlc_tpu.io.threaded_iter import ThreadedIter
from dmlc_tpu.utils.check import DMLCError, check


class CachedInputSplit(InputSplit):
    """Serve-and-cache on the first pass, cache-only afterwards.

    ``base`` may be a live InputSplitBase or a zero-arg factory for one; the
    factory is only invoked when the cache is missing, so a warm cache never
    touches the source filesystem (the files may be gone or remote).
    """

    def __init__(self, base, cache_file: str, capacity: int = 16,
                 splitter_cls=None):
        self._base_factory = base if callable(base) else (lambda: base)
        self._base: Optional[InputSplitBase] = base if not callable(base) else None
        self._splitter_cls = splitter_cls or (type(self._base) if self._base else None)
        check(self._splitter_cls is not None,
              "CachedInputSplit: a factory base requires splitter_cls for "
              "cache-only record extraction")
        self._detached: Optional[InputSplitBase] = None
        self.cache_file = cache_file
        self._tmp_file = cache_file + ".tmp"
        self._capacity = capacity
        self._chunk: Optional[_Chunk] = None
        self._iter: Optional[ThreadedIter] = None
        self._mode = "cached" if os.path.exists(cache_file) else "preproc"
        self._start_iter()

    @property
    def base(self) -> InputSplitBase:
        if self._base is None:
            self._base = self._base_factory()
        return self._base

    def _extractor(self) -> InputSplitBase:
        """Record extraction without touching the source filesystem.

        extract_next_record is stateless by design (operates only on the
        chunk), so a detached instance created without __init__ suffices in
        cache-only mode.
        """
        if self._base is not None:
            return self._base
        if self._detached is None:
            self._detached = object.__new__(self._splitter_cls)
        return self._detached

    # ---------------- producers ----------------

    def _preproc_chunks(self) -> Iterator[bytes]:
        """First pass: pull from base, tee every chunk to the cache file."""
        with open(self._tmp_file, "wb") as fo:
            while True:
                chunk = self.base.next_chunk()
                if chunk is None:
                    break
                data = bytes(chunk) if not isinstance(chunk, bytes) else chunk
                fo.write(struct.pack("<Q", len(data)))
                fo.write(data)
                yield data
        os.replace(self._tmp_file, self.cache_file)
        self._mode = "cached"

    def _cached_chunks(self) -> Iterator[bytes]:
        with open(self.cache_file, "rb") as fi:
            while True:
                header = fi.read(8)
                if not header:
                    return
                check(len(header) == 8,
                      f"{self.cache_file} has invalid cache file format")
                (size,) = struct.unpack("<Q", header)
                data = fi.read(size)
                check(len(data) == size,
                      f"{self.cache_file} has invalid cache file format")
                yield data

    def _start_iter(self) -> None:
        if self._iter is not None:
            self._iter.destroy()
        factory = self._preproc_chunks if self._mode == "preproc" else self._cached_chunks
        self._iter = ThreadedIter.from_factory(factory, max_capacity=self._capacity)

    # ---------------- consumer ----------------

    def next_chunk(self) -> Optional[memoryview]:
        if self._chunk is not None and not self._chunk.exhausted:
            out = self._chunk.data[self._chunk.pos:]
            self._chunk = None
            return out
        data = self._iter.next()
        return memoryview(data) if data is not None else None

    def next_record(self) -> Optional[memoryview]:
        while True:
            if self._chunk is not None:
                rec = self._extractor().extract_next_record(self._chunk)
                if rec is not None:
                    return rec
            data = self._iter.next()
            if data is None:
                return None
            self._chunk = _Chunk(data)

    def before_first(self) -> None:
        self._chunk = None
        if self._mode == "preproc":
            # first pass was interrupted mid-write: drop the partial cache
            # and restart the pass (the tmp/rename protocol keeps the real
            # cache file untouched)
            self._iter.destroy()
            try:
                os.remove(self._tmp_file)
            except OSError:
                pass
            self.base.before_first()
            self._start_iter()
        else:
            self._start_iter()

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        raise DMLCError(
            "CachedInputSplit does not support reset_partition; the cache is "
            "bound to one partition (cached_input_split.h:87-89)")

    def hint_chunk_size(self, chunk_size: int) -> None:
        if self._base is not None:
            self._base.hint_chunk_size(chunk_size)

    def close(self) -> None:
        if self._iter is not None:
            self._iter.destroy()
        if self._base is not None:
            self._base.close()
        try:
            os.remove(self._tmp_file)
        except OSError:
            pass
