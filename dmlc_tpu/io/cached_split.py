"""Chunk-cache decorator for input splits.

Equivalent of reference src/io/cached_input_split.h: the first pass serves
chunks while writing them to a local cache file; later passes stream
straight from the cache (InitCachedIter, cached_input_split.h:166-189),
skipping filesystem/remote reads entirely. Selected by a ``#cachefile`` URI
suffix (src/io.cc:119-123) with the partition-qualified ``.splitN.partK``
name from URISpec.

Improvements over the reference:

- the cache is staged to a store-allocated ``.tmp``, **fsynced**, and
  atomically published through the tiered artifact store
  (:mod:`dmlc_tpu.store` — manifest record, byte-budget enforcement,
  orphan-``.tmp`` GC; docs/store.md) — a crashed first pass can never
  leave a truncated cache, a crash between write and rename can never
  publish a cache whose frames never hit the platter, and a warm pass
  pins the cache so eviction cannot take it away mid-epoch;
- cache format v1 is versioned (``DMLCCHK1`` header) and every frame is
  ``[u64 size][u32 crc32][bytes]`` — a warm pass verifies each frame, and
  a failed check is a classified **cache fault**
  (:class:`~dmlc_tpu.utils.check.CacheCorruptionError`, retryable), not a
  bare struct error: the bad cache is dropped, chunks re-read from the
  source, the cache rewritten, and the event counted under
  ``cache_corruptions`` / ``cache_rebuilds`` (docs/resilience.md).
  Headerless caches from older builds invalidate cleanly at open
  (rebuilt from source, counted under ``cache_invalidations``).
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Iterator, Optional

from dmlc_tpu.io import faults
from dmlc_tpu.io import resilience as _resilience
from dmlc_tpu.io.input_split import InputSplit, InputSplitBase, _Chunk
from dmlc_tpu.io.threaded_iter import ThreadedIter
from dmlc_tpu.utils.check import CacheCorruptionError, DMLCError, check

CHUNK_CACHE_MAGIC = b"DMLCCHK1"
_FRAME_FMT = "<QI"  # payload size, payload crc32
_FRAME_LEN = struct.calcsize(_FRAME_FMT)


class CachedInputSplit(InputSplit):
    """Serve-and-cache on the first pass, cache-only afterwards.

    ``base`` may be a live InputSplitBase or a zero-arg factory for one; the
    factory is only invoked when the cache is missing (or needs a healing
    rebuild), so a healthy warm cache never touches the source filesystem
    (the files may be gone or remote).
    """

    def __init__(self, base, cache_file: str, capacity: int = 16,
                 splitter_cls=None):
        self._base_factory = base if callable(base) else (lambda: base)
        self._base: Optional[InputSplitBase] = base if not callable(base) else None
        self._splitter_cls = splitter_cls or (type(self._base) if self._base else None)
        check(self._splitter_cls is not None,
              "CachedInputSplit: a factory base requires splitter_cls for "
              "cache-only record extraction")
        self._detached: Optional[InputSplitBase] = None
        self.cache_file = cache_file
        self._tmp_file: Optional[str] = None  # store-allocated per pass
        self._capacity = capacity
        self._chunk: Optional[_Chunk] = None
        self._iter: Optional[ThreadedIter] = None
        self._pinned = False
        self._mode = "cached" if self._cache_usable() else "preproc"
        if self._mode == "cached":
            self._pin_cache()
        self._start_iter()

    def _store(self):
        from dmlc_tpu.io.block_cache import _artifact_store

        return _artifact_store(self.cache_file)

    def _pin_cache(self) -> None:
        """Eviction pin (docs/store.md): while this split serves the
        cache, a byte-budget squeeze may never evict it."""
        if not self._pinned:
            self._store().pin(self.cache_file)
            self._pinned = True

    def _unpin_cache(self) -> None:
        if self._pinned:
            self._pinned = False
            try:
                self._store().drop(self.cache_file)
            except OSError:
                pass

    def _cache_usable(self) -> bool:
        """A published cache with the current format header. A header from
        another format/version (including the headerless v0 layout) is a
        stale cache: drop it and rebuild from source."""
        if not os.path.exists(self.cache_file):
            # an eviction-vanished cache heals via rebuild; the store
            # counts store_rebuilds_after_eviction (docs/store.md). The
            # light probe never creates state for an unmanaged dir.
            from dmlc_tpu.io.block_cache import _store_manager

            _store_manager().note_missing(self.cache_file)
            return False
        try:
            with open(self.cache_file, "rb") as fi:
                head = fi.read(len(CHUNK_CACHE_MAGIC))
        except OSError:
            head = b""
        if head == CHUNK_CACHE_MAGIC:
            return True
        _resilience.record_event("cache_invalidations")
        self._unpin_cache()
        self._store().discard(self.cache_file)
        return False

    @property
    def base(self) -> InputSplitBase:
        if self._base is None:
            self._base = self._base_factory()
        return self._base

    def _extractor(self) -> InputSplitBase:
        """Record extraction without touching the source filesystem.

        extract_next_record is stateless by design (operates only on the
        chunk), so a detached instance created without __init__ suffices in
        cache-only mode.
        """
        if self._base is not None:
            return self._base
        if self._detached is None:
            self._detached = object.__new__(self._splitter_cls)
        return self._detached

    # ---------------- producers ----------------

    def _preproc_chunks(self) -> Iterator[bytes]:
        """First pass: pull from base, tee every chunk to the cache file."""
        store = self._store()
        self._tmp_file = store.stage_path(self.cache_file)
        with open(self._tmp_file, "wb") as fo:
            fo.write(CHUNK_CACHE_MAGIC)
            while True:
                chunk = self.base.next_chunk()
                if chunk is None:
                    break
                data = bytes(chunk) if not isinstance(chunk, bytes) else chunk
                fo.write(struct.pack(_FRAME_FMT, len(data),
                                     zlib.crc32(data) & 0xFFFFFFFF))
                fo.write(data)
                yield data
            # atomic publish through the store: fsync BEFORE the rename
            # (a crash in the window can never publish a complete-looking
            # cache whose frames were never flushed), manifest record,
            # byte-budget enforcement (docs/store.md)
            store.publish_file(self._tmp_file, self.cache_file,
                               tier="chunk_cache", fobj=fo)
        self._tmp_file = None
        self._mode = "cached"
        self._pin_cache()

    def _cached_chunks(self) -> Iterator[bytes]:
        served_bytes = 0
        try:
            with open(self.cache_file, "rb") as fi:
                head = fi.read(len(CHUNK_CACHE_MAGIC))
                if head != CHUNK_CACHE_MAGIC:
                    raise CacheCorruptionError(
                        f"{self.cache_file}: bad chunk-cache header")
                while True:
                    faults.maybe_fail("cache_read", self.cache_file)
                    header = fi.read(_FRAME_LEN)
                    if not header:
                        return
                    if len(header) != _FRAME_LEN:
                        raise CacheCorruptionError(
                            f"{self.cache_file}: torn frame header")
                    size, crc = struct.unpack(_FRAME_FMT, header)
                    data = fi.read(size)
                    if len(data) != size:
                        raise CacheCorruptionError(
                            f"{self.cache_file}: torn frame payload")
                    if zlib.crc32(data) & 0xFFFFFFFF != crc:
                        raise CacheCorruptionError(
                            f"{self.cache_file}: frame crc mismatch")
                    yield data
                    served_bytes += size
        except CacheCorruptionError:
            # classified cache fault (resilience.classify -> retryable):
            # drop the bad cache, fall back to re-reading the source,
            # rewrite the cache, and resume the stream where it broke —
            # consumers see an unbroken chunk sequence, never the error.
            # The resume skips BYTES, not frames: the re-read may group
            # chunks differently (e.g. the split's chunk_bytes changed
            # since the cache was built) but the concatenated byte stream
            # is identical, and every frame boundary sits on a record
            # boundary, so a mid-chunk tail still starts at a record head
            _resilience.record_event("cache_corruptions")
            _resilience.record_event("cache_rebuilds")
            self._unpin_cache()
            self._store().discard(self.cache_file)
            self._mode = "preproc"
            self.base.before_first()
            skip = served_bytes
            for data in self._preproc_chunks():
                if skip >= len(data):
                    skip -= len(data)
                    continue
                if skip:
                    data = data[skip:]
                    skip = 0
                yield data

    def _start_iter(self) -> None:
        if self._iter is not None:
            self._iter.destroy()
        factory = self._preproc_chunks if self._mode == "preproc" else self._cached_chunks
        self._iter = ThreadedIter.from_factory(factory, max_capacity=self._capacity)

    # ---------------- consumer ----------------

    def next_chunk(self) -> Optional[memoryview]:
        if self._chunk is not None and not self._chunk.exhausted:
            out = self._chunk.data[self._chunk.pos:]
            self._chunk = None
            return out
        data = self._iter.next()
        return memoryview(data) if data is not None else None

    def next_record(self) -> Optional[memoryview]:
        while True:
            if self._chunk is not None:
                rec = self._extractor().extract_next_record(self._chunk)
                if rec is not None:
                    return rec
            data = self._iter.next()
            if data is None:
                return None
            self._chunk = _Chunk(data)

    def before_first(self) -> None:
        self._chunk = None
        if self._mode == "preproc":
            # first pass was interrupted mid-write: drop the partial
            # staging file and restart the pass (the stage/publish
            # protocol keeps the real cache file untouched)
            self._iter.destroy()
            self._drop_tmp()
            self.base.before_first()
            self._start_iter()
        else:
            self._start_iter()

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        raise DMLCError(
            "CachedInputSplit does not support reset_partition; the cache is "
            "bound to one partition (cached_input_split.h:87-89)")

    def hint_chunk_size(self, chunk_size: int) -> None:
        if self._base is not None:
            self._base.hint_chunk_size(chunk_size)

    def _drop_tmp(self) -> None:
        tmp, self._tmp_file = self._tmp_file, None
        if tmp is not None:
            try:
                os.remove(tmp)
            except OSError:
                pass

    def close(self) -> None:
        if self._iter is not None:
            self._iter.destroy()
        if self._base is not None:
            self._base.close()
        self._unpin_cache()
        self._drop_tmp()
