"""Producer/consumer prefetch pipeline.

Behavioral equivalent of reference include/dmlc/threadediter.h: a single
producer thread fills a bounded queue ahead of the consumer, with

- cell recycling so buffers are reused instead of reallocated
  (Next/Recycle, threadediter.h:443-488),
- ``before_first`` epoch reset that interrupts and restarts the producer
  (signal kBeforeFirst, threadediter.h:210-235),
- exceptions in the producer captured and rethrown on the consumer side
  (threadediter.h:406-436, 490-505),
- clean destruction joining the thread (kDestroy + ScopedThread,
  threadediter.h:283-313),
- an OPT-IN bounded producer-restart path (``restart_policy``): a
  retryable-class source error (see :func:`dmlc_tpu.io.resilience.classify`)
  consumes restart budget — backoff, reposition via ``restart_fn``, keep
  producing — instead of poisoning the pipeline; fatal errors and exhausted
  budgets rethrow on the consumer as before.

The producer callback contract matches the reference's ``next(cell)``:
``produce_fn(cell) -> (ok, cell)`` where ``cell`` is a recycled buffer or
None, and ok=False signals end of stream. A simpler ``iterator`` front-end
(:func:`ThreadedIter.from_factory`) covers the common case.
"""

from __future__ import annotations

import os
import threading
from collections import deque
from typing import Any, Callable, Deque, Generic, Optional, Tuple, TypeVar

from dmlc_tpu.io import resilience as _resilience
from dmlc_tpu.utils import telemetry as _telemetry
from dmlc_tpu.utils.check import DMLCError
from dmlc_tpu.utils.timer import get_time

T = TypeVar("T")

# producer signals (threadediter.h:243-247)
_SIG_PRODUCE = 0
_SIG_BEFORE_FIRST = 1
_SIG_DESTROY = 2


def _fast_forward(it, n: int):
    """Skip the first ``n`` items of a freshly rebuilt source — the
    deterministic replay both restart paths use. A source that yields fewer
    items than already delivered surfaces loudly (a bare StopIteration
    leaking into the pipeline would read as silent truncation)."""
    for _ in range(n):
        try:
            next(it)
        except StopIteration:
            raise DMLCError(
                "producer restart: source yielded fewer items than already "
                "delivered — non-deterministic factory?") from None
    return it


def _stall_timeout() -> float:
    """Opt-in pipeline stall watchdog (seconds; 0 = off, the default).

    A wedged producer — most commonly a device backend whose transfer hangs
    (e.g. a dead TPU tunnel) — otherwise blocks the consumer silently and
    forever. With ``DMLC_PIPELINE_STALL_TIMEOUT=N`` the consumer raises a
    diagnosable error after waiting N seconds with a live but unproductive
    producer. Off by default: a legitimately slow first chunk (GB-scale
    remote reads) must never be killed by an arbitrary limit.
    """
    return float(os.environ.get("DMLC_PIPELINE_STALL_TIMEOUT", "0") or 0)


def _restart_budget_dict(policy, used: int) -> dict:
    """The restart budget as structured data — one schema for both
    pipeline primitives, published inside the stall diagnostic."""
    return {
        "enabled": policy is not None,
        "used": used,
        "limit": max(0, policy.max_attempts - 1) if policy is not None
        else 0,
    }


def _publish_stall_diagnostic(diag: dict) -> None:
    """Publish a stall diagnostic as a structured info metric on the
    telemetry registry, keyed by component, pool label, and pipeline
    scope (a pipeline runs several pools — parse fan-out + convert — and
    their diagnostics must not overwrite each other) — the
    machine-readable twin of the DMLCError message (tests and monitors
    assert on this dict, never on message text)."""
    _telemetry.REGISTRY.info(
        _telemetry.STALL_METRIC, component=diag.get("component", ""),
        label=diag.get("label", ""),
        pipeline=_telemetry.current_scope() or "").set(diag)


class ThreadedIter(Generic[T]):
    """Bounded-queue prefetch iterator with recycling + epoch reset."""

    def __init__(
        self,
        produce_fn: Callable[[Optional[T]], Tuple[bool, Optional[T]]],
        before_first_fn: Optional[Callable[[], None]] = None,
        max_capacity: int = 8,
        restart_fn: Optional[Callable[[int], None]] = None,
        restart_policy: Optional["_resilience.RetryPolicy"] = None,
    ):
        self._produce = produce_fn
        self._before_first = before_first_fn
        self._capacity = max_capacity
        self._lock = threading.Condition()
        self._queue: Deque[T] = deque()
        self._free: Deque[T] = deque()
        self._produce_end = False
        self._signal = _SIG_PRODUCE
        self._signal_processed = False
        self._exc: Optional[BaseException] = None
        self._destroyed = False
        self.stall_seconds = 0.0  # consumer time spent waiting on the producer
        # bounded producer restart (opt-in): on a retryable-class produce
        # error, back off and call restart_fn(items_produced_this_epoch) to
        # reposition the source, consuming one unit of the per-epoch budget
        # (restart_policy.max_attempts - 1). Without restart_fn the produce
        # callback is simply re-invoked — only correct for producers whose
        # state survives a failed call (NOT dead generators).
        self._restart_fn = restart_fn
        self._restart_policy = (
            restart_policy if restart_policy is not None
            else (_resilience.default_policy() if restart_fn else None))
        self._epoch_produced = 0   # items queued since epoch start
        self._epoch_restarts = 0   # budget consumed this epoch
        self.restarts = 0          # lifetime restart count
        self.restart_giveups = 0   # budget-exhausted poisonings
        self.last_producer_error: Optional[str] = None
        # the producer runs under the owning pipeline's telemetry scope so
        # spans/metrics it records land under the right label: captured at
        # construction, ADOPTED from the first consumer pull when built
        # outside any scope (a ThreadedInputSplit is constructed with the
        # parser, before the DeviceIter that owns it exists) — the loop
        # re-installs it each iteration, so adoption takes effect mid-run
        self._scope = _telemetry.current_scope()
        self._thread = threading.Thread(target=self._producer_loop,
                                        daemon=True)
        self._thread.start()

    def _budget_state(self) -> str:
        """Human retry-budget summary for diagnostics."""
        pol = self._restart_policy
        if pol is None:
            return "producer restart disabled"
        return (f"producer restarts {self._epoch_restarts}/"
                f"{max(0, pol.max_attempts - 1)} used this epoch")

    def _budget_dict(self) -> dict:
        return _restart_budget_dict(self._restart_policy,
                                    self._epoch_restarts)

    def _try_restart(self, exc: BaseException) -> bool:
        """Classify a producer error; on a retryable class with budget left,
        back off, reposition the source, and report True (keep producing)."""
        with self._lock:
            if self._signal != _SIG_PRODUCE:  # reset/destroy pending: bail
                return False
            used = self._epoch_restarts
            produced = self._epoch_produced
        verdict = _resilience.restart_verdict(self._restart_policy, used, exc)
        if verdict == "giveup":
            self.restart_giveups += 1
            _resilience.record_event("producer_giveups")
            return False
        if verdict != "restart":
            return False
        with self._lock:
            self._epoch_restarts += 1
            self.restarts += 1
        _resilience.record_event("producer_restarts")
        _resilience.restart_backoff(self._restart_policy, used, exc)
        if self._restart_fn is not None:
            # reposition failures propagate to the caller's except branch
            self._restart_fn(produced)
        return True

    # ---------------- producer side ----------------

    def _producer_loop(self) -> None:
        while True:
            _telemetry.set_scope(self._scope)  # one TLS store per item
            cell: Optional[T] = None
            with self._lock:
                # wait for: destroy/reset signal, or space to produce
                self._lock.wait_for(
                    lambda: self._signal != _SIG_PRODUCE
                    or (not self._produce_end and (len(self._queue) < self._capacity or self._free))
                )
                if self._signal == _SIG_DESTROY:
                    self._signal_processed = True
                    self._lock.notify_all()
                    return
                if self._signal == _SIG_BEFORE_FIRST:
                    # epoch reset: drop queued items into the free list
                    while self._queue:
                        self._free.append(self._queue.popleft())
                    try:
                        if self._before_first is not None:
                            self._before_first()
                        self._produce_end = False
                        self._epoch_produced = 0
                        self._epoch_restarts = 0  # fresh budget per epoch
                    except BaseException as exc:  # noqa: BLE001 - rethrown on consumer
                        self._exc = exc
                        self._produce_end = True
                    self._signal = _SIG_PRODUCE
                    self._signal_processed = True
                    self._lock.notify_all()
                    continue
                if self._free:
                    cell = self._free.popleft()
            # run the producer outside the lock (threadediter.h:365 next())
            try:
                ok, value = self._produce(cell)
            except BaseException as exc:  # noqa: BLE001 - captured for consumer
                self.last_producer_error = f"{type(exc).__name__}: {exc}"
                try:
                    restarted = self._try_restart(exc)
                except BaseException as exc2:  # noqa: BLE001 - reposition died
                    restarted = False
                    exc = exc2
                    self.last_producer_error = f"{type(exc2).__name__}: {exc2}"
                if restarted:
                    with self._lock:
                        if cell is not None:  # return the borrowed cell
                            self._free.append(cell)
                    continue
                with self._lock:
                    self._exc = exc
                    self._produce_end = True
                    self._lock.notify_all()
                continue
            with self._lock:
                if ok:
                    self._queue.append(value)  # type: ignore[arg-type]
                    self._epoch_produced += 1
                else:
                    self._produce_end = True
                    if cell is not None:
                        self._free.append(cell)
                self._lock.notify_all()

    # ---------------- consumer side ----------------

    def adopt_scope(self, label: Optional[str]) -> None:
        """Install ``label`` as this pipeline's scope if it was built
        outside any (monotonic None -> label, so benign if raced). The
        owning ``DeviceIter`` walks its source chain and calls this at
        construction, so prefetch work done BEFORE the first pull is
        already scoped (docs/observability.md)."""
        if self._scope is None and label is not None:
            self._scope = label

    def next(self) -> Optional[T]:
        """Pop the next item; None at end of stream. Rethrows producer errors."""
        if self._destroyed:
            raise DMLCError("ThreadedIter: already destroyed")
        if self._scope is None:
            # scope adoption (see __init__): the first scoped consumer owns
            # this pipeline — monotonic None -> label, so benign if raced
            self._scope = _telemetry.current_scope()
        t0 = get_time()
        timeout = _stall_timeout()
        with self._lock:
            if timeout > 0:
                if not self._lock.wait_for(
                    lambda: self._queue or self._produce_end, timeout=timeout
                ):
                    alive = self._thread.is_alive()
                    # the diagnostic is DATA first: published on the
                    # metrics registry so monitors/tests read structure,
                    # not message text (docs/observability.md)
                    _publish_stall_diagnostic({
                        "component": "ThreadedIter",
                        "timeout_seconds": timeout,
                        "producer_alive": alive,
                        "queue_len": len(self._queue),
                        "free_cells": len(self._free),
                        "last_producer_error": self.last_producer_error,
                        "restart_budget": self._budget_dict(),
                    })
                    raise DMLCError(
                        f"pipeline stalled: no item produced in {timeout:.0f}s "
                        f"(producer thread {'alive but blocked' if alive else 'dead'}, "
                        f"queue empty, free cells {len(self._free)}; "
                        f"last producer error: "
                        f"{self.last_producer_error or 'none'}; "
                        f"{self._budget_state()}). A hung "
                        f"device transfer or remote read is the usual cause; "
                        f"unset DMLC_PIPELINE_STALL_TIMEOUT to wait forever"
                    )
            else:
                self._lock.wait_for(lambda: self._queue or self._produce_end)
            self.stall_seconds += get_time() - t0
            if self._queue:
                item = self._queue.popleft()
                self._lock.notify_all()
                return item
            self._check_exc_locked()
            return None

    def set_capacity(self, max_capacity: int) -> None:
        """Live-resize the prefetch window (the autotuner's
        ``convert_ahead`` knob in natural-block mode): growing lets the
        producer run further ahead immediately; shrinking only gates NEW
        production — already-queued items still drain to the consumer,
        so delivery order and content are untouched."""
        with self._lock:
            self._capacity = max(1, int(max_capacity))
            self._lock.notify_all()

    def recycle(self, item: T) -> None:
        """Return a consumed cell for reuse (threadediter.h:476-488)."""
        with self._lock:
            self._free.append(item)
            self._lock.notify_all()
            self._check_exc_locked()

    def before_first(self) -> None:
        """Reset to the epoch start; blocks until the producer acknowledges."""
        with self._lock:
            self._check_exc_locked()
            self._signal = _SIG_BEFORE_FIRST
            self._signal_processed = False
            self._lock.notify_all()
            self._lock.wait_for(lambda: self._signal_processed)
            self._signal_processed = False
            self._check_exc_locked()

    def destroy(self) -> None:
        """Stop and join the producer thread."""
        if self._destroyed:
            return
        with self._lock:
            self._signal = _SIG_DESTROY
            self._signal_processed = False
            self._lock.notify_all()
        self._thread.join(timeout=30.0)
        self._destroyed = True

    def _check_exc_locked(self) -> None:
        if self._exc is not None:
            exc, self._exc = self._exc, None
            self._produce_end = True
            raise exc

    def __iter__(self):
        while True:
            item = self.next()
            if item is None:
                return
            yield item

    def __del__(self):  # pragma: no cover - best effort
        try:
            self.destroy()
        except Exception:
            pass

    # ---------------- convenience front-end ----------------

    @staticmethod
    def from_factory(
        iterator_factory: Callable[[], Any], max_capacity: int = 8,
        restart_policy: Optional["_resilience.RetryPolicy"] = None,
    ) -> "ThreadedIter":
        """Prefetch over a restartable iterator factory.

        Each epoch calls ``iterator_factory()`` for a fresh iterator; this is
        the Pythonic face of the (next_fn, beforefirst_fn) pair.

        With ``restart_policy``, a retryable-class error from the iterator
        consumes restart budget: a FRESH iterator is built and fast-forwarded
        past the items already delivered (the factory must be deterministic),
        so the consumer sees an uninterrupted, in-order stream. Without it, a
        dead generator would otherwise surface the error and end the epoch.
        """
        state = {"it": iterator_factory()}

        def produce(cell):
            try:
                return True, next(state["it"])
            except StopIteration:
                return False, None

        def before_first():
            state["it"] = iterator_factory()

        def restart(produced: int) -> None:
            # skip what the consumer already has (deterministic factory)
            state["it"] = _fast_forward(iterator_factory(), produced)

        return ThreadedIter(
            produce, before_first, max_capacity=max_capacity,
            restart_fn=restart if restart_policy is not None else None,
            restart_policy=restart_policy)


class OrderedWorkerPool(Generic[T]):
    """Serial-pull, parallel-work, in-order-delivery prefetch pool.

    The pool form of :class:`ThreadedIter`'s producer machinery (same
    consumer contract: ``next() -> item | None`` at end of stream, worker
    exceptions rethrown on the consumer side, ``destroy()`` joins): items
    are pulled from ONE serial source iterator — the pull is serialized
    under a dedicated lock and each pulled item takes a sequence number,
    so source order is the law — then ``work_fn(item)`` runs CONCURRENTLY
    across ``num_workers`` threads, and results are handed to the
    consumer strictly in pull order.

    Built for pipeline stages whose per-item work releases the GIL (numpy
    packing, host layout conversion): work-for-item-N+1 overlaps whatever
    the consumer does with item N (DeviceIter's convert/dispatch overlap).
    ``max_ahead`` bounds pulled-but-undelivered items (backpressure); the
    instantaneous overshoot is at most ``num_workers`` items already past
    the window check when it closes.
    """

    def __init__(
        self,
        source_factory: Callable[[], Any],
        work_fn: Callable[[Any], T],
        num_workers: int = 2,
        max_ahead: int = 4,
        restart_policy: Optional["_resilience.RetryPolicy"] = None,
        counter_label: str = "producer",
    ):
        self._source_factory = source_factory
        self._source = source_factory()
        self._work = work_fn
        self._ahead = max(1, int(max_ahead))
        self._lock = threading.Condition()
        self._pull_lock = threading.Lock()
        self._results: dict = {}
        self._seq = 0    # next sequence number to assign at pull time
        self._want = 0   # next sequence number the consumer delivers
        self._produce_end = False
        self._poisoned = False  # a work_fn error was delivered: terminal
        self._src_exc: Optional[BaseException] = None
        self._destroyed = False
        self.stall_seconds = 0.0  # consumer time waiting on the workers
        # which resilience counters this pool's restarts bump: the generic
        # "producer_*" pair by default; the parse fan-out labels its pool
        # "parse" so parse-source restarts are distinguishable in
        # DeviceIter.stats()['resilience'] / the bench JSON
        self._counter_label = counter_label
        # bounded source restart (opt-in, like ThreadedIter): a retryable
        # pull error rebuilds the source via source_factory() and
        # fast-forwards past the seq items already pulled, so sequence
        # numbers — and therefore delivery order — are preserved across a
        # mid-stream restart. The factory must be deterministic.
        self._restart_policy = restart_policy
        self.restarts = 0
        self.restart_giveups = 0
        self.last_producer_error: Optional[str] = None
        # workers run under the owning pipeline's scope: captured at
        # construction, adopted from the first consumer pull otherwise
        # (see ThreadedIter)
        self._scope = _telemetry.current_scope()
        # live resize (docs/data.md autotune): _shrink holds exit credits
        # surplus workers consume at their next loop top; num_workers is
        # the current TARGET width (threads alive minus pending exits)
        self._shrink = 0
        self.num_workers = max(1, int(num_workers))
        self._threads = [
            threading.Thread(target=self._worker_loop, daemon=True)
            for _ in range(self.num_workers)
        ]
        for t in self._threads:
            t.start()

    def _budget_state(self) -> str:
        pol = self._restart_policy
        if pol is None:
            return "source restart disabled"
        return (f"source restarts {self.restarts}/"
                f"{max(0, pol.max_attempts - 1)} used")

    def _budget_dict(self) -> dict:
        return _restart_budget_dict(self._restart_policy, self.restarts)

    def _try_source_restart(self, exc: BaseException) -> bool:
        """Called under ``_pull_lock`` after a source pull raised. On a
        retryable class with budget left: back off, rebuild the source, and
        skip the ``seq`` items already pulled (order is law — the skip keeps
        every outstanding sequence number valid)."""
        verdict = _resilience.restart_verdict(self._restart_policy,
                                              self.restarts, exc)
        if verdict == "giveup":
            self.restart_giveups += 1
            _resilience.record_event(f"{self._counter_label}_giveups")
            return False
        if verdict != "restart":
            return False
        used = self.restarts
        self.restarts += 1
        _resilience.record_event(f"{self._counter_label}_restarts")
        _resilience.restart_backoff(self._restart_policy, used, exc)
        with self._lock:
            pulled = self._seq
        self._source = _fast_forward(self._source_factory(), pulled)
        return True

    # ---------------- worker side ----------------

    def _worker_loop(self) -> None:
        while True:
            _telemetry.set_scope(self._scope)  # one TLS store per item
            with self._lock:
                self._lock.wait_for(
                    lambda: self._destroyed or self._produce_end
                    or self._shrink > 0
                    or (self._seq - self._want) < self._ahead
                )
                if self._destroyed or self._produce_end:
                    return
                if self._shrink > 0:
                    # live shrink: consume one exit credit and retire —
                    # between the wait and the pull lock, so a retiring
                    # worker never holds an undelivered item
                    self._shrink -= 1
                    return
            with self._pull_lock:
                # re-check under the pull lock: another worker may have hit
                # end-of-stream (or destroy) while this one waited its turn
                if self._destroyed or self._produce_end:
                    return
                try:
                    item = next(self._source)
                except StopIteration:
                    with self._lock:
                        self._produce_end = True
                        self._lock.notify_all()
                    return
                except BaseException as exc:  # noqa: BLE001 - rethrown on consumer
                    self.last_producer_error = f"{type(exc).__name__}: {exc}"
                    try:
                        restarted = self._try_source_restart(exc)
                    except BaseException as exc2:  # noqa: BLE001 - replay died
                        restarted = False
                        exc = exc2
                        self.last_producer_error = (
                            f"{type(exc2).__name__}: {exc2}")
                    if restarted:
                        continue  # releases the pull lock, re-enters the wait
                    with self._lock:
                        self._src_exc = exc
                        self._produce_end = True
                        self._lock.notify_all()
                    return
                with self._lock:
                    seq = self._seq
                    self._seq += 1
            # the parallel stage: outside every lock
            try:
                out = ("ok", self._work(item))
            except BaseException as exc:  # noqa: BLE001 - rethrown in order
                out = ("exc", exc)
            with self._lock:
                self._results[seq] = out
                self._lock.notify_all()

    # ---------------- consumer side ----------------

    def adopt_scope(self, label: Optional[str]) -> None:
        """See :meth:`ThreadedIter.adopt_scope` — same contract."""
        if self._scope is None and label is not None:
            self._scope = label

    def next(self) -> Optional[T]:
        """Pop the next result in source order; None at end of stream.

        A ``work_fn`` exception is rethrown at the position of the item
        that raised (earlier items still deliver) and POISONS the pool:
        later calls return None — items past a failure must never be
        handed out, or a consumer pairing deliveries with per-item
        bookkeeping (DeviceIter's resume-annotation fifo) would desync by
        one. A source-iterator exception is rethrown after all
        successfully pulled items drain.
        """
        if self._destroyed:
            raise DMLCError("OrderedWorkerPool: already destroyed")
        if self._poisoned:
            return None
        if self._scope is None:
            # scope adoption: the first scoped consumer owns this pool
            self._scope = _telemetry.current_scope()
        t0 = get_time()
        timeout = _stall_timeout()
        with self._lock:
            ready = lambda: (  # noqa: E731
                self._want in self._results
                or (self._produce_end and self._want >= self._seq))
            if timeout > 0:
                if not self._lock.wait_for(ready, timeout=timeout):
                    alive = sum(t.is_alive() for t in self._threads)
                    _publish_stall_diagnostic({
                        "component": "OrderedWorkerPool",
                        "label": self._counter_label,
                        "timeout_seconds": timeout,
                        "workers_alive": alive,
                        "workers": self.num_workers,
                        "waiting_for": self._want,
                        "pulled": self._seq,
                        "last_producer_error": self.last_producer_error,
                        "restart_budget": self._budget_dict(),
                    })
                    raise DMLCError(
                        f"pipeline stalled: no item produced in {timeout:.0f}s "
                        f"({alive}/{len(self._threads)} workers alive, "
                        f"waiting for #{self._want} of {self._seq} pulled; "
                        f"last producer error: "
                        f"{self.last_producer_error or 'none'}; "
                        f"{self._budget_state()}). "
                        f"A hung device transfer or remote read is the usual "
                        f"cause; unset DMLC_PIPELINE_STALL_TIMEOUT to wait "
                        f"forever")
            else:
                self._lock.wait_for(ready)
            self.stall_seconds += get_time() - t0
            if self._want in self._results:
                kind, value = self._results.pop(self._want)
                self._want += 1
                self._lock.notify_all()  # window opened: let a worker pull
                if kind == "exc":
                    self._produce_end = True
                    self._poisoned = True
                    raise value
                return value
            if self._src_exc is not None:
                exc, self._src_exc = self._src_exc, None
                raise exc
            return None

    def resize(self, num_workers: int) -> int:
        """Live-resize the worker pool (the autotuner's pool-width
        knobs): growth spawns threads that join the same serial pull +
        in-order delivery machinery, shrink posts exit credits surplus
        workers consume at their next loop top. Sequence numbers — and
        therefore delivery order and content — are unaffected in both
        directions. Returns the new target width."""
        n = max(1, int(num_workers))
        spawn = []
        with self._lock:
            if self._destroyed:
                return self.num_workers
            # drop retired/dead threads so diagnostics count live ones
            self._threads = [t for t in self._threads if t.is_alive()]
            delta = n - self.num_workers
            self.num_workers = n
            if delta > 0:
                # cancel pending exits first, then top up with threads
                cancel = min(self._shrink, delta)
                self._shrink -= cancel
                for _ in range(delta - cancel):
                    t = threading.Thread(target=self._worker_loop,
                                         daemon=True)
                    self._threads.append(t)
                    spawn.append(t)
            elif delta < 0:
                self._shrink += -delta
            self._lock.notify_all()
        for t in spawn:
            t.start()
        return n

    def set_max_ahead(self, max_ahead: int) -> None:
        """Live-resize the pulled-but-undelivered window (the
        ``convert_ahead`` knob): growing opens the window immediately;
        shrinking only gates NEW pulls — items already in flight still
        deliver in order."""
        with self._lock:
            self._ahead = max(1, int(max_ahead))
            self._lock.notify_all()

    def destroy(self) -> None:
        """Stop and join the worker threads."""
        if self._destroyed:
            return
        with self._lock:
            self._destroyed = True
            self._lock.notify_all()
        for t in self._threads:
            t.join(timeout=30.0)

    def __iter__(self):
        while True:
            item = self.next()
            if item is None:
                return
            yield item

    def __del__(self):  # pragma: no cover - best effort
        try:
            self.destroy()
        except Exception:
            pass


