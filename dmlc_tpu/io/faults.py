"""Deterministic, seedable fault injection for the I/O stack.

Every guarded network attempt in the package (each
``RetryPolicy.call`` attempt — stream fetches, metadata requests,
writes) passes through :func:`maybe_fail` before touching the transport.
An active :class:`FaultPlan` can therefore fail the Nth read / open /
request / connect with a chosen error CLASS — the real exception types
(``urllib.error.HTTPError``, ``ConnectionResetError``, ``TimeoutError``,
``urllib.error.URLError``) — so every retry / resume / give-up /
fail-fast path is exercised in tier-1 tests with zero network egress.

Plan grammar (``;``-separated clauses)::

    clause     := op ['~' substr] '@' occurrence ['=' error]
    op         := 'read' | 'open' | 'write' | 'request' | 'connect' | ...
    occurrence := N | N '..' M | N '+'        (1-based, per clause)
    error      := 'http-<code>' | 'reset' | 'timeout' | 'unreachable'
                  | 'corrupt' | 'conn' | 'torn'   (default: 'http-503')

The op is the call-site label passed to ``maybe_fail``: ``read`` fires on
stream block fetches, ``open`` on metadata/stat/open requests, ``write``
on upload requests, ``request`` on other control requests, and
``connect`` on EVERY guarded attempt regardless of label (the lowest
seam). ``cache_read`` fires on cache-frame/segment reads (the chunk cache
and the block cache), where the natural error class is ``corrupt`` — a
:class:`~dmlc_tpu.utils.check.CacheCorruptionError` that exercises the
drop-cache/re-parse/rewrite healing path without touching bytes on disk.
The control-plane ops cover the data service (docs/service.md):
``dispatch_rpc`` fires on every dispatcher round trip (workers, clients,
fleet bootstrap — the seam sits inside ``service.dispatcher.request``)
and ``worker_rpc`` on client->worker connections (stream / find /
count). Their natural error classes are ``conn`` (connection refused —
the peer is down, e.g. a dispatcher between kill and restart) and
``torn`` (the peer died mid-reply), both retryable, so chaos plans
drive dispatcher-restart and torn-reply-storm paths deterministically.
``preempt`` is the elastic-membership seam: every parse worker checks it
once per heartbeat with its worker id as the subject, and ANY firing —
whatever error class the clause names — is consumed as a preemption
notice (``preemption_notices``) that begins a graceful drain rather than
surfacing as an exception, so rolling-preemption chaos is one plan away
(``preempt~rank0@1``).
``~substr`` restricts a clause to calls whose subject (URL/path)
contains the substring; occurrences are counted per clause over its
matching calls only, so plans are deterministic under interleaving from
other streams.

Examples::

    read@1..2=http-503      # first two block reads answer 503, then heal
    open~part-3@1=http-403  # opening part-3 fails fatally once
    read@4=reset            # the 4th read dies with a connection reset
    connect@2+=timeout      # every guarded attempt from the 2nd on hangs
    dispatch_rpc@2..4=conn  # dispatcher unreachable for three round trips
    worker_rpc@1=torn       # first client->worker exchange dies mid-reply
    preempt~rank0@1         # worker rank0 gets a preemption notice: drains

Activate with the :func:`inject` context manager, or process-wide with
``DMLC_FAULT_PLAN`` (the env hook — read lazily on the first guarded
call, re-parsed whenever the value changes). See docs/resilience.md.
"""

from __future__ import annotations

import email.message
import io as _pyio
import os
import re
import threading
import urllib.error
from contextlib import contextmanager
from typing import List, Optional

from dmlc_tpu.utils.check import CacheCorruptionError, DMLCError

_CLAUSE_RE = re.compile(
    r"^(?P<op>[A-Za-z_][\w-]*)"
    r"(?:~(?P<substr>[^@]*))?"
    r"@(?P<lo>\d+)(?:(?P<range>\.\.(?P<hi>\d+))|(?P<plus>\+))?"
    r"(?:=(?P<err>[\w-]+))?$"
)


def _build_error(spec: str, what: str) -> BaseException:
    if spec.startswith("http-"):
        code = int(spec[5:])
        hdrs = email.message.Message()
        return urllib.error.HTTPError(
            what or "fault://injected", code,
            f"injected http {code}", hdrs, _pyio.BytesIO(b""))
    if spec == "reset":
        return ConnectionResetError(104, "injected connection reset")
    if spec == "timeout":
        return TimeoutError("injected timeout")
    if spec == "unreachable":
        return urllib.error.URLError(OSError("injected: host unreachable"))
    if spec == "corrupt":
        return CacheCorruptionError(
            f"injected cache corruption: {what or 'fault://injected'}")
    if spec == "conn":
        return ConnectionRefusedError(
            111, f"injected: connection refused: "
                 f"{what or 'fault://injected'}")
    if spec == "torn":
        return ConnectionError(
            f"injected: torn reply from {what or 'fault://injected'}")
    raise DMLCError(f"fault plan: unknown error class {spec!r}")


class _Clause:
    __slots__ = ("op", "substr", "lo", "hi", "err", "calls", "fired")

    def __init__(self, op: str, substr: Optional[str], lo: int,
                 hi: Optional[int], err: str):
        self.op = op
        self.substr = substr
        self.lo = lo
        self.hi = hi  # None = open-ended ('N+')
        self.err = err
        self.calls = 0  # matching calls seen
        self.fired = 0  # faults actually raised

    def matches(self, op: str, what: str) -> bool:
        return op == self.op and (not self.substr or self.substr in what)

    def due(self) -> bool:
        if self.hi is None:
            return self.calls >= self.lo
        return self.lo <= self.calls <= self.hi


class FaultPlan:
    """A parsed fault plan with its (thread-safe) occurrence counters."""

    def __init__(self, spec: str):
        self.spec = spec
        self._lock = threading.Lock()
        self._clauses: List[_Clause] = []
        for raw in spec.split(";"):
            raw = raw.strip()
            if not raw:
                continue
            m = _CLAUSE_RE.match(raw)
            if m is None:
                raise DMLCError(
                    f"fault plan: bad clause {raw!r} "
                    f"(expected op[~substr]@N[..M|+][=error])")
            lo = int(m.group("lo"))
            hi = int(m.group("hi")) if m.group("hi") else (
                None if m.group("plus") else lo)
            err = m.group("err") or "http-503"
            _build_error(err, "")  # validate the error class at parse time
            self._clauses.append(
                _Clause(m.group("op"), m.group("substr"), lo, hi, err))

    def check(self, op: str, what: str = "") -> Optional[BaseException]:
        """Count this call against every matching clause; return the error
        to raise if one is due (first matching clause wins)."""
        due: Optional[_Clause] = None
        with self._lock:
            for clause in self._clauses:
                if not clause.matches(op, what):
                    continue
                clause.calls += 1
                if due is None and clause.due():
                    clause.fired += 1
                    due = clause
        if due is None:
            return None
        return _build_error(due.err, what)

    def fired(self) -> int:
        """Total faults injected so far (all clauses)."""
        with self._lock:
            return sum(c.fired for c in self._clauses)


# active plan: module-global so pipeline/producer threads see it too
_active: Optional[FaultPlan] = None
_env_cache: Optional[FaultPlan] = None  # lazily parsed DMLC_FAULT_PLAN


def active_plan() -> Optional[FaultPlan]:
    """The plan guarding calls right now: an :func:`inject` plan if one is
    open, else the (cached) ``DMLC_FAULT_PLAN`` env plan, else None."""
    global _env_cache
    if _active is not None:
        return _active
    spec = os.environ.get("DMLC_FAULT_PLAN")
    if not spec:
        _env_cache = None
        return None
    if _env_cache is None or _env_cache.spec != spec:
        _env_cache = FaultPlan(spec)
    return _env_cache


def maybe_fail(op: str, what: str = "") -> None:
    """The injection seam: raise the planned error for this call, if any.

    Called with the call-site label and subject (URL/path) before every
    guarded I/O attempt. No-op (two dict reads) when no plan is active.
    """
    plan = active_plan()
    if plan is None:
        return
    exc = plan.check(op, str(what))
    if exc is not None:
        raise exc


@contextmanager
def inject(plan):
    """Activate a fault plan for the dynamic extent of the block.

    ``plan`` is a :class:`FaultPlan` or a spec string. Yields the plan (its
    ``fired()`` count lets tests assert exact injected-fault totals).
    Nests: the previous plan is restored on exit.
    """
    global _active
    if not isinstance(plan, FaultPlan):
        plan = FaultPlan(str(plan))
    prev = _active
    _active = plan
    try:
        yield plan
    finally:
        _active = prev


def reset() -> None:
    """Drop any active/env-cached plan state (test isolation)."""
    global _active, _env_cache
    _active = None
    _env_cache = None
