"""URI parsing + the dmlc URI sugar spec.

Equivalents of reference io.h:539-554 (URI: protocol/host/name) and
src/io/uri_spec.h:42-75 (URISpec: ``path?format=libsvm&k=v#cachefile``,
with the cache file gaining a ``.splitN.partK`` suffix for multi-part
loads, uri_spec.h:47-53).
"""

from __future__ import annotations

from typing import Dict

from dmlc_tpu.utils.check import DMLCError


class URI:
    """``protocol://host/path`` split — analog of dmlc::io::URI (io.h:539)."""

    def __init__(self, uri: str):
        self.raw = uri
        pos = uri.find("://")
        if pos < 0:
            self.protocol = "file://"
            self.host = ""
            self.name = uri
        else:
            self.protocol = uri[: pos + 3]
            rest = uri[pos + 3:]
            slash = rest.find("/")
            if slash < 0:
                self.host, self.name = rest, ""
            else:
                self.host, self.name = rest[:slash], rest[slash:]

    def str_nohost(self) -> str:
        """protocol + name, host dropped (io.h: used for FS-relative paths)."""
        return self.protocol + self.name if self.protocol != "file://" else self.name

    def __str__(self) -> str:
        if self.protocol == "file://" and not self.host:
            return self.name
        return f"{self.protocol}{self.host}{self.name}"

    def __repr__(self) -> str:  # pragma: no cover
        return f"URI({str(self)!r})"


class URISpec:
    """URI sugar: ``real_uri?k=v&k2=v2#cache_file`` (uri_spec.h:42-75).

    Extensions over the reference: a fragment of the form
    ``#blockcache=<path>`` selects the parse-once columnar **block cache**
    (docs/data.md) instead of the raw chunk cache — ``block_cache`` then
    carries the raw path (partition qualification happens at the resolver,
    :func:`dmlc_tpu.data.parsers.create_parser`) and ``cache_file`` stays
    None. A ``#snapshot=<path>`` fragment selects the device-native
    **snapshot store** (docs/data.md snapshot section): ``snapshot``
    carries the raw path, resolved/qualified the same way, and arms
    ``DeviceIter``'s warm snapshot serving through the parser's
    ``snapshot_path`` attribute. A ``#service=<host:port>`` fragment
    selects the disaggregated **RowBlock data service**
    (docs/service.md): ``service`` carries the dispatcher address and the
    rest of the URI is informational (the dispatcher owns the dataset
    spec).
    """

    def __init__(self, uri: str, part_index: int = 0, num_parts: int = 1):
        name_cache = uri.split("#")
        self.block_cache: str | None = None
        self.snapshot: str | None = None
        self.service: str | None = None
        if len(name_cache) == 2:
            cache = name_cache[1]
            if cache.startswith("blockcache="):
                path = cache[len("blockcache="):]
                if not path:
                    raise DMLCError(
                        "empty path in `#blockcache=` URI suffix")
                self.block_cache = path
                self.cache_file: str | None = None
            elif cache.startswith("snapshot="):
                path = cache[len("snapshot="):]
                if not path:
                    raise DMLCError(
                        "empty path in `#snapshot=` URI suffix")
                self.snapshot = path
                self.cache_file = None
            elif cache.startswith("service="):
                addr = cache[len("service="):]
                if not addr or ":" not in addr:
                    raise DMLCError(
                        "`#service=` URI suffix needs a host:port "
                        "dispatcher address")
                self.service = addr
                self.cache_file = None
            else:
                if num_parts != 1:
                    cache = f"{cache}.split{num_parts}.part{part_index}"
                self.cache_file = cache
        elif len(name_cache) == 1:
            self.cache_file = None
        else:
            raise DMLCError("only one `#` is allowed in file path for cachefile specification")
        name_args = name_cache[0].split("?")
        self.args: Dict[str, str] = {}
        if len(name_args) == 2:
            for i, kv in enumerate(name_args[1].split("&")):
                if "=" not in kv:
                    raise DMLCError(f"Invalid uri argument format for arg {i + 1}: {kv!r}")
                key, value = kv.split("=", 1)
                self.args[key] = value
        elif len(name_args) != 1:
            raise DMLCError("only one `?` is allowed in file path for argument specification")
        self.uri = name_args[0]
