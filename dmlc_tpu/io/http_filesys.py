"""HTTP(S) read-only filesystem with Range requests.

Parity with the reference's http(s):// read support, which lives inside its
S3 module (s3_filesys.cc CURLReadStreamBase: ``Range: bytes=N-`` GETs,
restart-on-seek, s3_filesys.cc:498-701) — rebuilt on urllib with a buffered
block reader instead of a curl multi loop.

Every block fetch runs under the shared :class:`RetryPolicy`
(:mod:`dmlc_tpu.io.resilience`): transient faults (5xx/429, connection
reset, timeout) are retried with jittered backoff — honoring a 429's
``Retry-After`` as the backoff floor — and a mid-read failure refetches at
the CURRENT byte offset, so the consumer resumes mid-file instead of
restarting the epoch. Fatal classes (4xx auth, malformed URI) surface in
one attempt. The subclassed cloud streams (s3/gcs/azure/hdfs) inherit all
of this through ``_fetch_retry``.

Cloud filesystems (gs/s3/hdfs/azure) register their protocol slots here so
`get_filesystem` gives actionable errors; their signed-auth clients are
deliberately deferred (a zero-egress build environment cannot exercise them) — the
FileSystem registry is the extension point, matching the reference's
GetInstance dispatch (src/io.cc:30-71).
"""

from __future__ import annotations

import io as _pyio
import urllib.error
import urllib.request
from typing import List, Optional

from dmlc_tpu.io.filesystem import (
    FILE_TYPE, FileInfo, FileSystem, register_filesystem,
)
from dmlc_tpu.io.resilience import RetryPolicy, default_policy
from dmlc_tpu.io.uri import URI
from dmlc_tpu.utils.check import DMLCError

_BLOCK = 1 << 20  # read-ahead granularity


class HttpReadStream(_pyio.RawIOBase):
    """Seekable read-only stream over HTTP Range requests."""

    def __init__(self, url: str, size: Optional[int] = None,
                 policy: Optional[RetryPolicy] = None):
        super().__init__()
        self.url = url
        self._policy = policy or default_policy()
        self._pos = 0
        self._size = (size if size is not None
                      else _content_length(url, self._policy))
        self._buf = b""
        self._buf_start = 0

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def seek(self, offset: int, whence: int = 0) -> int:
        if whence == 0:
            self._pos = offset
        elif whence == 1:
            self._pos += offset
        elif whence == 2:
            self._pos = self._size + offset
        return self._pos

    def tell(self) -> int:
        return self._pos

    def _fetch(self, start: int, end: int) -> bytes:
        """One block attempt. Raises RAW transport errors (except 416 =
        EOF) — classification and retry live in :meth:`_fetch_retry`."""
        req = urllib.request.Request(
            self.url, headers={"Range": f"bytes={start}-{end - 1}"})
        try:
            with urllib.request.urlopen(
                    req, timeout=self._policy.attempt_timeout) as resp:
                body = resp.read()
                if resp.status == 206:
                    return body
                # server ignored the Range header and sent the whole file
                # (some simple servers do): keep the whole body as the buffer
                # so we never transfer it again, and serve the slice
                self._buf = body
                self._buf_start = 0
                return body[start:end]
        except urllib.error.HTTPError as exc:
            if exc.code == 416:  # requested range not satisfiable = EOF
                return b""
            raise

    def _fetch_retry(self, start: int, end: int) -> bytes:
        """Fetch a block under the shared retry budget. A retried fetch at
        ``start > 0`` is a mid-stream RESUME: the refetch re-requests the
        same byte range, so the consumer's position is exact — the Range
        machinery is the reopen-at-offset path."""
        return self._policy.call(
            lambda: self._fetch(start, end),
            op="read", what=self.url, resume_offset=start)

    def readinto(self, b) -> int:
        # BufferedReader drives RawIOBase through readinto
        data = self.read(len(b))
        b[: len(data)] = data
        return len(data)

    def read(self, n: int = -1) -> bytes:
        if n is None or n < 0:
            n = max(self._size - self._pos, 0)
        if n == 0 or self._pos >= self._size:
            return b""
        out = bytearray()
        while n > 0 and self._pos < self._size:
            buf_off = self._pos - self._buf_start
            if 0 <= buf_off < len(self._buf):
                take = min(n, len(self._buf) - buf_off)
                out += self._buf[buf_off:buf_off + take]
                self._pos += take
                n -= take
                continue
            # refill read-ahead block at current position
            start = self._pos
            end = min(start + max(_BLOCK, n), self._size)
            fetched = self._fetch_retry(start, end)
            if not fetched:
                break
            # on 200-servers _fetch installed the full body as the buffer;
            # otherwise install this block
            if not (self._buf_start == 0 and len(self._buf) == self._size):
                self._buf = fetched
                self._buf_start = start
        return bytes(out)


def _content_length(url: str, policy: Optional[RetryPolicy] = None) -> int:
    policy = policy or default_policy()

    def attempt() -> int:
        req = urllib.request.Request(url, method="HEAD")
        with urllib.request.urlopen(
                req, timeout=policy.attempt_timeout) as resp:
            length = resp.headers.get("Content-Length")
            if length is None:
                raise DMLCError(f"http: no Content-Length for {url}")
            return int(length)

    return policy.call(attempt, op="open", what=url)


class HttpFileSystem(FileSystem):
    """Read-only http/https file access; no listing (like the reference's
    http support: read streams only)."""

    native_resilience = True  # HttpReadStream resumes at the failed offset

    _instance: Optional["HttpFileSystem"] = None

    @classmethod
    def instance(cls, uri: Optional[URI] = None) -> "HttpFileSystem":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def get_path_info(self, path: URI) -> FileInfo:
        url = str(path)
        return FileInfo(path, _content_length(url), FILE_TYPE)

    def list_directory(self, path: URI) -> List[FileInfo]:
        raise DMLCError("http filesystem does not support directory listing")

    def open(self, path: URI, mode: str):
        if mode != "r":
            raise DMLCError("http filesystem is read-only")
        return _pyio.BufferedReader(HttpReadStream(str(path)))


def _deferred_cloud_fs(protocol: str, hint: str):
    def factory(uri: URI) -> FileSystem:
        raise DMLCError(
            f"{protocol} filesystem is not bundled in this build: {hint}. "
            f"Register an implementation with "
            f"dmlc_tpu.io.filesystem.register_filesystem({protocol!r}, ...)")
    return factory


register_filesystem("http://", HttpFileSystem.instance)
register_filesystem("https://", HttpFileSystem.instance)
register_filesystem(
    "gs://", _deferred_cloud_fs(
        "gs://", "needs google-cloud-storage or a signed-URL proxy"))
register_filesystem(
    "s3://", _deferred_cloud_fs(
        "s3://", "needs an AWS SigV4 client (reference: src/io/s3_filesys.cc)"))
register_filesystem(
    "hdfs://", _deferred_cloud_fs(
        "hdfs://", "needs libhdfs (reference: src/io/hdfs_filesys.cc)"))
register_filesystem(
    "azure://", _deferred_cloud_fs(
        "azure://", "needs azure-storage (reference stub: src/io/azure_filesys.cc)"))
