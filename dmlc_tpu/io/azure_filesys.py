"""Azure Blob Storage filesystem: a working REST client (SharedKey / SAS).

The reference's Azure member is a stub — ``GetPathInfo`` returns an empty
``FileInfo`` and ``Open``/``OpenForRead`` return NULL; only ``ListDirectory``
works, through the azure-storage-cpp SDK (azure_filesys.h:22-31,
azure_filesys.cc:33-41). This client implements the FULL FileSystem surface
over the Blob service REST API with urllib alone, exceeding the reference's
capability while keeping its contract:

- URI form ``azure://container/path`` — container is the URI host
  (src/io.cc:61, azure_filesys.cc "container name not specified in azure");
- env ``AZURE_STORAGE_ACCOUNT`` / ``AZURE_STORAGE_ACCESS_KEY``
  (azure_filesys.cc:33-38), plus ``AZURE_STORAGE_SAS_TOKEN`` as the
  keyless alternative the SDK era didn't have;
- reads: ranged GET through the shared buffered HTTP reader (the same
  pread shape as the S3/HDFS members);
- metadata: Get Blob Properties (HEAD) with prefix-listing fallback for
  directory-ness, List Blobs with ``delimiter=/`` for listing;
- writes: buffered Put Blob for small objects, Put Block + Put Block List
  for large ones (the multipart analog of the S3 write path,
  s3_filesys.cc:768-1010), with per-request retry.

``AZURE_ENDPOINT`` overrides ``https://{account}.blob.core.windows.net`` —
the hermetic-test seam, like ``S3_ENDPOINT`` / ``GCS_ENDPOINT``.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import io as _pyio
import os
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
from email.utils import formatdate
from typing import Dict, List, Optional, Tuple

from dmlc_tpu.io.filesystem import (
    DIR_TYPE, FILE_TYPE, FileInfo, FileSystem, register_filesystem,
)
from dmlc_tpu.io.http_filesys import HttpReadStream
from dmlc_tpu.io.resilience import RetryPolicy, default_policy
from dmlc_tpu.io.uri import URI
from dmlc_tpu.utils.check import DMLCError, check

_API_VERSION = "2021-08-06"


class AzureConfig:
    def __init__(self) -> None:
        # blobs above this upload as staged blocks (Put Block / Put Block
        # List); 32 MB mirrors the reference S3 writer's part-buffer order
        # of magnitude. Read per-instance (like DMLC_S3_WRITE_BUFFER_MB in
        # the S3 member) so the env knob works after package import.
        self.block_bytes = int(
            os.environ.get("AZURE_BLOCK_MB", "32")) * (1 << 20)
        self.account = os.environ.get("AZURE_STORAGE_ACCOUNT")
        self.key = os.environ.get("AZURE_STORAGE_ACCESS_KEY")
        self.sas = os.environ.get("AZURE_STORAGE_SAS_TOKEN", "").lstrip("?")
        check(bool(self.account),
              "Need to set environment variable AZURE_STORAGE_ACCOUNT "
              "to use Azure")
        check(bool(self.key or self.sas),
              "Need AZURE_STORAGE_ACCESS_KEY (SharedKey) or "
              "AZURE_STORAGE_SAS_TOKEN to use Azure")
        endpoint = os.environ.get("AZURE_ENDPOINT")
        self.endpoint = (endpoint.rstrip("/") if endpoint
                         else f"https://{self.account}.blob.core.windows.net")


def string_to_sign(method: str, account: str, path: str,
                   query: Dict[str, str], headers: Dict[str, str]) -> str:
    """Blob-service SharedKey StringToSign (2015-02-21+ format).

    ``headers`` must already include the x-ms-* set; standard headers are
    picked from it case-insensitively. Exposed for golden-format tests.
    """
    low = {k.lower(): v for k, v in headers.items()}

    def std(name: str) -> str:
        v = low.get(name, "")
        # Content-Length: empty string when zero (2015-02-21 change)
        return "" if name == "content-length" and v in ("0", "") else v

    canon_headers = "".join(
        f"{k}:{low[k]}\n" for k in sorted(low) if k.startswith("x-ms-"))
    canon_resource = f"/{account}{path}"
    for k in sorted(query, key=str.lower):
        canon_resource += f"\n{k.lower()}:{query[k]}"
    return "\n".join([
        method.upper(),
        std("content-encoding"), std("content-language"),
        std("content-length"), std("content-md5"), std("content-type"),
        # Date is signed via x-ms-date in the canonicalized headers; the
        # standalone Date line must then be empty
        "" if "x-ms-date" in low else std("date"),
        std("if-modified-since"), std("if-match"), std("if-none-match"),
        std("if-unmodified-since"), std("range"),
    ]) + "\n" + canon_headers + canon_resource


def sign_shared_key(cfg: AzureConfig, method: str, path: str,
                    query: Dict[str, str], headers: Dict[str, str]) -> str:
    sts = string_to_sign(method, cfg.account, path, query, headers)
    mac = hmac.new(base64.b64decode(cfg.key), sts.encode("utf-8"),
                   hashlib.sha256)
    return (f"SharedKey {cfg.account}:"
            f"{base64.b64encode(mac.digest()).decode('ascii')}")


def _request(cfg: AzureConfig, method: str, path: str,
             query: Optional[Dict[str, str]] = None,
             headers: Optional[Dict[str, str]] = None,
             data: Optional[bytes] = None,
             op: str = "request",
             policy: Optional[RetryPolicy] = None,
             retry: bool = True,
             ) -> Tuple[int, bytes, Dict[str, str]]:
    """One authenticated request under the shared retry policy. ``path`` is
    the container/blob path starting with '/'; returns (status, body,
    response headers). 404 returns instead of raising (directory probes
    need it). Fault classification lives in the shared classifier (this
    used to hard-code ``code < 500`` — now 429/408 retry too, with any
    ``Retry-After`` honored as the backoff floor); each attempt re-signs
    with a fresh ``x-ms-date``. ``retry=False`` runs one raw attempt for
    the read stream, whose budget lives in ``_fetch_retry``."""
    query = dict(query or {})
    pol = policy or default_policy()

    def attempt() -> Tuple[int, bytes, Dict[str, str]]:
        q = dict(query)
        hdrs = {"x-ms-date": formatdate(usegmt=True),
                "x-ms-version": _API_VERSION}
        hdrs.update(headers or {})
        if data is not None:
            hdrs["content-length"] = str(len(data))
            # set the type explicitly (and sign it): urllib would otherwise
            # inject application/x-www-form-urlencoded AFTER signing
            hdrs.setdefault("content-type", "application/octet-stream")
        qpath = urllib.parse.quote(path)
        if cfg.key:
            # CanonicalizedResource is built from the path as it appears in
            # the request line, i.e. the percent-encoded form
            hdrs["Authorization"] = sign_shared_key(cfg, method, qpath, q,
                                                    hdrs)
        elif cfg.sas:
            q.update(urllib.parse.parse_qsl(cfg.sas))
        qs = urllib.parse.urlencode(sorted(q.items()))
        url = cfg.endpoint + qpath + (f"?{qs}" if qs else "")
        req = urllib.request.Request(url, data=data, method=method.upper())
        for k, v in hdrs.items():
            req.add_header(k, v)
        try:
            with urllib.request.urlopen(
                    req, timeout=pol.attempt_timeout) as resp:
                # lower-case header keys: HTTP headers are case-insensitive
                # and proxies/emulators emit e.g. content-length — a
                # case-sensitive lookup would read size 0 and truncate reads
                return resp.status, resp.read(), {
                    k.lower(): v for k, v in resp.headers.items()}
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                return 404, b"", {}
            try:
                body = exc.read()
            except Exception:  # noqa: BLE001 - body already gone
                body = b""
            if body:
                # the actionable detail (AuthenticationFailed code, the
                # server's string-to-sign) lives in the XML body — fold it
                # into the message so the surfaced DMLCError keeps it
                exc.msg = f"{exc.msg}: {body[:200]!r}"
            raise

    if not retry:
        return attempt()
    return pol.call(attempt, op=op, what=f"azure:/{path}")


def _parse_azure_uri(path: URI) -> Tuple[str, str]:
    check(bool(path.host), "container name not specified in azure URI "
                           "(azure://container/path)")
    return path.host, path.name.lstrip("/")


class AzureReadStream(HttpReadStream):
    """Buffered range reader over signed GET Blob requests."""

    def __init__(self, cfg: AzureConfig, container: str, key: str, size: int):
        self._cfg = cfg
        self._blob_path = f"/{container}/{key}"
        super().__init__(cfg.endpoint + self._blob_path, size=size)

    def _fetch(self, start: int, end: int) -> bytes:
        status, body, _ = _request(
            self._cfg, "GET", self._blob_path,
            headers={"range": f"bytes={start}-{end - 1}"},
            retry=False)  # the stream-level _fetch_retry owns the budget
        check(status in (200, 206), f"azure range GET -> {status}")
        if status == 200:
            # server/proxy ignored the Range header and sent the whole
            # blob: keep it as the buffer (never transfer it again) and
            # serve the requested slice — same contract as the parent
            # HttpReadStream._fetch
            self._buf = body
            self._buf_start = 0
            return body[start:end]
        return body


class AzureWriteStream(_pyio.RawIOBase):
    """Block-blob writer: small payloads go up as one Put Blob; larger ones
    stage ``AZURE_BLOCK_MB``-sized chunks with Put Block as they accumulate
    and commit with Put Block List on close (the S3 multipart analog)."""

    def __init__(self, cfg: AzureConfig, container: str, key: str):
        self._cfg = cfg
        self._path = f"/{container}/{key}"
        self._buf = bytearray()
        self._block_ids: List[str] = []
        self._closed = False

    def writable(self) -> bool:
        return True

    def write(self, b) -> int:
        self._buf += bytes(b)
        block = self._cfg.block_bytes
        while len(self._buf) >= block:
            self._stage(bytes(self._buf[:block]))
            del self._buf[:block]
        return len(b)

    def _stage(self, chunk: bytes) -> None:
        bid = base64.b64encode(
            f"{len(self._block_ids):08d}".encode("ascii")).decode("ascii")
        status, _, _ = _request(
            self._cfg, "PUT", self._path,
            query={"comp": "block", "blockid": bid}, data=chunk, op="write")
        check(status in (200, 201), f"azure Put Block -> {status}")
        self._block_ids.append(bid)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if not self._block_ids:
            # single-shot Put Blob
            status, _, _ = _request(
                self._cfg, "PUT", self._path, data=bytes(self._buf),
                headers={"x-ms-blob-type": "BlockBlob"}, op="write")
            check(status in (200, 201), f"azure Put Blob -> {status}")
        else:
            if self._buf:
                self._stage(bytes(self._buf))
            xml = ("<?xml version='1.0' encoding='utf-8'?><BlockList>"
                   + "".join(f"<Latest>{b}</Latest>" for b in self._block_ids)
                   + "</BlockList>").encode("utf-8")
            status, _, _ = _request(
                self._cfg, "PUT", self._path, query={"comp": "blocklist"},
                data=xml, op="write")
            check(status in (200, 201), f"azure Put Block List -> {status}")
        self._buf = bytearray()
        super().close()


class AzureFileSystem(FileSystem):
    """Blob-service FileSystem (full surface; the reference stubs all but
    ListDirectory, azure_filesys.h:22-31)."""

    native_resilience = True  # AzureReadStream resumes via _fetch_retry

    def __init__(self, cfg: AzureConfig):
        self.cfg = cfg

    @classmethod
    def instance(cls, uri: Optional[URI] = None) -> "AzureFileSystem":
        return cls(AzureConfig())

    def _list(self, container: str, prefix: str,
              delimiter: str = "/") -> List[Tuple[str, int, str]]:
        out: List[Tuple[str, int, str]] = []
        marker = ""
        while True:
            query = {"restype": "container", "comp": "list",
                     "prefix": prefix}
            if delimiter:
                query["delimiter"] = delimiter
            if marker:
                query["marker"] = marker
            status, body, _ = _request(self.cfg, "GET", f"/{container}",
                                       query=query, op="open")
            check(status == 200, f"azure List Blobs -> {status}")
            root = ET.fromstring(body)
            blobs = root.find("Blobs")
            if blobs is not None:
                for el in blobs:
                    name = el.findtext("Name", "")
                    if el.tag == "Blob":
                        size = int(el.findtext(
                            "Properties/Content-Length", "0"))
                        out.append((name, size, FILE_TYPE))
                    elif el.tag == "BlobPrefix":
                        out.append((name.rstrip("/"), 0, DIR_TYPE))
            marker = root.findtext("NextMarker", "") or ""
            if not marker:
                return out

    def get_path_info(self, path: URI) -> FileInfo:
        container, key = _parse_azure_uri(path)
        status, _, headers = _request(self.cfg, "HEAD",
                                      f"/{container}/{key}", op="open")
        if status == 200:
            return FileInfo(path, int(headers.get("content-length", 0)),
                            FILE_TYPE)
        prefix = key.rstrip("/") + "/" if key else ""
        if self._list(container, prefix):
            return FileInfo(path, 0, DIR_TYPE)
        raise DMLCError(f"azure path not found: {str(path)}")

    def list_directory(self, path: URI) -> List[FileInfo]:
        container, key = _parse_azure_uri(path)
        prefix = key.rstrip("/") + "/" if key else ""
        return [FileInfo(URI(f"azure://{container}/{name}"), size, typ)
                for name, size, typ in self._list(container, prefix)]

    def open(self, path: URI, mode: str):
        container, key = _parse_azure_uri(path)
        if "r" in mode:
            info = self.get_path_info(path)
            check(info.type == FILE_TYPE, f"not a file: {str(path)}")
            return _pyio.BufferedReader(
                AzureReadStream(self.cfg, container, key, info.size))
        if "w" in mode:
            return _pyio.BufferedWriter(
                AzureWriteStream(self.cfg, container, key))
        raise DMLCError(f"unsupported azure open mode {mode!r}")

    def open_for_read(self, path: URI):
        return self.open(path, "rb")


register_filesystem("azure://", AzureFileSystem.instance)
