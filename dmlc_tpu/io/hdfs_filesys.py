"""HDFS filesystem via WebHDFS/HttpFS: pure-Python, no JNI.

The reference's HDFS client (src/io/hdfs_filesys.cc:10-193) links libhdfs
and a JVM — the wrong trade on a TPU-VM, where shipping a Hadoop runtime
for input streaming is pure overhead. WebHDFS exposes the same namenode
semantics over REST (Hadoop ships it on the namenode HTTP port, and HttpFS
speaks the identical protocol through a gateway), so this client covers the
reference's capability surface with urllib alone:

- reads: ``op=OPEN&offset=N&length=M`` range reads through the shared
  buffered HTTP reader — the analog of the chunked ``hdfsRead``/``hdfsPread``
  loop (hdfs_filesys.cc:31-58); the namenode's 307 redirect to a datanode is
  followed automatically;
- metadata: ``op=GETFILESTATUS`` / ``op=LISTSTATUS``
  (hdfs_filesys.cc GetPathInfo/ListDirectory);
- writes: ``op=CREATE`` two-step (namenode hands out the datanode location,
  payload is PUT there on close), buffered like the reference's write path;
- auth: ``user.name`` from ``HADOOP_USER_NAME``/``USER``, or a delegation
  token from ``HDFS_DELEGATION_TOKEN`` (kerberized clusters mint one with
  ``hdfs fetchdt``).

URI forms: ``hdfs://namenode:9870/path`` (port = the namenode's HTTP port;
default 9870 when omitted). ``HDFS_WEBHDFS_ENDPOINT`` overrides the whole
endpoint — the hermetic-test seam, like ``S3_ENDPOINT``/``GCS_ENDPOINT``.
"""

from __future__ import annotations

import io as _pyio
import json
import os
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, List, Optional

from dmlc_tpu.io.filesystem import (
    DIR_TYPE, FILE_TYPE, FileInfo, FileSystem, register_filesystem,
)
from dmlc_tpu.io.http_filesys import HttpReadStream
from dmlc_tpu.io.resilience import RetryPolicy, default_policy
from dmlc_tpu.io.uri import URI
from dmlc_tpu.utils.check import DMLCError, check

_DEFAULT_HTTP_PORT = 9870  # namenode HTTP (Hadoop 3 default)


class HdfsConfig:
    def __init__(self, uri: Optional[URI] = None) -> None:
        endpoint = os.environ.get("HDFS_WEBHDFS_ENDPOINT")
        if endpoint:
            self.endpoint = endpoint.rstrip("/")
        else:
            check(uri is not None and uri.host,
                  "hdfs:// URI needs a namenode host (hdfs://host[:port]/path)"
                  " or HDFS_WEBHDFS_ENDPOINT")
            host, _, port = uri.host.partition(":")
            self.endpoint = f"http://{host}:{port or _DEFAULT_HTTP_PORT}"
        self.user = os.environ.get("HADOOP_USER_NAME") or os.environ.get("USER")
        self.delegation = os.environ.get("HDFS_DELEGATION_TOKEN")

    def url(self, path: str, op: str, **params: str) -> str:
        query: Dict[str, str] = {"op": op}
        if self.delegation:
            query["delegation"] = self.delegation
        elif self.user:
            query["user.name"] = self.user
        query.update(params)
        if not path.startswith("/"):
            path = "/" + path
        return (f"{self.endpoint}/webhdfs/v1"
                f"{urllib.parse.quote(path)}?"
                + urllib.parse.urlencode(sorted(query.items())))


def _request(url: str, method: str = "GET", data: Optional[bytes] = None,
             op: str = "request",
             policy: Optional[RetryPolicy] = None, retry: bool = True):
    """One WebHDFS request under the shared retry policy; returns the live
    response. Transient statuses raise raw for the classifier; deterministic
    failures surface the namenode's RemoteException message in one attempt.
    ``retry=False`` runs a single attempt (the read stream's budget lives
    in the inherited ``_fetch_retry``)."""
    pol = policy or default_policy()

    def attempt():
        req = urllib.request.Request(url, data=data, method=method)
        try:
            return urllib.request.urlopen(req, timeout=pol.attempt_timeout)
        except urllib.error.HTTPError as exc:
            if exc.code in (408, 429) or exc.code >= 500:
                raise  # transient: retried (or resumed) by the caller
            # webhdfs errors carry a RemoteException JSON body
            try:
                detail = json.loads(exc.read()).get("RemoteException", {})
                msg = detail.get("message", str(exc))
            except Exception:  # noqa: BLE001 - non-JSON error body
                msg = str(exc)
            raise DMLCError(
                f"webhdfs {method} failed ({exc.code}): {msg}") from exc

    if not retry:
        return attempt()
    return pol.call(attempt, op=op, what=url)


class HdfsReadStream(HttpReadStream):
    """Buffered range reader over ``op=OPEN`` — the pread analog
    (hdfs_filesys.cc:46-58); short reads are absorbed by the buffer loop."""

    def __init__(self, cfg: HdfsConfig, path: str, size: int):
        self._cfg = cfg
        self._path = path
        super().__init__(cfg.url(path, "OPEN"), size=size)

    def _fetch(self, start: int, end: int) -> bytes:
        url = self._cfg.url(self._path, "OPEN", offset=str(start),
                            length=str(end - start))
        # single raw attempt: the inherited _fetch_retry owns the budget
        with _request(url, retry=False) as resp:
            return resp.read()


class HdfsWriteStream(_pyio.RawIOBase):
    """Buffer-then-PUT writer: op=CREATE against the namenode, payload to
    the returned datanode location on close (the two-step WebHDFS create)."""

    def __init__(self, cfg: HdfsConfig, path: str, overwrite: bool = True):
        self._cfg = cfg
        self._path = path
        self._overwrite = "true" if overwrite else "false"
        self._buf = bytearray()
        self._closed = False

    def writable(self) -> bool:
        return True

    def write(self, b) -> int:
        self._buf += bytes(b)
        return len(b)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        url = self._cfg.url(self._path, "CREATE",
                            overwrite=self._overwrite, noredirect="true")
        with _request(url, method="PUT", op="write") as resp:
            body = resp.read()
            location = resp.headers.get("Location")
        if not location and body:
            try:
                location = json.loads(body).get("Location")
            except ValueError:
                location = None
        check(location is not None,
              "webhdfs CREATE returned no datanode location")
        with _request(location, method="PUT", data=bytes(self._buf),
                      op="write"):
            pass
        self._buf = bytearray()
        super().close()


def _info_from_status(base: URI, name: str, st: Dict) -> FileInfo:
    kind = FILE_TYPE if st.get("type") == "FILE" else DIR_TYPE
    path = base if not name else URI(str(base).rstrip("/") + "/" + name)
    return FileInfo(path, int(st.get("length", 0)), kind)


class HdfsFileSystem(FileSystem):
    """WebHDFS-backed FileSystem (capability parity with
    src/io/hdfs_filesys.cc, minus the JVM)."""

    native_resilience = True  # HdfsReadStream resumes via _fetch_retry

    def __init__(self, cfg: HdfsConfig):
        self.cfg = cfg

    @classmethod
    def instance(cls, uri: URI) -> "HdfsFileSystem":
        return cls(HdfsConfig(uri))

    def get_path_info(self, path: URI) -> FileInfo:
        url = self.cfg.url(path.name, "GETFILESTATUS")
        with _request(url, op="open") as resp:
            st = json.loads(resp.read())["FileStatus"]
        return _info_from_status(path, "", st)

    def list_directory(self, path: URI) -> List[FileInfo]:
        url = self.cfg.url(path.name, "LISTSTATUS")
        with _request(url, op="open") as resp:
            statuses = json.loads(resp.read())["FileStatuses"]["FileStatus"]
        return [_info_from_status(path, st.get("pathSuffix", ""), st)
                for st in statuses]

    def open(self, path: URI, mode: str):
        if mode == "r":
            size = self.get_path_info(path).size
            return _pyio.BufferedReader(
                HdfsReadStream(self.cfg, path.name, size))
        if mode in ("w", "a"):
            # append maps to CREATE-overwrite for parity with the reference's
            # O_WRONLY semantics (hdfs_filesys.cc Open: append unsupported
            # without dfs.support.append; we take the same stance)
            if mode == "a":
                raise DMLCError(
                    "webhdfs append not supported; write whole objects")
            return _pyio.BufferedWriter(HdfsWriteStream(self.cfg, path.name))
        raise DMLCError(f"unsupported hdfs open mode {mode!r}")


register_filesystem("hdfs://", HdfsFileSystem.instance)
register_filesystem("viewfs://", HdfsFileSystem.instance)
