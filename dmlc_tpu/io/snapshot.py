"""Device-native snapshot store: post-convert device-layout batches on disk.

The parse-once block cache (:mod:`dmlc_tpu.io.block_cache`) stores PARSER
output — warm epochs still pay the full host-side convert/pack cost per
batch, which caps them near the text-parse ceiling even though the
measured ``device_put`` floor sits ~70x higher (ROADMAP item 3). This
module stores the pipeline one stage later, at the highest-leverage point
left: the exact *post-convert, device-layout* batches ``DeviceIter``
ships — packed dense ``[B, num_col + 2]`` slabs (features | label |
weight) in float32 or bfloat16, padded-ELL sparse batches, or
int8-quantized slabs with per-column scale — at one fixed batch geometry
recorded in the footer. Warm snapshot epochs then mmap each batch's
segments straight into the transfer path and issue the one ``device_put``
with **zero host convert work**: the warm rate is bounded by transfer,
not host packing (the ads-scale training-infra recipe, arXiv:2501.10546
§4; tf.data's materialize-the-expensive-prefix argument,
arXiv:2101.12127 §5).

Format v1 ("DMLCSN01", pinned by ``tests/data/snapshot_v1.golden``) is a
sibling of block-cache v1 built from the SAME machinery
(:func:`~dmlc_tpu.io.block_cache.write_segments` /
:func:`~dmlc_tpu.io.block_cache.read_segments` /
:func:`~dmlc_tpu.io.block_cache.finish_container` /
:func:`~dmlc_tpu.io.block_cache.open_container`)::

    [header]   magic "DMLCSN01" (8B) + version u32 LE + 4 zero pad bytes
    [segments] per batch, its positional arrays (a0, a1, ...): the v1
               segment encoding — 64-byte-aligned starts, raw
               little-endian C-order bytes, one crc32 per batch
    [footer]   utf-8 JSON (sort_keys): {"version", "signature",
               "geometry", "rows", "batches": [{"kind", "pos", "end",
               "rows", "crc", "resume", "arrays": {name: [dtype_str,
               abs_offset, nbytes]}, "shapes": {name: [dims...]}}, ...]}
    [tail]     u64 footer_offset + u64 footer_len + u32 footer_crc LE
               + magic "DMLCSN01"

A batch is ``(kind, arr0, arr1, ...)`` — exactly a ``DeviceIter`` host
batch minus the leading kind string: ``("dense_packed", xp)``,
``("dense", x, y, w)``, ``("ell", indices, values, label, weight)``,
``("dense_packed_q8", q8, scale)``. Arrays may be 2-D (the footer stores
shapes; :func:`~dmlc_tpu.io.block_cache.read_segments` views are reshaped
on load), so one decode path serves every fixed-geometry layout.

Staleness is TWO-keyed: the ``signature`` (source files + parser config,
same discipline as the block cache) catches source drift, and the
``geometry`` — ``{batch_size, num_col, layout, x_dtype, pack_aux, quant,
drop_remainder, max_nnz}`` — catches pipeline-shape drift: a snapshot
written at a different batch size or dtype must self-invalidate at open
(:func:`open_snapshot` drops it and counts ``snapshot_invalidations``),
never serve wrong-shaped batches.

This module owns the FORMAT plus the order-following feed
(:class:`SnapshotIter`); the pipeline integration — the shadow write over
the convert stage, the ``snapshot_read`` stage attribution, checkpoints —
lives in :mod:`dmlc_tpu.data.device` (the io layer stays free of
data-layer imports, like the block cache).
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Callable, List, Optional, Tuple

import numpy as np

from dmlc_tpu.io import faults
from dmlc_tpu.io import resilience as _resilience
from dmlc_tpu.utils import knobs as _knobs
from dmlc_tpu.utils import telemetry as _telemetry
from dmlc_tpu.utils.check import CacheCorruptionError, DMLCError, check
from dmlc_tpu.utils.timer import get_time

SNAPSHOT_MAGIC = b"DMLCSN01"
SNAPSHOT_VERSION = 1

# positional segment names: batch arrays are stored in tuple order under
# a0..aN (a snapshot batch is (kind, *arrays), not the named CSR columns
# of the block cache) — bounded so the canonical write order is total
MAX_BATCH_ARRAYS = 8
SNAPSHOT_SEGMENT_NAMES = tuple(f"a{i}" for i in range(MAX_BATCH_ARRAYS))


def quantize_int8(arr) -> Tuple[np.ndarray, np.ndarray]:
    """Per-column symmetric int8 quantization of a 2-D float batch:
    returns ``(q8, scale)`` with ``scale`` float32 per column
    (``absmax / 127``; zero columns get scale 1.0 so dequant is exact
    zeros). The device dequantizes with one fused multiply
    (``dequant_q8``) — the opt-in that quarters snapshot bytes for value
    ranges that tolerate 8-bit precision.

    Thin wrapper: the implementation lives in
    :mod:`dmlc_tpu.ops.device_decode` (the single sanctioned device-side
    dtype path, quantize and dequant audited as one pair). Imported
    lazily — this module must stay importable without jax (the service
    frame codec's no-jax contract)."""
    from dmlc_tpu.ops.device_decode import quantize_int8 as _impl

    return _impl(arr)


class SnapshotWriter:
    """Streams checksummed device-layout batches to a store-allocated
    staging file; :meth:`finish` writes the footer (geometry + per-batch
    resume annotations) and publishes through the artifact store — the
    shadow half of a cold epoch (the convert stage's output tees in
    here)."""

    def __init__(self, path: str, signature: Optional[dict] = None,
                 geometry: Optional[dict] = None):
        from dmlc_tpu.io import block_cache as _bc

        self._bc = _bc
        self.path = path
        self._sig = signature or {}
        self._geom = _bc._normalize(geometry or {})
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        # process-unique staging name from the store (docs/store.md):
        # concurrent writers can never clobber each other's bytes
        self.tmp_path = _bc._artifact_store(path).stage_path(path)
        self._f = open(self.tmp_path, "wb")
        self._f.write(_bc.container_header(SNAPSHOT_MAGIC, SNAPSHOT_VERSION))
        self._entries: List[dict] = []
        self._rows = 0
        self._finished = False

    def add_batch(self, kind: str, arrays, rows: int,
                  resume: Optional[dict] = None) -> None:
        """Append one device-layout batch: ``arrays`` is the positional
        tuple of numpy arrays behind ``kind`` (2-D allowed — shapes are
        recorded); ``resume`` is the pipeline's resume annotation for the
        position just after this batch, stored so warm epochs re-attach
        byte-exact checkpoint states."""
        check(self._f is not None and not self._finished,
              "SnapshotWriter: writer already finished/aborted")
        check(len(arrays) <= MAX_BATCH_ARRAYS,
              f"SnapshotWriter: batch carries {len(arrays)} arrays "
              f"(max {MAX_BATCH_ARRAYS})")
        t_span = get_time()
        f = self._f
        arrs = [np.ascontiguousarray(a) for a in arrays]
        segments = {SNAPSHOT_SEGMENT_NAMES[i]: a.reshape(-1)
                    for i, a in enumerate(arrs)}
        pos = self._bc._pad_to(f, self._bc._ALIGN)
        end, crc, arr_meta = self._bc.write_segments(
            f, segments, names=SNAPSHOT_SEGMENT_NAMES)
        resume_json = (json.loads(json.dumps(resume))
                       if resume is not None else None)
        self._entries.append({
            "kind": str(kind), "pos": pos, "end": end, "rows": int(rows),
            "crc": crc, "resume": resume_json, "arrays": arr_meta,
            "shapes": {SNAPSHOT_SEGMENT_NAMES[i]: list(a.shape)
                       for i, a in enumerate(arrs)},
        })
        self._rows += int(rows)
        # the shadow write's own cost, visible on the trace timeline next
        # to the convert spans it rides behind (cold-epoch overhead is a
        # real stage even though stats() folds it into consumer wall)
        _telemetry.record_span("snapshot_write", t_span,
                               get_time() - t_span, rows=int(rows))

    def finish(self) -> None:
        """Write footer + tail, fsync, atomically publish at ``path``."""
        check(self._f is not None and not self._finished,
              "SnapshotWriter: writer already finished/aborted")
        footer = {
            "version": SNAPSHOT_VERSION,
            "signature": self._sig,
            "geometry": self._geom,
            "rows": self._rows,
            "batches": self._entries,
        }
        f, self._f = self._f, None
        self._bc.finish_container(f, self.tmp_path, self.path, footer,
                                  SNAPSHOT_MAGIC)
        self._finished = True

    def abort(self) -> None:
        """Drop the partial tmp file (interrupted cold pass)."""
        if self._f is not None:
            self._f.close()
            self._f = None
        try:
            os.remove(self.tmp_path)
        except OSError:
            pass

    def close(self) -> None:
        if not self._finished:
            self.abort()


class SnapshotReader:
    """mmap-backed snapshot reader: batches decode to zero-copy read-only
    numpy views in the stored shapes.

    Views alias the mmap — numpy pins the buffer owner via the view's
    ``base`` chain, and :meth:`close` tolerates still-exported views
    (GC reclaims the mmap once the last one dies), the same lifetime
    contract as the block-cache reader.
    """

    def __init__(self, path: str, signature: Optional[dict] = None,
                 geometry: Optional[dict] = None, verify: bool = True):
        from dmlc_tpu.io import block_cache as _bc

        self._bc = _bc
        self.path = path
        self.verify = verify
        self._store_pinned = False
        self._file, self._mm, footer = _bc.open_container(
            path, SNAPSHOT_MAGIC, SNAPSHOT_VERSION, f"snapshot {path}")
        try:
            self.signature = footer.get("signature") or {}
            self.geometry = footer.get("geometry") or {}
            self.rows = int(footer.get("rows", 0))
            self._batches = footer["batches"]
            if signature is not None and self.signature != _bc._normalize(
                    signature):
                raise DMLCError(
                    f"snapshot {path}: source signature mismatch "
                    f"(stale snapshot)")
            if geometry is not None and self.geometry != _bc._normalize(
                    geometry):
                # the load-bearing staleness check this format adds: a
                # snapshot written at a different batch_size / x_dtype /
                # padding config must never serve wrong-shaped batches
                raise DMLCError(
                    f"snapshot {path}: batch geometry mismatch "
                    f"(stored {self.geometry})")
            # pin/refcount (docs/store.md): a warm epoch streaming this
            # snapshot can never lose it to a byte-budget eviction
            _bc._artifact_store(path).pin(path)
            self._store_pinned = True
        except Exception:
            self.close()
            raise

    # ---------------- accessors ----------------

    @property
    def num_batches(self) -> int:
        return len(self._batches)

    @property
    def hold(self):
        """The buffer owner views must pin (the mmap)."""
        return self._mm

    def kind(self, i: int) -> str:
        return self._batches[i]["kind"]

    def resume(self, i: int) -> Optional[dict]:
        """The stored resume annotation of batch ``i`` (the pipeline
        position just after it), or None when the producer had none."""
        return self._batches[i]["resume"]

    def batch_rows(self, i: int) -> int:
        return int(self._batches[i]["rows"])

    def batch_nbytes(self, i: int) -> int:
        e = self._batches[i]
        return int(e["end"]) - int(e["pos"])

    def load_batch(self, i: int, copy: bool = False) -> tuple:
        """Decode batch ``i`` to ``(kind, arr0, arr1, ...)`` — zero-copy
        read-only views over the mmap, reshaped to the stored shapes.

        ``copy=True`` materializes into process memory (plan-ordered warm
        epochs serve a permuted pattern OS readahead cannot predict; the
        copy forces those page faults to land inside the caller's timed
        ``snapshot_read`` region — same attribution discipline as the
        block cache's permuted serves).

        Raises :class:`CacheCorruptionError` on a crc mismatch (or an
        injected ``snapshot_read`` fault) — the consumer heals by
        dropping the snapshot and re-converting from the source.
        """
        faults.maybe_fail("snapshot_read", self.path)
        entry = self._batches[i]
        if self.verify:
            with memoryview(self._mm)[
                    int(entry["pos"]): int(entry["end"])] as span:
                ok = zlib.crc32(span) & 0xFFFFFFFF == int(entry["crc"])
            if not ok:
                raise CacheCorruptionError(
                    f"snapshot {self.path}: crc mismatch on batch {i}")
        segments = self._bc.read_segments(self._mm, entry["arrays"])
        shapes = entry.get("shapes") or {}
        out = []
        for name in SNAPSHOT_SEGMENT_NAMES:
            if name not in segments:
                break
            arr = segments[name]
            shape = shapes.get(name)
            if shape is not None and len(shape) != 1:
                arr = arr.reshape(shape)
            if copy:
                arr = np.array(arr)
            out.append(arr)
        return (entry["kind"], *out)

    def batch_span(self, i: int, copy: bool = False) -> tuple:
        """Batch ``i`` as its raw container bytes: ``(kind, span,
        layout)`` with ``span`` the verbatim ``[pos, end)`` u8 view over
        the mmap and ``layout`` the hashable segment map
        (:func:`~dmlc_tpu.io.block_cache.span_layout`, offsets rebased
        to the span) — the device-decode tier's input: the consumer
        ``device_put``s the span untouched (one contiguous transfer)
        and :func:`dmlc_tpu.ops.device_decode.decode_span` slices and
        bitcasts it in HBM. No per-segment host views are built.

        crc + fault semantics match :meth:`load_batch`; ``copy=True``
        materializes the span (plan-ordered warm epochs — same
        attribution discipline as ``load_batch``)."""
        faults.maybe_fail("snapshot_read", self.path)
        entry = self._batches[i]
        pos, end = int(entry["pos"]), int(entry["end"])
        if self.verify:
            with memoryview(self._mm)[pos:end] as mv:
                ok = zlib.crc32(mv) & 0xFFFFFFFF == int(entry["crc"])
            if not ok:
                raise CacheCorruptionError(
                    f"snapshot {self.path}: crc mismatch on batch {i}")
        span = np.asarray(memoryview(self._mm)[pos:end])
        if copy:
            span = np.array(span)
        layout = self._bc.span_layout(entry["arrays"],
                                      entry.get("shapes"), base=pos)
        return entry["kind"], span, layout

    def close(self) -> None:
        # the eviction pin drops first, unconditionally (see the
        # block-cache reader: an unlinked-but-mapped file keeps serving)
        if getattr(self, "_store_pinned", False):
            self._store_pinned = False
            try:
                self._bc._artifact_store(self.path).drop(self.path)
            except OSError:
                pass
        # best-effort: the mmap cannot close while exported views are
        # alive (BufferError) — GC reclaims it once the last view dies
        mm = getattr(self, "_mm", None)
        if mm is not None:
            try:
                mm.close()
                self._mm = None
            except BufferError:
                pass
        f = getattr(self, "_file", None)
        if f is not None:
            self._file = None
            f.close()


def open_snapshot(path: str, signature: Optional[dict] = None,
                  geometry: Optional[dict] = None,
                  verify: bool = True) -> Optional[SnapshotReader]:
    """Open a published snapshot, or None when it is missing or must be
    rebuilt (unreadable / wrong version / signature mismatch / **batch
    geometry mismatch** — the stale file is dropped via the store and a
    ``snapshot_invalidations`` resilience event counted, so callers
    simply fall back to a cold convert pass). A miss on a path the store
    manifest marks as EVICTED counts ``store_rebuilds_after_eviction``
    (docs/store.md)."""
    from dmlc_tpu.io import block_cache as _bc

    if not os.path.exists(path):
        # light probe: only consults the store when the directory already
        # carries a manifest (never creates state for an unmanaged dir)
        _bc._store_manager().note_missing(path)
        return None
    try:
        return SnapshotReader(path, signature=signature, geometry=geometry,
                              verify=verify)
    except DMLCError:
        _resilience.record_event("snapshot_invalidations")
        _bc._artifact_store(path).discard(path)
        return None


class SnapshotIter:
    """The warm feed: serves a snapshot's batches in a given order with
    reads prefetched on a small
    :class:`~dmlc_tpu.io.threaded_iter.OrderedWorkerPool`, so loading
    (mmap fault + crc) of batch N+1 overlaps the transfer of batch N —
    the host half of the HBM double-buffer.

    ``order`` is an index array (an epoch plan's permutation over batch
    indices) or None for sequential; ``start`` resumes mid-epoch at a
    plan position. ``next()`` returns ``(host_batch, resume, nbytes)``
    with ``host_batch = (kind, *arrays)``, or None at end of epoch. Each
    read is timed into a ``snapshot_read`` span and reported through the
    ``on_read`` callback (the consumer's stage-busy meter).

    ``raw=True`` is the device-decode feed: ``host_batch`` becomes
    ``("device_span", span, layout, kind)`` — the batch's verbatim
    container bytes (:meth:`SnapshotReader.batch_span`) instead of
    decoded host views, for consumers that transfer the span untouched
    and decode in HBM. Resume annotations, ordering, and timing are
    identical, so checkpoint states restore across the two modes.
    """

    def __init__(self, reader: SnapshotReader,
                 order: Optional[np.ndarray] = None, start: int = 0,
                 read_workers: Optional[int] = None,
                 on_read: Optional[Callable[[float], None]] = None,
                 annotate: bool = False, raw: bool = False):
        from dmlc_tpu.io.threaded_iter import OrderedWorkerPool

        self.reader = reader
        self._order = order
        self._on_read = on_read
        self._annotate = annotate
        self._raw = raw
        n = reader.num_batches if order is None else len(order)
        workers = _knobs.resolve("snapshot_read_workers", read_workers)
        self._pool = OrderedWorkerPool(
            lambda: iter(range(int(start), int(n))),
            self._read,
            num_workers=workers,
            max_ahead=2 * workers,
            counter_label="snapshot_read")

    def resize(self, read_workers: int) -> bool:
        """Live read-pool resize (the autotuner's
        ``snapshot_read_workers`` knob): batches keep delivering in
        serving order across the width change. Always returns True."""
        n = max(1, int(read_workers))
        self._pool.resize(n)
        self._pool.set_max_ahead(2 * n)
        return True

    def _read(self, pos: int):
        reader = self.reader
        i = int(pos) if self._order is None else int(self._order[pos])
        t0 = get_time()
        try:
            with _telemetry.profiler_annotation("dmlc_tpu.snapshot_read",
                                                self._annotate):
                # permuted serves materialize HERE, inside the timed
                # region, so out-of-order page faults are attributed to
                # snapshot_read and never leak into dispatch/transfer
                copy = self._order is not None
                if self._raw:
                    kind, span, layout = reader.batch_span(i, copy=copy)
                    batch = ("device_span", span, layout, kind)
                else:
                    batch = reader.load_batch(i, copy=copy)
        finally:
            dt = get_time() - t0
            _telemetry.record_span("snapshot_read", t0, dt)
            if self._on_read is not None:
                self._on_read(dt)
        return batch, reader.resume(i), reader.batch_nbytes(i)

    @property
    def stall_seconds(self) -> float:
        return self._pool.stall_seconds

    @stall_seconds.setter
    def stall_seconds(self, value: float) -> None:
        self._pool.stall_seconds = value

    def next(self):
        return self._pool.next()

    def destroy(self) -> None:
        self._pool.destroy()
