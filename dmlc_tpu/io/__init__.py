"""IO layer: streams, filesystems, RecordIO, input splitting, prefetch.

TPU-native equivalent of reference layers 3-4 (include/dmlc/io.h, src/io/,
include/dmlc/recordio.h, include/dmlc/threadediter.h).
"""

from dmlc_tpu.io.uri import URI, URISpec
from dmlc_tpu.io.filesystem import (
    FileInfo, FileSystem, LocalFileSystem, MemoryFileSystem, get_filesystem,
)
from dmlc_tpu.io.resilience import (
    ResilientStream, RetryPolicy, classify, default_policy,
)
from dmlc_tpu.io.faults import FaultPlan, inject, maybe_fail
from dmlc_tpu.io.stream import open_stream, read_all, write_all
from dmlc_tpu.io.recordio import (
    RECORDIO_MAGIC, RecordIOWriter, RecordIOReader, RecordIOChunkReader,
    read_index_file, write_indexed_recordio,
)
from dmlc_tpu.io.threaded_iter import ThreadedIter
from dmlc_tpu.io.input_split import (
    InputSplit, LineSplitter, MmapLineSplit, RecordIOSplitter,
    IndexedRecordIOSplitter, ThreadedInputSplit, create_input_split,
    create_mmap_text_split,
)
from dmlc_tpu.io.cached_split import CachedInputSplit
from dmlc_tpu.io.block_cache import (
    BlockCacheReader, BlockCacheWriter, open_block_cache, source_signature,
)
from dmlc_tpu.io import http_filesys as _http_filesys  # registers http/cloud slots
from dmlc_tpu.io import s3_filesys as _s3_filesys  # replaces the s3:// slot
from dmlc_tpu.io import gcs_filesys as _gcs_filesys  # replaces the gs:// slot
from dmlc_tpu.io import hdfs_filesys as _hdfs_filesys  # replaces the hdfs:// slot
from dmlc_tpu.io import azure_filesys as _azure_filesys  # replaces the azure:// slot

__all__ = [
    "URI", "URISpec", "FileInfo", "FileSystem", "LocalFileSystem",
    "MemoryFileSystem", "get_filesystem", "open_stream", "read_all",
    "write_all",
    "ResilientStream", "RetryPolicy", "classify", "default_policy",
    "FaultPlan", "inject", "maybe_fail",
    "RECORDIO_MAGIC", "RecordIOWriter", "RecordIOReader", "RecordIOChunkReader",
    "read_index_file", "write_indexed_recordio",
    "ThreadedIter", "InputSplit", "LineSplitter", "MmapLineSplit",
    "RecordIOSplitter", "IndexedRecordIOSplitter", "ThreadedInputSplit",
    "create_input_split", "create_mmap_text_split",
    "BlockCacheReader", "BlockCacheWriter", "open_block_cache",
    "source_signature", "CachedInputSplit",
]
