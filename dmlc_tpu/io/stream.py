"""Stream factory.

Equivalent of reference ``Stream::Create(uri, flag)`` (io.h:57, src/io.cc:132)
and ``SeekStream::CreateForRead`` (io.h:127). Python file objects already
satisfy the Stream interface (read/write/seek/tell/close); this module is the
URI-dispatching factory plus small adapters.
"""

from __future__ import annotations

import io as _pyio
from typing import BinaryIO

from dmlc_tpu.io.filesystem import get_filesystem
from dmlc_tpu.io.resilience import ResilientStream
from dmlc_tpu.io.uri import URI
from dmlc_tpu.utils.check import DMLCError


def open_stream(uri: str, mode: str = "r", allow_null: bool = False,
                resilient: bool = False) -> BinaryIO | None:
    """Open a binary stream for a URI — analog of Stream::Create (src/io.cc:132).

    mode: 'r' read, 'w' write, 'a' append. Returns None when allow_null and
    the target cannot be opened (io.h:57 ``allow_null`` contract).

    ``resilient=True`` (reads only) wraps the stream in
    :class:`~dmlc_tpu.io.resilience.ResilientStream`: a retryable mid-read
    failure reopens the source and resumes at the current byte offset. The
    remote filesystems already resume internally at the range-fetch layer
    (``native_resilience = True``), so the flag is a no-op for them —
    wrapping would stack a second retry budget on the one they own. It adds
    the contract for everything else (local files on flaky network mounts,
    third-party plugins).
    """
    if mode not in ("r", "w", "a"):
        raise DMLCError(f"open_stream: bad mode {mode!r}")
    parsed = URI(uri)
    try:
        fs = get_filesystem(parsed)
        if (resilient and mode == "r"
                and not getattr(fs, "native_resilience", False)):
            return _pyio.BufferedReader(ResilientStream(
                lambda: fs.open(parsed, "r"), what=uri))
        return fs.open(parsed, mode)
    except DMLCError:
        if allow_null:
            return None
        raise


def read_all(uri: str) -> bytes:
    with open_stream(uri, "r") as f:
        return f.read()


def write_all(uri: str, data: bytes) -> None:
    with open_stream(uri, "w") as f:
        f.write(data)
