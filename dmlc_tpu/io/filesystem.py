"""FileSystem abstraction with a protocol registry.

Equivalent of reference io.h:582-631 (FileSystem interface) + src/io.cc:30-71
(protocol dispatch) + src/io/local_filesys.cc (local impl) +
src/io/filesys.cc:8-25 (recursive listing). A MemoryFileSystem is added for
hermetic tests (the reference tests against temp dirs; we support both).

Cloud members (GCS/S3/HDFS) register their protocol slots here; GCS is the
cloud-native member of the TPU rebuild (SURVEY.md §7) and arrives with the
native core. Unregistered protocols raise with the known-protocol list.
"""

from __future__ import annotations

import io as _pyio
import os
import threading
from typing import BinaryIO, Callable, Dict, List

from dmlc_tpu.io.uri import URI
from dmlc_tpu.utils.check import DMLCError

FILE_TYPE = "file"
DIR_TYPE = "directory"


class FileInfo:
    """path + size + type — analog of dmlc::io::FileInfo (io.h:560-570)."""

    def __init__(self, path: URI, size: int, type_: str):
        self.path = path
        self.size = size
        self.type = type_

    def __repr__(self) -> str:  # pragma: no cover
        return f"FileInfo({self.path}, size={self.size}, type={self.type})"


class FileSystem:
    """Abstract filesystem — analog of dmlc::io::FileSystem (io.h:582)."""

    # True for filesystems whose read streams already retry + resume at the
    # current byte offset internally (the remote range-GET clients).
    # open_stream(resilient=True) skips its ResilientStream wrapper for
    # these — stacking a second budget on top would multiply retries.
    native_resilience = False

    def get_path_info(self, path: URI) -> FileInfo:
        raise NotImplementedError

    def list_directory(self, path: URI) -> List[FileInfo]:
        raise NotImplementedError

    def list_directory_recursive(self, path: URI) -> List[FileInfo]:
        """BFS recursive listing — analog of filesys.cc:8-25."""
        out: List[FileInfo] = []
        queue = [path]
        while queue:
            dir_uri = queue.pop(0)
            for info in self.list_directory(dir_uri):
                if info.type == DIR_TYPE:
                    queue.append(info.path)
                else:
                    out.append(info)
        return out

    def open(self, path: URI, mode: str) -> BinaryIO:
        """Open a binary stream; mode in {'r','w','a'} (io.h:57 flags)."""
        raise NotImplementedError

    def open_for_read(self, path: URI) -> BinaryIO:
        return self.open(path, "r")

    def exists(self, path: URI) -> bool:
        try:
            self.get_path_info(path)
            return True
        except (DMLCError, OSError):
            return False


_FS_FACTORIES: Dict[str, Callable[[URI], FileSystem]] = {}
_FS_LOCK = threading.Lock()


def register_filesystem(protocol: str, factory: Callable[[URI], FileSystem]) -> None:
    with _FS_LOCK:
        _FS_FACTORIES[protocol] = factory


def get_filesystem(uri: URI | str) -> FileSystem:
    """Protocol dispatch — analog of FileSystem::GetInstance (src/io.cc:30-71)."""
    if isinstance(uri, str):
        uri = URI(uri)
    with _FS_LOCK:
        factory = _FS_FACTORIES.get(uri.protocol)
    if factory is None:
        raise DMLCError(
            f"unknown filesystem protocol {uri.protocol!r}; "
            f"known: {sorted(_FS_FACTORIES)}"
        )
    return factory(uri)


class LocalFileSystem(FileSystem):
    """POSIX filesystem — analog of src/io/local_filesys.cc."""

    _instance: "LocalFileSystem | None" = None

    @classmethod
    def instance(cls, uri: URI | None = None) -> "LocalFileSystem":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def get_path_info(self, path: URI) -> FileInfo:
        name = path.name
        try:
            st = os.stat(name)
        except OSError as exc:
            raise DMLCError(f"LocalFileSystem.get_path_info: {name!r}: {exc}") from exc
        type_ = DIR_TYPE if os.path.isdir(name) else FILE_TYPE
        return FileInfo(URI(name), st.st_size, type_)

    def list_directory(self, path: URI) -> List[FileInfo]:
        name = path.name
        try:
            entries = sorted(os.listdir(name))
        except OSError as exc:
            raise DMLCError(f"LocalFileSystem.list_directory: {name!r}: {exc}") from exc
        out = []
        for entry in entries:
            full = os.path.join(name, entry)
            try:
                out.append(self.get_path_info(URI(full)))
            except DMLCError:
                # tolerate dangling symlinks like local_filesys.cc:99-145
                continue
        return out

    def open(self, path: URI, mode: str) -> BinaryIO:
        name = path.name
        if name == "stdin" and mode == "r":
            return _pyio.BufferedReader(_pyio.FileIO(0, "rb", closefd=False))
        if name == "stdout" and mode in ("w", "a"):
            return _pyio.BufferedWriter(_pyio.FileIO(1, "wb", closefd=False))
        pymode = {"r": "rb", "w": "wb", "a": "ab"}.get(mode)
        if pymode is None:
            raise DMLCError(f"LocalFileSystem.open: bad mode {mode!r}")
        try:
            return open(name, pymode)
        except OSError as exc:
            raise DMLCError(f"LocalFileSystem.open: {name!r}: {exc}") from exc


class _MemFile(_pyio.BytesIO):
    """BytesIO flushing back to the in-memory store on close."""

    def __init__(self, store: Dict[str, bytes], key: str, data: bytes = b""):
        super().__init__(data)
        self._store = store
        self._key = key
        self._writable = True

    def close(self) -> None:
        if self.closed:
            return
        if self._writable:
            self._store[self._key] = self.getvalue()
        super().close()


class MemoryFileSystem(FileSystem):
    """In-memory FS under ``mem://`` for hermetic tests.

    Not in the reference (it tests against TemporaryDirectory,
    filesystem.h:54); added because it makes parser/split tests run on
    in-memory corpora, the same spirit as unittest_parser.cc's in-memory
    data iters.
    """

    _instance: "MemoryFileSystem | None" = None

    def __init__(self):
        self.store: Dict[str, bytes] = {}

    @classmethod
    def instance(cls, uri: URI | None = None) -> "MemoryFileSystem":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    @classmethod
    def reset(cls) -> None:
        cls._instance = None

    def _key(self, path: URI) -> str:
        # include the host segment: mem://bucket/a.txt -> "bucket/a.txt"
        return path.host + path.name

    def get_path_info(self, path: URI) -> FileInfo:
        key = self._key(path)
        if key in self.store:
            return FileInfo(URI("mem://" + key), len(self.store[key]), FILE_TYPE)
        prefix = key.rstrip("/") + "/"
        if any(k.startswith(prefix) for k in self.store):
            return FileInfo(URI("mem://" + key), 0, DIR_TYPE)
        raise DMLCError(f"MemoryFileSystem: no such path {key!r}")

    def list_directory(self, path: URI) -> List[FileInfo]:
        prefix = self._key(path).rstrip("/") + "/"
        seen: Dict[str, FileInfo] = {}
        for key, data in sorted(self.store.items()):
            if not key.startswith(prefix):
                continue
            rest = key[len(prefix):]
            if "/" in rest:
                sub = rest.split("/", 1)[0]
                seen.setdefault(sub, FileInfo(URI("mem://" + prefix + sub), 0, DIR_TYPE))
            else:
                seen[rest] = FileInfo(URI("mem://" + key), len(data), FILE_TYPE)
        if not seen:
            raise DMLCError(f"MemoryFileSystem: no such directory {path.raw!r}")
        return list(seen.values())

    def open(self, path: URI, mode: str) -> BinaryIO:
        key = self._key(path)
        if mode == "r":
            if key not in self.store:
                raise DMLCError(f"MemoryFileSystem: no such file {key!r}")
            f = _pyio.BytesIO(self.store[key])
            return f
        if mode == "w":
            return _MemFile(self.store, key)
        if mode == "a":
            f = _MemFile(self.store, key, self.store.get(key, b""))
            f.seek(0, 2)
            return f
        raise DMLCError(f"MemoryFileSystem.open: bad mode {mode!r}")


register_filesystem("file://", LocalFileSystem.instance)
register_filesystem("mem://", MemoryFileSystem.instance)
