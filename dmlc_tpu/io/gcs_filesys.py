"""GCS filesystem: bearer-token JSON/XML API client over urllib.

The cloud-native member of the TPU rebuild (SURVEY.md §7: "local + GCS
instead of S3/HDFS as the cloud-native member"): on a TPU-VM the metadata
server hands out OAuth tokens, so no key material ships with the job.

Design mirrors the reference's S3 client surface (src/io/s3_filesys.cc) with
GCS auth:
- reads: ``Range: bytes=N-M`` GETs on the media endpoint, buffered via the
  shared HTTP block reader;
- listing: JSON objects.list with prefix+delimiter and page tokens;
- writes: single-shot media upload on close (multipart/resumable upload is
  not needed below the write-buffer size the reference uses);
- auth: ``GCS_OAUTH_TOKEN`` / ``GOOGLE_OAUTH_ACCESS_TOKEN`` env, else the
  TPU-VM metadata server, else anonymous (public buckets).

``GCS_ENDPOINT`` overrides the API base URL — the test seam for a local
fake server, like ``S3_ENDPOINT`` in :mod:`dmlc_tpu.io.s3_filesys`.
"""

from __future__ import annotations

import io as _pyio
import json
import os
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, List, Optional, Tuple

from dmlc_tpu.io.filesystem import (
    DIR_TYPE, FILE_TYPE, FileInfo, FileSystem, register_filesystem,
)
from dmlc_tpu.io.http_filesys import HttpReadStream
from dmlc_tpu.io.resilience import RetryPolicy, default_policy
from dmlc_tpu.io.uri import URI
from dmlc_tpu.utils.check import DMLCError, check
from dmlc_tpu.utils.timer import get_time

_METADATA_TOKEN_URL = ("http://metadata.google.internal/computeMetadata/v1/"
                       "instance/service-accounts/default/token")


def _auth_token() -> Optional[str]:
    tok = (os.environ.get("GCS_OAUTH_TOKEN")
           or os.environ.get("GOOGLE_OAUTH_ACCESS_TOKEN"))
    if tok:
        return tok
    # TPU-VM / GCE metadata server: cache the token until shortly before its
    # expiry; cache a miss too (the probe hangs nowhere but costs a timeout)
    global _metadata_token, _metadata_expiry
    now = get_time()
    if now < _metadata_expiry:
        return _metadata_token
    req = urllib.request.Request(
        _METADATA_TOKEN_URL, headers={"Metadata-Flavor": "Google"})
    try:
        with urllib.request.urlopen(req, timeout=1) as resp:
            payload = json.loads(resp.read())
        _metadata_token = payload.get("access_token")
        # refresh 60s early; tokens default to ~3600s
        _metadata_expiry = now + max(int(payload.get("expires_in", 300)) - 60, 30)
    except (urllib.error.URLError, OSError, ValueError):
        _metadata_token = None
        _metadata_expiry = now + 300  # re-probe absent metadata every 5 min
    return _metadata_token


_metadata_token: Optional[str] = None
_metadata_expiry = float("-inf")


class GcsConfig:
    def __init__(self) -> None:
        self.endpoint = os.environ.get(
            "GCS_ENDPOINT", "https://storage.googleapis.com")

    def headers(self) -> Dict[str, str]:
        tok = _auth_token()
        return {"Authorization": f"Bearer {tok}"} if tok else {}

    def media_url(self, bucket: str, key: str) -> str:
        return (f"{self.endpoint}/storage/v1/b/{bucket}/o/"
                f"{urllib.parse.quote(key, safe='')}?alt=media")

    def meta_url(self, bucket: str, key: str) -> str:
        return (f"{self.endpoint}/storage/v1/b/{bucket}/o/"
                f"{urllib.parse.quote(key, safe='')}")

    def list_url(self, bucket: str, query: Dict[str, str]) -> str:
        return (f"{self.endpoint}/storage/v1/b/{bucket}/o?"
                + urllib.parse.urlencode(sorted(query.items())))

    def upload_url(self, bucket: str, key: str) -> str:
        return (f"{self.endpoint}/upload/storage/v1/b/{bucket}/o?"
                + urllib.parse.urlencode(
                    {"uploadType": "media", "name": key}))


def _parse_gs_uri(uri: URI) -> Tuple[str, str]:
    return uri.host, uri.name.lstrip("/")


class GcsReadStream(HttpReadStream):
    """Range-GET reader with bearer auth."""

    def __init__(self, cfg: GcsConfig, bucket: str, key: str, size: int):
        self._cfg = cfg
        super().__init__(cfg.media_url(bucket, key), size=size)

    def _fetch(self, start: int, end: int) -> bytes:
        """One attempt, raw errors (retry/resume live in the inherited
        ``_fetch_retry``); the bearer token is re-read per attempt so a
        metadata-server rotation heals mid-stream."""
        headers = {"Range": f"bytes={start}-{end - 1}"}
        headers.update(self._cfg.headers())
        req = urllib.request.Request(self.url, headers=headers)
        try:
            with urllib.request.urlopen(
                    req, timeout=self._policy.attempt_timeout) as resp:
                body = resp.read()
                return body if resp.status == 206 else body[start:end]
        except urllib.error.HTTPError as exc:
            if exc.code == 416:
                return b""
            raise


class GcsWriteStream(_pyio.RawIOBase):
    """Buffer-and-upload writer (single media upload on close)."""

    def __init__(self, cfg: GcsConfig, bucket: str, key: str):
        super().__init__()
        self._cfg = cfg
        self._bucket = bucket
        self._key = key
        self._buf = bytearray()
        self._done = False

    def writable(self) -> bool:
        return True

    def write(self, b) -> int:
        self._buf += bytes(b)
        return len(b)

    def close(self) -> None:
        if self._done:
            return
        self._done = True
        url = self._cfg.upload_url(self._bucket, self._key)
        policy = default_policy()

        def attempt() -> None:
            # a media upload is a single idempotent PUT-equivalent: safe to
            # re-POST the whole buffer on a transient failure
            headers = {"Content-Type": "application/octet-stream"}
            headers.update(self._cfg.headers())
            req = urllib.request.Request(
                url, data=bytes(self._buf), method="POST", headers=headers)
            with urllib.request.urlopen(
                    req, timeout=max(policy.attempt_timeout, 300)) as resp:
                check(resp.status in (200, 201),
                      f"gcs upload failed: {resp.status}")

        policy.call(attempt, op="write",
                    what=f"gs://{self._bucket}/{self._key}")
        super().close()


class GcsFileSystem(FileSystem):
    """gs:// FileSystem over the JSON API."""

    native_resilience = True  # GcsReadStream resumes via _fetch_retry

    _instance: Optional["GcsFileSystem"] = None

    def __init__(self, cfg: Optional[GcsConfig] = None):
        self.cfg = cfg or GcsConfig()

    @classmethod
    def instance(cls, uri: Optional[URI] = None) -> "GcsFileSystem":
        if cls._instance is None:
            cls._instance = cls()
        else:
            cls._instance.cfg = GcsConfig()
        return cls._instance

    def _get_json(self, url: str,
                  cfg: Optional[GcsConfig] = None) -> Tuple[int, dict]:
        policy = default_policy()

        def attempt() -> Tuple[int, dict]:
            req = urllib.request.Request(
                url, headers=(cfg or self.cfg).headers())
            try:
                with urllib.request.urlopen(
                        req, timeout=policy.attempt_timeout) as resp:
                    return resp.status, json.loads(resp.read() or b"{}")
            except urllib.error.HTTPError as exc:
                if exc.code == 429 or exc.code >= 500:
                    raise  # transient: let the shared policy retry it
                return exc.code, {}  # deterministic status: callers branch

        return policy.call(attempt, op="open", what=url)

    def get_path_info(self, path: URI,
                      cfg: Optional[GcsConfig] = None) -> FileInfo:
        if cfg is None:
            cfg = self.cfg  # snapshot across the HEAD + fallback listing
        bucket, key = _parse_gs_uri(path)
        status, meta = self._get_json(cfg.meta_url(bucket, key), cfg=cfg)
        if status == 200:
            return FileInfo(path, int(meta.get("size", 0)), FILE_TYPE)
        prefix = key.rstrip("/") + "/" if key else ""
        entries = self._list(bucket, prefix, max_results=1, max_total=1,
                             cfg=cfg)
        if entries:
            return FileInfo(path, 0, DIR_TYPE)
        raise DMLCError(f"gcs path not found: {str(path)}")

    def _list(self, bucket: str, prefix: str, max_results: int = 1000,
              max_total: Optional[int] = None,
              cfg: Optional[GcsConfig] = None) -> List[Tuple[str, int, str]]:
        cfg = cfg or self.cfg  # one snapshot for every page
        out: List[Tuple[str, int, str]] = []
        token: Optional[str] = None
        while True:
            query = {"prefix": prefix, "delimiter": "/",
                     "maxResults": str(max_results)}
            if token:
                query["pageToken"] = token
            status, data = self._get_json(cfg.list_url(bucket, query),
                                          cfg=cfg)
            check(status == 200, f"gcs list failed: {status}")
            for item in data.get("items", []):
                out.append((item["name"], int(item.get("size", 0)), FILE_TYPE))
            for p in data.get("prefixes", []):
                out.append((p, 0, DIR_TYPE))
            token = data.get("nextPageToken")
            if not token or (max_total is not None and len(out) >= max_total):
                return out

    def list_directory(self, path: URI) -> List[FileInfo]:
        bucket, key = _parse_gs_uri(path)
        prefix = key.rstrip("/") + "/" if key else ""
        return [
            FileInfo(URI(f"gs://{bucket}/{k}"), size, typ)
            for k, size, typ in self._list(bucket, prefix)
        ]

    def open(self, path: URI, mode: str):
        cfg = self.cfg  # snapshot: stat + stream must share one config
        bucket, key = _parse_gs_uri(path)
        if "r" in mode:
            info = self.get_path_info(path, cfg=cfg)
            check(info.type == FILE_TYPE, f"not a file: {str(path)}")
            return _pyio.BufferedReader(
                GcsReadStream(cfg, bucket, key, info.size))
        if "w" in mode:
            return _pyio.BufferedWriter(GcsWriteStream(cfg, bucket, key))
        raise DMLCError(f"unsupported gcs open mode {mode!r}")

    def open_for_read(self, path: URI):
        return self.open(path, "rb")


register_filesystem("gs://", GcsFileSystem.instance)
