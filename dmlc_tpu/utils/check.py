"""Error type + CHECK helpers + logging.

TPU-native equivalent of reference include/dmlc/logging.h: glog-style
``CHECK*`` macros that raise :class:`DMLCError` (the reference's
fatal-throws-``dmlc::Error`` default, logging.h:29, base.h:21) and an
env-gated debug logger (``DMLC_LOG_DEBUG``, reference logging.h:131-146).
"""

from __future__ import annotations

import logging
import os
import sys


class DMLCError(RuntimeError):
    """Raised by failed checks — analog of ``dmlc::Error`` (logging.h:29)."""


class CacheCorruptionError(DMLCError):
    """An on-disk cache integrity check failed (CRC mismatch, torn frame,
    bad framing). Classified RETRYABLE by the resilience layer: the owner
    of the cache drops it, falls back to re-reading/re-parsing the source,
    and rewrites — the fault heals instead of failing the epoch (counted
    under ``cache_corruptions`` / ``cache_rebuilds``, docs/resilience.md).
    """


_LOGGER: logging.Logger | None = None


def get_logger() -> logging.Logger:
    """Process-wide logger; level gated by DMLC_LOG_DEBUG like logging.h:131-146."""
    global _LOGGER
    if _LOGGER is None:
        logger = logging.getLogger("dmlc_tpu")
        if not logger.handlers:
            handler = logging.StreamHandler(sys.stderr)
            handler.setFormatter(
                logging.Formatter("[%(asctime)s] %(levelname)s %(name)s: %(message)s")
            )
            logger.addHandler(handler)
        debug = os.environ.get("DMLC_LOG_DEBUG", "0") not in ("", "0", "false", "False")
        logger.setLevel(logging.DEBUG if debug else logging.INFO)
        _LOGGER = logger
    return _LOGGER


def _fail(msg: str, detail: str = "") -> None:
    text = msg if not detail else f"{msg}: {detail}"
    raise DMLCError(text)


def check(cond: bool, msg: str = "check failed") -> None:
    """``CHECK(cond)`` — reference logging.h:205."""
    if not cond:
        _fail(msg)


def check_eq(a, b, msg: str = "") -> None:
    if not (a == b):
        _fail(f"check failed: {a!r} == {b!r}", msg)


def check_ne(a, b, msg: str = "") -> None:
    if not (a != b):
        _fail(f"check failed: {a!r} != {b!r}", msg)


def check_lt(a, b, msg: str = "") -> None:
    if not (a < b):
        _fail(f"check failed: {a!r} < {b!r}", msg)


def check_le(a, b, msg: str = "") -> None:
    if not (a <= b):
        _fail(f"check failed: {a!r} <= {b!r}", msg)


def check_gt(a, b, msg: str = "") -> None:
    if not (a > b):
        _fail(f"check failed: {a!r} > {b!r}", msg)


def check_ge(a, b, msg: str = "") -> None:
    if not (a >= b):
        _fail(f"check failed: {a!r} >= {b!r}", msg)
