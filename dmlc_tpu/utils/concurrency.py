"""Concurrency primitives: blocking queues with kill-signal semantics.

Behavioral equivalent of reference include/dmlc/concurrency.h:
``ConcurrentBlockingQueue`` (concurrency.h:69-141) in FIFO and priority
modes, including ``signal_for_kill`` which wakes every blocked ``pop`` with
an empty result so worker threads can exit, and ``size``/``resume`` to
reuse the queue after a kill. A ``Spinlock`` (concurrency.h:25) makes no
sense under the GIL, so ``threading.Lock`` is the exported alias — the
reference itself documents its spinlock as a std::mutex drop-in.

The vendored moodycamel lock-free queues (concurrentqueue.h,
blockingconcurrentqueue.h) are a non-goal: their role (cross-thread
hand-off) is covered by this module and :mod:`dmlc_tpu.io.threaded_iter`,
and the native C++ core uses its own mutex+cv bounded queue
(native/src/reader.cc).
"""

from __future__ import annotations

import heapq
import itertools
import threading
from collections import deque
from typing import Any, Generic, List, Optional, Tuple, TypeVar

T = TypeVar("T")

# GIL makes a user-space spinlock strictly worse than the built-in lock;
# exported for API parity with dmlc::Spinlock call sites
Spinlock = threading.Lock


class ConcurrentBlockingQueue(Generic[T]):
    """Thread-safe blocking queue, FIFO or priority ordered.

    ``pop`` blocks until an item arrives or :meth:`signal_for_kill` is
    called; after a kill every blocked and future ``pop`` returns ``None``
    until :meth:`resume`. Matches ConcurrentBlockingQueue semantics
    (concurrency.h:69-141) with ``type=kFIFO|kPriority``.
    """

    FIFO = "fifo"
    PRIORITY = "priority"

    def __init__(self, kind: str = FIFO):
        if kind not in (self.FIFO, self.PRIORITY):
            raise ValueError(f"unknown queue kind {kind!r}")
        self._kind = kind
        self._cv = threading.Condition()
        self._fifo: deque = deque()
        self._heap: List[Tuple[int, int, Any]] = []
        # tie-breaker so equal priorities stay FIFO and items never compare
        self._seq = itertools.count()
        self._killed = False

    def push(self, value: T, priority: int = 0) -> None:
        with self._cv:
            if self._kind == self.FIFO:
                self._fifo.append(value)
            else:
                # max-priority first (reference pops highest priority)
                heapq.heappush(self._heap, (-priority, next(self._seq), value))
            self._cv.notify()

    def pop(self, timeout: Optional[float] = None) -> Optional[T]:
        """Blocking pop; None on kill-signal (or timeout, if given)."""
        with self._cv:
            if not self._cv.wait_for(
                lambda: self._killed or self._nonempty(), timeout
            ):
                return None
            if self._killed:
                return None
            if self._kind == self.FIFO:
                return self._fifo.popleft()
            return heapq.heappop(self._heap)[2]

    def try_pop(self) -> Optional[T]:
        with self._cv:
            if self._killed or not self._nonempty():
                return None
            if self._kind == self.FIFO:
                return self._fifo.popleft()
            return heapq.heappop(self._heap)[2]

    def signal_for_kill(self) -> None:
        """Wake all blocked pops with None (SignalForKill, concurrency.h:120)."""
        with self._cv:
            self._killed = True
            self._cv.notify_all()

    def resume(self) -> None:
        """Clear the kill flag so the queue can be reused."""
        with self._cv:
            self._killed = False
            self._cv.notify_all()

    def size(self) -> int:
        with self._cv:
            return len(self._fifo) + len(self._heap)

    def _nonempty(self) -> bool:
        return bool(self._fifo) or bool(self._heap)
