"""Wall-clock timing + lightweight throughput counters.

Analog of reference include/dmlc/timer.h (GetTime, timer.h:27-47) plus the
inline MB/sec progress logging pattern used by the load path
(basic_row_iter.h:68-81, disk_row_iter.h:117-140).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, Optional

from dmlc_tpu.utils import telemetry as _telemetry
from dmlc_tpu.utils.check import get_logger


def get_time() -> float:
    """Seconds, monotonic — analog of dmlc::GetTime (timer.h:27)."""
    return time.monotonic()


class StageMeter:
    """Thread-safe named-stage seconds accumulator, backed by the
    telemetry metrics registry.

    The pipeline-attribution primitive (tf.data's per-stage cost naming,
    arXiv:2101.12127 §4): each pipeline stage adds its measured seconds
    under a fixed name, and :meth:`seconds` / :func:`format_stage_table`
    turn the totals into an attribution table. Stages are declared up
    front so a table always carries every column, even the zero ones —
    a missing stage in a report is indistinguishable from an unmeasured
    one, which is exactly the "unaccounted 50%" failure mode this exists
    to close.

    Each (stage) cell IS a registry counter under ``metric`` with a
    ``pipeline=scope`` label — so ``DeviceIter.stats()``, the pod
    snapshot a worker ships to the tracker, and any future autotuner all
    read the SAME books (no second bookkeeping path). ``scope`` defaults
    to a fresh process-unique label so independent meters never alias.
    """

    def __init__(self, *stages: str,
                 metric: str = _telemetry.STAGE_BUSY_METRIC,
                 scope: Optional[str] = None):
        self._metric = metric
        self.scope = scope if scope is not None else \
            _telemetry.new_pipeline_label("meter")
        self._lock = threading.Lock()  # guards handle-map growth only
        self._handles: Dict[str, _telemetry.Counter] = {
            s: _telemetry.REGISTRY.counter(metric, stage=s,
                                           pipeline=self.scope)
            for s in stages
        }

    def _handle(self, stage: str) -> "_telemetry.Counter":
        h = self._handles.get(stage)
        if h is None:
            with self._lock:
                h = self._handles.get(stage)
                if h is None:
                    h = _telemetry.REGISTRY.counter(
                        self._metric, stage=stage, pipeline=self.scope)
                    self._handles[stage] = h
        return h

    def add(self, stage: str, seconds: float) -> None:
        self._handle(stage).inc(seconds)

    def seconds(self) -> Dict[str, float]:
        """Snapshot of cumulative per-stage seconds."""
        with self._lock:
            handles = dict(self._handles)
        return {s: h.value for s, h in handles.items()}

    def total(self) -> float:
        return sum(self.seconds().values())


def format_stage_table(stages: Dict[str, float], wall: float,
                       order: Optional[Iterable[str]] = None) -> str:
    """Render a per-stage attribution table (seconds + % of wall).

    ``wall`` is the reference wall-clock the stages decompose; the
    trailing ``other`` row is the unattributed residue (wall - sum), so
    the table always accounts for 100% of wall and an attribution gap is
    visible instead of silent.
    """
    keys = list(order) if order is not None else list(stages)
    rows = [(k, stages.get(k, 0.0)) for k in keys]
    covered = sum(s for _, s in rows)
    rows.append(("other", max(0.0, wall - covered)))
    width = max(len(k) for k, _ in rows)
    lines = [f"{'stage':<{width}}  seconds  % of wall"]
    for name, sec in rows:
        pct = 100.0 * sec / wall if wall > 0 else 0.0
        lines.append(f"{name:<{width}}  {sec:7.3f}  {pct:8.1f}%")
    lines.append(f"{'wall':<{width}}  {wall:7.3f}  {100.0 if wall > 0 else 0.0:8.1f}%")
    return "\n".join(lines)


class Timer:
    """Context-manager stopwatch."""

    def __init__(self):
        self.start = 0.0
        self.elapsed = 0.0

    def __enter__(self) -> "Timer":
        self.start = get_time()
        return self

    def __exit__(self, *exc) -> None:
        self.elapsed = get_time() - self.start


class ThroughputMeter:
    """Bytes-in / items-out counter logging every `log_every_mb` MB.

    Mirrors the reference's inline progress logging in BasicRowIter::Init
    (basic_row_iter.h:68-81): logs ``N MB read, X MB/sec`` every 10 MB and a
    final summary. Also tracks consumer stall time, the observability hook
    the TPU pipeline needs to prove "zero input-bound stalls".
    """

    def __init__(self, name: str = "load", log_every_mb: float = 10.0, silent: bool = False):
        self.name = name
        self.log_every = log_every_mb * (1 << 20)
        self.silent = silent
        self.bytes = 0
        self.items = 0
        self.stall_seconds = 0.0
        self._next_log = self.log_every
        self._start: Optional[float] = None

    def start(self) -> None:
        if self._start is None:
            self._start = get_time()

    def add(self, nbytes: int, nitems: int = 0) -> None:
        self.start()
        self.bytes += nbytes
        self.items += nitems
        if not self.silent and self.bytes >= self._next_log:
            self._next_log += self.log_every
            get_logger().info(
                "%s: %.1f MB read, %.2f MB/sec", self.name, self.mb, self.mb_per_sec
            )

    def add_stall(self, seconds: float) -> None:
        self.stall_seconds += seconds

    @property
    def mb(self) -> float:
        return self.bytes / (1 << 20)

    @property
    def elapsed(self) -> float:
        return 0.0 if self._start is None else get_time() - self._start

    @property
    def mb_per_sec(self) -> float:
        e = self.elapsed
        return self.mb / e if e > 0 else 0.0

    def summary(self) -> dict:
        return {
            "name": self.name,
            "mb": self.mb,
            "items": self.items,
            "seconds": self.elapsed,
            "mb_per_sec": self.mb_per_sec,
            "stall_seconds": self.stall_seconds,
        }

    def log_final(self) -> None:
        if not self.silent:
            get_logger().info(
                "%s: finished %.1f MB in %.2f s, %.2f MB/sec (stall %.3f s)",
                self.name, self.mb, self.elapsed, self.mb_per_sec, self.stall_seconds,
            )
