"""Parameter reflection system.

TPU-native equivalent of reference include/dmlc/parameter.h: declarative typed
fields with defaults, ranges, enums, aliases and doc generation
(DMLC_DECLARE_FIELD / FieldEntry<T>::set_default/set_range/set_lower_bound/
add_enum/describe, parameter.h:265-298, 549-900), kwargs ``init`` with
unknown-key policies (parameter.h:77-84, 140-165), JSON round-trip
(parameter.h:190-202), ``__DOC__``-style docstring generation
(parameter.h:214-218), and typed env access (GetEnv/SetEnv,
parameter.h:50-61).

Usage::

    class CSVParserParam(Parameter):
        format = field(str, default="csv")
        label_column = field(int, default=-1, lower_bound=-1,
                             help="Column index of the label.")

    p = CSVParserParam()
    unknown = p.init({"label_column": "3", "junk": "1"}, allow_unknown=True)
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Iterable, List, Optional, Tuple, Type

from dmlc_tpu.utils.check import DMLCError


def _parse_bool(s: str) -> bool:
    t = s.strip().lower()
    if t in ("1", "true", "yes", "on"):
        return True
    if t in ("0", "false", "no", "off"):
        return False
    raise ValueError(f"cannot parse bool from {s!r}")


class Field:
    """One declared parameter — analog of FieldEntry<T> (parameter.h:549+)."""

    def __init__(
        self,
        type_: Type,
        default: Any = ...,
        *,
        lower_bound: Any = None,
        upper_bound: Any = None,
        enum: Optional[Iterable[Any]] = None,
        aliases: Iterable[str] = (),
        help: str = "",
    ):
        self.type = type_
        self.default = default
        self.lower_bound = lower_bound
        self.upper_bound = upper_bound
        self.enum = list(enum) if enum is not None else None
        self.aliases = list(aliases)
        self.help = help
        self.name: str = "<unbound>"

    # -- string -> typed value, mirroring FieldEntry::Set (istream parse) --
    def parse(self, value: Any) -> Any:
        if isinstance(value, self.type) and not (self.type is int and isinstance(value, bool)):
            out = value
        elif self.type is bool:
            out = _parse_bool(str(value))
        else:
            try:
                out = self.type(value)
            except (TypeError, ValueError) as exc:
                raise DMLCError(
                    f"parameter {self.name}: cannot parse {value!r} as {self.type.__name__}"
                ) from exc
        self.validate(out)
        return out

    def validate(self, value: Any) -> None:
        """Range/enum constraints — set_range/add_enum (parameter.h:600s)."""
        if self.lower_bound is not None and value < self.lower_bound:
            raise DMLCError(
                f"parameter {self.name}: value {value!r} below lower bound {self.lower_bound!r}"
            )
        if self.upper_bound is not None and value > self.upper_bound:
            raise DMLCError(
                f"parameter {self.name}: value {value!r} above upper bound {self.upper_bound!r}"
            )
        if self.enum is not None and value not in self.enum:
            raise DMLCError(
                f"parameter {self.name}: value {value!r} not in allowed set {self.enum!r}"
            )


def field(type_: Type, default: Any = ..., **kwargs) -> Field:
    """Declare a parameter field — analog of DMLC_DECLARE_FIELD (parameter.h:265)."""
    return Field(type_, default, **kwargs)


class Parameter:
    """Base class for declarative parameter structs (parameter.h:104-298)."""

    __fields__: Dict[str, Field]
    __alias_map__: Dict[str, str]

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        fields: Dict[str, Field] = {}
        # inherit parent fields, least-derived first so overrides win
        # (CRTP parameter structs don't inherit in the reference, but it is
        # natural in Python)
        for base in reversed(cls.__mro__[1:]):
            if isinstance(base, type) and issubclass(base, Parameter) and base is not Parameter:
                fields.update(getattr(base, "__fields__", {}))
        for name, value in list(cls.__dict__.items()):
            if isinstance(value, Field):
                value.name = name
                fields[name] = value
                delattr_safe(cls, name)
        cls.__fields__ = fields
        alias_map: Dict[str, str] = {}
        for name, f in fields.items():
            for alias in f.aliases:
                if alias in fields or alias in alias_map:
                    raise DMLCError(f"parameter alias {alias!r} collides")
                alias_map[alias] = name
        cls.__alias_map__ = alias_map

    def __init__(self, **kwargs):
        for name, f in self.__fields__.items():
            if f.default is not ...:
                object.__setattr__(self, name, f.default)
        self.init(kwargs)

    # -- kwargs init with unknown-key policy (parameter.h:77-84,140-165) --
    def init(self, kwargs: Dict[str, Any], *, allow_unknown: bool = False) -> Dict[str, Any]:
        """Set fields from a string/any dict; returns the unknown leftovers.

        ``allow_unknown=False`` mirrors kAllowUnknown=false: unknown keys
        raise. Missing fields without defaults raise, as the reference's
        RunInit does for required fields (parameter.h:857-880).
        """
        unknown: Dict[str, Any] = {}
        for key, value in kwargs.items():
            name = self.__alias_map__.get(key, key)
            f = self.__fields__.get(name)
            if f is None:
                if not allow_unknown:
                    raise DMLCError(
                        f"{type(self).__name__}: unknown parameter {key!r}; "
                        f"known: {sorted(self.__fields__)}"
                    )
                unknown[key] = value
                continue
            object.__setattr__(self, name, f.parse(value))
        for name, f in self.__fields__.items():
            if not hasattr(self, name):
                raise DMLCError(
                    f"{type(self).__name__}: required parameter {name!r} not set"
                )
        return unknown

    def to_dict(self) -> Dict[str, Any]:
        """Analog of __DICT__ (parameter.h:204-212)."""
        return {name: getattr(self, name) for name in self.__fields__}

    # -- JSON round trip (parameter.h:190-202) --
    def save_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    def load_json(self, text: str, *, allow_unknown: bool = False) -> Dict[str, Any]:
        return self.init(json.loads(text), allow_unknown=allow_unknown)

    @classmethod
    def doc(cls) -> str:
        """Generated docstring — analog of __DOC__ (parameter.h:214-218)."""
        lines: List[str] = [f"Parameters of {cls.__name__}:"]
        for name, f in cls.__fields__.items():
            default = "required" if f.default is ... else f"default={f.default!r}"
            constraints = []
            if f.lower_bound is not None:
                constraints.append(f">={f.lower_bound!r}")
            if f.upper_bound is not None:
                constraints.append(f"<={f.upper_bound!r}")
            if f.enum is not None:
                constraints.append(f"in {f.enum!r}")
            extra = (", " + ", ".join(constraints)) if constraints else ""
            lines.append(f"  {name} ({f.type.__name__}, {default}{extra}): {f.help}")
        return "\n".join(lines)

    def __repr__(self) -> str:  # pragma: no cover
        items = ", ".join(f"{k}={v!r}" for k, v in self.to_dict().items())
        return f"{type(self).__name__}({items})"


def delattr_safe(cls, name):
    try:
        delattr(cls, name)
    except AttributeError:
        pass


# -- typed env access, analog of GetEnv/SetEnv (parameter.h:50-61) --

def get_env(key: str, type_: Type, default: Any):
    raw = os.environ.get(key)
    if raw is None or raw == "":
        return default
    if type_ is bool:
        return _parse_bool(raw)
    return type_(raw)


def set_env(key: str, value: Any) -> None:
    os.environ[key] = str(value)
