"""Global factory registry.

TPU-native equivalent of reference include/dmlc/registry.h: named singleton
registries of factory entries with ``register``/``find``/``alias``/``list``
(reference Registry<E>::__REGISTER__/Find/AddAlias, registry.h:48-126).
Python gives us decorators instead of static-init macros
(DMLC_REGISTRY_REGISTER, registry.h:229-235).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Generic, Iterable, Optional, TypeVar

from dmlc_tpu.utils.check import DMLCError

T = TypeVar("T")

_REGISTRIES: Dict[str, "Registry"] = {}
_REGISTRIES_LOCK = threading.Lock()


class RegistryEntry(Generic[T]):
    """Factory entry — analog of FunctionRegEntryBase (registry.h:150-226)."""

    def __init__(self, name: str, body: T, description: str = ""):
        self.name = name
        self.body = body
        self.description = description
        self.arguments: list[tuple[str, str, str]] = []  # (name, type, description)

    def describe(self, description: str) -> "RegistryEntry[T]":
        self.description = description
        return self

    def add_argument(self, name: str, type_str: str, description: str) -> "RegistryEntry[T]":
        self.arguments.append((name, type_str, description))
        return self

    def __repr__(self) -> str:  # pragma: no cover
        return f"RegistryEntry({self.name!r})"


class Registry(Generic[T]):
    """Named registry of factories — analog of Registry<EntryType> (registry.h:26-126)."""

    def __init__(self, kind: str):
        self.kind = kind
        self._entries: Dict[str, RegistryEntry[T]] = {}
        self._lock = threading.Lock()

    @staticmethod
    def get(kind: str) -> "Registry":
        """Singleton per kind — analog of Registry::Get() (registry.h:78-89)."""
        with _REGISTRIES_LOCK:
            reg = _REGISTRIES.get(kind)
            if reg is None:
                reg = Registry(kind)
                _REGISTRIES[kind] = reg
            return reg

    def register(self, name: str, description: str = "", override: bool = False) -> Callable[[T], T]:
        """Decorator registering ``body`` under ``name``."""

        def deco(body: T) -> T:
            with self._lock:
                if name in self._entries and not override:
                    raise DMLCError(f"{self.kind}: entry {name!r} already registered")
                self._entries[name] = RegistryEntry(name, body, description)
            return body

        return deco

    def add_alias(self, name: str, alias: str) -> None:
        """Analog of AddAlias (registry.h:63-72)."""
        with self._lock:
            if name not in self._entries:
                raise DMLCError(f"{self.kind}: cannot alias unknown entry {name!r}")
            if alias in self._entries:
                raise DMLCError(f"{self.kind}: alias {alias!r} already registered")
            self._entries[alias] = self._entries[name]

    def find(self, name: str) -> Optional[RegistryEntry[T]]:
        """Analog of Find (registry.h:55-61); None when missing."""
        with self._lock:
            return self._entries.get(name)

    def lookup(self, name: str) -> RegistryEntry[T]:
        """Find-or-raise with the available names in the message."""
        entry = self.find(name)
        if entry is None:
            raise DMLCError(
                f"{self.kind}: unknown entry {name!r}; known: {sorted(self._entries)}"
            )
        return entry

    def create(self, name: str, *args, **kwargs):
        """Lookup + call the factory body."""
        return self.lookup(name).body(*args, **kwargs)

    def list_names(self) -> Iterable[str]:
        with self._lock:
            return sorted(self._entries)
