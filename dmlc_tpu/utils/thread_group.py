"""Managed, named thread lifecycle: ThreadGroup / TimerThread / queue workers.

Behavioral equivalent of reference include/dmlc/thread_group.h: a
``ThreadGroup`` owns named threads (create/launch, thread_group.h:488-493),
supports cooperative shutdown of one or all threads
(request_shutdown_all, thread_group.h:443-451), and ships two managed
worker shapes — ``BlockingQueueThread`` draining a
:class:`~dmlc_tpu.utils.concurrency.ConcurrentBlockingQueue`
(thread_group.h:530) and ``TimerThread`` firing a callback on a fixed
period (thread_group.h:645).

Threads here are cooperative: the run callable receives a
:class:`ShutdownToken` and is expected to poll ``token.stopped`` (or use
``token.wait(dt)`` as its sleep) — matching the reference's
``request_shutdown`` + ``ThreadGroup::Thread::joinable`` contract rather
than killing threads from outside.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

from dmlc_tpu.utils.check import DMLCError
from dmlc_tpu.utils.concurrency import ConcurrentBlockingQueue


class ShutdownToken:
    """Cooperative stop flag handed to every managed thread."""

    def __init__(self) -> None:
        self._event = threading.Event()
        self._hooks: list = []

    @property
    def stopped(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Sleep until shutdown is requested; True if it was."""
        return self._event.wait(timeout)

    def on_request(self, hook: Callable[[], None]) -> None:
        """Run ``hook`` when shutdown is requested — the escape hatch for
        threads parked in a blocking call the flag can't reach (fires
        immediately if shutdown was already requested)."""
        self._hooks.append(hook)
        if self.stopped:
            hook()

    def request(self) -> None:
        self._event.set()
        for hook in self._hooks:
            hook()


class ManagedThread:
    """A named thread owned by a ThreadGroup (ThreadGroup::Thread)."""

    def __init__(self, name: str, target: Callable[[ShutdownToken], Any],
                 daemon: bool = True):
        self.name = name
        self.token = ShutdownToken()
        self._exc: Optional[BaseException] = None

        def _run() -> None:
            try:
                target(self.token)
            except BaseException as exc:  # surfaced on join()
                self._exc = exc

        from dmlc_tpu.utils import telemetry as _telemetry

        # inherit the creator's pipeline scope (see telemetry.scoped_target)
        self._thread = threading.Thread(
            target=_telemetry.scoped_target(_run), name=name, daemon=daemon)

    def start(self) -> None:
        self._thread.start()

    def request_shutdown(self) -> None:
        self.token.request()

    @property
    def joinable(self) -> bool:
        return self._thread.is_alive()

    def join(self, timeout: Optional[float] = None) -> None:
        """Join; rethrows anything the thread body raised."""
        self._thread.join(timeout)
        if self._exc is not None:
            exc, self._exc = self._exc, None
            raise exc


class ThreadGroup:
    """Registry of named managed threads (thread_group.h:95-300).

    ``create`` registers + starts a thread under a unique name; names of
    finished threads can be reused. ``request_shutdown_all`` asks every
    live thread to stop; ``join_all`` joins them (rethrowing the first
    thread exception).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._threads: Dict[str, ManagedThread] = {}

    def create(self, name: str, target: Callable[[ShutdownToken], Any],
               daemon: bool = True) -> ManagedThread:
        with self._lock:
            old = self._threads.get(name)
            if old is not None and old.joinable:
                raise DMLCError(f"thread {name!r} is already running")
            t = ManagedThread(name, target, daemon=daemon)
            self._threads[name] = t
        t.start()
        return t

    def get(self, name: str) -> Optional[ManagedThread]:
        with self._lock:
            return self._threads.get(name)

    def request_shutdown_all(self) -> None:
        with self._lock:
            threads = list(self._threads.values())
        for t in threads:
            t.request_shutdown()

    def join_all(self, timeout: Optional[float] = None) -> None:
        with self._lock:
            threads = list(self._threads.values())
        first_exc: Optional[BaseException] = None
        for t in threads:
            try:
                t.join(timeout)
            except BaseException as exc:
                if first_exc is None:
                    first_exc = exc
        if first_exc is not None:
            raise first_exc

    def __enter__(self) -> "ThreadGroup":
        return self

    def __exit__(self, *exc_info: Any) -> None:
        self.request_shutdown_all()
        self.join_all()


def blocking_queue_thread(
    group: ThreadGroup,
    name: str,
    queue: ConcurrentBlockingQueue,
    on_item: Callable[[Any], None],
) -> ManagedThread:
    """Start a managed worker draining `queue` (BlockingQueueThread,
    thread_group.h:530). Shutdown = token.request() + queue.signal_for_kill()
    (pop then returns None and the loop exits)."""

    def _run(token: ShutdownToken) -> None:
        # a kill-signalled pop returns None immediately, so the shutdown
        # hook below is what makes ThreadGroup.__exit__ joinable
        token.on_request(queue.signal_for_kill)
        while not token.stopped:
            item = queue.pop()
            if item is None:
                return
            on_item(item)

    return group.create(name, _run)


def timer_thread(
    group: ThreadGroup,
    name: str,
    period_seconds: float,
    callback: Callable[[], None],
    run_first_immediately: bool = False,
) -> ManagedThread:
    """Start a managed periodic-callback thread (TimerThread,
    thread_group.h:645). The period is the gap between callback *starts*;
    shutdown interrupts the sleep immediately."""
    if period_seconds <= 0:
        raise DMLCError("timer period must be positive")

    def _run(token: ShutdownToken) -> None:
        if run_first_immediately and not token.stopped:
            callback()
        while not token.wait(period_seconds):
            callback()

    return group.create(name, _run)
