"""Always-on pipeline telemetry: span tracer + labeled metrics registry.

tf.data's lesson (arXiv:2101.12127) is that AUTOTUNE and fleet-scale
debugging are both built on exactly one thing — a uniform, low-overhead
instrumentation layer over every pipeline stage — and the tf.data-service
paper (arXiv:2210.14826) adds that per-worker metrics must be aggregable
across hosts before a dispatcher can balance them. This module is that
layer for the ingest tier, and the sensor substrate the ROADMAP item 4
feedback controller will read. Two primitives:

**Span tracer** — fixed-size per-thread ring buffers recording
``(name, tid, start_ns, dur_ns, labels)`` spans. Recording is lock-free on
the hot path (each ring has exactly one writer: its thread) and bounded
(old spans overwrite, drops are counted), so it stays on in production.
Every pipeline stage emits spans at the SAME code sites that feed the
stage-seconds counters — read / parse in :mod:`dmlc_tpu.data.parsers`,
cache_read there + cache_write in :mod:`dmlc_tpu.io.block_cache`,
convert / dispatch / transfer in :mod:`dmlc_tpu.data.device`, and the
data-service wire quartet (service_encode / service_send on parse
workers, service_recv / service_decode on clients,
:mod:`dmlc_tpu.service.frame`) — so a trace timeline and
``DeviceIter.stats()`` can never tell different stories.
Export as Chrome-trace/Perfetto JSON via ``DMLC_TPU_TRACE=chrome:<path>``
(dumped when the ``DeviceIter`` closes) or ``DeviceIter.dump_trace(path)``
/ :func:`export_chrome_trace`.

**Metrics registry** — named counters / gauges / histograms / info blobs
with label scoping. The single source of truth behind
``DeviceIter.stats()`` (its :class:`~dmlc_tpu.utils.timer.StageMeter`
stage counters are registry counters), the resilience counters
(:mod:`dmlc_tpu.io.resilience` keeps its public
``counters_snapshot/delta/reset`` API on top of it), the pipeline stall
diagnostics, and the ``bench.py`` JSON line. ``make lint-metrics`` fails
ad-hoc bookkeeping added beside it.

**Pipeline scoping** — a thread-local label (:func:`scope`) stamped onto
every span and metric recorded while it is active. The pipeline thread
primitives (``ThreadedIter`` / ``OrderedWorkerPool`` / the native feed
threads / ``ManagedThread``) capture their creator's scope and install it
in the threads they spawn, so everything a ``DeviceIter`` causes — down
to filesystem retries on a producer thread — lands under that pipeline's
label. Two concurrent pipelines therefore keep disjoint books (the
cross-contamination fix for ``stats()['resilience']``).

**Pod aggregation** — :func:`pod_snapshot` serializes this process's
registry into a compact JSON-able dict; workers ship it to the rendezvous
tracker over the heartbeat path (``WorkerClient.report_metrics``) and the
tracker logs the merged per-rank × per-stage table
(:func:`format_pod_table`), so an 8-host run is debuggable from one
place. See docs/observability.md.

**Fleet observability plane** (schema v2) — four additions on top of
the substrate. *Distributed tracing*: a thread-local trace context
(:func:`trace` / :func:`current_trace`) stamps optional
``trace_id``/``parent_id``/``span_id`` fields onto spans, and its
compact wire form (:func:`trace_context_wire`) rides service RPCs so
one (job, part) is one trace from ``next_split`` to ``device_put``;
:func:`export_pod_trace` merges per-peer snapshots into ONE Perfetto
timeline with pid = role and per-peer clock offsets. *Prometheus
exposition*: :func:`render_prometheus` serializes the registry in text
exposition format (the ``metrics_text`` RPC), with a bounded
time-series ring (:func:`sample_metrics_history`,
``DMLC_TPU_METRICS_HISTORY``) behind the gauges. *Decision ledger*:
:func:`record_decision` is the one structured event shape every
controller (autotune / autoscaler / dispatcher / store / worker) emits.
*Bounded registry*: past ``DMLC_TPU_METRICS_MAX_PIPELINES`` pipeline
scopes the least-recently-touched one retires, its tallies folded into
process totals — the registry twin of span-ring retirement.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
from contextlib import contextmanager
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

# bumped whenever the span schema, the pod-snapshot layout, or a
# registry metric name consumed across processes changes — the tracker
# refuses to merge snapshots from a different schema, and bench.py /
# make bench-smoke gate the value. v2: spans gained optional
# trace_id/parent_id/span_id distributed-tracing fields and snapshots a
# "decisions" summary (docs/observability.md Distributed tracing).
SCHEMA_VERSION = 2

# the canonical pipeline stages (benchmarks/_common.STAGE_ORDER mirrors
# this; DeviceIter.stats()['stages'] carries exactly these keys)
STAGES = ("read", "cache_read", "parse", "convert", "dispatch", "transfer")

# registry metric names (docs/observability.md has the full table)
STAGE_BUSY_METRIC = "stage_busy_seconds"
STAGE_WALL_METRIC = "stage_wall_seconds"
RESILIENCE_METRIC = "resilience_events"
STALL_METRIC = "pipeline_stall"
# consumer-side input-bound waiting: every second the consumer measurably
# waited for input (host-batch waits + sampled transfer landings) — the
# counter the autotuner trusts where stall_seconds alone under-reads a
# transfer-bound epoch (VERDICT r5 weak #4)
INPUT_WAIT_METRIC = "input_wait_seconds"
# autotuner mirrors (dmlc_tpu.data.autotune): per-knob current-value
# gauges + a steps counter, labeled by pipeline scope
AUTOTUNE_KNOB_METRIC = "autotune_knob"
AUTOTUNE_STEP_METRIC = "autotune_steps"
# tiered artifact store (dmlc_tpu.store): live on-disk bytes under
# management, gauge labeled (root, tier) — evictions/rebuilds ride the
# resilience counter like every other classified event (docs/store.md)
STORE_BYTES_METRIC = "store_bytes"
# multi-tenant data service (dmlc_tpu.service, docs/service.md): both
# labeled by `job`. The wait counter is the CLIENT-side per-job input
# starvation signal (every second a ServiceParser waits on the wire) the
# fleet autoscaler aggregates from the tracker pod table; the parts
# counter is the WORKER-side per-job parts-served tally. They ride
# pod_snapshot()['jobs'] so the pod table shows a per-job breakdown next
# to per-rank stages (docs/observability.md).
SERVICE_JOB_WAIT_METRIC = "service_job_input_wait_seconds"
SERVICE_JOB_PARTS_METRIC = "service_job_parts"
# per-job input-wait SLO target (register_job(slo_wait_frac=),
# docs/service.md Production QoS): a job-labeled gauge each
# ServiceParser publishes from its config reply, so the pod table shows
# every job's wait NEXT TO the target the autoscaler steers it under
SERVICE_JOB_SLO_METRIC = "service_job_slo_wait_frac"
# wire v2 compression ledger (dmlc_tpu.service.frame, docs/service.md
# Wire v2): raw vs on-wire bytes for every served data frame, labeled by
# `job` — sent/raw is the live compression ratio the pod table and bench
# report; identity transports tick both equally so the ratio reads 1.0
SERVICE_WIRE_RAW_METRIC = "service_wire_bytes_raw"
SERVICE_WIRE_SENT_METRIC = "service_wire_bytes_sent"
# control-decision audit ledger (docs/observability.md Decision ledger):
# every autotuner step, fleet grow/drain, QoS throttle, store eviction,
# hedge and worker drain is one record_decision() event — this counter
# is its registry shadow, labeled (component, action), so decisions are
# countable next to the metrics that triggered them
DECISION_METRIC = "decision_events"


# ---------------- pipeline scoping ----------------

_tls = threading.local()
_scope_seq = itertools.count(1)


def new_pipeline_label(prefix: str = "pipeline") -> str:
    """A process-unique pipeline label (``pipeline-1``, ``pipeline-2``...)."""
    return f"{prefix}-{next(_scope_seq)}"


def current_scope() -> Optional[str]:
    """The pipeline label active on this thread, or None."""
    return getattr(_tls, "scope", None)


def set_scope(label: Optional[str]) -> None:
    """Install ``label`` as this thread's pipeline scope (thread primitives
    call this at thread start with the scope captured at construction)."""
    _tls.scope = label


@contextmanager
def scope(label: Optional[str]):
    """Run a block under a pipeline scope; restores the previous one."""
    prev = current_scope()
    set_scope(label)
    try:
        yield label
    finally:
        set_scope(prev)


# ---------------- distributed trace context ----------------
#
# A trace context is ``(trace_id, span_id)``: the trace a causal chain
# belongs to, plus the span id the NEXT hop parents under. It crosses
# processes as an optional ``{"trace": {"tid", "sid"}}`` JSON key on
# service control RPCs and stream requests (old peers ignore unknown
# keys, so wire framing and goldens are untouched — docs/service.md),
# and within a process a thread-local mirror stamps trace_id/parent_id
# onto every span recorded while it is installed.

# in-process override for the DMLC_TPU_TRACE_CONTEXT master switch —
# bench.py's trace-overhead leg flips propagation off for its baseline
# epoch without touching the environment of spawned threads
_trace_propagation: Optional[bool] = None


def set_trace_propagation(enabled: Optional[bool]) -> None:
    """Force trace-context propagation on/off for this process
    (``None`` restores the ``DMLC_TPU_TRACE_CONTEXT`` env default)."""
    global _trace_propagation
    _trace_propagation = None if enabled is None else bool(enabled)


def trace_propagation_enabled() -> bool:
    """Master switch for cross-process trace context: on by default,
    ``DMLC_TPU_TRACE_CONTEXT=0`` (or :func:`set_trace_propagation`)
    turns the wire key + span stamping off."""
    if _trace_propagation is not None:
        return _trace_propagation
    return os.environ.get("DMLC_TPU_TRACE_CONTEXT", "").strip() != "0"


def new_trace_id() -> str:
    """A fresh 64-bit hex trace id (one per (job, part) causal chain)."""
    return os.urandom(8).hex()


def new_span_id() -> str:
    """A fresh 32-bit hex span id (for spans that hand a context on)."""
    return os.urandom(4).hex()


def current_trace() -> Optional[Tuple[str, str]]:
    """The ``(trace_id, parent span_id)`` context active on this thread,
    or None."""
    return getattr(_tls, "trace", None)


def set_trace(ctx: Optional[Tuple[str, str]]) -> None:
    """Install ``ctx`` as this thread's trace context."""
    _tls.trace = ctx


@contextmanager
def trace(trace_id: Optional[str], span_id: str = ""):
    """Run a block under a trace context — spans recorded inside inherit
    ``trace_id``/``parent_id`` automatically; restores the previous
    context. A falsy ``trace_id`` clears the context for the block."""
    prev = current_trace()
    set_trace((trace_id, span_id) if trace_id else None)
    try:
        yield
    finally:
        set_trace(prev)


def trace_context_wire(
        ctx: Optional[Tuple[str, str]] = None) -> Optional[dict]:
    """The compact wire form ``{"tid", "sid"}`` of ``ctx`` (default:
    this thread's context), or None when absent/disabled. Callers attach
    it under the ``"trace"`` request key only when non-None, so peers
    that predate tracing never see the key."""
    if not trace_propagation_enabled():
        return None
    if ctx is None:
        ctx = current_trace()
    if not ctx or not ctx[0]:
        return None
    return {"tid": ctx[0], "sid": ctx[1] or ""}


def trace_context_from_wire(obj: Any) -> Optional[Tuple[str, str]]:
    """Parse an incoming ``"trace"`` wire key back into a context.
    Malformed shapes yield None — observability must never fail an
    RPC."""
    if not trace_propagation_enabled() or not isinstance(obj, dict):
        return None
    tid = obj.get("tid")
    if not isinstance(tid, str) or not tid:
        return None
    sid = obj.get("sid")
    return (tid, sid if isinstance(sid, str) else "")


# ---------------- span tracer ----------------

def _ring_capacity() -> int:
    try:
        return max(64, int(os.environ.get(
            "DMLC_TPU_TRACE_RING_SPANS", "8192") or 8192))
    except ValueError:
        return 8192


def _max_rings() -> int:
    try:
        return max(8, int(os.environ.get(
            "DMLC_TPU_TRACE_MAX_RINGS", "512") or 512))
    except ValueError:
        return 512


class _SpanRing:
    """One thread's fixed-size span buffer. Single writer (the owning
    thread), so ``record`` takes no lock; readers (export) see a racy but
    structurally safe snapshot — every retained entry is a complete tuple
    because the list-slot store is atomic under the GIL."""

    __slots__ = ("tid", "thread_name", "thread", "capacity", "entries",
                 "idx", "total", "counts")

    def __init__(self, tid: int, thread_name: str, capacity: int,
                 thread: Optional[threading.Thread] = None):
        self.tid = tid
        self.thread_name = thread_name
        self.thread = thread  # liveness probe for ring retirement
        self.capacity = capacity
        self.entries: List[Optional[tuple]] = [None] * capacity
        self.idx = 0
        self.total = 0
        self.counts: Dict[str, int] = {}

    def record(self, name: str, start_ns: int, dur_ns: int,
               pipeline: Optional[str], labels: Optional[dict],
               trace_id: Optional[str] = None,
               parent_id: Optional[str] = None,
               span_id: Optional[str] = None) -> None:
        self.entries[self.idx] = (name, start_ns, dur_ns, pipeline, labels,
                                  trace_id, parent_id, span_id)
        self.idx = (self.idx + 1) % self.capacity
        self.total += 1
        self.counts[name] = self.counts.get(name, 0) + 1

    def snapshot(self) -> List[tuple]:
        # oldest-first: the wrapped segment precedes the head segment
        if self.total < self.capacity:
            ent = self.entries[: self.idx]
        else:
            ent = self.entries[self.idx:] + self.entries[: self.idx]
        return [e for e in ent if e is not None]

    def clear(self) -> None:
        self.entries = [None] * self.capacity
        self.idx = 0
        self.total = 0
        self.counts = {}


_rings_lock = threading.Lock()
_rings: List[_SpanRing] = []
# retired dead-thread rings fold their books here so span_counts() /
# spans_dropped() stay monotonic after retirement
_retired_counts: Dict[str, int] = {}
_retired_dropped = 0


def _retire_dead_ring_locked() -> None:
    """Memory bound for thread churn: pipelines create producer/worker
    threads per epoch, and each thread that ever recorded a span owns a
    ring. Past ``DMLC_TPU_TRACE_MAX_RINGS`` rings, drop the oldest ring
    whose thread has exited — its retained spans leave the trace (counted
    as dropped) but its totals are preserved."""
    global _retired_dropped
    if len(_rings) < _max_rings():
        return
    for i, ring in enumerate(_rings):
        if ring.thread is not None and not ring.thread.is_alive():
            dead = _rings.pop(i)
            for name, n in dead.counts.items():
                _retired_counts[name] = _retired_counts.get(name, 0) + n
            _retired_dropped += dead.total
            return


def _my_ring() -> _SpanRing:
    ring = getattr(_tls, "ring", None)
    if ring is None:
        t = threading.current_thread()
        ring = _SpanRing(t.ident or 0, t.name, _ring_capacity(), thread=t)
        with _rings_lock:
            _retire_dead_ring_locked()
            _rings.append(ring)
        _tls.ring = ring
    return ring


def record_span(name: str, start_s: float, dur_s: float,
                trace_id: Optional[str] = None,
                parent_id: Optional[str] = None,
                span_id: Optional[str] = None, **labels) -> None:
    """Record one stage span. ``start_s`` is a ``get_time()`` monotonic
    timestamp, ``dur_s`` its measured duration — the SAME values the
    caller feeds its stage-seconds counter, so per-stage span sums always
    reconcile with the attribution. The active pipeline scope rides along
    automatically, and so does the active trace context: explicit
    ``trace_id``/``parent_id`` win, otherwise this thread's installed
    context (:func:`trace`) links the span into its distributed trace.
    ``span_id`` names THIS span so a downstream hop can parent under it."""
    if trace_id is None:
        ctx = current_trace()
        if ctx is not None:
            trace_id = ctx[0]
            if parent_id is None:
                parent_id = ctx[1] or None
    _my_ring().record(name, int(start_s * 1e9), int(dur_s * 1e9),
                      current_scope(), labels or None,
                      trace_id, parent_id, span_id)


@contextmanager
def span(name: str, **labels):
    """Measure a block as one span (convenience form of
    :func:`record_span` for call sites that keep no counter of their own)."""
    import time

    t0 = time.monotonic()
    try:
        yield
    finally:
        record_span(name, t0, time.monotonic() - t0, **labels)


def spans_snapshot(pipeline: Optional[str] = None) -> List[dict]:
    """Retained spans across all threads, oldest-first per thread, as
    dicts; optionally filtered to one pipeline label."""
    with _rings_lock:
        rings = list(_rings)
    out = []
    for entry in rings:
        for (name, start_ns, dur_ns, pipe, labels,
             trace_id, parent_id, span_id) in entry.snapshot():
            if pipeline is not None and pipe != pipeline:
                continue
            row = {"name": name, "tid": entry.tid,
                   "thread": entry.thread_name, "start_ns": start_ns,
                   "dur_ns": dur_ns, "pipeline": pipe,
                   "labels": labels or {}}
            # optional distributed-tracing fields (schema v2): present
            # only on spans that belong to a trace, so v1-era consumers
            # of the row shape keep working untouched
            if trace_id:
                row["trace_id"] = trace_id
            if parent_id:
                row["parent_id"] = parent_id
            if span_id:
                row["span_id"] = span_id
            out.append(row)
    out.sort(key=lambda s: s["start_ns"])
    return out


def span_counts() -> Dict[str, int]:
    """Spans RECORDED per name since process start (not just retained —
    neither ring overwrites nor dead-ring retirement lower these)."""
    with _rings_lock:
        rings = list(_rings)
        out = dict(_retired_counts)
    for ring in rings:
        for name, n in list(ring.counts.items()):
            out[name] = out.get(name, 0) + n
    return out


def spans_dropped() -> int:
    """Spans recorded but no longer exportable (ring overwrites + rings
    retired with their thread)."""
    with _rings_lock:
        return _retired_dropped + sum(
            max(0, r.total - r.capacity) for r in _rings)


def reset_spans() -> None:
    """Clear every ring (tests; production rings just wrap)."""
    global _retired_dropped
    with _rings_lock:
        for ring in _rings:
            ring.clear()
        _retired_counts.clear()
        _retired_dropped = 0


def export_chrome_trace(path: str, pipeline: Optional[str] = None) -> int:
    """Write the retained spans as Chrome-trace/Perfetto JSON (object
    form: ``{"traceEvents": [...]}``, complete-event ``ph: "X"``, ts/dur
    in microseconds). Returns the number of events written. The file is
    written to ``<path>.tmp`` then atomically published."""
    pid = os.getpid()
    events: List[dict] = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": "dmlc_tpu"},
    }]
    with _rings_lock:
        rings = list(_rings)
    for ring in rings:
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": ring.tid, "args": {"name": ring.thread_name}})
    rows = spans_snapshot(pipeline)
    for s in rows:
        args = dict(s["labels"])
        if s["pipeline"]:
            args["pipeline"] = s["pipeline"]
        for k in ("trace_id", "parent_id", "span_id"):
            if s.get(k):
                args[k] = s[k]
        events.append({
            "name": s["name"], "cat": "dmlc_tpu", "ph": "X",
            "pid": pid, "tid": s["tid"],
            "ts": s["start_ns"] / 1e3, "dur": s["dur_ns"] / 1e3,
            "args": args,
        })
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "telemetry_schema_version": SCHEMA_VERSION,
            "spans_dropped": spans_dropped(),
        },
    }
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return len(rows)


# ---------------- trace-mode knob ----------------

def trace_mode() -> Tuple[str, Optional[str]]:
    """Parse ``DMLC_TPU_TRACE`` (docs/data.md):

    - ``1`` -> ``('annotate', None)`` — wrap transfer/convert/dispatch/
      cache_read in ``jax.profiler.TraceAnnotation`` so they show up in a
      jax profiler / Perfetto device trace
    - ``chrome:<path>`` -> ``('chrome', path)`` — dump the span rings as a
      Chrome trace to ``path`` when the pipeline closes
    - anything else (including unset / ``0``) -> ``('off', None)`` — the
      historical contract was exactly ``DMLC_TPU_TRACE=1``, so unknown
      values stay off rather than silently arming per-batch annotations
    """
    value = os.environ.get("DMLC_TPU_TRACE", "").strip()
    if value == "1":
        return "annotate", None
    if value.startswith("chrome:"):
        return "chrome", value[len("chrome:"):]
    return "off", None


@contextmanager
def profiler_annotation(name: str, enabled: bool = True):
    """``jax.profiler.TraceAnnotation`` when enabled (and jax importable);
    a no-op otherwise. Callers cache ``trace_mode()[0] == 'annotate'`` so
    the env parse never sits on a per-batch path."""
    if not enabled:
        yield
        return
    try:
        from jax import profiler as _profiler
    except Exception:  # noqa: BLE001 - tracing must never break the pipeline
        yield
        return
    with _profiler.TraceAnnotation(name):
        yield


# ---------------- metrics registry ----------------

class _Metric:
    __slots__ = ("lock", "labels")

    def __init__(self, labels: Dict[str, str]):
        self.lock = threading.Lock()
        self.labels = labels


class Counter(_Metric):
    """Monotonic float counter (stage seconds use float increments)."""

    __slots__ = ("_value",)
    kind = "counter"

    def __init__(self, labels):
        super().__init__(labels)
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self.lock:
            self._value += n

    @property
    def value(self) -> float:
        with self.lock:
            return self._value


class Gauge(_Metric):
    __slots__ = ("_value",)
    kind = "gauge"

    def __init__(self, labels):
        super().__init__(labels)
        self._value = 0.0

    def set(self, v: float) -> None:
        with self.lock:
            self._value = float(v)

    @property
    def value(self) -> float:
        with self.lock:
            return self._value


class Histogram(_Metric):
    """count/sum/min/max summary (enough for stall and latency shapes
    without bucket-boundary bikeshedding; percentiles can come later)."""

    __slots__ = ("_count", "_sum", "_min", "_max")
    kind = "histogram"

    def __init__(self, labels):
        super().__init__(labels)
        self._count = 0
        self._sum = 0.0
        self._min = None
        self._max = None

    def observe(self, v: float) -> None:
        v = float(v)
        with self.lock:
            self._count += 1
            self._sum += v
            self._min = v if self._min is None else min(self._min, v)
            self._max = v if self._max is None else max(self._max, v)

    @property
    def value(self) -> dict:
        with self.lock:
            return {"count": self._count, "sum": self._sum,
                    "min": self._min, "max": self._max}


class Info(_Metric):
    """A structured JSON-able dict (e.g. the pipeline stall diagnostic):
    last write wins, read back verbatim."""

    __slots__ = ("_value",)
    kind = "info"

    def __init__(self, labels):
        super().__init__(labels)
        self._value: Optional[dict] = None

    def set(self, value: dict) -> None:
        with self.lock:
            self._value = dict(value)

    @property
    def value(self) -> Optional[dict]:
        with self.lock:
            return dict(self._value) if self._value is not None else None


def _metrics_max_pipelines() -> int:
    """``DMLC_TPU_METRICS_MAX_PIPELINES`` knob-table row: how many
    distinct per-pipeline label scopes the registry retains before
    retiring the least-recently-touched one (docs/observability.md)."""
    from dmlc_tpu.utils import knobs as _knobs
    return _knobs.resolve("metrics_max_pipelines")


class MetricsRegistry:
    """Named, labeled metrics. ``counter/gauge/histogram/info`` get or
    create the handle for an exact (name, labels) pair — handles are
    cheap to cache at call sites (StageMeter does) so the hot path is one
    small per-metric lock, never the registry lock.

    **Bounded pipeline scopes** — a service constructing fresh pipelines
    forever (each ``DeviceIter``/``ServiceParser`` scope stamps a
    process-unique ``pipeline`` label on ~a dozen metrics) must not grow
    the registry without bound. Past ``DMLC_TPU_METRICS_MAX_PIPELINES``
    distinct pipeline scopes, the least-recently-touched scope is
    retired: its counters and histograms fold into the ``pipeline=""``
    process-total bucket (so ``sum``/``sum_by`` over every other label
    are unchanged — the same books-preserved pattern as span-ring
    retirement), its gauges and info blobs (stale per-instance state)
    are dropped."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[tuple, _Metric] = {}
        # pipeline-scope LRU: label -> logical touch stamp (a metric
        # creation under that scope); retirement tally for the pod table
        self._pipeline_touch: Dict[str, int] = {}
        self._touch_seq = itertools.count(1)
        self._retired_pipelines = 0

    def _retire_pipeline_locked(self, pipeline: str) -> None:
        self._pipeline_touch.pop(pipeline, None)
        self._retired_pipelines += 1
        tag = ("pipeline", pipeline)
        victims = [k for k in self._metrics if tag in k[2]]
        for key in victims:
            old = self._metrics.pop(key)
            if not isinstance(old, (Counter, Histogram)):
                continue  # gauges/info are per-instance state, not tallies
            kind, name, label_items = key
            folded = tuple(sorted((lk, "" if lk == "pipeline" else lv)
                                  for lk, lv in label_items))
            tgt_key = (kind, name, folded)
            tgt = self._metrics.get(tgt_key)
            if tgt is None:
                tgt = type(old)(dict(folded))
                self._metrics[tgt_key] = tgt
            if isinstance(old, Counter):
                tgt.inc(old.value)
            else:
                v = old.value
                with tgt.lock:
                    tgt._count += v["count"]
                    tgt._sum += v["sum"]
                    if v["min"] is not None:
                        tgt._min = (v["min"] if tgt._min is None
                                    else min(tgt._min, v["min"]))
                    if v["max"] is not None:
                        tgt._max = (v["max"] if tgt._max is None
                                    else max(tgt._max, v["max"]))

    def _touch_pipeline_locked(self, pipeline: str) -> None:
        self._pipeline_touch[pipeline] = next(self._touch_seq)
        if len(self._pipeline_touch) <= _metrics_max_pipelines():
            return
        oldest = min(self._pipeline_touch, key=self._pipeline_touch.get)
        if oldest != pipeline:
            self._retire_pipeline_locked(oldest)

    def retired_pipelines(self) -> int:
        """Pipeline scopes retired (folded into process totals) so far."""
        with self._lock:
            return self._retired_pipelines

    def _get(self, cls, name: str, labels: Dict[str, str]) -> _Metric:
        key = (cls.kind, name, tuple(sorted(labels.items())))
        m = self._metrics.get(key)
        if m is None:
            with self._lock:
                m = self._metrics.get(key)
                if m is None:
                    m = cls(dict(labels))
                    self._metrics[key] = m
                    p = labels.get("pipeline")
                    if p:
                        self._touch_pipeline_locked(p)
        return m

    def counter(self, name: str, **labels) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, **labels) -> Histogram:
        return self._get(Histogram, name, labels)

    def info(self, name: str, **labels) -> Info:
        return self._get(Info, name, labels)

    # -------- read side --------

    def _rows(self, name: Optional[str], kind: Optional[str],
              label_filter: Dict[str, str]) -> Iterable[Tuple[tuple, _Metric]]:
        with self._lock:
            items = list(self._metrics.items())
        for key, m in items:
            k, n, _ = key
            if name is not None and n != name:
                continue
            if kind is not None and k != kind:
                continue
            if any(m.labels.get(fk) != fv for fk, fv in label_filter.items()):
                continue
            yield key, m

    def snapshot(self, name: Optional[str] = None, kind: Optional[str] = None,
                 **label_filter) -> List[dict]:
        """Matching metrics as ``{"kind", "name", "labels", "value"}`` rows."""
        return [{"kind": key[0], "name": key[1], "labels": dict(m.labels),
                 "value": m.value}
                for key, m in self._rows(name, kind, label_filter)]

    def sum(self, name: str, **label_filter) -> float:
        """Total over matching counters/gauges."""
        return sum(m.value for _, m in self._rows(name, None, label_filter)
                   if isinstance(m, (Counter, Gauge)))

    def sum_by(self, name: str, by: str, **label_filter) -> Dict[str, float]:
        """Per-``by``-label totals over matching counters/gauges."""
        out: Dict[str, float] = {}
        for _, m in self._rows(name, None, label_filter):
            if isinstance(m, (Counter, Gauge)):
                k = m.labels.get(by, "")
                out[k] = out.get(k, 0.0) + m.value
        return out

    def clear(self, name: Optional[str] = None) -> None:
        """Drop matching metrics entirely (tests / counter reset)."""
        with self._lock:
            if name is None:
                self._metrics.clear()
                self._pipeline_touch.clear()
                self._retired_pipelines = 0
            else:
                self._metrics = {k: v for k, v in self._metrics.items()
                                 if k[1] != name}


REGISTRY = MetricsRegistry()


# ---------------- control-decision audit ledger ----------------

# retained decision events per process: the ledger is a bounded ring
# (old events drop, the DECISION_METRIC counters stay monotonic), sized
# for "why did the fleet do that" forensics, not for history
DECISION_HISTORY_LIMIT = 256

_decisions_lock = threading.Lock()
_decisions: List[dict] = []
_decisions_total = 0


def record_decision(component: str, action: str,
                    trigger: Optional[dict] = None,
                    outcome: Optional[Any] = None, **extra) -> dict:
    """Append one structured control-decision event to the audit ledger
    (docs/observability.md Decision ledger). One shape for every
    controller: ``component`` (autotune / autoscaler / dispatcher /
    store / worker), ``action`` (grow, drain, evict, hedge, throttle,
    ...), ``trigger`` (the metric deltas that fired it), ``outcome``
    (what changed). The event also bumps the ``decision_events``
    registry counter and inherits the active trace context so a
    decision shows up inside the trace it affected. Returns the event
    dict — fleet components journal exactly this via the dispatcher
    append-journal."""
    import time

    global _decisions_total
    event: Dict[str, Any] = {
        "ts": round(time.monotonic(), 6),
        "component": str(component),
        "action": str(action),
    }
    if trigger:
        event["trigger"] = dict(trigger)
    if outcome is not None:
        event["outcome"] = outcome
    ctx = current_trace()
    if ctx and ctx[0]:
        event["trace_id"] = ctx[0]
    for k, v in extra.items():
        if v is not None:
            event[k] = v
    with _decisions_lock:
        _decisions.append(event)
        _decisions_total += 1
        if len(_decisions) > DECISION_HISTORY_LIMIT:
            del _decisions[: len(_decisions) - DECISION_HISTORY_LIMIT]
    REGISTRY.counter(DECISION_METRIC, component=str(component),
                     action=str(action)).inc()
    return event


def decisions_snapshot(component: Optional[str] = None) -> List[dict]:
    """Retained decision events, oldest-first, optionally filtered to
    one component. Dicts are copies — callers may annotate freely."""
    with _decisions_lock:
        events = list(_decisions)
    return [dict(e) for e in events
            if component is None or e.get("component") == component]


def decisions_total() -> int:
    """Decisions RECORDED since process start (ring drops don't lower
    this)."""
    with _decisions_lock:
        return _decisions_total


def decision_counts() -> Dict[str, int]:
    """``component.action`` -> count since process start, from the
    registry shadow counter (monotonic across ring drops) — what
    ``pod_snapshot()['decisions']`` ships to the tracker."""
    out: Dict[str, int] = {}
    for row in REGISTRY.snapshot(DECISION_METRIC, "counter"):
        labels = row["labels"]
        key = f"{labels.get('component', '?')}.{labels.get('action', '?')}"
        out[key] = out.get(key, 0) + int(round(row["value"]))
    return out


def reset_decisions() -> None:
    """Clear the ledger (tests; production rings just wrap)."""
    global _decisions_total
    with _decisions_lock:
        _decisions.clear()
        _decisions_total = 0
    REGISTRY.clear(DECISION_METRIC)


# ---------------- bounded metrics time-series ring ----------------

def _metrics_history_limit() -> int:
    """``DMLC_TPU_METRICS_HISTORY`` knob-table row: samples retained in
    the bounded time-series ring behind the gauges."""
    from dmlc_tpu.utils import knobs as _knobs
    return _knobs.resolve("metrics_history")


_history_lock = threading.Lock()
_history: List[dict] = []


def sample_metrics_history(now: Optional[float] = None) -> dict:
    """Capture one bounded time-series sample of the hot fleet gauges —
    per-job input wait, wire bytes, store bytes, decision count — so
    post-hoc questions like "what did input_wait look like when the
    autoscaler grew" are answerable from the ring alone. The fleet
    autoscaler samples once per control tick; anything else may call it
    too (the ring just wraps)."""
    import time

    sample = {
        "ts": round(time.monotonic() if now is None else now, 6),
        "input_wait_seconds": round(REGISTRY.sum(INPUT_WAIT_METRIC), 4),
        "job_wait_seconds": {
            j: round(v, 4) for j, v in
            REGISTRY.sum_by(SERVICE_JOB_WAIT_METRIC, "job").items() if j},
        "wire_bytes_raw": int(REGISTRY.sum(SERVICE_WIRE_RAW_METRIC)),
        "wire_bytes_sent": int(REGISTRY.sum(SERVICE_WIRE_SENT_METRIC)),
        "store_bytes": int(REGISTRY.sum(STORE_BYTES_METRIC)),
        "decisions": decisions_total(),
    }
    limit = _metrics_history_limit()
    with _history_lock:
        _history.append(sample)
        if len(_history) > limit:
            del _history[: len(_history) - limit]
    return dict(sample)


def metrics_history() -> List[dict]:
    """The retained time-series samples, oldest-first (copies)."""
    with _history_lock:
        return [dict(s) for s in _history]


def reset_metrics_history() -> None:
    """Clear the ring (tests)."""
    with _history_lock:
        _history.clear()


# ---------------- Prometheus text-format exposition ----------------

_PROM_PREFIX = "dmlc_tpu_"


def _prom_name(name: str) -> str:
    out = []
    for ch in name:
        out.append(ch if (ch.isalnum() and ch.isascii()) or ch in "_:"
                   else "_")
    base = "".join(out)
    if base and base[0].isdigit():
        base = "_" + base
    return _PROM_PREFIX + base


def _prom_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    parts = []
    for k in sorted(labels):
        v = str(labels[k]).replace("\\", "\\\\").replace("\n", "\\n") \
            .replace('"', '\\"')
        parts.append(f'{k}="{v}"')
    return "{" + ",".join(parts) + "}"


def render_prometheus(rows: Optional[List[dict]] = None) -> str:
    """Render registry snapshot rows as Prometheus text exposition
    format (docs/observability.md Prometheus exposition). Stable naming
    contract: every metric is prefixed ``dmlc_tpu_``, counters gain the
    conventional ``_total`` suffix, histograms expose their
    count/sum/min/max summary as ``_count``/``_sum``/``_min``/``_max``
    samples, info blobs (structured JSON, not numeric) are skipped.
    Output is deterministically sorted; the ``metrics_text`` RPC on
    dispatcher and workers serves exactly this."""
    if rows is None:
        rows = REGISTRY.snapshot()
    typed: Dict[str, str] = {}
    samples: List[Tuple[str, str, float]] = []
    for row in rows:
        kind = row["kind"]
        if kind == "info":
            continue
        name = _prom_name(row["name"])
        labels = {k: v for k, v in (row["labels"] or {}).items()
                  if v not in (None, "")}
        if kind == "counter":
            typed.setdefault(name + "_total", "counter")
            samples.append((name + "_total", _prom_labels(labels),
                            float(row["value"])))
        elif kind == "gauge":
            typed.setdefault(name, "gauge")
            samples.append((name, _prom_labels(labels),
                            float(row["value"])))
        elif kind == "histogram":
            v = row["value"] or {}
            for part in ("count", "sum", "min", "max"):
                pv = v.get(part)
                if pv is None:
                    continue
                typed.setdefault(f"{name}_{part}", "gauge")
                samples.append((f"{name}_{part}", _prom_labels(labels),
                                float(pv)))
    lines: List[str] = []
    last_name = None
    for name, label_str, value in sorted(samples):
        if name != last_name:
            lines.append(f"# TYPE {name} {typed[name]}")
            last_name = name
        try:
            text = str(int(value)) if value == int(value) else repr(value)
        except (OverflowError, ValueError):  # inf / nan
            text = repr(value)
        lines.append(f"{name}{label_str} {text}")
    return "\n".join(lines) + ("\n" if lines else "")


def parse_prometheus_text(text: str) -> List[Tuple[str, Dict[str, str],
                                                   float]]:
    """Minimal Prometheus text-format parser — the round-trip check
    behind the bench-smoke gate and the exposition tests. Returns
    ``(name, labels, value)`` samples; raises ValueError on any
    malformed sample line."""
    import re

    sample_re = re.compile(
        r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})?\s+(\S+)$')
    label_re = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
    out: List[Tuple[str, Dict[str, str], float]] = []
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        m = sample_re.match(line)
        if m is None:
            raise ValueError(f"malformed exposition line: {raw!r}")
        name, _, label_blob, value_text = m.groups()
        labels: Dict[str, str] = {}
        if label_blob:
            pos = 0
            while pos < len(label_blob):
                lm = label_re.match(label_blob, pos)
                if lm is None:
                    raise ValueError(f"malformed labels: {raw!r}")
                labels[lm.group(1)] = (lm.group(2)
                                       .replace('\\"', '"')
                                       .replace("\\n", "\n")
                                       .replace("\\\\", "\\"))
                pos = lm.end()
                if pos < len(label_blob):
                    if label_blob[pos] != ",":
                        raise ValueError(f"malformed labels: {raw!r}")
                    pos += 1
        try:
            value = float(value_text)
        except ValueError:
            raise ValueError(f"malformed sample value: {raw!r}") from None
        out.append((name, labels, value))
    return out


# ---------------- pod-scale aggregation ----------------

def pod_snapshot() -> dict:
    """This process's registry as a compact JSON-able snapshot — what a
    worker ships to the tracker over the heartbeat path. Stage seconds and
    resilience events are summed ACROSS pipeline labels (the tracker's
    unit of balance is the host, not the pipeline instance)."""
    stages = REGISTRY.sum_by(STAGE_BUSY_METRIC, "stage")
    # 'transfer' lives on the wall meter only (it is a sampled consumer-
    # side probe, not a pipeline-thread busy counter) — merge it in so a
    # transfer-bound rank is visible in the pod table
    transfer = REGISTRY.sum_by(STAGE_WALL_METRIC, "stage").get("transfer")
    if transfer:
        stages["transfer"] = stages.get("transfer", 0.0) + transfer
    events = REGISTRY.sum_by(RESILIENCE_METRIC, "event")
    # per-job data-service breakdown (docs/service.md multi-tenant
    # service): client-side input wait + worker-side parts served,
    # keyed by job — the autoscaler's fleet-wide signal is the sum of
    # these across ranks (additive key; schema stays v1 because old
    # readers ignore it and every v1 field is unchanged)
    job_waits = REGISTRY.sum_by(SERVICE_JOB_WAIT_METRIC, "job")
    job_parts = REGISTRY.sum_by(SERVICE_JOB_PARTS_METRIC, "job")
    jobs = {j: {"input_wait_seconds": round(job_waits.get(j, 0.0), 4),
                "parts": int(round(job_parts.get(j, 0)))}
            for j in sorted(set(job_waits) | set(job_parts)) if j}
    # SLO targets ride beside the wait they bound (docs/service.md
    # Production QoS) — a gauge, identical across a job's ranks, so the
    # pod table can show wait-vs-target per job at a glance
    for j, slo in REGISTRY.sum_by(SERVICE_JOB_SLO_METRIC, "job").items():
        if j and slo and j in jobs:
            jobs[j]["slo_wait_frac"] = round(slo, 4)
    return {
        "telemetry_schema_version": SCHEMA_VERSION,
        "stages": {k: round(v, 4) for k, v in stages.items() if k},
        "resilience": {k: int(round(v)) for k, v in events.items() if k},
        "jobs": jobs,
        # tiered artifact store (docs/store.md): this host's live bytes
        # under management + its eviction/rebuild tallies, so the pod
        # table shows which rank's disk the budget is squeezing
        "store": {
            "store_bytes": int(REGISTRY.sum(STORE_BYTES_METRIC)),
            "store_evictions": int(round(
                events.get("store_evictions", 0))),
            "store_rebuilds_after_eviction": int(round(
                events.get("store_rebuilds_after_eviction", 0))),
        },
        "spans": span_counts(),
        "spans_dropped": spans_dropped(),
        # control-decision ledger summary (schema v2): component.action
        # tallies, so the pod table shows every rank's control activity
        # next to the stage seconds it acted on
        "decisions": decision_counts(),
    }


def _format_jobs_cell(jobs: dict) -> str:
    """One rank's per-job breakdown cell: ``job=wait<seconds>s/parts<n>``
    per job (docs/observability.md per-job pod-table rows)."""
    cells = []
    for j in sorted(jobs):
        rec = jobs[j] or {}
        cell = (f"{j}=wait{float(rec.get('input_wait_seconds', 0.0)):.3f}s"
                f"/parts{int(rec.get('parts', 0))}")
        if rec.get("slo_wait_frac"):
            # the job's input-wait SLO target next to its wait — the
            # at-a-glance "is the autoscaler holding the contract" cell
            cell += f"/slo{float(rec['slo_wait_frac']):.2f}"
        cells.append(cell)
    return " ".join(cells) if cells else "-"


def format_pod_table(by_rank: Dict[int, dict]) -> str:
    """Merged per-rank × per-stage seconds table from worker snapshots
    (what the tracker logs), with a trailing per-job breakdown column
    (job-labeled input wait + parts served — the fleet autoscaler's
    operator-visible input signal). Ranks whose snapshot carries a
    different schema version are listed but not merged."""
    stage_cols = list(STAGES)
    extras = sorted({s for snap in by_rank.values()
                     for s in (snap.get("stages") or {})
                     if s not in STAGES})
    stage_cols += extras
    width = max([5] + [len(s) for s in stage_cols])
    header = "rank  " + "  ".join(f"{s:>{width}}" for s in stage_cols) \
        + "  resilience  jobs  decisions"
    lines = [header]
    totals = {s: 0.0 for s in stage_cols}
    job_totals: Dict[str, Dict[str, float]] = {}
    decision_totals: Dict[str, int] = {}
    for rank in sorted(by_rank):
        snap = by_rank[rank] or {}
        if snap.get("telemetry_schema_version") != SCHEMA_VERSION:
            lines.append(f"{rank:>4}  [schema "
                         f"{snap.get('telemetry_schema_version')!r} != "
                         f"{SCHEMA_VERSION}: not merged]")
            continue
        stages = snap.get("stages") or {}
        cells = []
        for s in stage_cols:
            v = float(stages.get(s, 0.0))
            totals[s] += v
            cells.append(f"{v:>{width}.3f}")
        res = snap.get("resilience") or {}
        hot = {k: v for k, v in sorted(res.items()) if v}
        # store_evictions/rebuilds already ride the resilience dict;
        # surface the rank's live store bytes next to them when nonzero
        store_bytes = (snap.get("store") or {}).get("store_bytes")
        if store_bytes:
            hot["store_bytes"] = int(store_bytes)
        jobs = snap.get("jobs") or {}
        for j, rec in jobs.items():
            tot = job_totals.setdefault(j, {"input_wait_seconds": 0.0,
                                            "parts": 0})
            tot["input_wait_seconds"] += float(
                (rec or {}).get("input_wait_seconds", 0.0))
            tot["parts"] += int((rec or {}).get("parts", 0))
            slo = (rec or {}).get("slo_wait_frac")
            if slo:
                # a target, not a tally: identical across ranks, so the
                # sum row carries it through max, never addition
                tot["slo_wait_frac"] = max(float(slo),
                                           float(tot.get("slo_wait_frac",
                                                         0.0)))
        # control-decision tallies (schema v2): every autoscale / evict /
        # hedge / throttle this rank performed, as component.action:n
        decisions = snap.get("decisions") or {}
        for d, n in decisions.items():
            decision_totals[d] = decision_totals.get(d, 0) + int(n)
        dec_cell = " ".join(f"{d}:{int(n)}" for d, n in
                            sorted(decisions.items()) if n) or "-"
        lines.append(f"{rank:>4}  " + "  ".join(cells)
                     + f"  {hot if hot else '-'}"
                     + f"  {_format_jobs_cell(jobs)}"
                     + f"  {dec_cell}")
    lines.append("-" * len(header))
    lines.append(" sum  " + "  ".join(
        f"{totals[s]:>{width}.3f}" for s in stage_cols)
        + (f"  jobs: {_format_jobs_cell(job_totals)}"
           if job_totals else "")
        + ("  decisions: " + " ".join(
            f"{d}:{n}" for d, n in sorted(decision_totals.items()) if n)
           if decision_totals else ""))
    return "\n".join(lines)


def component_snapshot(role: str) -> dict:
    """Everything ONE component ships for a merged pod timeline — the
    ``trace_dump`` RPC reply body on dispatcher and workers, and what
    ``LocalFleet.dump_trace`` collects locally. ``now`` is this
    process's monotonic clock at snapshot time: the puller pairs it with
    its own RPC request/reply midpoint to estimate the peer's clock
    offset (docs/observability.md Distributed tracing)."""
    import time

    return {"peer": str(role), "pid": os.getpid(),
            "schema": SCHEMA_VERSION, "now": round(time.monotonic(), 6),
            "spans": spans_snapshot(), "decisions": decisions_snapshot()}


def export_pod_trace(path: str, peers: List[dict]) -> int:
    """Merge per-peer span + decision snapshots into ONE Chrome-trace/
    Perfetto JSON — the fleet-wide timeline (docs/observability.md
    Distributed tracing). Each peer dict carries:

    - ``peer``: display name (``dispatcher``, ``worker-0``, ``client``,
      ``rank-3``...) — becomes the Perfetto process name, so pid = role
    - ``schema``: the peer's ``telemetry_schema_version``
    - ``clock_offset_s``: seconds to ADD to the peer's timestamps to
      land them on the caller's clock (estimated from RPC request/reply
      midpoints — see ``LocalFleet.dump_trace``); 0.0 for local spans
    - ``spans``: :func:`spans_snapshot` rows
    - ``decisions``: :func:`decisions_snapshot` events, rendered as
      instant events on the peer's timeline

    A peer at a DIFFERENT schema version is listed, never merged: its
    process shows up with one explicit ``schema-mismatch`` annotation
    instant event and none of its spans — the same refuse-to-merge
    contract as :func:`format_pod_table`, so a mixed-version fleet
    degrades loudly instead of rendering garbage. Returns the number of
    span events written; the file is written to ``<path>.tmp`` then
    atomically published."""
    events: List[dict] = []
    written = 0
    skipped_peers: List[str] = []
    for pid, peer in enumerate(peers, start=1):
        name = str(peer.get("peer") or f"peer-{pid}")
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": name}})
        schema = peer.get("schema")
        offset_us = float(peer.get("clock_offset_s") or 0.0) * 1e6
        if schema != SCHEMA_VERSION:
            # listed, not merged: one loud annotation, zero spans
            skipped_peers.append(name)
            events.append({
                "name": "schema-mismatch", "cat": "dmlc_tpu", "ph": "i",
                "pid": pid, "tid": 0, "ts": 0.0, "s": "p",
                "args": {"schema": schema, "expected": SCHEMA_VERSION,
                         "note": "peer listed, spans not merged"},
            })
            continue
        threads_named = set()
        for s in peer.get("spans") or []:
            tid = s.get("tid", 0)
            if tid not in threads_named:
                threads_named.add(tid)
                events.append({"name": "thread_name", "ph": "M",
                               "pid": pid, "tid": tid,
                               "args": {"name": s.get("thread", "")}})
            args = dict(s.get("labels") or {})
            if s.get("pipeline"):
                args["pipeline"] = s["pipeline"]
            for k in ("trace_id", "parent_id", "span_id"):
                if s.get(k):
                    args[k] = s[k]
            events.append({
                "name": s["name"], "cat": "dmlc_tpu", "ph": "X",
                "pid": pid, "tid": tid,
                "ts": s["start_ns"] / 1e3 + offset_us,
                "dur": s["dur_ns"] / 1e3,
                "args": args,
            })
            written += 1
        for d in peer.get("decisions") or []:
            events.append({
                "name": f"{d.get('component', '?')}.{d.get('action', '?')}",
                "cat": "dmlc_tpu_decision", "ph": "i", "pid": pid,
                "tid": 0, "ts": float(d.get("ts", 0.0)) * 1e6 + offset_us,
                "s": "p", "args": dict(d),
            })
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "telemetry_schema_version": SCHEMA_VERSION,
            "peers": [str(p.get("peer") or "") for p in peers],
            "peers_not_merged": skipped_peers,
        },
    }
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return written


# ---------------- thread-scope inheritance helper ----------------

def scoped_target(fn: Callable[..., Any],
                  label: Optional[str] = None) -> Callable[..., Any]:
    """Wrap a thread target so it runs under ``label`` (default: the scope
    active where THIS call happens — i.e. the creator's scope). The
    pipeline thread primitives use this so spans/metrics recorded on their
    workers land under the right pipeline."""
    if label is None:
        label = current_scope()

    def run(*args, **kwargs):
        set_scope(label)
        return fn(*args, **kwargs)

    return run
