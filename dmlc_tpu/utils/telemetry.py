"""Always-on pipeline telemetry: span tracer + labeled metrics registry.

tf.data's lesson (arXiv:2101.12127) is that AUTOTUNE and fleet-scale
debugging are both built on exactly one thing — a uniform, low-overhead
instrumentation layer over every pipeline stage — and the tf.data-service
paper (arXiv:2210.14826) adds that per-worker metrics must be aggregable
across hosts before a dispatcher can balance them. This module is that
layer for the ingest tier, and the sensor substrate the ROADMAP item 4
feedback controller will read. Two primitives:

**Span tracer** — fixed-size per-thread ring buffers recording
``(name, tid, start_ns, dur_ns, labels)`` spans. Recording is lock-free on
the hot path (each ring has exactly one writer: its thread) and bounded
(old spans overwrite, drops are counted), so it stays on in production.
Every pipeline stage emits spans at the SAME code sites that feed the
stage-seconds counters — read / parse in :mod:`dmlc_tpu.data.parsers`,
cache_read there + cache_write in :mod:`dmlc_tpu.io.block_cache`,
convert / dispatch / transfer in :mod:`dmlc_tpu.data.device`, and the
data-service wire quartet (service_encode / service_send on parse
workers, service_recv / service_decode on clients,
:mod:`dmlc_tpu.service.frame`) — so a trace timeline and
``DeviceIter.stats()`` can never tell different stories.
Export as Chrome-trace/Perfetto JSON via ``DMLC_TPU_TRACE=chrome:<path>``
(dumped when the ``DeviceIter`` closes) or ``DeviceIter.dump_trace(path)``
/ :func:`export_chrome_trace`.

**Metrics registry** — named counters / gauges / histograms / info blobs
with label scoping. The single source of truth behind
``DeviceIter.stats()`` (its :class:`~dmlc_tpu.utils.timer.StageMeter`
stage counters are registry counters), the resilience counters
(:mod:`dmlc_tpu.io.resilience` keeps its public
``counters_snapshot/delta/reset`` API on top of it), the pipeline stall
diagnostics, and the ``bench.py`` JSON line. ``make lint-metrics`` fails
ad-hoc bookkeeping added beside it.

**Pipeline scoping** — a thread-local label (:func:`scope`) stamped onto
every span and metric recorded while it is active. The pipeline thread
primitives (``ThreadedIter`` / ``OrderedWorkerPool`` / the native feed
threads / ``ManagedThread``) capture their creator's scope and install it
in the threads they spawn, so everything a ``DeviceIter`` causes — down
to filesystem retries on a producer thread — lands under that pipeline's
label. Two concurrent pipelines therefore keep disjoint books (the
cross-contamination fix for ``stats()['resilience']``).

**Pod aggregation** — :func:`pod_snapshot` serializes this process's
registry into a compact JSON-able dict; workers ship it to the rendezvous
tracker over the heartbeat path (``WorkerClient.report_metrics``) and the
tracker logs the merged per-rank × per-stage table
(:func:`format_pod_table`), so an 8-host run is debuggable from one
place. See docs/observability.md.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
from contextlib import contextmanager
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

# bumped whenever the span schema, the pod-snapshot layout, or a
# registry metric name consumed across processes changes — the tracker
# refuses to merge snapshots from a different schema, and bench.py /
# make bench-smoke gate the value
SCHEMA_VERSION = 1

# the canonical pipeline stages (benchmarks/_common.STAGE_ORDER mirrors
# this; DeviceIter.stats()['stages'] carries exactly these keys)
STAGES = ("read", "cache_read", "parse", "convert", "dispatch", "transfer")

# registry metric names (docs/observability.md has the full table)
STAGE_BUSY_METRIC = "stage_busy_seconds"
STAGE_WALL_METRIC = "stage_wall_seconds"
RESILIENCE_METRIC = "resilience_events"
STALL_METRIC = "pipeline_stall"
# consumer-side input-bound waiting: every second the consumer measurably
# waited for input (host-batch waits + sampled transfer landings) — the
# counter the autotuner trusts where stall_seconds alone under-reads a
# transfer-bound epoch (VERDICT r5 weak #4)
INPUT_WAIT_METRIC = "input_wait_seconds"
# autotuner mirrors (dmlc_tpu.data.autotune): per-knob current-value
# gauges + a steps counter, labeled by pipeline scope
AUTOTUNE_KNOB_METRIC = "autotune_knob"
AUTOTUNE_STEP_METRIC = "autotune_steps"
# tiered artifact store (dmlc_tpu.store): live on-disk bytes under
# management, gauge labeled (root, tier) — evictions/rebuilds ride the
# resilience counter like every other classified event (docs/store.md)
STORE_BYTES_METRIC = "store_bytes"
# multi-tenant data service (dmlc_tpu.service, docs/service.md): both
# labeled by `job`. The wait counter is the CLIENT-side per-job input
# starvation signal (every second a ServiceParser waits on the wire) the
# fleet autoscaler aggregates from the tracker pod table; the parts
# counter is the WORKER-side per-job parts-served tally. They ride
# pod_snapshot()['jobs'] so the pod table shows a per-job breakdown next
# to per-rank stages (docs/observability.md).
SERVICE_JOB_WAIT_METRIC = "service_job_input_wait_seconds"
SERVICE_JOB_PARTS_METRIC = "service_job_parts"
# per-job input-wait SLO target (register_job(slo_wait_frac=),
# docs/service.md Production QoS): a job-labeled gauge each
# ServiceParser publishes from its config reply, so the pod table shows
# every job's wait NEXT TO the target the autoscaler steers it under
SERVICE_JOB_SLO_METRIC = "service_job_slo_wait_frac"
# wire v2 compression ledger (dmlc_tpu.service.frame, docs/service.md
# Wire v2): raw vs on-wire bytes for every served data frame, labeled by
# `job` — sent/raw is the live compression ratio the pod table and bench
# report; identity transports tick both equally so the ratio reads 1.0
SERVICE_WIRE_RAW_METRIC = "service_wire_bytes_raw"
SERVICE_WIRE_SENT_METRIC = "service_wire_bytes_sent"


# ---------------- pipeline scoping ----------------

_tls = threading.local()
_scope_seq = itertools.count(1)


def new_pipeline_label(prefix: str = "pipeline") -> str:
    """A process-unique pipeline label (``pipeline-1``, ``pipeline-2``...)."""
    return f"{prefix}-{next(_scope_seq)}"


def current_scope() -> Optional[str]:
    """The pipeline label active on this thread, or None."""
    return getattr(_tls, "scope", None)


def set_scope(label: Optional[str]) -> None:
    """Install ``label`` as this thread's pipeline scope (thread primitives
    call this at thread start with the scope captured at construction)."""
    _tls.scope = label


@contextmanager
def scope(label: Optional[str]):
    """Run a block under a pipeline scope; restores the previous one."""
    prev = current_scope()
    set_scope(label)
    try:
        yield label
    finally:
        set_scope(prev)


# ---------------- span tracer ----------------

def _ring_capacity() -> int:
    try:
        return max(64, int(os.environ.get(
            "DMLC_TPU_TRACE_RING_SPANS", "8192") or 8192))
    except ValueError:
        return 8192


def _max_rings() -> int:
    try:
        return max(8, int(os.environ.get(
            "DMLC_TPU_TRACE_MAX_RINGS", "512") or 512))
    except ValueError:
        return 512


class _SpanRing:
    """One thread's fixed-size span buffer. Single writer (the owning
    thread), so ``record`` takes no lock; readers (export) see a racy but
    structurally safe snapshot — every retained entry is a complete tuple
    because the list-slot store is atomic under the GIL."""

    __slots__ = ("tid", "thread_name", "thread", "capacity", "entries",
                 "idx", "total", "counts")

    def __init__(self, tid: int, thread_name: str, capacity: int,
                 thread: Optional[threading.Thread] = None):
        self.tid = tid
        self.thread_name = thread_name
        self.thread = thread  # liveness probe for ring retirement
        self.capacity = capacity
        self.entries: List[Optional[tuple]] = [None] * capacity
        self.idx = 0
        self.total = 0
        self.counts: Dict[str, int] = {}

    def record(self, name: str, start_ns: int, dur_ns: int,
               pipeline: Optional[str], labels: Optional[dict]) -> None:
        self.entries[self.idx] = (name, start_ns, dur_ns, pipeline, labels)
        self.idx = (self.idx + 1) % self.capacity
        self.total += 1
        self.counts[name] = self.counts.get(name, 0) + 1

    def snapshot(self) -> List[tuple]:
        # oldest-first: the wrapped segment precedes the head segment
        if self.total < self.capacity:
            ent = self.entries[: self.idx]
        else:
            ent = self.entries[self.idx:] + self.entries[: self.idx]
        return [e for e in ent if e is not None]

    def clear(self) -> None:
        self.entries = [None] * self.capacity
        self.idx = 0
        self.total = 0
        self.counts = {}


_rings_lock = threading.Lock()
_rings: List[_SpanRing] = []
# retired dead-thread rings fold their books here so span_counts() /
# spans_dropped() stay monotonic after retirement
_retired_counts: Dict[str, int] = {}
_retired_dropped = 0


def _retire_dead_ring_locked() -> None:
    """Memory bound for thread churn: pipelines create producer/worker
    threads per epoch, and each thread that ever recorded a span owns a
    ring. Past ``DMLC_TPU_TRACE_MAX_RINGS`` rings, drop the oldest ring
    whose thread has exited — its retained spans leave the trace (counted
    as dropped) but its totals are preserved."""
    global _retired_dropped
    if len(_rings) < _max_rings():
        return
    for i, ring in enumerate(_rings):
        if ring.thread is not None and not ring.thread.is_alive():
            dead = _rings.pop(i)
            for name, n in dead.counts.items():
                _retired_counts[name] = _retired_counts.get(name, 0) + n
            _retired_dropped += dead.total
            return


def _my_ring() -> _SpanRing:
    ring = getattr(_tls, "ring", None)
    if ring is None:
        t = threading.current_thread()
        ring = _SpanRing(t.ident or 0, t.name, _ring_capacity(), thread=t)
        with _rings_lock:
            _retire_dead_ring_locked()
            _rings.append(ring)
        _tls.ring = ring
    return ring


def record_span(name: str, start_s: float, dur_s: float, **labels) -> None:
    """Record one stage span. ``start_s`` is a ``get_time()`` monotonic
    timestamp, ``dur_s`` its measured duration — the SAME values the
    caller feeds its stage-seconds counter, so per-stage span sums always
    reconcile with the attribution. The active pipeline scope rides along
    automatically."""
    _my_ring().record(name, int(start_s * 1e9), int(dur_s * 1e9),
                      current_scope(), labels or None)


@contextmanager
def span(name: str, **labels):
    """Measure a block as one span (convenience form of
    :func:`record_span` for call sites that keep no counter of their own)."""
    import time

    t0 = time.monotonic()
    try:
        yield
    finally:
        record_span(name, t0, time.monotonic() - t0, **labels)


def spans_snapshot(pipeline: Optional[str] = None) -> List[dict]:
    """Retained spans across all threads, oldest-first per thread, as
    dicts; optionally filtered to one pipeline label."""
    with _rings_lock:
        rings = list(_rings)
    out = []
    for ring in rings:
        for name, start_ns, dur_ns, pipe, labels in ring.snapshot():
            if pipeline is not None and pipe != pipeline:
                continue
            out.append({"name": name, "tid": ring.tid,
                        "thread": ring.thread_name, "start_ns": start_ns,
                        "dur_ns": dur_ns, "pipeline": pipe,
                        "labels": labels or {}})
    out.sort(key=lambda s: s["start_ns"])
    return out


def span_counts() -> Dict[str, int]:
    """Spans RECORDED per name since process start (not just retained —
    neither ring overwrites nor dead-ring retirement lower these)."""
    with _rings_lock:
        rings = list(_rings)
        out = dict(_retired_counts)
    for ring in rings:
        for name, n in list(ring.counts.items()):
            out[name] = out.get(name, 0) + n
    return out


def spans_dropped() -> int:
    """Spans recorded but no longer exportable (ring overwrites + rings
    retired with their thread)."""
    with _rings_lock:
        return _retired_dropped + sum(
            max(0, r.total - r.capacity) for r in _rings)


def reset_spans() -> None:
    """Clear every ring (tests; production rings just wrap)."""
    global _retired_dropped
    with _rings_lock:
        for ring in _rings:
            ring.clear()
        _retired_counts.clear()
        _retired_dropped = 0


def export_chrome_trace(path: str, pipeline: Optional[str] = None) -> int:
    """Write the retained spans as Chrome-trace/Perfetto JSON (object
    form: ``{"traceEvents": [...]}``, complete-event ``ph: "X"``, ts/dur
    in microseconds). Returns the number of events written. The file is
    written to ``<path>.tmp`` then atomically published."""
    pid = os.getpid()
    events: List[dict] = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": "dmlc_tpu"},
    }]
    with _rings_lock:
        rings = list(_rings)
    for ring in rings:
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": ring.tid, "args": {"name": ring.thread_name}})
    rows = spans_snapshot(pipeline)
    for s in rows:
        args = dict(s["labels"])
        if s["pipeline"]:
            args["pipeline"] = s["pipeline"]
        events.append({
            "name": s["name"], "cat": "dmlc_tpu", "ph": "X",
            "pid": pid, "tid": s["tid"],
            "ts": s["start_ns"] / 1e3, "dur": s["dur_ns"] / 1e3,
            "args": args,
        })
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "telemetry_schema_version": SCHEMA_VERSION,
            "spans_dropped": spans_dropped(),
        },
    }
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return len(rows)


# ---------------- trace-mode knob ----------------

def trace_mode() -> Tuple[str, Optional[str]]:
    """Parse ``DMLC_TPU_TRACE`` (docs/data.md):

    - ``1`` -> ``('annotate', None)`` — wrap transfer/convert/dispatch/
      cache_read in ``jax.profiler.TraceAnnotation`` so they show up in a
      jax profiler / Perfetto device trace
    - ``chrome:<path>`` -> ``('chrome', path)`` — dump the span rings as a
      Chrome trace to ``path`` when the pipeline closes
    - anything else (including unset / ``0``) -> ``('off', None)`` — the
      historical contract was exactly ``DMLC_TPU_TRACE=1``, so unknown
      values stay off rather than silently arming per-batch annotations
    """
    value = os.environ.get("DMLC_TPU_TRACE", "").strip()
    if value == "1":
        return "annotate", None
    if value.startswith("chrome:"):
        return "chrome", value[len("chrome:"):]
    return "off", None


@contextmanager
def profiler_annotation(name: str, enabled: bool = True):
    """``jax.profiler.TraceAnnotation`` when enabled (and jax importable);
    a no-op otherwise. Callers cache ``trace_mode()[0] == 'annotate'`` so
    the env parse never sits on a per-batch path."""
    if not enabled:
        yield
        return
    try:
        from jax import profiler as _profiler
    except Exception:  # noqa: BLE001 - tracing must never break the pipeline
        yield
        return
    with _profiler.TraceAnnotation(name):
        yield


# ---------------- metrics registry ----------------

class _Metric:
    __slots__ = ("lock", "labels")

    def __init__(self, labels: Dict[str, str]):
        self.lock = threading.Lock()
        self.labels = labels


class Counter(_Metric):
    """Monotonic float counter (stage seconds use float increments)."""

    __slots__ = ("_value",)
    kind = "counter"

    def __init__(self, labels):
        super().__init__(labels)
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self.lock:
            self._value += n

    @property
    def value(self) -> float:
        with self.lock:
            return self._value


class Gauge(_Metric):
    __slots__ = ("_value",)
    kind = "gauge"

    def __init__(self, labels):
        super().__init__(labels)
        self._value = 0.0

    def set(self, v: float) -> None:
        with self.lock:
            self._value = float(v)

    @property
    def value(self) -> float:
        with self.lock:
            return self._value


class Histogram(_Metric):
    """count/sum/min/max summary (enough for stall and latency shapes
    without bucket-boundary bikeshedding; percentiles can come later)."""

    __slots__ = ("_count", "_sum", "_min", "_max")
    kind = "histogram"

    def __init__(self, labels):
        super().__init__(labels)
        self._count = 0
        self._sum = 0.0
        self._min = None
        self._max = None

    def observe(self, v: float) -> None:
        v = float(v)
        with self.lock:
            self._count += 1
            self._sum += v
            self._min = v if self._min is None else min(self._min, v)
            self._max = v if self._max is None else max(self._max, v)

    @property
    def value(self) -> dict:
        with self.lock:
            return {"count": self._count, "sum": self._sum,
                    "min": self._min, "max": self._max}


class Info(_Metric):
    """A structured JSON-able dict (e.g. the pipeline stall diagnostic):
    last write wins, read back verbatim."""

    __slots__ = ("_value",)
    kind = "info"

    def __init__(self, labels):
        super().__init__(labels)
        self._value: Optional[dict] = None

    def set(self, value: dict) -> None:
        with self.lock:
            self._value = dict(value)

    @property
    def value(self) -> Optional[dict]:
        with self.lock:
            return dict(self._value) if self._value is not None else None


class MetricsRegistry:
    """Named, labeled metrics. ``counter/gauge/histogram/info`` get or
    create the handle for an exact (name, labels) pair — handles are
    cheap to cache at call sites (StageMeter does) so the hot path is one
    small per-metric lock, never the registry lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[tuple, _Metric] = {}

    def _get(self, cls, name: str, labels: Dict[str, str]) -> _Metric:
        key = (cls.kind, name, tuple(sorted(labels.items())))
        m = self._metrics.get(key)
        if m is None:
            with self._lock:
                m = self._metrics.get(key)
                if m is None:
                    m = cls(dict(labels))
                    self._metrics[key] = m
        return m

    def counter(self, name: str, **labels) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, **labels) -> Histogram:
        return self._get(Histogram, name, labels)

    def info(self, name: str, **labels) -> Info:
        return self._get(Info, name, labels)

    # -------- read side --------

    def _rows(self, name: Optional[str], kind: Optional[str],
              label_filter: Dict[str, str]) -> Iterable[Tuple[tuple, _Metric]]:
        with self._lock:
            items = list(self._metrics.items())
        for key, m in items:
            k, n, _ = key
            if name is not None and n != name:
                continue
            if kind is not None and k != kind:
                continue
            if any(m.labels.get(fk) != fv for fk, fv in label_filter.items()):
                continue
            yield key, m

    def snapshot(self, name: Optional[str] = None, kind: Optional[str] = None,
                 **label_filter) -> List[dict]:
        """Matching metrics as ``{"kind", "name", "labels", "value"}`` rows."""
        return [{"kind": key[0], "name": key[1], "labels": dict(m.labels),
                 "value": m.value}
                for key, m in self._rows(name, kind, label_filter)]

    def sum(self, name: str, **label_filter) -> float:
        """Total over matching counters/gauges."""
        return sum(m.value for _, m in self._rows(name, None, label_filter)
                   if isinstance(m, (Counter, Gauge)))

    def sum_by(self, name: str, by: str, **label_filter) -> Dict[str, float]:
        """Per-``by``-label totals over matching counters/gauges."""
        out: Dict[str, float] = {}
        for _, m in self._rows(name, None, label_filter):
            if isinstance(m, (Counter, Gauge)):
                k = m.labels.get(by, "")
                out[k] = out.get(k, 0.0) + m.value
        return out

    def clear(self, name: Optional[str] = None) -> None:
        """Drop matching metrics entirely (tests / counter reset)."""
        with self._lock:
            if name is None:
                self._metrics.clear()
            else:
                self._metrics = {k: v for k, v in self._metrics.items()
                                 if k[1] != name}


REGISTRY = MetricsRegistry()


# ---------------- pod-scale aggregation ----------------

def pod_snapshot() -> dict:
    """This process's registry as a compact JSON-able snapshot — what a
    worker ships to the tracker over the heartbeat path. Stage seconds and
    resilience events are summed ACROSS pipeline labels (the tracker's
    unit of balance is the host, not the pipeline instance)."""
    stages = REGISTRY.sum_by(STAGE_BUSY_METRIC, "stage")
    # 'transfer' lives on the wall meter only (it is a sampled consumer-
    # side probe, not a pipeline-thread busy counter) — merge it in so a
    # transfer-bound rank is visible in the pod table
    transfer = REGISTRY.sum_by(STAGE_WALL_METRIC, "stage").get("transfer")
    if transfer:
        stages["transfer"] = stages.get("transfer", 0.0) + transfer
    events = REGISTRY.sum_by(RESILIENCE_METRIC, "event")
    # per-job data-service breakdown (docs/service.md multi-tenant
    # service): client-side input wait + worker-side parts served,
    # keyed by job — the autoscaler's fleet-wide signal is the sum of
    # these across ranks (additive key; schema stays v1 because old
    # readers ignore it and every v1 field is unchanged)
    job_waits = REGISTRY.sum_by(SERVICE_JOB_WAIT_METRIC, "job")
    job_parts = REGISTRY.sum_by(SERVICE_JOB_PARTS_METRIC, "job")
    jobs = {j: {"input_wait_seconds": round(job_waits.get(j, 0.0), 4),
                "parts": int(round(job_parts.get(j, 0)))}
            for j in sorted(set(job_waits) | set(job_parts)) if j}
    # SLO targets ride beside the wait they bound (docs/service.md
    # Production QoS) — a gauge, identical across a job's ranks, so the
    # pod table can show wait-vs-target per job at a glance
    for j, slo in REGISTRY.sum_by(SERVICE_JOB_SLO_METRIC, "job").items():
        if j and slo and j in jobs:
            jobs[j]["slo_wait_frac"] = round(slo, 4)
    return {
        "telemetry_schema_version": SCHEMA_VERSION,
        "stages": {k: round(v, 4) for k, v in stages.items() if k},
        "resilience": {k: int(round(v)) for k, v in events.items() if k},
        "jobs": jobs,
        # tiered artifact store (docs/store.md): this host's live bytes
        # under management + its eviction/rebuild tallies, so the pod
        # table shows which rank's disk the budget is squeezing
        "store": {
            "store_bytes": int(REGISTRY.sum(STORE_BYTES_METRIC)),
            "store_evictions": int(round(
                events.get("store_evictions", 0))),
            "store_rebuilds_after_eviction": int(round(
                events.get("store_rebuilds_after_eviction", 0))),
        },
        "spans": span_counts(),
        "spans_dropped": spans_dropped(),
    }


def _format_jobs_cell(jobs: dict) -> str:
    """One rank's per-job breakdown cell: ``job=wait<seconds>s/parts<n>``
    per job (docs/observability.md per-job pod-table rows)."""
    cells = []
    for j in sorted(jobs):
        rec = jobs[j] or {}
        cell = (f"{j}=wait{float(rec.get('input_wait_seconds', 0.0)):.3f}s"
                f"/parts{int(rec.get('parts', 0))}")
        if rec.get("slo_wait_frac"):
            # the job's input-wait SLO target next to its wait — the
            # at-a-glance "is the autoscaler holding the contract" cell
            cell += f"/slo{float(rec['slo_wait_frac']):.2f}"
        cells.append(cell)
    return " ".join(cells) if cells else "-"


def format_pod_table(by_rank: Dict[int, dict]) -> str:
    """Merged per-rank × per-stage seconds table from worker snapshots
    (what the tracker logs), with a trailing per-job breakdown column
    (job-labeled input wait + parts served — the fleet autoscaler's
    operator-visible input signal). Ranks whose snapshot carries a
    different schema version are listed but not merged."""
    stage_cols = list(STAGES)
    extras = sorted({s for snap in by_rank.values()
                     for s in (snap.get("stages") or {})
                     if s not in STAGES})
    stage_cols += extras
    width = max([5] + [len(s) for s in stage_cols])
    header = "rank  " + "  ".join(f"{s:>{width}}" for s in stage_cols) \
        + "  resilience  jobs"
    lines = [header]
    totals = {s: 0.0 for s in stage_cols}
    job_totals: Dict[str, Dict[str, float]] = {}
    for rank in sorted(by_rank):
        snap = by_rank[rank] or {}
        if snap.get("telemetry_schema_version") != SCHEMA_VERSION:
            lines.append(f"{rank:>4}  [schema "
                         f"{snap.get('telemetry_schema_version')!r} != "
                         f"{SCHEMA_VERSION}: not merged]")
            continue
        stages = snap.get("stages") or {}
        cells = []
        for s in stage_cols:
            v = float(stages.get(s, 0.0))
            totals[s] += v
            cells.append(f"{v:>{width}.3f}")
        res = snap.get("resilience") or {}
        hot = {k: v for k, v in sorted(res.items()) if v}
        # store_evictions/rebuilds already ride the resilience dict;
        # surface the rank's live store bytes next to them when nonzero
        store_bytes = (snap.get("store") or {}).get("store_bytes")
        if store_bytes:
            hot["store_bytes"] = int(store_bytes)
        jobs = snap.get("jobs") or {}
        for j, rec in jobs.items():
            tot = job_totals.setdefault(j, {"input_wait_seconds": 0.0,
                                            "parts": 0})
            tot["input_wait_seconds"] += float(
                (rec or {}).get("input_wait_seconds", 0.0))
            tot["parts"] += int((rec or {}).get("parts", 0))
            slo = (rec or {}).get("slo_wait_frac")
            if slo:
                # a target, not a tally: identical across ranks, so the
                # sum row carries it through max, never addition
                tot["slo_wait_frac"] = max(float(slo),
                                           float(tot.get("slo_wait_frac",
                                                         0.0)))
        lines.append(f"{rank:>4}  " + "  ".join(cells)
                     + f"  {hot if hot else '-'}"
                     + f"  {_format_jobs_cell(jobs)}")
    lines.append("-" * len(header))
    lines.append(" sum  " + "  ".join(
        f"{totals[s]:>{width}.3f}" for s in stage_cols)
        + (f"  jobs: {_format_jobs_cell(job_totals)}"
           if job_totals else ""))
    return "\n".join(lines)


# ---------------- thread-scope inheritance helper ----------------

def scoped_target(fn: Callable[..., Any],
                  label: Optional[str] = None) -> Callable[..., Any]:
    """Wrap a thread target so it runs under ``label`` (default: the scope
    active where THIS call happens — i.e. the creator's scope). The
    pipeline thread primitives use this so spans/metrics recorded on their
    workers land under the right pipeline."""
    if label is None:
        label = current_scope()

    def run(*args, **kwargs):
        set_scope(label)
        return fn(*args, **kwargs)

    return run
