"""The pipeline knob table: one validated home for every tunable.

Before this module, each worker-count env knob was parsed at its point of
use with ``int(os.environ.get(NAME, "2") or 2)`` — garbage, zero, and
negative values silently fell back or crashed far from the typo, and the
set of tunables was only discoverable by grepping. Now every tunable the
ingest pipeline exposes — pool widths, queue depths, the autotuner's own
pacing — is one :class:`KnobSpec` row in :data:`KNOB_TABLE`, and every
read goes through :func:`resolve` (explicit arg > env > default) which
rejects non-integer / non-positive env values **loudly** at the read
site.

``make lint-metrics`` enforces the discipline: an ``os.environ`` read of
a tunable-shaped name (``DMLC_TPU_*_WORKERS``, ``DMLC_TPU_PREFETCH``,
``DMLC_TPU_CONVERT_AHEAD``, ``DMLC_TPU_AUTOTUNE*``) anywhere under
``dmlc_tpu/`` outside this module fails the gate — a new knob must be a
table row, never an ad-hoc parse.

The table also carries each knob's **autotune bounds**: the feedback
controller (:mod:`dmlc_tpu.data.autotune`) may only move a knob inside
``[lo, hi]``, where ``hi`` defaults to the host's CPU count for
worker-pool widths and both ends are overridable per knob via
``DMLC_TPU_AUTOTUNE_MIN_<KNOB>`` / ``DMLC_TPU_AUTOTUNE_MAX_<KNOB>``
(knob name upper-cased) — the operator's hard caps (docs/data.md
autotune section).
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional, Tuple, Union

from dmlc_tpu.utils.check import DMLCError, check

IntOrFn = Union[int, Callable[[], int]]


def _cpus() -> int:
    return os.cpu_count() or 1


class KnobSpec:
    """One tunable: its env name, default, and autotune bounds.

    ``default`` / ``hi`` may be callables (host-derived values like the
    CPU count are resolved at read time, not import time).
    """

    __slots__ = ("name", "env", "default", "lo", "hi", "doc")

    def __init__(self, name: str, env: Optional[str], default: IntOrFn,
                 lo: int, hi: IntOrFn, doc: str):
        self.name = name
        self.env = env
        self.default = default
        self.lo = int(lo)
        self.hi = hi
        self.doc = doc

    def default_value(self) -> int:
        d = self.default
        return int(d() if callable(d) else d)

    def hi_value(self) -> int:
        h = self.hi
        return int(h() if callable(h) else h)


# The registered tunables. Every knob the autotuner may touch — and every
# worker-count env the pipeline reads — is a row here; ``resolve`` /
# ``bounds`` look knobs up by name.
KNOB_TABLE: Dict[str, KnobSpec] = {
    spec.name: spec for spec in (
        KnobSpec(
            "parse_workers", "DMLC_TPU_PARSE_WORKERS",
            default=lambda: max(1, min(4, _cpus())), lo=1, hi=_cpus,
            doc="data-parallel chunk-parse fan-out width "
                "(ParallelTextParser pool)"),
        KnobSpec(
            "convert_workers", "DMLC_TPU_CONVERT_WORKERS",
            default=2, lo=1, hi=_cpus,
            doc="host layout-conversion pool width (DeviceIter)"),
        KnobSpec(
            "plan_read_workers", "DMLC_TPU_PLAN_READ_WORKERS",
            default=2, lo=1, hi=_cpus,
            doc="plan-ordered warm block-cache read pool width"),
        KnobSpec(
            "snapshot_read_workers", "DMLC_TPU_SNAPSHOT_READ_WORKERS",
            default=2, lo=1, hi=_cpus,
            doc="warm snapshot read pool width (SnapshotIter)"),
        KnobSpec(
            "convert_ahead", "DMLC_TPU_CONVERT_AHEAD",
            default=4, lo=1, hi=64,
            doc="converted-batch lookahead window (convert pool "
                "max_ahead / natural-block prefetch capacity)"),
        KnobSpec(
            "prefetch", "DMLC_TPU_PREFETCH",
            default=2, lo=1, hi=16,
            doc="device_put transfers issued ahead of consumption"),
        KnobSpec(
            "dispatch_workers", "DMLC_TPU_DISPATCH_WORKERS",
            default=32, lo=1, hi=1024,
            doc="data-service dispatcher concurrent connection-handler "
                "cap; excess connections shed with a retryable busy "
                "reply (docs/service.md control-plane recovery). Not an "
                "autotuned knob — the controller maps no stage to it"),
        KnobSpec(
            "hedge_factor", "DMLC_TPU_HEDGE_FACTOR",
            default=4, lo=1, hi=64,
            doc="straggler-hedging threshold: an in-flight part stuck "
                "past this multiple of the fleet's median "
                "grant->complete latency is speculatively re-issued to "
                "a second worker, first-complete-wins (docs/service.md "
                "elastic membership). Not an autotuned knob — hedging "
                "policy is the operator's duplicate-work budget"),
        KnobSpec(
            "drain_deadline", "DMLC_TPU_DRAIN_DEADLINE",
            default=30, lo=1, hi=86400,
            doc="seconds a draining worker keeps serving its "
                "frame-store-complete parts before the drain force-"
                "completes and remaining parts re-issue (docs/service.md "
                "elastic membership). Not an autotuned knob — the "
                "deadline is the preemption notice window"),
        KnobSpec(
            "fleet_min", "DMLC_TPU_FLEET_MIN",
            default=1, lo=1, hi=4096,
            doc="fleet autoscaler floor: the worker count the fleet "
                "never drains below (docs/service.md fleet autoscaling). "
                "Not a DeviceIter-autotuned knob — it bounds the FLEET "
                "controller, which moves worker count, not a pipeline "
                "stage"),
        KnobSpec(
            "fleet_max", "DMLC_TPU_FLEET_MAX",
            default=lambda: max(2, _cpus()), lo=1, hi=4096,
            doc="fleet autoscaler ceiling: the worker count grow events "
                "never exceed — the operator's capacity/cost cap "
                "(docs/service.md fleet autoscaling)"),
        KnobSpec(
            "service_pipeline_depth", "DMLC_TPU_SERVICE_PIPELINE_DEPTH",
            default=4, lo=1, hi=64,
            doc="wire v2 pipelined block requests a service client keeps "
                "in flight per stream — RTT hides behind the outstanding "
                "window; depth 1 degenerates to the v1 one-request-per-"
                "frame cadence (docs/service.md Wire v2). Autotuned: the "
                "controller maps the read stage to it when the source is "
                "a service stream"),
        KnobSpec(
            "claim_wait_deadline", "DMLC_TPU_CLAIM_WAIT_DEADLINE",
            default=30, lo=1, hi=86400,
            doc="seconds a service worker waits on a sibling's cold-build "
                "claim before giving up the wait and building the part "
                "itself (docs/service.md single-claim cold builds). Not "
                "an autotuned knob — the deadline is the operator's "
                "duplicate-work-vs-latency tradeoff under claim-holder "
                "failure"),
        KnobSpec(
            "metrics_history", "DMLC_TPU_METRICS_HISTORY",
            default=256, lo=1, hi=65536,
            doc="samples retained in the bounded metrics time-series "
                "ring behind the exposition gauges "
                "(telemetry.sample_metrics_history — the fleet "
                "autoscaler records one per control tick, so 'what did "
                "input_wait look like when the fleet grew' is "
                "answerable post hoc; docs/observability.md Prometheus "
                "exposition). Not an autotuned knob — it sizes a "
                "diagnostic buffer, not a pipeline stage"),
        KnobSpec(
            "metrics_max_pipelines", "DMLC_TPU_METRICS_MAX_PIPELINES",
            default=512, lo=8, hi=1048576,
            doc="distinct per-pipeline metric scopes the registry "
                "retains before the least-recently-touched scope is "
                "retired with its counters folded into process totals "
                "— the registry twin of DMLC_TPU_TRACE_MAX_RINGS "
                "(docs/observability.md). Not an autotuned knob — it "
                "bounds bookkeeping, not throughput"),
        KnobSpec(
            "fleet_scale_interval", "DMLC_TPU_FLEET_SCALE_INTERVAL",
            default=10, lo=1, hi=3600,
            doc="seconds between fleet-autoscaler control ticks: each "
                "tick aggregates per-job input_wait_seconds deltas from "
                "the tracker pod table and may grow (live join) or "
                "shrink (graceful drain) the fleet by ONE worker — "
                "paired with hysteresis so decisions never flap "
                "(docs/service.md fleet autoscaling)"),
    )
}


def _parse_positive_int(raw: str, what: str) -> int:
    """Loud validation of a tunable's env value: integers >= 1 only —
    zero, negatives, and garbage raise instead of silently defaulting
    (a typo'd knob must fail the run, not quietly mistune it)."""
    try:
        value = int(raw)
    except (TypeError, ValueError):
        raise DMLCError(
            f"{what}={raw!r}: not an integer — worker counts and queue "
            f"depths must be whole numbers >= 1 (docs/data.md autotune "
            f"section lists every knob)") from None
    check(value >= 1,
          f"{what}={value}: must be >= 1 (0/negative would disable the "
          f"stage; unset the variable to use the default instead)")
    return value


def resolve(name: str, explicit: Optional[int] = None) -> int:
    """The one knob read path: explicit argument > env > table default.

    Explicit arguments keep the historical clamp-to-floor behavior
    (``max(lo, int(value))`` — callers constructing pipelines
    programmatically are allowed to pass 0 and get the floor); env
    values are validated LOUDLY via :func:`_parse_positive_int`.
    """
    spec = KNOB_TABLE.get(name)
    check(spec is not None, f"unknown knob {name!r}; registered knobs: "
                            f"{sorted(KNOB_TABLE)}")
    if explicit is not None:
        return max(spec.lo, int(explicit))
    if spec.env:
        raw = os.environ.get(spec.env, "").strip()
        if raw:
            return _parse_positive_int(raw, spec.env)
    return spec.default_value()


def bounds(name: str) -> Tuple[int, int]:
    """The autotuner's hard caps for ``name``: the table's ``[lo, hi]``
    narrowed by ``DMLC_TPU_AUTOTUNE_MIN_<KNOB>`` /
    ``DMLC_TPU_AUTOTUNE_MAX_<KNOB>`` env overrides (validated loudly;
    an inverted pair raises)."""
    spec = KNOB_TABLE.get(name)
    check(spec is not None, f"unknown knob {name!r}; registered knobs: "
                            f"{sorted(KNOB_TABLE)}")
    lo, hi = spec.lo, spec.hi_value()
    env_lo = os.environ.get(f"DMLC_TPU_AUTOTUNE_MIN_{name.upper()}",
                            "").strip()
    env_hi = os.environ.get(f"DMLC_TPU_AUTOTUNE_MAX_{name.upper()}",
                            "").strip()
    if env_lo:
        lo = _parse_positive_int(env_lo,
                                 f"DMLC_TPU_AUTOTUNE_MIN_{name.upper()}")
    if env_hi:
        hi = _parse_positive_int(env_hi,
                                 f"DMLC_TPU_AUTOTUNE_MAX_{name.upper()}")
    check(lo <= hi,
          f"autotune bounds for {name}: min {lo} > max {hi} "
          f"(check the DMLC_TPU_AUTOTUNE_MIN/MAX_{name.upper()} pair)")
    return lo, hi


def store_budget_bytes(explicit: Optional[int] = None) -> Optional[int]:
    """The artifact store's total on-disk byte budget
    (docs/store.md): explicit argument > ``DMLC_TPU_STORE_BUDGET_BYTES``
    env (validated loudly: integer >= 1) > None (unbounded — the
    historical fill-the-volume behavior). Not an autotune knob — the
    budget is the operator's capacity contract, never a value the
    controller may move — but it lives here so the knob lint gate covers
    the read and a typo'd budget fails the run instead of silently
    unbounding the store."""
    if explicit is not None:
        value = int(explicit)
        check(value >= 1,
              f"store_budget_bytes={value}: must be >= 1 (omit the "
              f"budget entirely for an unbounded store)")
        return value
    raw = os.environ.get("DMLC_TPU_STORE_BUDGET_BYTES", "").strip()
    if not raw:
        return None
    return _parse_positive_int(raw, "DMLC_TPU_STORE_BUDGET_BYTES")


def store_job_budget_bytes(explicit: Optional[int] = None) -> Optional[int]:
    """Per-tenant artifact-store byte budget (docs/store.md per-job
    budgets): explicit argument > ``DMLC_TPU_STORE_JOB_BUDGET_BYTES``
    env (validated loudly: integer >= 1) > None (no per-job cap — only
    the fleet-wide ``DMLC_TPU_STORE_BUDGET_BYTES`` applies). Layered on
    the PR 11 eviction pass: a job over its budget sheds ITS OWN
    cheapest unpinned artifacts first, so one tenant's cold builds can
    never evict a sibling's warm set. Not an autotune knob — isolation
    budgets are the operator's tenancy contract."""
    if explicit is not None:
        value = int(explicit)
        check(value >= 1,
              f"store_job_budget_bytes={value}: must be >= 1 (omit the "
              f"budget entirely for uncapped tenants)")
        return value
    raw = os.environ.get("DMLC_TPU_STORE_JOB_BUDGET_BYTES", "").strip()
    if not raw:
        return None
    return _parse_positive_int(raw, "DMLC_TPU_STORE_JOB_BUDGET_BYTES")


def qos_max_inflight(explicit: Optional[int] = None) -> Optional[int]:
    """Fleet-wide parts-in-flight ceiling for the data service
    (docs/service.md Production QoS): explicit argument >
    ``DMLC_TPU_QOS_MAX_INFLIGHT`` env (validated loudly: integer >= 1) >
    None (no ceiling — the historical grant-whatever-workers-ask
    behavior). When the sum of granted-not-completed parts across every
    job reaches the ceiling, the dispatcher sheds further grants and
    locate replies turn ``{"throttled": true}`` — overload degrades to
    bounded queueing instead of fleet collapse. Not an autotune knob —
    the ceiling is the operator's overload contract."""
    if explicit is not None:
        value = int(explicit)
        check(value >= 1,
              f"qos_max_inflight={value}: must be >= 1 (omit the ceiling "
              f"entirely for unbounded admission)")
        return value
    raw = os.environ.get("DMLC_TPU_QOS_MAX_INFLIGHT", "").strip()
    if not raw:
        return None
    return _parse_positive_int(raw, "DMLC_TPU_QOS_MAX_INFLIGHT")


def store_gc_age_seconds(explicit: Optional[int] = None) -> int:
    """Minimum age before an orphaned ``.tmp`` staging file is
    garbage-collected at store open (docs/store.md): explicit argument >
    ``DMLC_TPU_STORE_GC_AGE_SECONDS`` env (validated: integer >= 1) >
    600. The gate exists so a LIVE concurrent writer's in-flight staging
    file is never raced."""
    if explicit is not None:
        value = int(explicit)
        check(value >= 1, f"store_gc_age_seconds={value}: must be >= 1")
        return value
    raw = os.environ.get("DMLC_TPU_STORE_GC_AGE_SECONDS", "").strip()
    if not raw:
        return 600
    return _parse_positive_int(raw, "DMLC_TPU_STORE_GC_AGE_SECONDS")


PARSE_ENGINES = ("auto", "native-batch", "native", "python")


def parse_engine(explicit: Optional[str] = None) -> str:
    """The text-parse engine selector (docs/data.md engine-selection
    table): explicit argument (the ``engine=`` knob of ``create_parser``
    or a ``?engine=`` URI arg) > ``DMLC_TPU_PARSE_ENGINE`` env >
    ``auto``. Values:

    - ``auto``: today's routing — fully-native stream reader for plain
      local corpora, the native chunk feeder for remote ones, the Python
      engine otherwise;
    - ``native-batch``: the chunk-batch SIMD parser that materializes
      block-cache segment spans directly (the cold-path engine);
    - ``native``: the streaming native reader only;
    - ``python``: the vectorized numpy engine (the historical
      ``?engine=python`` opt-out).

    Not an autotuned knob — engine choice changes which code parses, so
    it is pinned by the operator; it lives here so the knob lint gate
    covers the env read and a typo'd engine fails the run loudly."""
    raw = (explicit if explicit is not None
           else os.environ.get("DMLC_TPU_PARSE_ENGINE", "").strip() or "auto")
    value = str(raw).strip().lower()
    check(value in PARSE_ENGINES,
          f"parse engine {raw!r}: must be one of {PARSE_ENGINES} "
          f"(DMLC_TPU_PARSE_ENGINE / create_parser(engine=...) / "
          f"?engine= URI arg — docs/data.md engine-selection table)")
    return value


WIRE_COMPRESSION_MODES = ("auto", "off", "zlib", "zstd", "lz4")


def wire_compression(explicit: Optional[str] = None) -> str:
    """The wire v2 per-segment compression selector (docs/service.md
    Wire v2): explicit argument > ``DMLC_TPU_WIRE_COMPRESSION`` env >
    ``auto``. Values:

    - ``auto``: offer every codec this process has (preference order
      zstd > lz4 > zlib) and let stream-open negotiation pick;
    - ``off``: identity only — never offer or accept a codec;
    - ``zlib`` / ``zstd`` / ``lz4``: offer exactly that codec (a codec
      whose module is missing falls back to identity at negotiation,
      never crashes — no hard dependency).

    Not an autotuned knob — codec choice is negotiated per stream, not a
    value the controller may move; it lives here so the knob lint gate
    covers the env read and a typo'd mode fails the run loudly."""
    raw = (explicit if explicit is not None
           else os.environ.get("DMLC_TPU_WIRE_COMPRESSION", "").strip()
           or "auto")
    value = str(raw).strip().lower()
    check(value in WIRE_COMPRESSION_MODES,
          f"wire compression {raw!r}: must be one of "
          f"{WIRE_COMPRESSION_MODES} (DMLC_TPU_WIRE_COMPRESSION — "
          f"docs/service.md Wire v2)")
    return value


def autotune_enabled(explicit: Optional[bool] = None) -> bool:
    """The master switch: an explicit argument wins; otherwise
    ``DMLC_TPU_AUTOTUNE=1`` arms the controller (any other value — or
    unset — leaves it off, the historical static-knob behavior)."""
    if explicit is not None:
        return bool(explicit)
    return os.environ.get("DMLC_TPU_AUTOTUNE", "").strip() == "1"


def device_decode(explicit: Optional[bool] = None) -> bool:
    """The device-decode tier switch (docs/data.md three-tier decode
    table): an explicit argument (``DeviceIter(device_decode=...)``)
    wins; otherwise ``DMLC_TPU_DEVICE_DECODE=1`` arms it (any other
    value — or unset — leaves the warm path on host snapshot views, the
    historical behavior). Armed, a snapshot-warm epoch ``device_put``s
    each batch's raw container span verbatim and decodes it in HBM
    (:mod:`dmlc_tpu.ops.device_decode`) — zero per-batch host numpy
    decode. Not an autotuned knob — the controller maps the
    ``device_decode`` stage onto ``prefetch`` (deeper transfer
    lookahead), it never flips the tier itself; registered here so the
    knob lint gate covers the env read."""
    if explicit is not None:
        return bool(explicit)
    return os.environ.get("DMLC_TPU_DEVICE_DECODE", "").strip() == "1"


def autotune_interval(explicit: Optional[int] = None) -> int:
    """Mid-epoch controller pacing: run a tuning step every N delivered
    batches (0 = epoch boundaries only, the default). Explicit argument
    > ``DMLC_TPU_AUTOTUNE_INTERVAL`` env (validated: integer >= 0) >
    0."""
    if explicit is not None:
        value = int(explicit)
        check(value >= 0, f"autotune_interval={value}: must be >= 0")
        return value
    raw = os.environ.get("DMLC_TPU_AUTOTUNE_INTERVAL", "").strip()
    if not raw:
        return 0
    try:
        value = int(raw)
    except ValueError:
        raise DMLCError(
            f"DMLC_TPU_AUTOTUNE_INTERVAL={raw!r}: not an integer") from None
    check(value >= 0,
          f"DMLC_TPU_AUTOTUNE_INTERVAL={value}: must be >= 0 "
          "(0 = tune at epoch boundaries only)")
    return value
