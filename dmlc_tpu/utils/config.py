"""key=value config-file parser.

TPU-native equivalent of reference include/dmlc/config.h + src/config.cc:
tokenizes ``key = value`` lines with quoted strings (incl. escaped quotes) and
``#`` comments (Tokenizer, config.cc:30-80), supports multi-value mode where a
repeated key keeps all values (config.h:63-70), and renders a proto-text style
string (``ToProtoString``, config.h:102).
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Tuple

from dmlc_tpu.utils.check import DMLCError


def _tokenize(text: str) -> Iterator[str]:
    """Yield tokens: bare words and quoted strings. ``#`` starts a comment."""
    i, n = 0, len(text)
    while i < n:
        ch = text[i]
        if ch == "#":
            while i < n and text[i] != "\n":
                i += 1
        elif ch == '"':
            j = i + 1
            out = []
            while j < n:
                if text[j] == "\\" and j + 1 < n and text[j + 1] == '"':
                    out.append('"')
                    j += 2
                elif text[j] == '"':
                    break
                else:
                    out.append(text[j])
                    j += 1
            if j >= n:
                raise DMLCError("config: unterminated quoted string")
            yield '"' + "".join(out)  # mark as string token
            i = j + 1
        elif ch == "=":
            yield "="
            i += 1
        elif ch.isspace():
            i += 1
        else:
            j = i
            while j < n and not text[j].isspace() and text[j] not in "=#":
                j += 1
            yield text[i:j]
            i = j


class Config:
    """Ordered key=value config — analog of dmlc::Config (config.h:40-175)."""

    def __init__(self, text: str = "", multi_value: bool = False):
        self.multi_value = multi_value
        self._order: List[Tuple[str, str]] = []
        self._map: Dict[str, List[str]] = {}
        if text:
            self.load(text)

    def load(self, text: str) -> None:
        tokens = list(_tokenize(text))
        if len(tokens) % 3 != 0:
            raise DMLCError(f"config: dangling tokens {tokens[-(len(tokens) % 3):]!r}")
        for i in range(0, len(tokens), 3):
            key, eq, value = tokens[i], tokens[i + 1], tokens[i + 2]
            if eq != "=" or key == "=" or value == "=":
                raise DMLCError(f"config: expected 'key = value' near {tokens[i:i+3]!r}")
            if key.startswith('"'):
                key = key[1:]
            if value.startswith('"'):
                value = value[1:]
            self.set(key, value)

    def set(self, key: str, value: str) -> None:
        if not self.multi_value and key in self._map:
            # single-value mode: last assignment wins (config.h:63 SetParam)
            self._map[key] = [value]
            self._order = [(k, v) for (k, v) in self._order if k != key]
        else:
            self._map.setdefault(key, []).append(value)
        self._order.append((key, value))

    def get(self, key: str) -> str:
        """Last value for key — GetParam (config.h:56)."""
        if key not in self._map:
            raise DMLCError(f"config: key {key!r} not found")
        return self._map[key][-1]

    def get_all(self, key: str) -> List[str]:
        return list(self._map.get(key, []))

    def __contains__(self, key: str) -> bool:
        return key in self._map

    def items(self) -> List[Tuple[str, str]]:
        """Insertion-ordered (key, value) pairs — Config iteration order."""
        return list(self._order)

    def to_proto_string(self) -> str:
        """Proto-text rendering — ToProtoString (config.h:102)."""
        out = []
        for key, value in self._order:
            out.append(f'{key} : "{value}"' if not _is_number(value) else f"{key} : {value}")
        return "\n".join(out) + ("\n" if out else "")


def _is_number(s: str) -> bool:
    try:
        float(s)
        return True
    except ValueError:
        return False
