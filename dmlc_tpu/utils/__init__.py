"""Core utilities: logging/check, registry, parameters, config, serializer.

TPU-native equivalents of reference layers 0-2 (include/dmlc/logging.h,
registry.h, parameter.h, config.h, serializer.h, timer.h).
"""

from dmlc_tpu.utils.check import DMLCError, check, check_eq, check_ne, check_lt, check_le, check_gt, check_ge, get_logger
from dmlc_tpu.utils.registry import Registry
from dmlc_tpu.utils.params import Parameter, field
from dmlc_tpu.utils.config import Config
from dmlc_tpu.utils.timer import Timer, get_time
from dmlc_tpu.utils.concurrency import ConcurrentBlockingQueue, Spinlock
from dmlc_tpu.utils.thread_group import (
    ManagedThread, ShutdownToken, ThreadGroup, blocking_queue_thread,
    timer_thread,
)

__all__ = [
    "DMLCError", "check", "check_eq", "check_ne", "check_lt", "check_le",
    "check_gt", "check_ge", "get_logger", "Registry", "Parameter", "field",
    "Config", "Timer", "get_time",
    "ConcurrentBlockingQueue", "Spinlock", "ManagedThread", "ShutdownToken",
    "ThreadGroup", "blocking_queue_thread", "timer_thread",
]
