"""Binary stream serialization, endian-stable.

TPU-native equivalent of reference include/dmlc/serializer.h +
include/dmlc/io.h typed ``Stream::Read<T>/Write<T>`` (io.h:38-105): scalars,
strings, sequences, dicts, numpy arrays — always little-endian on the wire
(the reference's DMLC_IO_USE_LITTLE_ENDIAN=1 default, endian.h:39, with
byte-swapping on big-endian hosts, serializer.h:83-104).

Works on any file-like object with ``read``/``write`` (our Stream classes,
open files, BytesIO).
"""

from __future__ import annotations

import struct
from typing import Any, BinaryIO, Dict, List, Sequence

import numpy as np

from dmlc_tpu.utils.check import DMLCError

# wire format codes for fixed-width scalars
_FMT = {
    "int8": "<b", "uint8": "<B",
    "int32": "<i", "uint32": "<I",
    "int64": "<q", "uint64": "<Q",
    "float32": "<f", "float64": "<d",
    "bool": "<B",
}


def write_scalar(stream: BinaryIO, value, kind: str) -> None:
    stream.write(struct.pack(_FMT[kind], value))


def read_scalar(stream: BinaryIO, kind: str):
    fmt = _FMT[kind]
    size = struct.calcsize(fmt)
    data = _read_exact(stream, size)
    return struct.unpack(fmt, data)[0]


def _read_exact(stream: BinaryIO, size: int) -> bytes:
    data = stream.read(size)
    if len(data) != size:
        raise DMLCError(f"serializer: expected {size} bytes, got {len(data)} (truncated stream)")
    return data


def write_bytes(stream: BinaryIO, data: bytes) -> None:
    """length-prefixed bytes — string handler (serializer.h:160s uses u64 len)."""
    write_scalar(stream, len(data), "uint64")
    stream.write(data)


def read_bytes(stream: BinaryIO) -> bytes:
    n = read_scalar(stream, "uint64")
    return _read_exact(stream, n)


def write_str(stream: BinaryIO, s: str) -> None:
    write_bytes(stream, s.encode("utf-8"))


def read_str(stream: BinaryIO) -> str:
    return read_bytes(stream).decode("utf-8")


def write_ndarray(stream: BinaryIO, arr: np.ndarray) -> None:
    """dtype-tagged, shape-prefixed array; data always little-endian.

    The reference serializes std::vector<POD> as [u64 size][raw bytes]
    (serializer.h:128-158); we add dtype + ndim + shape so arrays round-trip
    without external schema.
    """
    arr = np.ascontiguousarray(arr)
    dt = arr.dtype.newbyteorder("<")
    write_str(stream, dt.str)
    write_scalar(stream, arr.ndim, "uint32")
    for dim in arr.shape:
        write_scalar(stream, dim, "uint64")
    stream.write(arr.astype(dt, copy=False).tobytes())


def read_ndarray(stream: BinaryIO) -> np.ndarray:
    dtype = np.dtype(read_str(stream))
    ndim = read_scalar(stream, "uint32")
    shape = tuple(read_scalar(stream, "uint64") for _ in range(ndim))
    count = 1
    for dim in shape:
        count *= dim
    data = _read_exact(stream, count * dtype.itemsize)
    return np.frombuffer(data, dtype=dtype).reshape(shape).copy()


# -- generic composite serializer (serializer.h STL handlers) --

_TAG_NONE, _TAG_BOOL, _TAG_INT, _TAG_FLOAT, _TAG_STR, _TAG_BYTES, _TAG_LIST, _TAG_DICT, _TAG_NDARRAY = range(9)


def write_obj(stream: BinaryIO, obj: Any) -> None:
    """Recursive tagged serialization of python composites + numpy arrays."""
    if obj is None:
        write_scalar(stream, _TAG_NONE, "uint8")
    elif isinstance(obj, bool):
        write_scalar(stream, _TAG_BOOL, "uint8")
        write_scalar(stream, int(obj), "uint8")
    elif isinstance(obj, int):
        if not (-(1 << 63) <= obj < (1 << 63)):
            raise DMLCError(f"serializer: int {obj} out of int64 range")
        write_scalar(stream, _TAG_INT, "uint8")
        write_scalar(stream, obj, "int64")
    elif isinstance(obj, float):
        write_scalar(stream, _TAG_FLOAT, "uint8")
        write_scalar(stream, obj, "float64")
    elif isinstance(obj, str):
        write_scalar(stream, _TAG_STR, "uint8")
        write_str(stream, obj)
    elif isinstance(obj, (bytes, bytearray)):
        write_scalar(stream, _TAG_BYTES, "uint8")
        write_bytes(stream, bytes(obj))
    elif isinstance(obj, (list, tuple)):
        write_scalar(stream, _TAG_LIST, "uint8")
        write_scalar(stream, len(obj), "uint64")
        for item in obj:
            write_obj(stream, item)
    elif isinstance(obj, dict):
        write_scalar(stream, _TAG_DICT, "uint8")
        write_scalar(stream, len(obj), "uint64")
        for key, value in obj.items():
            if not isinstance(key, str):
                raise DMLCError("serializer: dict keys must be str")
            write_str(stream, key)
            write_obj(stream, value)
    elif isinstance(obj, np.ndarray):
        write_scalar(stream, _TAG_NDARRAY, "uint8")
        write_ndarray(stream, obj)
    elif isinstance(obj, np.generic):
        write_obj(stream, obj.item())
    else:
        raise DMLCError(f"serializer: unsupported type {type(obj).__name__}")


def read_obj(stream: BinaryIO) -> Any:
    tag = read_scalar(stream, "uint8")
    if tag == _TAG_NONE:
        return None
    if tag == _TAG_BOOL:
        return bool(read_scalar(stream, "uint8"))
    if tag == _TAG_INT:
        return read_scalar(stream, "int64")
    if tag == _TAG_FLOAT:
        return read_scalar(stream, "float64")
    if tag == _TAG_STR:
        return read_str(stream)
    if tag == _TAG_BYTES:
        return read_bytes(stream)
    if tag == _TAG_LIST:
        n = read_scalar(stream, "uint64")
        return [read_obj(stream) for _ in range(n)]
    if tag == _TAG_DICT:
        n = read_scalar(stream, "uint64")
        return {read_str(stream): read_obj(stream) for _ in range(n)}
    if tag == _TAG_NDARRAY:
        return read_ndarray(stream)
    raise DMLCError(f"serializer: bad tag {tag}")


class Serializable:
    """Interface analog of dmlc::Serializable (io.h:132-146)."""

    def save(self, stream: BinaryIO) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def load(self, stream: BinaryIO) -> None:  # pragma: no cover - interface
        raise NotImplementedError
