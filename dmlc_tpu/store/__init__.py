"""Unified tiered-store manager for published on-disk artifacts
(chunk caches / block caches / device-native snapshots): one directory
layout + crash-safe manifest, atomic publish with orphan GC, pin/drop
refcounts, byte budgets with cost-aware eviction. See
:mod:`dmlc_tpu.store.manager` and docs/store.md. The flock'd append-only
JSONL substrate (:class:`~dmlc_tpu.store.journal.AppendJournal`) is
shared with the data-service dispatcher's assignment journal, and
:func:`signature_hash` doubles as the data service's cross-job
share-by-signature key: the multi-tenant dispatcher digests each job's
dataset identity with it to assign shared block-cache paths, so two
jobs over the same corpus converge on the same published artifacts and
the fleet parses that corpus exactly once (docs/store.md
share-by-signature; docs/service.md multi-tenant service)."""

from dmlc_tpu.store.journal import AppendJournal
from dmlc_tpu.store.manager import (
    COMPACT_BYTES,
    COMPACT_LINES,
    MAGIC_TIERS,
    MANIFEST_NAME,
    STORE_DIRNAME,
    TIER_COST,
    TIERS,
    ArtifactStore,
    current_publish_owner,
    note_missing,
    publish_owner,
    reset_stores,
    signature_hash,
    store_counters,
    store_for,
    tier_for_magic,
)

__all__ = [
    "AppendJournal",
    "ArtifactStore", "COMPACT_BYTES", "COMPACT_LINES", "MAGIC_TIERS",
    "MANIFEST_NAME", "STORE_DIRNAME", "TIER_COST", "TIERS",
    "current_publish_owner", "note_missing", "publish_owner",
    "reset_stores", "signature_hash", "store_counters",
    "store_for", "tier_for_magic",
]
