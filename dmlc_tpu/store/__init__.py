"""Unified tiered-store manager for published on-disk artifacts
(chunk caches / block caches / device-native snapshots): one directory
layout + crash-safe manifest, atomic publish with orphan GC, pin/drop
refcounts, byte budgets with cost-aware eviction. See
:mod:`dmlc_tpu.store.manager` and docs/store.md. The flock'd append-only
JSONL substrate (:class:`~dmlc_tpu.store.journal.AppendJournal`) is
shared with the data-service dispatcher's assignment journal."""

from dmlc_tpu.store.journal import AppendJournal
from dmlc_tpu.store.manager import (
    COMPACT_BYTES,
    COMPACT_LINES,
    MAGIC_TIERS,
    MANIFEST_NAME,
    STORE_DIRNAME,
    TIER_COST,
    TIERS,
    ArtifactStore,
    note_missing,
    reset_stores,
    signature_hash,
    store_counters,
    store_for,
    tier_for_magic,
)

__all__ = [
    "AppendJournal",
    "ArtifactStore", "COMPACT_BYTES", "COMPACT_LINES", "MAGIC_TIERS",
    "MANIFEST_NAME", "STORE_DIRNAME", "TIER_COST", "TIERS",
    "note_missing", "reset_stores", "signature_hash", "store_counters",
    "store_for", "tier_for_magic",
]
