"""Unified tiered-store manager: one lifecycle for every on-disk artifact.

Three artifact tiers persist on disk — ``DMLCCHK1`` chunk caches
(:mod:`dmlc_tpu.io.cached_split`), ``DMLCBC01`` block caches
(:mod:`dmlc_tpu.io.block_cache`), ``DMLCSN01`` device-native snapshots
(:mod:`dmlc_tpu.io.snapshot`). They share one segment codec, but before
this module each invented its own lifecycle, and nothing bounded disk: a
long-lived fleet fills the volume and dies. The tf.data-service paper
(arXiv:2210.14826) makes the structural case — a shared input tier only
pays off when its cached artifacts are managed as first-class service
state — and tf.data (arXiv:2101.12127) shows reuse of materialized input
artifacts is the dominant cost lever. This module is that state manager:

- **One directory layout + crash-safe manifest.** Every directory that
  holds published artifacts owns a ``.dmlc_store/`` sidecar with an
  append-only JSONL journal of publish / pin / drop / evict / rebuild
  events (tier, byte size, build-cost class, signature hash, pid, seq).
  Appends happen under an ``flock`` so concurrent processes (e.g. two
  service workers) never tear it; a torn final line from a crash is
  skipped at replay. The journal compacts automatically past
  :data:`COMPACT_LINES` lines. The append/flock/torn-tail/compaction
  mechanics live in the shared :class:`~dmlc_tpu.store.journal.\
AppendJournal` — the same substrate the data-service dispatcher's
  assignment journal recovers from (docs/service.md control-plane
  recovery).
- **Atomic publish through the store.** Writers stage to a
  process-unique ``<path>.<pid>.<seq>.tmp`` (:meth:`ArtifactStore.\
stage_path` — two processes publishing the same signature can never
  clobber each other's half-written bytes) and publish via
  :meth:`ArtifactStore.publish_file` (fsync + ``os.replace`` + journal,
  all inside the store — ``make lint-store`` fails direct publishes
  elsewhere). Orphaned ``.tmp`` files from crashed writers are
  garbage-collected at store open, age-gated by
  ``DMLC_TPU_STORE_GC_AGE_SECONDS`` so a live concurrent writer is never
  raced.
- **Pin/refcount.** Readers pin the artifact they serve
  (:meth:`ArtifactStore.pin` / :meth:`ArtifactStore.drop`, refcounted
  per pid); eviction never touches a pinned artifact, so a worker
  serving a warm epoch cannot lose its tier mid-epoch. Pins of dead
  pids are ignored at replay — a crashed reader cannot wedge the
  budget.
- **Byte budgets with cost-aware eviction.** With
  ``DMLC_TPU_STORE_BUDGET_BYTES`` set (via the knob table,
  :func:`dmlc_tpu.utils.knobs.store_budget_bytes`), every publish
  enforces the budget: unpinned artifacts are evicted cheapest-to-
  rebuild first — snapshots (a warm cache still skips the parse), then
  block caches, then chunk caches (a rebuild re-reads the possibly
  remote source) — LRU within a tier. Eviction surfaces to readers as
  the existing vanished-cache path: the next open misses, the pipeline
  transparently rebuilds, and the stream stays byte-identical. The
  store remembers the eviction (a tombstone in the manifest) so the
  healing open counts ``store_rebuilds_after_eviction`` next to
  ``store_evictions``.

Telemetry: current on-disk bytes ride the registry as the
:data:`~dmlc_tpu.utils.telemetry.STORE_BYTES_METRIC` gauge (labeled
``root``/``tier``); evictions and eviction-triggered rebuilds are
resilience events (``store_evictions`` / ``store_rebuilds_after_\
eviction``), so they land in ``DeviceIter.stats()['resilience']``, the
bench JSON line, and the tracker pod table like every other classified
event. :func:`store_counters` packages all three for ``stats()['store']``
and :func:`~dmlc_tpu.utils.telemetry.pod_snapshot`. See docs/store.md.
"""

from __future__ import annotations

import contextlib
import hashlib
import itertools
import json
import os
import re
import threading
from typing import Dict, List, Optional

from dmlc_tpu.io import resilience as _resilience
from dmlc_tpu.store.journal import AppendJournal
from dmlc_tpu.utils import knobs as _knobs
from dmlc_tpu.utils import telemetry as _telemetry
from dmlc_tpu.utils.check import check

# the sidecar directory one ArtifactStore owns inside its root
STORE_DIRNAME = ".dmlc_store"
MANIFEST_NAME = "manifest.jsonl"
LOCK_NAME = "lock"

# journal compaction thresholds: past COMPACT_LINES lines (checked at
# every replay) — or past COMPACT_BYTES on a pin/drop append (a warm
# steady state pins/drops every epoch without ever replaying, so the
# append path must bound the file too) — the journal is rewritten as
# the live state (publish + tombstone + live-pin lines)
COMPACT_LINES = 4096
COMPACT_BYTES = 1 << 18

# the staging-name shape stage_path() allocates: <final>.<pid>.<seq>.tmp
# — orphan GC parses the pid back out so a LIVE local writer's staging
# file is never collected, however stale its mtime
_STAGE_RE = re.compile(r"\.(\d+)\.\d+\.tmp$")

# the managed tiers in BUILD-COST order — index IS the cost class, and
# eviction walks it ascending: snapshots are cheapest to rebuild (the
# block cache below them still skips the parse), chunk caches dearest
# (a rebuild re-reads the possibly-remote source)
TIERS = ("snapshot", "block_cache", "chunk_cache")
TIER_COST = {tier: cost for cost, tier in enumerate(TIERS)}

# container magics of the store-managed formats (pinned by the formats'
# golden files — the store never parses past these 8 bytes)
MAGIC_TIERS = {
    b"DMLCSN01": "snapshot",
    b"DMLCBC01": "block_cache",
    b"DMLCCHK1": "chunk_cache",
}

_stage_seq = itertools.count(1)

# the active publish owner (a service job name), thread-local: service
# workers wrap a part's whole parse in publish_owner(job) so every
# artifact the parse publishes — however deep in the block-cache /
# chunk-cache machinery the write happens — lands in the manifest with
# its owning-job ledger entry (docs/store.md per-job budgets)
_owner_tls = threading.local()


@contextlib.contextmanager
def publish_owner(job: Optional[str]):
    """Attribute every publish on this thread to ``job`` for the scope
    (nested scopes restore the outer owner). The owner rides the
    manifest's publish events, so per-tenant budget eviction can filter
    candidates by owning job."""
    prev = getattr(_owner_tls, "job", None)
    _owner_tls.job = str(job) if job else None
    try:
        yield
    finally:
        _owner_tls.job = prev


def current_publish_owner() -> Optional[str]:
    """The thread's active publish-owner job, or None (unowned — only
    the fleet-wide budget applies to such artifacts)."""
    return getattr(_owner_tls, "job", None)


def tier_for_magic(magic: bytes) -> str:
    """The tier a container magic publishes under."""
    tier = MAGIC_TIERS.get(bytes(magic))
    check(tier is not None,
          f"store: unknown container magic {magic!r} — store-managed "
          f"formats are {sorted(m.decode() for m in MAGIC_TIERS)}")
    return tier


def signature_hash(signature) -> Optional[str]:
    """Short stable digest of an artifact's staleness signature (the
    manifest records identity, not the full — possibly large — file
    list)."""
    if signature is None:
        return None
    payload = json.dumps(signature, sort_keys=True,
                         separators=(",", ":"), default=str)
    return hashlib.sha1(payload.encode()).hexdigest()[:16]


def _pid_alive(pid: int) -> bool:
    if pid == os.getpid():
        return True
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except (PermissionError, OSError):  # exists, other owner
        return True
    return True


class _Entry:
    """Replayed live state of one artifact."""

    __slots__ = ("name", "tier", "bytes", "sig", "seq", "pins", "evicted",
                 "job")

    def __init__(self, name: str, tier: str, nbytes: int,
                 sig: Optional[str], seq: int,
                 job: Optional[str] = None):
        self.name = name
        self.tier = tier
        self.bytes = int(nbytes)
        self.sig = sig
        self.seq = seq          # last event seq — the LRU clock
        self.pins: Dict[int, int] = {}   # pid -> refcount
        self.evicted = False    # tombstone: evicted, rebuild not yet seen
        self.job = job          # owning-job ledger (per-tenant budgets)

    def pinned(self) -> bool:
        return any(n > 0 and _pid_alive(pid)
                   for pid, n in self.pins.items())


class ArtifactStore:
    """The lifecycle manager of one directory of published artifacts.

    Obtain instances through :func:`store_for` (process-cached per root);
    construction garbage-collects orphaned ``.tmp`` staging files, adopts
    store-managed artifacts published before the manifest existed, and
    enforces the byte budget once.
    """

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self._dir = os.path.join(self.root, STORE_DIRNAME)
        self._manifest = os.path.join(self._dir, MANIFEST_NAME)
        self._lock_path = os.path.join(self._dir, LOCK_NAME)
        os.makedirs(self._dir, exist_ok=True)
        # the shared append-only JSONL substrate (flock'd appends,
        # torn-tail skip, atomic rewrite) — store.journal.AppendJournal
        self._journal = AppendJournal(self._manifest,
                                      lock_path=self._lock_path)
        # live cold-build claims (docs/store.md single-claim builds):
        # name -> {"owner", "pid"}; refreshed by every replay
        self._claims: Dict[str, dict] = {}
        with self._locked():
            self._gc_orphans_locked()
            state = self._replay_locked()
            self._adopt_strays_locked(state)
            self._enforce_budget_locked(state)
            self._set_gauges_locked(state)

    # ---------------- locking ----------------

    def _locked(self):
        """In-process mutex + cross-process ``flock`` over the sidecar
        (the journal's lock, reentrant per thread)."""
        return self._journal.locked()

    # ---------------- journal ----------------

    def _append_locked(self, event: dict, sync: bool = False) -> None:
        # publish/evict records must survive a crash — a lost pin/drop
        # line only loses an ephemeral per-pid refcount
        self._journal.append(event, sync=sync)

    def _read_lines_locked(self) -> List[str]:
        return self._journal.read_lines()

    def _replay_locked(self) -> Dict[str, _Entry]:
        """Reconstruct live state from the journal. Undecodable lines
        (only the torn tail of a crashed append can be one — appends are
        single writes under the lock) are skipped; pins of dead pids are
        dropped; entries whose file vanished outside the store (manual
        rm) are dropped without a tombstone."""
        lines = self._read_lines_locked()
        entries: Dict[str, _Entry] = {}
        claims: Dict[str, dict] = {}
        for seq, raw in enumerate(lines):
            try:
                ev = json.loads(raw)
            except ValueError:
                continue
            op = ev.get("op")
            name = ev.get("path")
            if not isinstance(name, str):
                continue
            if op == "publish":
                tier = ev.get("tier")
                if tier not in TIER_COST:
                    continue
                e = _Entry(name, tier, int(ev.get("bytes", 0) or 0),
                           ev.get("sig"), seq, job=ev.get("job"))
                prev = entries.get(name)
                if prev is not None:
                    e.pins = prev.pins  # pins survive a republish
                entries[name] = e
                # a publish completes whatever cold build claimed the
                # path — the claim dissolves with the artifact live
                claims.pop(name, None)
            elif op == "pin":
                e = entries.get(name)
                if e is not None:
                    pid = int(ev.get("pid", 0) or 0)
                    e.pins[pid] = e.pins.get(pid, 0) + 1
                    e.seq = seq  # a pin is a use: advances the LRU clock
            elif op == "drop":
                e = entries.get(name)
                if e is not None:
                    pid = int(ev.get("pid", 0) or 0)
                    n = e.pins.get(pid, 0) - 1
                    if n > 0:
                        e.pins[pid] = n
                    else:
                        e.pins.pop(pid, None)
            elif op == "evict":
                e = entries.get(name)
                if e is not None:
                    e.evicted = True
                    e.seq = seq
            elif op == "remove":
                # deliberate invalidation (stale signature, corruption
                # heal): no tombstone — the rebuild it triggers is not
                # an eviction casualty
                entries.pop(name, None)
                claims.pop(name, None)
            elif op == "rebuild":
                e = entries.get(name)
                if e is not None and e.evicted:
                    entries.pop(name, None)
            elif op == "claim":
                claims[name] = {"owner": str(ev.get("owner", "")),
                                "pid": int(ev.get("pid", 0) or 0)}
            elif op == "release":
                cur = claims.get(name)
                if cur is not None and cur["owner"] == ev.get("owner"):
                    claims.pop(name, None)
        for name, e in list(entries.items()):
            e.pins = {pid: n for pid, n in e.pins.items()
                      if n > 0 and _pid_alive(pid)}
            if not e.evicted and not os.path.exists(
                    os.path.join(self.root, name)):
                del entries[name]
        # a claim whose holder's pid died is dropped — a crashed cold
        # builder must never wedge the fleet behind a stranded claim
        self._claims = {name: c for name, c in claims.items()
                        if _pid_alive(c["pid"])}
        self._maybe_compact_locked(entries, len(lines))
        return entries

    def _maybe_compact_locked(self, entries: Dict[str, _Entry],
                              nlines: int) -> None:
        if nlines <= COMPACT_LINES:
            return

        def live_events():
            for e in sorted(entries.values(), key=lambda e: e.seq):
                pub = {"op": "publish", "path": e.name, "tier": e.tier,
                       "bytes": e.bytes, "sig": e.sig,
                       "cost": TIER_COST[e.tier]}
                if e.job:
                    # the owning-job ledger survives compaction — a
                    # per-tenant budget squeeze after a compaction must
                    # still know whose artifact is whose
                    pub["job"] = e.job
                yield pub
                if e.evicted:
                    yield {"op": "evict", "path": e.name}
                for pid, n in e.pins.items():
                    for _ in range(n):
                        yield {"op": "pin", "path": e.name, "pid": pid}
            # live claims survive compaction (emitted after publishes so
            # the publish-clears-claim replay rule cannot eat them)
            for name, c in self._claims.items():
                yield {"op": "claim", "path": name,
                       "owner": c["owner"], "pid": c["pid"]}

        self._journal.rewrite(live_events())
        # replayed seqs are now compacted-file line numbers; entries keep
        # their relative LRU order, which is all eviction consults

    # ---------------- open-time maintenance ----------------

    def _gc_orphans_locked(self) -> None:
        """Remove ``*.tmp`` staging files abandoned by crashed writers.
        A staging name carries its writer's pid — a pid that is still
        alive on this host is a LIVE writer, never collected no matter
        how stale the mtime (a cold pass can stall behind retry backoff
        far longer than any age gate). Dead/foreign ``.tmp`` files are
        additionally age-gated, which covers pid recycling and writers
        on other hosts of a shared filesystem."""
        max_age = _knobs.store_gc_age_seconds()
        try:
            names = os.listdir(self.root)
        except OSError:
            return
        import time

        now = time.time()
        for name in names:
            if not name.endswith(".tmp"):
                continue
            m = _STAGE_RE.search(name)
            if m is not None and _pid_alive(int(m.group(1))):
                continue  # live local writer: racing it would corrupt
                #           an in-flight publish
            path = os.path.join(self.root, name)
            try:
                if not os.path.isfile(path):
                    continue
                if now - os.path.getmtime(path) <= max_age:
                    continue
                os.remove(path)
            except OSError:
                continue

    def _adopt_strays_locked(self, state: Dict[str, _Entry]) -> None:
        """Bring store-managed artifacts published before the manifest
        existed (older builds) under management: sniff the 8-byte magic,
        journal a publish. Adopted artifacts are budget-counted and
        evictable like any other."""
        try:
            names = os.listdir(self.root)
        except OSError:
            return
        seq = None  # manifest read once, then a running counter
        for name in sorted(names):
            if name in state or name.endswith(".tmp") \
                    or name == STORE_DIRNAME:
                continue
            path = os.path.join(self.root, name)
            try:
                if not os.path.isfile(path):
                    continue
                with open(path, "rb") as f:
                    magic = f.read(8)
            except OSError:
                continue
            tier = MAGIC_TIERS.get(magic)
            if tier is None:
                continue
            nbytes = os.path.getsize(path)
            if seq is None:
                seq = len(self._read_lines_locked())
            self._append_locked({"op": "publish", "path": name,
                                 "tier": tier, "bytes": nbytes,
                                 "sig": None, "cost": TIER_COST[tier],
                                 "adopted": True})
            state[name] = _Entry(name, tier, nbytes, None, seq)
            seq += 1

    # ---------------- budget / eviction ----------------

    def _enforce_budget_locked(self, state: Dict[str, _Entry],
                               protect: Optional[str] = None) -> None:
        # per-tenant pass FIRST (docs/store.md per-job budgets): a job
        # over DMLC_TPU_STORE_JOB_BUDGET_BYTES sheds ITS OWN artifacts,
        # so the offender is bounded before its pressure ever reaches
        # the fleet-wide pass — one tenant's cold builds can never evict
        # a sibling's warm set through the shared budget
        job_budget = _knobs.store_job_budget_bytes()
        if job_budget is not None:
            by_job: Dict[str, List[_Entry]] = {}
            for e in state.values():
                if not e.evicted and e.job:
                    by_job.setdefault(e.job, []).append(e)
            for owned in by_job.values():
                self._evict_over_locked(owned, job_budget, protect)
        budget = _knobs.store_budget_bytes()
        if budget is not None:
            live = [e for e in state.values() if not e.evicted]
            self._evict_over_locked(live, budget, protect)

    def _evict_over_locked(self, candidates: List[_Entry], budget: int,
                           protect: Optional[str]) -> None:
        """Evict from ``candidates`` until their live bytes fit
        ``budget``: cheapest-to-rebuild first (tier cost ascending), LRU
        within a tier (event seq ascending)."""
        total = sum(e.bytes for e in candidates if not e.evicted)
        for victim in sorted(candidates, key=lambda e: (TIER_COST[e.tier],
                                                        e.seq)):
            if total <= budget:
                break
            if victim.evicted or victim.name == protect \
                    or victim.pinned():
                # the just-published artifact and every pinned one are
                # exempt — with nothing else to evict the store may sit
                # over budget until a pin drops (docs/store.md)
                continue
            try:
                os.remove(os.path.join(self.root, victim.name))
            except OSError:
                pass
            self._append_locked({"op": "evict", "path": victim.name},
                                sync=True)
            victim.evicted = True
            total -= victim.bytes
            _resilience.record_event("store_evictions")
            _telemetry.record_decision(
                "store", "evict",
                trigger={"over_bytes": int(total + victim.bytes - budget),
                         "budget_bytes": int(budget),
                         "tier": victim.tier,
                         "bytes": int(victim.bytes)},
                outcome=f"evicted {victim.name} "
                        f"(tier {victim.tier}, seq {victim.seq})",
                root=self.root, job=victim.job or "")

    def _set_gauges_locked(self, state: Dict[str, _Entry]) -> None:
        per_tier = {tier: 0 for tier in TIERS}
        for e in state.values():
            if not e.evicted:
                per_tier[e.tier] += e.bytes
        for tier, nbytes in per_tier.items():
            _telemetry.REGISTRY.gauge(_telemetry.STORE_BYTES_METRIC,
                                      root=self.root,
                                      tier=tier).set(nbytes)

    # ---------------- public API ----------------

    def stage_path(self, final_path: str) -> str:
        """A process-unique staging path for ``final_path`` — concurrent
        writers (even of the same signature, e.g. two service workers
        racing the same part) each stream to their own ``.tmp`` and the
        atomic rename converges on one complete artifact."""
        return f"{final_path}.{os.getpid()}.{next(_stage_seq)}.tmp"

    def publish_file(self, tmp_path: str, final_path: str, tier: str,
                     signature=None, fobj=None,
                     job: Optional[str] = None) -> None:
        """The one publish path: fsync the staged bytes, atomically
        rename into place, journal the publish, enforce the byte budget.
        ``fobj`` is the still-open staging file when the caller has one
        (saves a reopen); it is closed here either way. ``job`` records
        the owning tenant in the manifest ledger (per-job budgets);
        defaults to the thread's :func:`publish_owner` scope."""
        check(tier in TIER_COST,
              f"store: unknown tier {tier!r}; managed tiers: {TIERS}")
        if job is None:
            job = current_publish_owner()
        if fobj is not None and not fobj.closed:
            # fsync BEFORE the atomic rename: without it a crash in the
            # window can publish a complete-looking artifact whose bytes
            # never hit the platter
            fobj.flush()
            os.fsync(fobj.fileno())
            fobj.close()
        else:
            with open(tmp_path, "rb") as f:
                os.fsync(f.fileno())
        name = self._name(final_path)
        with self._locked():
            os.replace(tmp_path, final_path)
            nbytes = os.path.getsize(final_path)
            pub = {"op": "publish", "path": name, "tier": tier,
                   "bytes": nbytes, "sig": signature_hash(signature),
                   "cost": TIER_COST[tier], "pid": os.getpid()}
            if job:
                pub["job"] = str(job)
            self._append_locked(pub, sync=True)
            state = self._replay_locked()
            self._enforce_budget_locked(state, protect=name)
            self._set_gauges_locked(state)

    def pin(self, path: str) -> None:
        """Refcount-protect ``path`` from eviction (per pid; journaled so
        other processes' eviction passes see it). Pinning a path the
        manifest does not know is a no-op — unknown files are never
        eviction candidates anyway."""
        with self._locked():
            self._append_locked({"op": "pin", "path": self._name(path),
                                 "pid": os.getpid()})
            self._compact_if_bloated_locked()

    def drop(self, path: str) -> None:
        """Release one :meth:`pin` reference."""
        with self._locked():
            self._append_locked({"op": "drop", "path": self._name(path),
                                 "pid": os.getpid()})
            self._compact_if_bloated_locked()

    def _compact_if_bloated_locked(self) -> None:
        """Bound the journal on the APPEND path too: a warm steady state
        pins/drops every epoch without ever publishing or replaying, and
        those appends alone must not grow the sidecar without bound
        (replay compacts past COMPACT_LINES)."""
        try:
            if os.path.getsize(self._manifest) <= COMPACT_BYTES:
                return
        except OSError:
            return
        self._replay_locked()

    def claim(self, path: str, owner: str) -> bool:
        """Single-claim the cold build of ``path`` fleet-wide.

        Returns True when ``owner`` now holds (or already held) the
        build claim; False when a DIFFERENT live owner does — the caller
        should wait for that builder's publish instead of running a
        duplicate cold pass (docs/service.md parse-once). The claim is
        journaled (crash-safe, cross-process via the manifest flock) and
        dissolves on the path's publish, an explicit :meth:`release`, or
        the claimant pid dying."""
        name = self._name(path)
        with self._locked():
            self._replay_locked()
            cur = self._claims.get(name)
            if cur is not None and cur["owner"] != owner:
                return False
            if cur is None:
                self._append_locked(
                    {"op": "claim", "path": name, "owner": str(owner),
                     "pid": os.getpid()}, sync=True)
                self._claims[name] = {"owner": str(owner),
                                      "pid": os.getpid()}
            return True

    def release(self, path: str, owner: str) -> None:
        """Release ``owner``'s build claim on ``path`` (no-op when not
        held — a publish already dissolved it)."""
        name = self._name(path)
        with self._locked():
            self._append_locked({"op": "release", "path": name,
                                 "owner": str(owner)})
            if self._claims.get(name, {}).get("owner") == str(owner):
                self._claims.pop(name, None)
            self._compact_if_bloated_locked()

    def claimant(self, path: str) -> Optional[str]:
        """The live owner token of ``path``'s build claim, or None."""
        with self._locked():
            self._replay_locked()
            cur = self._claims.get(self._name(path))
            return cur["owner"] if cur is not None else None

    def discard(self, path: str) -> None:
        """Deliberate removal (stale signature, corruption heal): delete
        the file and clear the manifest entry WITHOUT a tombstone — the
        rebuild this triggers is the caller's own healing, not an
        eviction casualty."""
        with self._locked():
            try:
                os.remove(path)
            except OSError:
                pass
            self._append_locked({"op": "remove",
                                 "path": self._name(path)}, sync=True)
            self._set_gauges_locked(self._replay_locked())

    def note_missing(self, path: str) -> None:
        """A reader found ``path`` absent. If the manifest shows it was
        evicted, the open that follows is an eviction-triggered rebuild:
        count ``store_rebuilds_after_eviction`` once and clear the
        tombstone."""
        with self._locked():
            state = self._replay_locked()
            e = state.get(self._name(path))
            if e is None or not e.evicted:
                return
            self._append_locked({"op": "rebuild",
                                 "path": self._name(path)}, sync=True)
            _resilience.record_event("store_rebuilds_after_eviction")

    # -------- read side --------

    def entries(self) -> List[dict]:
        """The live manifest state (diagnostics / tests): one dict per
        known artifact."""
        with self._locked():
            state = self._replay_locked()
        return [{"path": e.name, "tier": e.tier, "bytes": e.bytes,
                 "sig": e.sig, "pinned": e.pinned(),
                 "evicted": e.evicted, "job": e.job}
                for e in sorted(state.values(), key=lambda e: e.seq)]

    def total_bytes(self) -> int:
        """Live (non-evicted) artifact bytes under management."""
        with self._locked():
            state = self._replay_locked()
        return sum(e.bytes for e in state.values() if not e.evicted)

    def _name(self, path: str) -> str:
        name = os.path.basename(os.path.abspath(path))
        check(os.path.dirname(os.path.abspath(path)) == self.root,
              f"store at {self.root}: artifact {path} lives in a "
              f"different directory (use store_for(path))")
        return name


# ---------------- process-wide store registry ----------------

_stores: Dict[str, ArtifactStore] = {}
_stores_mu = threading.Lock()


def store_for(path: str) -> ArtifactStore:
    """The :class:`ArtifactStore` managing ``path``'s directory (cached
    per root for the process's life — open-time GC/adoption runs once)."""
    root = os.path.dirname(os.path.abspath(path))
    with _stores_mu:
        st = _stores.get(root)
        if st is None:
            st = ArtifactStore(root)
            _stores[root] = st
        return st


def reset_stores() -> None:
    """Forget cached store instances (tests: a fresh ``store_for`` re-runs
    open-time GC/adoption/budget enforcement)."""
    with _stores_mu:
        _stores.clear()


def note_missing(path: str) -> None:
    """Cheap missing-artifact probe for readers: consult the store ONLY
    when ``path``'s directory already carries a manifest sidecar. A
    directory the store never managed cannot hold an eviction tombstone,
    so a bare existence check of an unmanaged path stays one ``stat`` —
    it never creates the sidecar or pays the open-time directory scan
    (the probe may target a large read-only data directory)."""
    root = os.path.dirname(os.path.abspath(path))
    if not os.path.exists(os.path.join(root, STORE_DIRNAME,
                                       MANIFEST_NAME)):
        return
    store_for(path).note_missing(path)


def store_counters() -> Dict[str, int]:
    """The store's registry-backed counter triple — what
    ``DeviceIter.stats()['store']``, the bench JSON line, and
    :func:`~dmlc_tpu.utils.telemetry.pod_snapshot` carry:
    ``store_bytes`` (live bytes across every store this process touched),
    ``store_evictions``, ``store_rebuilds_after_eviction``."""
    events = _telemetry.REGISTRY.sum_by(_telemetry.RESILIENCE_METRIC,
                                        "event")
    return {
        "store_bytes": int(_telemetry.REGISTRY.sum(
            _telemetry.STORE_BYTES_METRIC)),
        "store_evictions": int(round(events.get("store_evictions", 0))),
        "store_rebuilds_after_eviction": int(round(
            events.get("store_rebuilds_after_eviction", 0))),
    }
