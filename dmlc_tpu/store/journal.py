"""Shared append-only JSONL journal: the crash-safe state substrate.

Two control-plane state machines persist through the same discipline —
the tiered artifact store's manifest (:mod:`dmlc_tpu.store.manager`) and
the data-service dispatcher's assignment journal
(:mod:`dmlc_tpu.service.dispatcher`). Before this module each would have
hand-rolled the same four mechanics; now both ride one
:class:`AppendJournal`:

- **flock'd appends** — one JSON object per line, written as a single
  ``write`` under an exclusive ``flock`` on a sidecar lock file, so
  concurrent processes never interleave bytes mid-line. On platforms
  without ``fcntl`` the journal degrades to in-process locking only.
- **torn-tail skip** — a crash mid-append can leave at most one
  undecodable final line (appends are single writes under the lock);
  :meth:`read_events` skips undecodable lines, so replay after a
  ``kill -9`` reconstructs exactly the state every *completed* append
  recorded.
- **fsync on demand** — events that must survive a crash pass
  ``sync=True``; bookkeeping-only events (whose loss costs nothing but
  an ephemeral refcount or a re-queue the replay performs anyway) skip
  the fsync.
- **atomic compaction** — :meth:`rewrite` stages the compacted live
  state to a process-unique sibling file, fsyncs, and renames it into
  place with ``os.replace``. The rename lives HERE, inside ``dmlc_tpu/store/``, so
  ``make lint-store`` keeps hand-rolled ``.tmp`` + ``os.replace``
  journal publishes from reappearing beside it.

Locking is reentrant per thread: :meth:`locked` tracks its own depth, so
a public method that holds the lock can call helpers that take it again
without the second ``flock`` on a fresh fd deadlocking the process.
"""

from __future__ import annotations

import json
import os
import threading
from contextlib import contextmanager
from typing import Dict, Iterable, List, Optional

try:  # POSIX cross-process lock; degrades to in-process locking without
    import fcntl as _fcntl
except ImportError:  # pragma: no cover - non-POSIX
    _fcntl = None


def encode_event(event: dict) -> str:
    """One journal line (sorted compact JSON, newline-terminated)."""
    return json.dumps(event, sort_keys=True, separators=(",", ":")) + "\n"


def decode_events(lines: List[str]) -> List[Dict]:
    """Decode journal lines, skipping undecodable ones (the torn tail
    of a crashed append) — shared by :meth:`AppendJournal.read_events`
    and replayers that already hold the lines."""
    events: List[Dict] = []
    for raw in lines:
        try:
            ev = json.loads(raw)
        except ValueError:
            continue
        if isinstance(ev, dict):
            events.append(ev)
    return events


class AppendJournal:
    """One append-only JSONL journal file + its cross-process lock."""

    def __init__(self, path: str, lock_path: Optional[str] = None):
        self.path = os.path.abspath(path)
        self.lock_path = lock_path or self.path + ".lock"
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._mu = threading.RLock()
        self._depth = 0

    # ---------------- locking ----------------

    @contextmanager
    def locked(self):
        """In-process mutex + cross-process ``flock``, reentrant per
        thread (a second :meth:`locked` from the holder is depth-counted
        instead of re-``flock``\\ ing a fresh fd, which would deadlock)."""
        with self._mu:
            if self._depth:
                self._depth += 1
                try:
                    yield
                finally:
                    self._depth -= 1
                return
            f = open(self.lock_path, "a+")
            try:
                if _fcntl is not None:
                    _fcntl.flock(f.fileno(), _fcntl.LOCK_EX)
                self._depth = 1
                try:
                    yield
                finally:
                    self._depth = 0
            finally:
                try:
                    if _fcntl is not None:
                        _fcntl.flock(f.fileno(), _fcntl.LOCK_UN)
                finally:
                    f.close()

    # ---------------- write side ----------------

    def append(self, event: dict, sync: bool = False) -> None:
        """Append one event under the lock. ``sync=True`` fsyncs — for
        records that must survive a crash; a lost unsynced line may only
        cost state the replay reconstructs anyway."""
        line = encode_event(event)
        with self.locked():
            with open(self.path, "a") as f:
                f.write(line)
                if sync:
                    f.flush()
                    os.fsync(f.fileno())

    def rewrite(self, events: Iterable[dict]) -> None:
        """Atomically replace the journal with ``events`` (compaction):
        stage to a process-unique sibling, fsync, ``os.replace``."""
        tmp = f"{self.path}.{os.getpid()}.compact"
        with self.locked():
            with open(tmp, "w") as f:
                for event in events:
                    f.write(encode_event(event))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)

    # ---------------- read side ----------------

    def read_lines(self) -> List[str]:
        """Raw journal lines (missing file reads as empty)."""
        with self.locked():
            try:
                with open(self.path, "r") as f:
                    return f.read().splitlines()
            except OSError:
                return []

    def read_events(self) -> List[Dict]:
        """Decoded events in append order; undecodable lines (the torn
        tail of a crashed append) are skipped."""
        return decode_events(self.read_lines())
