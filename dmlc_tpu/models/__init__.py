"""Model layer: the linear learners the reference substrate was built to feed.

dmlc-core itself contains no models, but its Row::SDot (data.h:146-161) and
RowBlock design exist to serve linear learners (XGBoost's linear booster,
wormhole's linear solvers). The flagship end-to-end slice here is therefore
a jit/pjit logistic-regression / linear-regression SGD learner over the
device pipeline — the SURVEY.md §7 "minimum slice" model — plus the
second-order factorization machine the libfm format exists to feed
(models/fm.py).
"""

from dmlc_tpu.models.als import AlsLearner, AlsParams
from dmlc_tpu.models.fm import FMLearner, FMParams
from dmlc_tpu.models.linear import LinearLearner, LinearParams

__all__ = ["AlsLearner", "AlsParams", "FMLearner", "FMParams",
           "LinearLearner", "LinearParams"]
