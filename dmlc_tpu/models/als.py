"""ALX-style sharded alternating least squares on the device pipeline.

ALX (arXiv:2112.02194) trains large-scale matrix factorization on TPU pods
by sharding the factor tables and turning the per-row least-squares solve
into dense batched linear algebra — gathers of the fixed side's factors,
normal equations on the MXU, `jnp.linalg.solve`, scatter of the solved
side. This learner is that recipe adapted to stream off dmlc_tpu's ingest
stack instead of a pre-materialized embedding layout:

* **Data encoding.** Each corpus row is one user's rating list in libsvm
  form — the float *label* carries the user/row id, the ``item:rating``
  features carry the observed entries. That means the whole existing
  parse / block-cache / shuffle / service stack moves ratings without a
  single new wire type: `EllBatch.label` delivers row ids to the jitted
  step, `indices`/`values` deliver the rated items.
* **User half-step, per batch.** For every row in the batch the normal
  equations ``A_u = V_u^T V_u + reg*I`` and ``b_u = V_u^T r_u`` are formed
  from gathers of the (fixed) item table — the right-hand side goes
  through :func:`dmlc_tpu.ops.pallas_sparse.ell_matvec_auto`, the
  sanctioned sparse hot-path entry that picks the Pallas one-hot kernel
  in its measured win band and the XLA gather elsewhere — then a batched
  ``jnp.linalg.solve`` and a row scatter update the user table exactly.
* **Item half-step, per epoch.** The item side's normal equations
  accumulate across the epoch inside ``opt_state`` (a ``[D+1, F, F]``
  gram and ``[D+1, F]`` rhs, scatter-added per batch) and are solved in
  one donated jitted :meth:`AlsLearner.finalize_items` at the epoch
  boundary — the streaming-friendly shape of ALX's alternation: each
  epoch is one full user sweep *and* one item solve.
* **Padding discipline.** ELL pad slots carry index ``num_items`` — the
  item table's sink row, pinned to zero. Pad gathers therefore contribute
  nothing to ``A_u``/``b_u``/the loss for free; pad scatter-adds land in
  the sink row and are zeroed again by ``finalize_items``.
* **Sharding.** Batches shard over the mesh data axis; both factor
  tables and the normal-equation accumulators stay replicated, so the
  per-device scatters reconcile through XLA's SPMD lowering (the pod
  story: `pod_sharding=` hands each host a disjoint set of user rows, so
  row scatters never conflict across hosts). The loss comes back
  replicated — addressable on every process. The step is compiled by
  :meth:`TrainLoopMixin._jit_step`, so the ``(params, opt_state)``
  buffers are donated: the big tables update in place.

The loss reported per step is the weighted mean squared error of the
freshly solved user rows against their observed ratings — with fixed
inputs and a fixed schedule the trajectory is fully deterministic, which
is what the mid-train checkpoint/restore byte-identity tests pin.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dmlc_tpu.models._loop import TrainLoopMixin
from dmlc_tpu.ops.sparse import EllBatch
from dmlc_tpu.utils.check import check


class AlsParams(NamedTuple):
    users: jax.Array  # [num_users, F] row factors, solved exactly per batch
    items: jax.Array  # [num_items + 1, F]; last row = ELL pad sink, pinned 0


class AlsOptState(NamedTuple):
    # epoch-accumulated item-side normal equations (sink row included so
    # pad scatters have somewhere inert to land)
    gram: jax.Array  # [num_items + 1, F, F]  sum of u u^T per observation
    rhs: jax.Array   # [num_items + 1, F]     sum of r * u per observation


class AlsLearner(TrainLoopMixin):
    """Sharded ALS / embedding-table factorization fed by DeviceIter.

    Feed it ELL batches whose ``label`` column carries integer user/row
    ids (``DeviceIter(layout='ell', num_col=model.device_num_col(), ...)``)
    — one corpus row per user per epoch. ``fit_epoch`` runs the user sweep
    and then :meth:`finalize_items`, so ``fit(epochs=N)`` performs N full
    alternations.
    """

    def __init__(
        self,
        num_users: int,
        num_items: int,
        num_factors: int = 8,
        reg: float = 0.1,
        init_scale: float = 0.1,
        seed: int = 0,
        mesh=None,
        data_axis: str = "data",
    ):
        check(num_users > 0 and num_items > 0 and num_factors > 0,
              "AlsLearner: num_users/num_items/num_factors must be positive")
        self.num_users = num_users
        self.num_items = num_items
        self.num_factors = num_factors
        self.reg = float(reg)
        self.mesh = mesh
        self.data_axis = data_axis
        key = jax.random.PRNGKey(seed)
        items = init_scale * jax.random.normal(
            key, (num_items + 1, num_factors), dtype=jnp.float32)
        self.params = AlsParams(
            users=jnp.zeros((num_users, num_factors), dtype=jnp.float32),
            items=items.at[-1].set(0.0),
        )
        self.opt_state = AlsOptState(
            gram=jnp.zeros((num_items + 1, num_factors, num_factors),
                           dtype=jnp.float32),
            rhs=jnp.zeros((num_items + 1, num_factors), dtype=jnp.float32),
        )
        self._step = self._build_step()
        self._finalize = self._build_finalize()
        self._eval = self._build_eval()

    # ---------------- DeviceIter surface ----------------

    def device_num_col(self) -> int:
        """The ``num_col`` a DeviceIter must use: pad index == num_items,
        the item table's pinned-zero sink row."""
        return self.num_items

    def batch_shardings(self):
        """ELL batch placement for a DeviceIter feeding this learner."""
        if self.mesh is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P

        row = NamedSharding(self.mesh, P(self.data_axis, None))
        vec = NamedSharding(self.mesh, P(self.data_axis))
        return EllBatch(indices=row, values=row, label=vec, weight=vec)

    def _rep_shardings(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        rep = NamedSharding(self.mesh, P())
        params_sh = jax.tree_util.tree_map(lambda _: rep, self.params)
        opt_sh = jax.tree_util.tree_map(lambda _: rep, self.opt_state)
        return rep, params_sh, opt_sh

    # ---------------- jitted functions ----------------

    def _build_step(self):
        reg_eye = self.reg * jnp.eye(self.num_factors, dtype=jnp.float32)
        num_items = self.num_items

        def step(params, opt_state, batch):
            from dmlc_tpu.ops.pallas_sparse import ell_matvec_auto

            idx = batch.indices                        # [B, K], pad = D
            vals = batch.values                        # [B, K], 0 at pads
            uid = batch.label.astype(jnp.int32)        # [B] user/row ids
            w = batch.weight                           # [B]
            v_g = jnp.take(params.items, idx, axis=0)  # [B, K, F]; 0 at pads
            # normal-equation RHS b_u = V_u^T r_u through the sparse
            # hot-path entry (Pallas in its band, XLA gather elsewhere)
            b = ell_matvec_auto(params.items, batch)   # [B, F]
            a = jnp.einsum("bkf,bkg->bfg", v_g, v_g) + reg_eye
            u = jnp.linalg.solve(a, b[..., None])[..., 0]  # [B, F]
            users = params.users.at[uid].set(u)
            # item-side normal equations: pad slots scatter into the sink
            # row (masked for the gram, rating 0 for the rhs) and are
            # zeroed again by finalize_items
            mask = (idx != num_items).astype(jnp.float32)      # [B, K]
            wk = mask * w[:, None]                             # [B, K]
            outer = u[:, None, :, None] * u[:, None, None, :]  # [B, 1, F, F]
            gram = opt_state.gram.at[idx].add(wk[..., None, None] * outer)
            rhs = opt_state.rhs.at[idx].add(
                (w[:, None] * vals)[..., None] * u[:, None, :])
            # weighted MSE of the freshly solved rows (pads are exact
            # zeros on both sides, so only the count needs the mask)
            pred = jnp.einsum("bkf,bf->bk", v_g, u)
            err = pred - vals
            den = jnp.maximum((wk).sum(), 1.0)
            loss = ((err * err) * wk).sum() / den
            return (AlsParams(users=users, items=params.items),
                    AlsOptState(gram=gram, rhs=rhs), loss)

        if self.mesh is None:
            return self._jit_step(step)
        rep, params_sh, opt_sh = self._rep_shardings()
        return self._jit_step(step, params_sh=params_sh,
                              batch_sh=self.batch_shardings(),
                              opt_sh=opt_sh, loss_sh=rep)

    def _build_finalize(self):
        reg_eye = self.reg * jnp.eye(self.num_factors, dtype=jnp.float32)

        def finalize(params, opt_state):
            a = opt_state.gram + reg_eye               # [D+1, F, F]
            items = jnp.linalg.solve(a, opt_state.rhs[..., None])[..., 0]
            items = items.at[-1].set(0.0)              # re-pin the pad sink
            return (AlsParams(users=params.users, items=items),
                    AlsOptState(gram=jnp.zeros_like(opt_state.gram),
                                rhs=jnp.zeros_like(opt_state.rhs)))

        if self.mesh is None:
            fn = jax.jit(finalize, donate_argnums=(0, 1))
        else:
            _, params_sh, opt_sh = self._rep_shardings()
            fn = jax.jit(finalize, donate_argnums=(0, 1),
                         in_shardings=(params_sh, opt_sh),
                         out_shardings=(params_sh, opt_sh))
        fn._donate_argnums = (0, 1)
        return fn

    def _build_eval(self):
        num_items = self.num_items

        def eval_fn(params, batch):
            idx = batch.indices
            vals = batch.values
            uid = batch.label.astype(jnp.int32)
            u = jnp.take(params.users, uid, axis=0)      # [B, F]
            v_g = jnp.take(params.items, idx, axis=0)    # [B, K, F]
            pred = jnp.einsum("bkf,bf->bk", v_g, u)
            wk = ((idx != num_items).astype(jnp.float32)
                  * batch.weight[:, None])
            err = pred - vals
            return ((err * err) * wk).sum(), wk.sum()

        if self.mesh is None:
            return jax.jit(eval_fn)
        from jax.sharding import NamedSharding, PartitionSpec as P

        # replicated scalar outputs: the cross-device reduction of the
        # sharded batch is the one psum XLA inserts for the whole pass
        rep = NamedSharding(self.mesh, P())
        return jax.jit(eval_fn, out_shardings=(rep, rep))

    # ---------------- training surface ----------------

    def finalize_items(self) -> None:
        """Solve the item half from the epoch's accumulated normal
        equations and reset the accumulators (donated — in place)."""
        self.params, self.opt_state = self._finalize(
            self.params, self.opt_state)

    def fit_epoch(self, device_iter, max_steps=None) -> Tuple[float, int]:
        """User sweep (inherited loop: device-side loss accumulation, one
        host sync) followed by the epoch-boundary item solve."""
        loss, n = super().fit_epoch(device_iter, max_steps=max_steps)
        self.finalize_items()
        return loss, n

    def eval_loss(self, device_iter, max_steps=None) -> float:
        """Weighted MSE over one pass. Per-host/per-device partials stay
        on device and reduce replicated; two host syncs total."""
        from dmlc_tpu.models._loop import host_scalar

        se, wsum, n = None, None, 0
        for batch in device_iter:
            s, t = self._eval(self.params, batch)
            se = s if se is None else se + s
            wsum = t if wsum is None else wsum + t
            n += 1
            if max_steps is not None and n >= max_steps:
                break
        device_iter.reset()
        if n == 0:
            return 0.0
        return host_scalar(se) / max(host_scalar(wsum), 1.0)

    # ---------------- checkpoint surface ----------------

    def state_dict(self) -> dict:
        """Host-side snapshot of the full training state — pairs with
        ``DeviceIter.state_dict()`` for mid-epoch checkpoints; restoring
        both reproduces the loss trajectory byte-identically."""
        return {
            "users": np.asarray(self.params.users),
            "items": np.asarray(self.params.items),
            "gram": np.asarray(self.opt_state.gram),
            "rhs": np.asarray(self.opt_state.rhs),
        }

    def load_state_dict(self, state: dict) -> None:
        self.params = AlsParams(users=jnp.asarray(state["users"]),
                                items=jnp.asarray(state["items"]))
        self.opt_state = AlsOptState(gram=jnp.asarray(state["gram"]),
                                     rhs=jnp.asarray(state["rhs"]))
