"""Linear learners (logistic / least-squares) on the device pipeline.

TPU-first design:
- pure functional step (params pytree in, params out) under ``jax.jit``,
- batch sharded over the mesh ``data`` axis, params replicated; XLA inserts
  the gradient ``psum`` over ICI (no hand-written allreduce — the tracker's
  ring topology, tracker.py:202-234, has no code analog here by design),
- optional feature-dim sharding of the weight vector over a ``model`` axis
  for very wide models (the dense path shards the [B, D] batch's D too),
- dense path hits the MXU via a plain matmul; sparse path uses the ELL
  gather (ops/sparse.ell_matvec).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dmlc_tpu.models._loop import TrainLoopMixin
from dmlc_tpu.ops.sparse import EllBatch, ell_matvec
from dmlc_tpu.utils.check import check


class LinearParams(NamedTuple):
    weight: jax.Array  # [W]; dense/ell: last slot is the padding sink,
    #                    pinned to 0 — for bcoo it is a real feature weight
    bias: jax.Array    # scalar


def init_params(weight_dim: int, num_class: int = 1,
                dtype=jnp.float32) -> LinearParams:
    if num_class > 1:
        # multinomial: weight [W, C], per-class bias (softmax objective)
        return LinearParams(
            weight=jnp.zeros((weight_dim, num_class), dtype=dtype),
            bias=jnp.zeros(num_class, dtype=dtype),
        )
    return LinearParams(
        weight=jnp.zeros(weight_dim, dtype=dtype),
        bias=jnp.zeros((), dtype=dtype),
    )


def _margin_dense(params: LinearParams, x: jax.Array) -> jax.Array:
    # x is [B, W] (features padded to the weight width): full-width matmul,
    # no slicing — keeps the model-axis sharding of both operands aligned
    return x @ params.weight + params.bias


def _margin_ell(params: LinearParams, batch: EllBatch,
                use_auto: bool = True) -> jax.Array:
    if use_auto:
        # single-device / replicated-weight case (the default): route
        # through the auto entry, which picks the pallas one-hot kernel
        # in its measured win band — lane-aligned D in [512, 4096] on a
        # TPU backend (SPARSE_TPU_r05.json; ell_matvec_auto's docstring
        # carries the A/B numbers and the one known in-band anomaly) —
        # and the XLA gather everywhere else. Sharded weights stay on
        # ell_matvec — pallas_call is not shard_map-aware here.
        from dmlc_tpu.ops.pallas_sparse import ell_matvec_auto

        return ell_matvec_auto(params.weight, batch) + params.bias
    return ell_matvec(params.weight, batch) + params.bias


def _loss_from_margin(margin, label, weight, objective: str, l2: float, params):
    if objective == "logistic":
        per = optax.sigmoid_binary_cross_entropy(margin, label)
    elif objective == "squared":
        per = 0.5 * (margin - label) ** 2
    elif objective == "softmax":
        # margin is [B, C]; labels are class ids carried in the float label
        per = optax.softmax_cross_entropy_with_integer_labels(
            margin, label.astype(jnp.int32))
    else:
        raise ValueError(f"unknown objective {objective!r}")
    den = jnp.maximum(weight.sum(), 1.0)
    loss = (per * weight).sum() / den
    if l2 > 0.0:
        # the padding sink is pinned to 0, so regularizing the full vector
        # adds nothing for it
        loss = loss + 0.5 * l2 * jnp.sum(params.weight ** 2)
    return loss


class LinearLearner(TrainLoopMixin):
    """Logistic / least-squares / multinomial-softmax learner with optax
    updates (the learner family the reference's Row::SDot was built for,
    data.h:146-161, widened to multi-class).

    ``layout`` must match the DeviceIter layout ('dense', 'ell', or
    'bcoo' — the last single-device, margin via bcoo_dot_general);
    ``objective='softmax'`` needs ``num_class >= 2`` and works on any
    layout — the ELL path gathers rows of the [W, C] table (labels are
    integer class ids carried in the float label column).
    """

    def __init__(
        self,
        num_col: int,
        objective: str = "logistic",
        layout: str = "dense",
        optimizer: Optional[optax.GradientTransformation] = None,
        learning_rate: float = 0.1,
        l2: float = 0.0,
        mesh=None,
        data_axis: str = "data",
        model_axis: Optional[str] = None,
        num_class: int = 1,
    ):
        check(layout in ("dense", "ell", "bcoo"),
              "LinearLearner: layout must be dense|ell|bcoo")
        check(layout != "bcoo" or mesh is None,
              "layout='bcoo' is single-device (matches DeviceIter bcoo)")
        check((objective == "softmax") == (num_class > 1),
              "softmax objective iff num_class > 1")
        self.num_class = num_class
        self.num_col = num_col
        self.objective = objective
        self.layout = layout
        self.l2 = l2
        self.mesh = mesh
        self.data_axis = data_axis
        self.model_axis = model_axis
        # weight length: num_col features + 1 padding sink, rounded up so a
        # model-axis sharding divides it evenly. BCOO batches carry real
        # coordinates only (pad entries are out-of-bounds and masked), so
        # no sink slot is needed there.
        if layout == "bcoo":
            self.weight_dim = num_col
        else:
            model_size = 1
            if mesh is not None and model_axis is not None:
                model_size = mesh.shape[model_axis]
            self.weight_dim = -(-(num_col + 1) // model_size) * model_size
        self.opt = optimizer or optax.sgd(learning_rate)
        self.params = init_params(self.weight_dim, num_class)
        self.opt_state = self.opt.init(self.params)
        self._step = self._build_step()
        self._predict = self._build_predict()
        self._accuracy = self._build_accuracy()

    def batch_shardings(self):
        """Batch placement for a DeviceIter feeding this learner (or None)."""
        return self._shardings()[1]

    def device_num_col(self) -> int:
        """The ``num_col`` a DeviceIter must use to feed this learner.

        dense: batches are [B, weight_dim] (zero columns beyond the data's
        features); ell: pad index = weight_dim - 1, the pinned-zero sink;
        bcoo: the true column count (OOB pad coords are masked).
        """
        if self.layout == "ell":
            return self.weight_dim - 1
        return self.weight_dim

    # ---------------- jitted functions ----------------

    def _margin(self, params: LinearParams, batch):
        if self.layout == "ell":
            return (_margin_ell(params, batch, use_auto=self.mesh is None),
                    batch.label, batch.weight)
        # dense and bcoo share one margin: _margin_dense's `x @ weight` is
        # bcoo_dot_general when x is a BCOO batch (AD-complete wrt weights)
        x, label, weight = batch
        return _margin_dense(params, x), label, weight

    def _pred_from_margin(self, margin: jax.Array) -> jax.Array:
        if self.num_class > 1:
            return jnp.argmax(margin, axis=-1).astype(jnp.float32)
        return (margin > 0).astype(jnp.float32)

    def loss_fn(self, params: LinearParams, batch) -> jax.Array:
        margin, label, weight = self._margin(params, batch)
        return _loss_from_margin(margin, label, weight, self.objective, self.l2, params)

    def _shardings(self):
        """(params, batch) shardings for pjit when a mesh is present."""
        if self.mesh is None:
            return None, None
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self.mesh
        if self.model_axis is not None:
            # feature-sharded weights (the TP analog for very wide models)
            if self.num_class > 1:
                p_w = NamedSharding(mesh, P(self.model_axis, None))
            else:
                p_w = NamedSharding(mesh, P(self.model_axis))
        else:
            p_w = NamedSharding(mesh, P())
        p_scalar = NamedSharding(mesh, P())
        params_sh = LinearParams(weight=p_w, bias=p_scalar)
        if self.layout == "ell":
            row = NamedSharding(mesh, P(self.data_axis, None))
            vec = NamedSharding(mesh, P(self.data_axis))
            batch_sh = EllBatch(indices=row, values=row, label=vec, weight=vec)
        else:
            if self.model_axis is not None:
                x_sh = NamedSharding(mesh, P(self.data_axis, self.model_axis))
            else:
                x_sh = NamedSharding(mesh, P(self.data_axis, None))
            vec = NamedSharding(mesh, P(self.data_axis))
            batch_sh = (x_sh, vec, vec)
        return params_sh, batch_sh

    def _build_step(self):
        def step(params, opt_state, batch):
            loss, grads = jax.value_and_grad(self.loss_fn)(params, batch)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            if self.layout != "bcoo":
                # keep the padding sink at zero so ELL gathers of pad slots
                # are inert (bcoo has no sink: its last weight is real)
                params = params._replace(
                    weight=params.weight.at[-1].set(0.0))
            return params, opt_state, loss

        params_sh, batch_sh = self._shardings()
        return self._jit_step(step, params_sh=params_sh, batch_sh=batch_sh)

    def _build_predict(self):
        def predict(params, batch):
            if self.layout == "ell":
                return _margin_ell(params, batch, use_auto=self.mesh is None)
            return _margin_dense(params, batch[0])  # dense or bcoo operand

        return jax.jit(predict)

    # ---------------- public API ----------------

    def predict(self, batch) -> jax.Array:
        return self._predict(self.params, batch)

