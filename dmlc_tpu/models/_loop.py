"""Shared training-loop surface for the learners.

One implementation of step/fit_epoch/fit/accuracy — including the SPMD
step-count contract (``steps_per_epoch`` / ``max_steps``): every process in
a pod must execute the same number of collective steps per epoch or the
pod deadlocks; agree on the cap with :func:`dmlc_tpu.parallel.sync_min`.

Learners provide ``self._step(params, opt_state, batch)``,
``self._margin(params, batch) -> (margin, label, weight)`` and
``self._pred_from_margin(margin)``; :meth:`TrainLoopMixin._build_accuracy`
derives the jitted on-device metric from those (replicated scalar outputs,
so results are addressable on every process). ``self.params`` /
``self.opt_state`` / ``self.mesh`` attributes are assumed.
"""

from __future__ import annotations

from typing import Tuple

from dmlc_tpu.utils.timer import get_time


class TrainLoopMixin:
    def _build_accuracy(self):
        """Jitted (correct_weighted, total_weight) over one batch; the
        reduction stays ON DEVICE so mesh-global batches spanning processes
        work (their per-row values are not host-addressable)."""
        import jax

        def acc_fn(params, batch):
            margin, label, weight = self._margin(params, batch)
            pred = self._pred_from_margin(margin)
            return ((pred == label) * weight).sum(), weight.sum()

        if self.mesh is None:
            return jax.jit(acc_fn)
        from jax.sharding import NamedSharding, PartitionSpec as P

        rep = NamedSharding(self.mesh, P())
        return jax.jit(acc_fn, out_shardings=(rep, rep))

    def step(self, batch) -> float:
        self.params, self.opt_state, loss = self._step(
            self.params, self.opt_state, batch)
        return loss

    def fit_epoch(self, device_iter, max_steps=None) -> Tuple[float, int]:
        """One pass over a DeviceIter; returns (mean loss, batches).
        ``max_steps`` is the SPMD step-count cap (module docstring)."""
        total, n = 0.0, 0
        for batch in device_iter:
            loss = self.step(batch)
            total += float(loss)
            n += 1
            if max_steps is not None and n >= max_steps:
                break
        device_iter.reset()
        return (total / max(n, 1)), n

    def fit(self, device_iter, epochs: int = 1, log_fn=None,
            steps_per_epoch=None):
        for epoch in range(epochs):
            t0 = get_time()
            loss, nb = self.fit_epoch(device_iter, max_steps=steps_per_epoch)
            if log_fn:
                log_fn(epoch, loss, nb, get_time() - t0)
        return self

    def accuracy(self, device_iter, max_steps=None) -> float:
        """Weighted accuracy over one pass, reduced ON DEVICE (replicated
        scalars — pod-safe); ``max_steps`` as in :meth:`fit_epoch`."""
        correct, total = 0.0, 0.0
        n = 0
        for batch in device_iter:
            c, t = self._accuracy(self.params, batch)
            correct += float(c)
            total += float(t)
            n += 1
            if max_steps is not None and n >= max_steps:
                break
        device_iter.reset()
        return correct / max(total, 1.0)
