"""Shared training-loop surface for the learners.

One implementation of step/fit_epoch/fit/accuracy — including the SPMD
step-count contract (``steps_per_epoch`` / ``max_steps``): every process in
a pod must execute the same number of collective steps per epoch or the
pod deadlocks; agree on the cap with :func:`dmlc_tpu.parallel.sync_min`.

Learners provide ``self._step(params, opt_state, batch)``,
``self._margin(params, batch) -> (margin, label, weight)`` and
``self._pred_from_margin(margin)``; :meth:`TrainLoopMixin._build_accuracy`
derives the jitted on-device metric from those (replicated scalar outputs,
so results are addressable on every process). ``self.params`` /
``self.opt_state`` / ``self.mesh`` attributes are assumed.

Two loop-wide contracts live here so every learner inherits them:

* **Donated step buffers.** :meth:`TrainLoopMixin._jit_step` compiles the
  update with ``donate_argnums=(0, 1)`` — the ``(params, opt_state)``
  input buffers are handed back to XLA so the outputs reuse their HBM
  instead of doubling peak parameter memory. The compiled callable is
  stamped with ``_donate_argnums`` so tests can pin the contract
  structurally (the CPU backend accepts but ignores donation, so
  ``is_deleted``-style checks would not hold under tier-1).

* **No per-step host sync.** The loop never forces a device→host transfer
  inside the epoch: losses and metric partials accumulate as device
  scalars and cross to the host once per epoch through
  :func:`host_scalar`, the loop's single sanctioned sync point. (The only
  other device→host traffic during an epoch is DeviceIter's sampled
  transfer sideband, which the loop does not control.) Keeping the epoch
  free of blocking syncs is what lets dispatch run ahead of the ingest
  pipeline and hide input latency.
"""

from __future__ import annotations

from typing import Tuple

from dmlc_tpu.utils.timer import get_time


def host_scalar(x) -> float:
    """Bring one device scalar to the host — the loop's sanctioned sync.

    Every device→host conversion the training loop performs funnels
    through here (once per epoch for the loss, twice per accuracy pass),
    so a regression test can monkeypatch this single name and count
    blocking syncs instead of auditing call sites.
    """
    return float(x)


class TrainLoopMixin:
    def _jit_step(self, step_fn, params_sh=None, batch_sh=None,
                  opt_sh=None, loss_sh=None):
        """Compile ``step_fn(params, opt_state, batch) -> (params,
        opt_state, loss)`` under the loop's donation contract.

        ``donate_argnums=(0, 1)`` donates the ``(params, opt_state)``
        input buffers: XLA aliases them to the outputs, making the step an
        in-place update rather than a 2x-peak-memory copy. When
        ``params_sh`` is given the mesh placement is pinned explicitly
        (``opt_sh``/``loss_sh`` pass through, ``None`` meaning "infer").
        """
        import jax

        if params_sh is None:
            fn = jax.jit(step_fn, donate_argnums=(0, 1))
        else:
            fn = jax.jit(step_fn, donate_argnums=(0, 1),
                         in_shardings=(params_sh, opt_sh, batch_sh),
                         out_shardings=(params_sh, opt_sh, loss_sh))
        fn._donate_argnums = (0, 1)
        return fn

    def _build_accuracy(self):
        """Jitted (correct_weighted, total_weight) over one batch; the
        reduction stays ON DEVICE so mesh-global batches spanning processes
        work (their per-row values are not host-addressable)."""
        import jax

        def acc_fn(params, batch):
            margin, label, weight = self._margin(params, batch)
            pred = self._pred_from_margin(margin)
            return ((pred == label) * weight).sum(), weight.sum()

        if self.mesh is None:
            return jax.jit(acc_fn)
        from jax.sharding import NamedSharding, PartitionSpec as P

        rep = NamedSharding(self.mesh, P())
        return jax.jit(acc_fn, out_shardings=(rep, rep))

    def step(self, batch):
        """One jitted update. Returns the loss as a DEVICE scalar — no
        host sync here; convert with :func:`host_scalar` when a float is
        actually needed."""
        self.params, self.opt_state, loss = self._step(
            self.params, self.opt_state, batch)
        return loss

    def fit_epoch(self, device_iter, max_steps=None) -> Tuple[float, int]:
        """One pass over a DeviceIter; returns (mean loss, batches).
        ``max_steps`` is the SPMD step-count cap (module docstring).

        The per-step losses accumulate on device; the single
        :func:`host_scalar` call at the end of the pass is the epoch's
        only blocking device→host sync.
        """
        total, n = None, 0
        for batch in device_iter:
            loss = self.step(batch)
            total = loss if total is None else total + loss
            n += 1
            if max_steps is not None and n >= max_steps:
                break
        device_iter.reset()
        if n == 0:
            return 0.0, 0
        return host_scalar(total) / n, n

    def fit(self, device_iter, epochs: int = 1, log_fn=None,
            steps_per_epoch=None):
        for epoch in range(epochs):
            t0 = get_time()
            loss, nb = self.fit_epoch(device_iter, max_steps=steps_per_epoch)
            if log_fn:
                log_fn(epoch, loss, nb, get_time() - t0)
        return self

    def accuracy(self, device_iter, max_steps=None) -> float:
        """Weighted accuracy over one pass, reduced ON DEVICE (replicated
        scalars — pod-safe); ``max_steps`` as in :meth:`fit_epoch`. The
        partials stay on device; the two :func:`host_scalar` calls at the
        end are the pass's only syncs."""
        correct, total = None, None
        n = 0
        for batch in device_iter:
            c, t = self._accuracy(self.params, batch)
            correct = c if correct is None else correct + c
            total = t if total is None else total + t
            n += 1
            if max_steps is not None and n >= max_steps:
                break
        device_iter.reset()
        if n == 0:
            return 0.0
        return host_scalar(correct) / max(host_scalar(total), 1.0)
