"""Factorization machine learner over the device pipeline.

The libfm text format the reference parses (src/data/libfm_parser.h) exists
to feed exactly this model family — second-order FMs (Rendle 2010) over
high-dimensional sparse features. This is the TPU-first formulation:

    margin(x) = w0 + <w, x> + 0.5 * sum_f [ (<V[:,f], x>)^2 - <V[:,f]^2, x^2> ]

- **dense path** (hashed/low-D data): two matmuls on the MXU —
  ``(x @ V)**2`` and ``(x**2) @ (V**2)`` — plus the linear term; everything
  fuses under one jit.
- **ELL path** (true high-D sparse, KDD-shaped): per-row gathers of the
  factor rows ``V[idx]`` (static [B, K, F] shapes; XLA vectorizes the
  gather+reduce), so the [D, F] factor table never materializes per batch.

Params are a pytree under ``jax.jit``; with a mesh, batches shard over the
``data`` axis and XLA inserts the gradient psum over ICI — identical SPMD
shape to :class:`dmlc_tpu.models.LinearLearner`, including the
``steps_per_epoch`` / ``max_steps`` collective step-count contract.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from dmlc_tpu.models._loop import TrainLoopMixin
from dmlc_tpu.ops.sparse import EllBatch
from dmlc_tpu.utils.check import check


class FMParams(NamedTuple):
    w0: jax.Array       # scalar bias
    w: jax.Array        # [W] linear weights; last slot = ELL padding sink
    v: jax.Array        # [W, F] factor rows; sink row pinned to 0


def _margin_dense(params: FMParams, x: jax.Array) -> jax.Array:
    linear = x @ params.w + params.w0
    xv = x @ params.v                       # [B, F] — MXU
    x2v2 = (x * x) @ (params.v * params.v)  # [B, F] — MXU
    return linear + 0.5 * jnp.sum(xv * xv - x2v2, axis=-1)


def _margin_bcoo(params: FMParams, mat) -> jax.Array:
    # sparse @ dense (bcoo_dot_general) for both contractions; the squared
    # operand is a second BCOO sharing the coords with squared values —
    # OOB pad coords stay masked in it too
    from jax.experimental import sparse as jsparse

    linear = mat @ params.w + params.w0
    xv = mat @ params.v                                     # [B, F]
    mat2 = jsparse.BCOO((mat.data * mat.data, mat.indices), shape=mat.shape)
    x2v2 = mat2 @ (params.v * params.v)                     # [B, F]
    return linear + 0.5 * jnp.sum(xv * xv - x2v2, axis=-1)


def _margin_ell(params: FMParams, batch: EllBatch) -> jax.Array:
    # gathers over the factor table; padding slots carry value 0 so they
    # contribute nothing to any sum
    w_g = jnp.take(params.w, batch.indices, axis=0)        # [B, K]
    v_g = jnp.take(params.v, batch.indices, axis=0)        # [B, K, F]
    val = batch.values                                     # [B, K]
    linear = jnp.sum(w_g * val, axis=-1) + params.w0
    s = jnp.einsum("bkf,bk->bf", v_g, val)                 # sum_k v_k x_k
    s2 = jnp.einsum("bkf,bk->bf", v_g * v_g, val * val)    # sum_k v_k^2 x_k^2
    return linear + 0.5 * jnp.sum(s * s - s2, axis=-1)


class FMLearner(TrainLoopMixin):
    """Second-order factorization machine (logistic or squared objective).

    ``layout`` matches the DeviceIter layout ('dense', 'ell', or 'bcoo' —
    the last single-device, both contractions via bcoo_dot_general); factors
    initialize to small gaussian noise (all-zero factors have zero gradient
    through the interaction term). With ``mesh``, batches shard over
    ``data_axis`` and the update psums over the pod.
    """

    def __init__(
        self,
        num_col: int,
        num_factors: int = 8,
        objective: str = "logistic",
        layout: str = "dense",
        optimizer: Optional[optax.GradientTransformation] = None,
        learning_rate: float = 0.05,
        init_scale: float = 0.01,
        l2: float = 0.0,
        seed: int = 0,
        mesh=None,
        data_axis: str = "data",
    ):
        check(layout in ("dense", "ell", "bcoo"),
              "FMLearner: layout must be dense|ell|bcoo")
        check(layout != "bcoo" or mesh is None,
              "layout='bcoo' is single-device (matches DeviceIter bcoo)")
        check(objective in ("logistic", "squared"),
              f"FMLearner: unknown objective {objective!r}")
        check(num_factors >= 1, "FMLearner: num_factors must be >= 1")
        self.num_col = num_col
        self.num_factors = num_factors
        self.objective = objective
        self.layout = layout
        self.l2 = l2
        self.mesh = mesh
        self.data_axis = data_axis
        # +1 = ELL/dense padding sink; BCOO pads with OOB coords instead,
        # so its last weight/factor row is real
        self.weight_dim = num_col if layout == "bcoo" else num_col + 1
        key = jax.random.PRNGKey(seed)
        v = init_scale * jax.random.normal(
            key, (self.weight_dim, num_factors), jnp.float32)
        if layout != "bcoo":
            v = v.at[-1].set(0.0)  # sink row inert
        self.params = FMParams(
            w0=jnp.zeros((), jnp.float32),
            w=jnp.zeros(self.weight_dim, jnp.float32),
            v=v,
        )
        self.opt = optimizer or optax.adam(learning_rate)
        self.opt_state = self.opt.init(self.params)
        self._step = self._build_step()
        self._accuracy = self._build_accuracy()
        self._predict = jax.jit(lambda params, batch: self._margin(params, batch)[0])

    def device_num_col(self) -> int:
        """The ``num_col`` a DeviceIter must use to feed this learner."""
        if self.layout == "ell":
            return self.weight_dim - 1
        return self.weight_dim

    def batch_shardings(self):
        return self._shardings()[1]

    # ---------------- jitted functions ----------------

    def _pred_from_margin(self, margin: jax.Array) -> jax.Array:
        return (margin > 0).astype(jnp.float32)

    def _margin(self, params: FMParams, batch):
        if self.layout == "ell":
            return _margin_ell(params, batch), batch.label, batch.weight
        x, label, weight = batch
        if self.layout == "bcoo":
            return _margin_bcoo(params, x), label, weight
        return _margin_dense(params, x), label, weight

    def loss_fn(self, params: FMParams, batch) -> jax.Array:
        margin, label, weight = self._margin(params, batch)
        if self.objective == "logistic":
            per = optax.sigmoid_binary_cross_entropy(margin, label)
        else:
            per = 0.5 * (margin - label) ** 2
        den = jnp.maximum(weight.sum(), 1.0)
        loss = (per * weight).sum() / den
        if self.l2 > 0.0:
            loss = loss + 0.5 * self.l2 * (
                jnp.sum(params.w ** 2) + jnp.sum(params.v ** 2))
        return loss

    def _shardings(self):
        if self.mesh is None:
            return None, None
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self.mesh
        rep = NamedSharding(mesh, P())
        params_sh = FMParams(w0=rep, w=rep, v=rep)
        vec = NamedSharding(mesh, P(self.data_axis))
        row = NamedSharding(mesh, P(self.data_axis, None))
        if self.layout == "ell":
            batch_sh = EllBatch(indices=row, values=row, label=vec, weight=vec)
        else:
            batch_sh = (row, vec, vec)
        return params_sh, batch_sh

    def _build_step(self):
        def step(params, opt_state, batch):
            loss, grads = jax.value_and_grad(self.loss_fn)(params, batch)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            if self.layout != "bcoo":
                # keep the padding sink inert (bcoo's last row is real)
                params = params._replace(
                    w=params.w.at[-1].set(0.0),
                    v=params.v.at[-1].set(0.0),
                )
            return params, opt_state, loss

        params_sh, batch_sh = self._shardings()
        if params_sh is None:
            return self._jit_step(step)
        from jax.sharding import NamedSharding, PartitionSpec as P

        rep = NamedSharding(self.mesh, P())
        opt_sh = jax.tree_util.tree_map(lambda _: rep, self.opt_state)
        return self._jit_step(step, params_sh=params_sh, batch_sh=batch_sh,
                              opt_sh=opt_sh, loss_sh=rep)

    def predict(self, batch) -> jax.Array:
        """Raw margin for a batch (apply sigmoid for probabilities)."""
        return self._predict(self.params, batch)
