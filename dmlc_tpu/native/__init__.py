"""ctypes bindings for the C++ native core, built on demand with g++.

No pybind11 in this image (see repo docs) — the C ABI in native/src/parse.cc
is loaded with ctypes and arrays are wrapped as numpy views that own the
malloc'd buffers via a finalizer (zero copies on the handoff).

Falls back cleanly: ``available()`` is False when the toolchain or build is
missing, and the Python parsers keep working.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import weakref
from typing import Optional

import numpy as np

from dmlc_tpu.utils.check import DMLCError, get_logger


class NeedsCsrError(DMLCError):
    """Input the dense scanner can't express (e.g. qid rows) — explicit
    signal (DenseResult.needs_csr) for callers to fall back to CSR, so no
    routing ever depends on error-message wording."""

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC_DIR = os.path.join(_REPO_ROOT, "native", "src")
# keep in sync with Makefile NATIVE_SRCS, native/CMakeLists.txt, and
# native/run_sanitizers.sh SRCS
_SRCS = [os.path.join(_SRC_DIR, f)
         for f in ("parse.cc", "reader.cc", "recordio.cc", "batch_parse.cc")]
_HDRS = [os.path.join(_SRC_DIR, f)
         for f in ("api.h", "strtonum.h", "parse_internal.h",
                   "buffer_pool.h")]
_BUILD_DIR = os.path.join(_REPO_ROOT, "native", "build")
_SO_PATH = os.path.join(_BUILD_DIR, "libdmlc_tpu_native.so")
_ABI_VERSION = 16

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


class _CsrBlockResult(ctypes.Structure):
    _fields_ = [
        ("n_rows", ctypes.c_int64),
        ("nnz", ctypes.c_int64),
        ("offset", ctypes.POINTER(ctypes.c_int64)),
        ("label", ctypes.POINTER(ctypes.c_float)),
        ("weight", ctypes.POINTER(ctypes.c_float)),
        ("qid", ctypes.POINTER(ctypes.c_int64)),
        ("index", ctypes.POINTER(ctypes.c_uint64)),
        ("field", ctypes.POINTER(ctypes.c_uint64)),
        ("value", ctypes.POINTER(ctypes.c_float)),
        ("error", ctypes.c_char_p),
    ]


class _DenseResult(ctypes.Structure):
    _fields_ = [
        ("n_rows", ctypes.c_int64),
        ("n_cols", ctypes.c_int64),
        ("x", ctypes.POINTER(ctypes.c_float)),
        ("label", ctypes.POINTER(ctypes.c_float)),
        ("weight", ctypes.POINTER(ctypes.c_float)),
        ("error", ctypes.c_char_p),
        ("needs_csr", ctypes.c_int32),
        ("x_bf16", ctypes.c_int32),
        ("packed_aux", ctypes.c_int32),
    ]


class _CsvResult(ctypes.Structure):
    _fields_ = [
        ("n_rows", ctypes.c_int64),
        ("n_cols", ctypes.c_int64),
        ("cells", ctypes.POINTER(ctypes.c_float)),
        ("error", ctypes.c_char_p),
    ]


class _CsvSplitResult(ctypes.Structure):
    _fields_ = [
        ("n_rows", ctypes.c_int64),
        ("n_feat_cols", ctypes.c_int64),
        ("values", ctypes.POINTER(ctypes.c_float)),
        ("label", ctypes.POINTER(ctypes.c_float)),
        ("weight", ctypes.POINTER(ctypes.c_float)),
        ("error", ctypes.c_char_p),
    ]


class _CooResult(ctypes.Structure):
    _fields_ = [
        ("n_rows", ctypes.c_int64),
        ("nnz", ctypes.c_int64),
        ("rows_padded", ctypes.c_int64),
        ("nnz_padded", ctypes.c_int64),
        ("coords", ctypes.POINTER(ctypes.c_int32)),
        ("values", ctypes.POINTER(ctypes.c_float)),
        ("label", ctypes.POINTER(ctypes.c_float)),
        ("weight", ctypes.POINTER(ctypes.c_float)),
        ("error", ctypes.c_char_p),
        ("values_elided", ctypes.c_int32),
        ("csr_wire", ctypes.c_int32),
        ("row_ptr", ctypes.POINTER(ctypes.c_int32)),
    ]


class _SegmentBlockResult(ctypes.Structure):
    _fields_ = [
        ("n_rows", ctypes.c_int64),
        ("nnz", ctypes.c_int64),
        ("num_col", ctypes.c_int64),
        ("buf", ctypes.POINTER(ctypes.c_char)),
        ("buf_len", ctypes.c_int64),
        ("seg_off", ctypes.c_int64 * 7),
        ("seg_len", ctypes.c_int64 * 7),
        ("crc32", ctypes.c_uint32),
        ("simd_level", ctypes.c_int32),
        ("error", ctypes.c_char_p),
    ]


class _RecordBatchResult(ctypes.Structure):
    _fields_ = [
        ("n_records", ctypes.c_int64),
        ("data_len", ctypes.c_int64),
        ("data", ctypes.POINTER(ctypes.c_char)),
        ("offsets", ctypes.POINTER(ctypes.c_int64)),
        ("error", ctypes.c_char_p),
    ]


def _build() -> bool:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    # no -march=native: the artifact may outlive the build host (shared FS,
    # copied checkouts) and ISA-specific code would SIGILL with no fallback
    cmd = [
        "g++", "-O3", "-std=c++17", "-fPIC", "-shared", "-pthread",
        "-D_FILE_OFFSET_BITS=64",
    ]
    san = os.environ.get("DMLC_TPU_SANITIZE", "")
    if san:
        # ASan/TSan toggle, mirroring the reference's DMLC_USE_SANITIZER
        # CMake option (cmake/Sanitizer.cmake)
        cmd += [f"-fsanitize={san}", "-g", "-fno-omit-frame-pointer"]
    cmd += ["-o", _SO_PATH] + _SRCS
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    except (OSError, subprocess.TimeoutExpired) as exc:
        get_logger().warning("native build failed to run: %s", exc)
        return False
    if proc.returncode != 0:
        get_logger().warning("native build failed:\n%s", proc.stderr[-2000:])
        return False
    return True


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        if os.environ.get("DMLC_TPU_NO_NATIVE", "0") not in ("", "0"):
            _build_failed = True
            return None
        so_mtime = os.path.getmtime(_SO_PATH) if os.path.exists(_SO_PATH) else -1
        need_build = so_mtime < 0 or any(
            os.path.exists(src) and os.path.getmtime(src) > so_mtime
            for src in _SRCS + _HDRS
        )
        if need_build and not _build():
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError as exc:
            # stale/corrupt artifact: rebuild once before giving up
            get_logger().warning("native load failed (%s); rebuilding", exc)
            try:
                os.unlink(_SO_PATH)
            except OSError:
                pass
            if not _build():
                _build_failed = True
                return None
            try:
                lib = ctypes.CDLL(_SO_PATH)
            except OSError as exc2:
                get_logger().warning("native load failed after rebuild: %s", exc2)
                _build_failed = True
                return None
        # version-check BEFORE declaring the full symbol table: a stale .so
        # (e.g. a cached build dir with fresh mtimes) would otherwise raise
        # AttributeError on symbols this ABI added, bypassing the rebuild
        if not _abi_ok(lib):
            get_logger().warning("native ABI mismatch; rebuilding")
            try:
                os.unlink(_SO_PATH)
                if not _build():
                    _build_failed = True
                    return None
                lib = ctypes.CDLL(_SO_PATH)
                if not _abi_ok(lib):
                    get_logger().warning("native ABI still mismatched after rebuild")
                    _build_failed = True
                    return None
            except OSError as exc:
                get_logger().warning("native ABI rebuild failed: %s", exc)
                _build_failed = True
                return None
        _declare(lib)
        _lib = lib
        return _lib


def _abi_ok(lib: ctypes.CDLL) -> bool:
    """True when the .so exports the expected ABI version. Tolerates
    binaries so old they predate the version symbol."""
    try:
        fn = lib.dmlc_native_abi_version
    except AttributeError:
        return False
    fn.restype = ctypes.c_int
    return fn() == _ABI_VERSION


def _declare(lib: ctypes.CDLL) -> None:
    lib.dmlc_parse_libsvm.restype = ctypes.POINTER(_CsrBlockResult)
    lib.dmlc_parse_libsvm.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, ctypes.c_int]
    lib.dmlc_parse_libfm.restype = ctypes.POINTER(_CsrBlockResult)
    lib.dmlc_parse_libfm.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, ctypes.c_int]
    lib.dmlc_parse_csv.restype = ctypes.POINTER(_CsvResult)
    lib.dmlc_parse_csv.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, ctypes.c_char]
    lib.dmlc_parse_libsvm_dense.restype = ctypes.POINTER(_DenseResult)
    lib.dmlc_parse_libsvm_dense.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, ctypes.c_int64,
        ctypes.c_int]
    lib.dmlc_free_dense.argtypes = [ctypes.c_void_p]
    # void* so finalizers never depend on ctypes class identity (which
    # changes across importlib.reload) — they may fire at interpreter exit
    lib.dmlc_free_block.argtypes = [ctypes.c_void_p]
    lib.dmlc_free_csv.argtypes = [ctypes.c_void_p]
    lib.dmlc_parse_csv_split.restype = ctypes.POINTER(_CsvSplitResult)
    lib.dmlc_parse_csv_split.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, ctypes.c_char,
        ctypes.c_int32, ctypes.c_int32]
    lib.dmlc_free_csv_split.argtypes = [ctypes.c_void_p]
    lib.dmlc_native_abi_version.restype = ctypes.c_int
    lib.dmlc_parse_batch.restype = ctypes.POINTER(_SegmentBlockResult)
    lib.dmlc_parse_batch.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_char, ctypes.c_int32, ctypes.c_int32]
    lib.dmlc_free_segblock.argtypes = [ctypes.c_void_p]
    lib.dmlc_simd_level.restype = ctypes.c_int
    lib.dmlc_crc32.restype = ctypes.c_uint32
    lib.dmlc_crc32.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.dmlc_recordio_extract.restype = ctypes.POINTER(_RecordBatchResult)
    lib.dmlc_recordio_extract.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.dmlc_free_records.argtypes = [ctypes.c_void_p]
    lib.dmlc_parse_coo.restype = ctypes.POINTER(_CooResult)
    lib.dmlc_parse_coo.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int32, ctypes.c_int32]
    lib.dmlc_free_coo.argtypes = [ctypes.c_void_p]
    lib.dmlc_reader_create.restype = ctypes.c_void_p
    lib.dmlc_reader_create.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int32, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
        ctypes.c_int64, ctypes.c_int32, ctypes.c_char, ctypes.c_int32,
        ctypes.c_int64, ctypes.c_int32, ctypes.c_int64, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32]
    lib.dmlc_reader_next.restype = ctypes.c_void_p
    lib.dmlc_reader_next.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32)]
    lib.dmlc_reader_before_first.argtypes = [ctypes.c_void_p]
    lib.dmlc_reader_bytes_read.restype = ctypes.c_int64
    lib.dmlc_reader_bytes_read.argtypes = [ctypes.c_void_p]
    lib.dmlc_reader_error.restype = ctypes.c_char_p
    lib.dmlc_reader_error.argtypes = [ctypes.c_void_p]
    lib.dmlc_reader_destroy.argtypes = [ctypes.c_void_p]
    lib.dmlc_feeder_create.restype = ctypes.c_void_p
    lib.dmlc_feeder_create.argtypes = [
        ctypes.c_int32, ctypes.c_int64, ctypes.c_int32, ctypes.c_char,
        ctypes.c_int32, ctypes.c_int64, ctypes.c_int32, ctypes.c_int64,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32]
    lib.dmlc_feeder_push.restype = ctypes.c_int32
    lib.dmlc_feeder_push.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
    lib.dmlc_feeder_finish.argtypes = [ctypes.c_void_p]
    lib.dmlc_feeder_abort.argtypes = [ctypes.c_void_p]
    lib.dmlc_feeder_fail.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.dmlc_feeder_next.restype = ctypes.c_void_p
    lib.dmlc_feeder_next.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32)]
    lib.dmlc_feeder_before_first.argtypes = [ctypes.c_void_p]
    lib.dmlc_feeder_bytes_read.restype = ctypes.c_int64
    lib.dmlc_feeder_bytes_read.argtypes = [ctypes.c_void_p]
    lib.dmlc_feeder_error.restype = ctypes.c_char_p
    lib.dmlc_feeder_error.argtypes = [ctypes.c_void_p]
    lib.dmlc_feeder_destroy.argtypes = [ctypes.c_void_p]
    lib.dmlc_indexed_reader_create.restype = ctypes.c_void_p
    lib.dmlc_indexed_reader_create.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int32, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
        ctypes.c_uint64, ctypes.c_int32]
    lib.dmlc_indexed_reader_next.restype = ctypes.c_void_p
    lib.dmlc_indexed_reader_next.argtypes = [ctypes.c_void_p]
    lib.dmlc_indexed_reader_before_first.argtypes = [ctypes.c_void_p]
    lib.dmlc_indexed_reader_skip.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64]
    lib.dmlc_indexed_reader_bytes_read.restype = ctypes.c_int64
    lib.dmlc_indexed_reader_bytes_read.argtypes = [ctypes.c_void_p]
    lib.dmlc_indexed_reader_error.restype = ctypes.c_char_p
    lib.dmlc_indexed_reader_error.argtypes = [ctypes.c_void_p]
    lib.dmlc_indexed_reader_destroy.argtypes = [ctypes.c_void_p]


def available() -> bool:
    return _load() is not None


def default_nthread() -> int:
    """min(user, cores/2) in the spirit of text_parser.h:33-34."""
    env = os.environ.get("DMLC_TPU_PARSE_THREADS")
    if env:
        return max(1, int(env))
    return max(2, (os.cpu_count() or 2) // 2)


class _HeldBuffer:
    """Array-interface shim binding a raw pointer to its _Owner.

    ``np.asarray`` on this object yields a zero-copy view whose ``base`` IS
    this shim — so the owner (and thus the malloc'd buffer) stays alive for
    as long as ANY derived view exists, including views JAX is still
    transferring from. No consumer bookkeeping required.
    """

    __slots__ = ("owner", "__array_interface__")

    def __init__(self, addr: int, nbytes: int, owner):
        self.owner = owner
        self.__array_interface__ = {
            "data": (addr, False),
            "shape": (nbytes,),
            "typestr": "|u1",
            "version": 3,
        }


def _view(ptr, n, dtype, owner):
    """Zero-copy numpy view over a malloc'd buffer; the view's base chain
    pins ``owner`` so the buffer cannot be freed while any view lives."""
    if not ptr or n == 0:
        return None
    dtype = np.dtype(dtype)
    addr = ctypes.cast(ptr, ctypes.c_void_p).value
    raw = np.asarray(_HeldBuffer(addr, n * dtype.itemsize, owner))
    return raw.view(dtype)


class _Owner:
    """Frees the C result when garbage collected."""

    __slots__ = ("__weakref__",)

    def __init__(self, lib, res, free_fn):
        weakref.finalize(self, free_fn, lib, ctypes.cast(res, ctypes.c_void_p).value)


def _free_block(lib, addr):
    lib.dmlc_free_block(addr)


def _free_csv(lib, addr):
    lib.dmlc_free_csv(addr)


def _free_csv_split(lib, addr):
    lib.dmlc_free_csv_split(addr)


def _chunk_buf(chunk):
    """``bytes | memoryview`` -> (c_char_p-compatible arg, length, keepalive).

    A memoryview (e.g. an mmap slice from the zero-copy chunk reader)
    passes its buffer ADDRESS straight through — no bytes() copy, no GIL
    held for a memcpy. Safe because every native scanner is strictly
    ``[data, data + len)`` bounded and copies what it keeps (the result
    arrays are its own mallocs). ``keepalive`` must stay referenced until
    the call returns.
    """
    if isinstance(chunk, bytes):
        return chunk, len(chunk), chunk
    if isinstance(chunk, bytearray):
        # c_char_p argtypes reject bytearray: materialize once
        data = bytes(chunk)
        return data, len(data), data
    view = memoryview(chunk)
    if view.nbytes == 0 or not view.c_contiguous:
        data = bytes(view)
        return data, len(data), data
    arr = np.frombuffer(view, np.uint8)
    return ctypes.c_char_p(arr.ctypes.data), arr.nbytes, (view, arr)


def parse_libsvm(chunk, nthread: int = 0, indexing_mode: int = 0):
    """Parse a libsvm chunk (bytes or memoryview) natively; returns dict
    of numpy arrays or None."""
    lib = _load()
    if lib is None:
        return None
    buf, n, keep = _chunk_buf(chunk)
    res = lib.dmlc_parse_libsvm(
        buf, n, nthread or default_nthread(), indexing_mode)
    del keep
    return _wrap_block(lib, res)


def parse_libfm(chunk, nthread: int = 0, indexing_mode: int = 0):
    lib = _load()
    if lib is None:
        return None
    buf, n, keep = _chunk_buf(chunk)
    res = lib.dmlc_parse_libfm(
        buf, n, nthread or default_nthread(), indexing_mode)
    del keep
    return _wrap_block(lib, res)


def _wrap_block(lib, res):
    r = res.contents
    if r.error:
        msg = r.error.decode()
        lib.dmlc_free_block(res)
        raise DMLCError(msg)
    owner = _Owner(lib, res, _free_block)
    n, nnz = r.n_rows, r.nnz
    out = {
        "offset": _view(r.offset, n + 1, np.int64, owner),
        "label": _view(r.label, n, np.float32, owner),
        "weight": _view(r.weight, n, np.float32, owner),
        "qid": _view(r.qid, n, np.int64, owner),
        "index": _view(r.index, nnz, np.uint64, owner),
        "field": _view(r.field, nnz, np.uint64, owner),
        "value": _view(r.value, nnz, np.float32, owner),
        "_owner": owner,
    }
    if n == 0:
        out["offset"] = np.zeros(1, np.int64)
        out["label"] = np.empty(0, np.float32)
    if out["index"] is None:
        out["index"] = np.empty(0, np.uint64)
    return out


def _free_dense(lib, addr):
    lib.dmlc_free_dense(addr)


def parse_libsvm_dense(chunk, num_col: int, nthread: int = 0,
                       indexing_mode: int = -1):
    """Parse libsvm straight to the dense device layout.

    Returns (x [n, num_col] float32, label, weight-or-None, owner) or None
    when native is unavailable. Raises DMLCError for inputs the dense scanner
    does not support (e.g. qid rows) — callers fall back to the CSR path.
    """
    lib = _load()
    if lib is None:
        return None
    buf, n, keep = _chunk_buf(chunk)
    res = lib.dmlc_parse_libsvm_dense(
        buf, n, nthread or default_nthread(), num_col, indexing_mode)
    del keep
    return _wrap_dense(lib, res, num_col)


def _wrap_dense(lib, res, num_col: int):
    r = res.contents
    if r.error:
        msg = r.error.decode()
        needs_csr = bool(r.needs_csr)
        lib.dmlc_free_dense(res)
        raise NeedsCsrError(msg) if needs_csr else DMLCError(msg)
    owner = _Owner(lib, res, _free_dense)
    n = r.n_rows
    x_dtype = bf16_dtype() if r.x_bf16 else np.float32
    if n == 0:
        return (np.zeros((0, num_col), x_dtype),
                np.empty(0, np.float32), None, owner, False)
    if r.packed_aux:
        # packed layout: x is [n, num_col + 2] with label/weight as the
        # trailing columns (ONE device_put per batch downstream); the
        # label/weight views alias those columns for host-side consumers
        xp = _view(r.x, n * (num_col + 2), x_dtype, owner).reshape(
            n, num_col + 2)
        return xp, xp[:, num_col], xp[:, num_col + 1], owner, True
    x = _view(r.x, n * num_col, x_dtype, owner).reshape(n, num_col)
    label = _view(r.label, n, np.float32, owner)
    weight = _view(r.weight, n, np.float32, owner)
    return x, label, weight, owner, False


def bf16_dtype():
    """bfloat16 as a numpy dtype (ml_dtypes ships with jax) — the ONE
    lookup shared by the native view wrapper and the Python fallbacks."""
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


def parse_csv(chunk, delimiter: str = ",", nthread: int = 0):
    """Parse a csv chunk (bytes or memoryview) natively -> (cells [n, ncol]
    float32, owner) or None.

    The caller must keep ``owner`` referenced while using ``cells``.
    """
    lib = _load()
    if lib is None:
        return None
    buf, n, keep = _chunk_buf(chunk)
    res = lib.dmlc_parse_csv(
        buf, n, nthread or default_nthread(),
        delimiter.encode()[0] if delimiter else b","[0])
    del keep
    return _wrap_csv(lib, res)


def _wrap_csv(lib, res):
    r = res.contents
    if r.error:
        msg = r.error.decode()
        lib.dmlc_free_csv(res)
        raise DMLCError(msg)
    owner = _Owner(lib, res, _free_csv)
    n, c = r.n_rows, r.n_cols
    if n == 0 or c == 0:
        return np.zeros((0, 0), np.float32), owner
    cells = _view(r.cells, n * c, np.float32, owner)
    return cells.reshape(n, c), owner


def _wrap_csv_split(lib, res):
    """(values[n,k], label|None, weight|None, n_rows, owner) — all views
    zero-copy over the C buffers; the RowBlock skeleton (index/offset) is
    format-implied and supplied by the caller's cache."""
    r = res.contents
    if r.error:
        msg = r.error.decode()
        lib.dmlc_free_csv_split(res)
        raise DMLCError(msg)
    owner = _Owner(lib, res, _free_csv_split)
    n, k = r.n_rows, r.n_feat_cols
    if n == 0:
        return np.zeros((0, 0), np.float32), None, None, 0, owner
    values = (_view(r.values, n * k, np.float32, owner).reshape(n, k)
              if k else np.zeros((n, 0), np.float32))
    label = _view(r.label, n, np.float32, owner)
    weight = _view(r.weight, n, np.float32, owner)
    return values, label, weight, int(n), owner


# canonical segment slot order — io/block_cache.py SEGMENT_NAMES and the
# native DMLC_SEG_* constants, kept in one tuple with the on-disk dtypes
_BATCH_SEGMENTS = (
    ("offset", "<i8"), ("label", "<f4"), ("weight", "<f4"), ("qid", "<i8"),
    ("field", "<u8"), ("index", "<u8"), ("value", "<f4"),
)

# dmlc_parse_batch fmt codes (shared with the stream reader's FMT_*)
BATCH_FMT = {"libsvm": 0, "csv": 2, "libfm": 3}


def _free_segblock(lib, addr):
    lib.dmlc_free_segblock(addr)


def simd_level() -> int:
    """The batch scanner's runtime-dispatched scan ISA on this host:
    0 scalar, 1 SSE2, 2 AVX2, 3 NEON. -1 when native is unavailable."""
    lib = _load()
    if lib is None:
        return -1
    return int(lib.dmlc_simd_level())


def crc32(data) -> int:
    """zlib-compatible crc32 via the native slice-by-8 kernel (tests pin
    it against Python zlib.crc32). None when native is unavailable."""
    lib = _load()
    if lib is None:
        return None
    data = bytes(data) if not isinstance(data, bytes) else data
    return int(lib.dmlc_crc32(data, len(data)))


def parse_batch(chunk, fmt: str, nthread: int = 0, indexing_mode: int = 0,
                delimiter: str = ",", label_col: int = -1,
                weight_col: int = -1):
    """Parse a whole text chunk straight into a block-cache v1 segment
    span (the chunk-batch cold path, native/src/batch_parse.cc).

    Returns None when native is unavailable, else a dict:

    - ``segments``: {name: zero-copy numpy view} of the present arrays —
      exactly what ``RowBlock.from_segments`` consumes;
    - ``data``: one uint8 view over the whole span — the byte-identical
      payload a ``DMLCBC01`` block / service BLOCK frame stores;
    - ``arrays``: {name: [dtype_str, span_offset, nbytes]} — the footer/
      meta schema of the span (offsets relative to ``data``);
    - ``rows`` / ``nnz`` / ``num_col`` / ``crc`` (zlib-compatible crc32
      of ``data``) / ``simd_level`` / ``_owner`` (keep referenced while
      any view is alive).

    Raises DMLCError on malformed input (message parity with the other
    native scanners).
    """
    lib = _load()
    if lib is None:
        return None
    code = BATCH_FMT.get(fmt)
    if code is None:
        raise DMLCError(f"parse_batch: unsupported format {fmt!r}")
    buf, n, keep = _chunk_buf(chunk)
    res = lib.dmlc_parse_batch(
        buf, n, nthread or default_nthread(), code, indexing_mode,
        delimiter.encode()[0] if delimiter else b","[0],
        label_col, weight_col)
    del keep
    if not res:
        raise DMLCError("batch parse: out of memory")
    r = res.contents
    if r.error:
        msg = r.error.decode()
        lib.dmlc_free_segblock(res)
        raise DMLCError(msg)
    owner = _Owner(lib, res, _free_segblock)
    rows = int(r.n_rows)
    out = {
        "rows": rows,
        "nnz": int(r.nnz),
        "num_col": int(r.num_col),
        "crc": int(r.crc32),
        "simd_level": int(r.simd_level),
        "segments": {},
        "arrays": {},
        "data": None,
        "_owner": owner,
    }
    if rows == 0:
        return out
    span = _view(r.buf, int(r.buf_len), np.uint8, owner)
    out["data"] = span if span is not None else np.empty(0, np.uint8)
    for slot, (name, dtype_str) in enumerate(_BATCH_SEGMENTS):
        off = int(r.seg_off[slot])
        if off < 0:
            continue
        nbytes = int(r.seg_len[slot])
        dt = np.dtype(dtype_str)
        # a present-but-empty segment (index of a label-only chunk) is a
        # real footer entry — mirror write_segments, which records those
        out["segments"][name] = (
            out["data"][off: off + nbytes].view(dt) if nbytes
            else np.empty(0, dt))
        out["arrays"][name] = [dtype_str, off, nbytes]
    return out


def _free_records(lib, addr):
    lib.dmlc_free_records(addr)


def recordio_extract(data) -> "tuple[np.ndarray, np.ndarray]":
    """Extract all records from a span of RecordIO bytes (must start at a
    record head and hold only whole records). Returns (payload u8 array,
    offsets int64 [n+1]) — record i is ``payload[offsets[i]:offsets[i+1]]``.
    Zero-copy over the native buffer. None when native is unavailable."""
    lib = _load()
    if lib is None:
        return None
    data = bytes(data) if not isinstance(data, bytes) else data
    res = lib.dmlc_recordio_extract(data, len(data))
    if not res:
        raise DMLCError("recordio: out of memory")
    return _wrap_records(lib, res)


def _wrap_records(lib, res):
    r = res.contents
    if r.error:
        msg = r.error.decode()
        lib.dmlc_free_records(res)
        raise DMLCError(msg)
    owner = _Owner(lib, res, _free_records)
    n = r.n_records
    offsets = _view(r.offsets, n + 1, np.int64, owner)
    payload = _view(r.data, r.data_len, np.uint8, owner)
    if offsets is None:
        offsets = np.zeros(1, np.int64)
    if payload is None:
        payload = np.empty(0, np.uint8)
    return payload, offsets


# ---------------- streaming reader ----------------

FMT_LIBSVM = 0
FMT_LIBSVM_DENSE = 1
FMT_CSV = 2
FMT_LIBFM = 3
FMT_RECORDIO = 4
FMT_RECORDIO_CHUNK = 5
FMT_LIBSVM_COO = 6
FMT_LIBFM_COO = 7
FMT_CSV_SPLIT = 8


def _free_coo(lib, addr):
    lib.dmlc_free_coo(addr)


def _wrap_coo(lib, res):
    """Wrap a CooResult as a dict of zero-copy views.

    ``coords`` is int32 [nnz_padded, 2] — or, on csr_wire blocks, cols-only
    int32 [nnz_padded] with ``row_ptr`` int32 [rows_padded + 1] (half the
    coordinate transfer bytes; the consumer rebuilds row ids on device,
    data/device.py); ``values`` is None when the block is all-ones and
    elision was requested (consumer synthesizes on device);
    ``n_rows``/``nnz`` are the REAL counts (shape dims carry bucket pad)."""
    r = res.contents
    if r.error:
        msg = r.error.decode()
        lib.dmlc_free_coo(res)
        raise DMLCError(msg)
    owner = _Owner(lib, res, _free_coo)
    if r.csr_wire:
        coords = _view(r.coords, r.nnz_padded, np.int32, owner)
        coords = coords if coords is not None else np.zeros((0,), np.int32)
        row_ptr = _view(r.row_ptr, r.rows_padded + 1, np.int32, owner)
    else:
        coords = _view(r.coords, 2 * r.nnz_padded, np.int32, owner)
        coords = coords.reshape(r.nnz_padded, 2) if coords is not None \
            else np.zeros((0, 2), np.int32)
        row_ptr = None
    return {
        "n_rows": int(r.n_rows),
        "nnz": int(r.nnz),
        "rows_padded": int(r.rows_padded),
        "coords": coords,
        "row_ptr": row_ptr,
        "values": (None if r.values_elided
                   else _view(r.values, r.nnz_padded, np.float32, owner)),
        "label": _view(r.label, r.rows_padded, np.float32, owner),
        "weight": _view(r.weight, r.rows_padded, np.float32, owner),
        "_owner": owner,
    }


def _wrap_stream_result(lib, ptr, fmt_value, num_col):
    """Wrap a dmlc_reader_next/dmlc_feeder_next result by format tag."""
    if fmt_value in (FMT_LIBSVM, FMT_LIBFM):
        return fmt_value, _wrap_block(
            lib, ctypes.cast(ptr, ctypes.POINTER(_CsrBlockResult)))
    if fmt_value == FMT_LIBSVM_DENSE:
        return fmt_value, _wrap_dense(
            lib, ctypes.cast(ptr, ctypes.POINTER(_DenseResult)), num_col)
    if fmt_value in (FMT_RECORDIO, FMT_RECORDIO_CHUNK):
        return fmt_value, _wrap_records(
            lib, ctypes.cast(ptr, ctypes.POINTER(_RecordBatchResult)))
    if fmt_value in (FMT_LIBSVM_COO, FMT_LIBFM_COO):
        return fmt_value, _wrap_coo(
            lib, ctypes.cast(ptr, ctypes.POINTER(_CooResult)))
    if fmt_value == FMT_CSV_SPLIT:
        return fmt_value, _wrap_csv_split(
            lib, ctypes.cast(ptr, ctypes.POINTER(_CsvSplitResult)))
    return fmt_value, _wrap_csv(
        lib, ctypes.cast(ptr, ctypes.POINTER(_CsvResult)))


class Reader:
    """Native read->chunk->parse pipeline over a byte-range partition.

    Wraps reader.cc: a C++ producer thread loads record-aligned chunks of
    this partition and parses them with worker threads; :meth:`next` blocks
    (GIL released) until a parsed block is ready and wraps it zero-copy.
    """

    def __init__(self, paths, sizes, part_index: int, num_parts: int,
                 fmt: int, num_col: int = 0, indexing_mode: int = 0,
                 delimiter: str = ",", nthread: int = 0,
                 chunk_bytes: int = 1 << 20, queue_depth: int = 4,
                 batch_rows: int = 0, label_col: int = -1,
                 weight_col: int = -1, out_bf16: bool = False,
                 row_bucket: int = 0, nnz_bucket: int = 0,
                 elide_unit: bool = False, csr_wire: bool = False,
                 pack_aux: bool = False):
        lib = _load()
        if lib is None:
            raise DMLCError("native core unavailable")
        self._lib = lib
        self._fmt = fmt
        self._num_col = num_col
        arr_p = (ctypes.c_char_p * len(paths))(
            *[os.fsencode(p) for p in paths])
        arr_s = (ctypes.c_int64 * len(sizes))(*sizes)
        self._h = lib.dmlc_reader_create(
            arr_p, arr_s, len(paths), part_index, num_parts, fmt, num_col,
            indexing_mode, delimiter.encode()[0] if delimiter else b","[0],
            nthread or default_nthread(), chunk_bytes, queue_depth,
            batch_rows, label_col, weight_col, 1 if out_bf16 else 0,
            row_bucket, nnz_bucket, 1 if elide_unit else 0,
            1 if csr_wire else 0, 1 if pack_aux else 0)
        if not self._h:
            raise DMLCError(
                "native reader creation failed (out of memory or threads)")
        self._check_error()

    def _check_error(self) -> None:
        err = self._lib.dmlc_reader_error(self._h)
        if err:
            raise DMLCError(err.decode())

    def next(self):
        """Next parsed block as ``(fmt, wrapped)`` where wrapped is:
        FMT_LIBSVM/FMT_LIBFM -> dict of CSR arrays (like parse_libsvm);
        FMT_LIBSVM_DENSE -> (x, label, weight, owner);
        FMT_CSV -> (cells, owner). None at end of partition. ``fmt`` can
        downgrade from FMT_LIBSVM_DENSE to FMT_LIBSVM mid-stream when the
        dense scanner meets qid rows."""
        if self._h is None:
            return None
        fmt = ctypes.c_int32(self._fmt)
        ptr = self._lib.dmlc_reader_next(self._h, ctypes.byref(fmt))
        if not ptr:
            self._check_error()
            return None
        return _wrap_stream_result(self._lib, ptr, fmt.value, self._num_col)

    def before_first(self) -> None:
        if self._h is not None:
            self._lib.dmlc_reader_before_first(self._h)

    @property
    def bytes_read(self) -> int:
        return self._lib.dmlc_reader_bytes_read(self._h) if self._h is not None else 0

    def close(self) -> None:
        if self._h is not None:
            self._lib.dmlc_reader_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class Feeder:
    """Push-mode native pipeline: the caller streams raw partition bytes in
    (from ANY filesystem — S3/GCS/HTTP range reads) and pulls parsed blocks
    out; chunking at record boundaries, threaded parsing, and batch repack
    run in C++ exactly as in :class:`Reader`.

    Contract: one feed thread calls ``push`` repeatedly then ``finish``;
    ``push`` blocks (GIL released) for backpressure. Before ``before_first``
    or ``close``, call ``abort`` and JOIN the feed thread.
    """

    def __init__(self, fmt: int, num_col: int = 0, indexing_mode: int = 0,
                 delimiter: str = ",", nthread: int = 0,
                 chunk_bytes: int = 1 << 20, queue_depth: int = 4,
                 batch_rows: int = 0, label_col: int = -1,
                 weight_col: int = -1, out_bf16: bool = False,
                 row_bucket: int = 0, nnz_bucket: int = 0,
                 elide_unit: bool = False, csr_wire: bool = False,
                 pack_aux: bool = False):
        lib = _load()
        if lib is None:
            raise DMLCError("native core unavailable")
        self._lib = lib
        self._fmt = fmt
        self._num_col = num_col
        self._h = lib.dmlc_feeder_create(
            fmt, num_col, indexing_mode,
            delimiter.encode()[0] if delimiter else b","[0],
            nthread or default_nthread(), chunk_bytes, queue_depth,
            batch_rows, label_col, weight_col, 1 if out_bf16 else 0,
            row_bucket, nnz_bucket, 1 if elide_unit else 0,
            1 if csr_wire else 0, 1 if pack_aux else 0)
        if not self._h:
            raise DMLCError("native feeder creation failed")

    def push(self, data) -> bool:
        """Feed bytes; False when the pipeline stopped (error/abort)."""
        if self._h is None:
            return False
        return self._lib.dmlc_feeder_push(self._h, bytes(data), len(data)) == 0

    def finish(self) -> None:
        if self._h is not None:
            self._lib.dmlc_feeder_finish(self._h)

    def abort(self) -> None:
        if self._h is not None:
            self._lib.dmlc_feeder_abort(self._h)

    def fail(self, msg: str) -> None:
        """Record a feed-side failure and end the stream; the consumer's
        next() raises once queued results drain."""
        if self._h is not None:
            self._lib.dmlc_feeder_fail(self._h, msg.encode()[:512])

    def next(self):
        if self._h is None:
            return None
        fmt = ctypes.c_int32(self._fmt)
        ptr = self._lib.dmlc_feeder_next(self._h, ctypes.byref(fmt))
        if not ptr:
            err = self._lib.dmlc_feeder_error(self._h)
            if err:
                raise DMLCError(err.decode())
            return None
        return _wrap_stream_result(self._lib, ptr, fmt.value, self._num_col)

    def before_first(self) -> None:
        if self._h is not None:
            self._lib.dmlc_feeder_before_first(self._h)

    def error(self):
        """The sticky pipeline error string, or None. Errors survive
        before_first (the native reader stays stopped) — callers that want
        a clean restart after a failure must rebuild the Feeder."""
        if self._h is None:
            return None
        err = self._lib.dmlc_feeder_error(self._h)
        return err.decode() if err else None

    @property
    def bytes_read(self) -> int:
        return (self._lib.dmlc_feeder_bytes_read(self._h)
                if self._h is not None else 0)

    def close(self) -> None:
        if self._h is not None:
            self._lib.dmlc_feeder_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class IndexedReader:
    """Native indexed-recordio pipeline: record-count partitioning over an
    external index, batched contiguous reads, per-epoch shuffled seeks —
    reader.cc IndexedReader (indexed_recordio_split.cc:12-41,159-233).

    :meth:`next` blocks (GIL released) until a batch of extracted record
    payloads is ready and wraps it zero-copy as ``(payload, offsets)``.
    """

    def __init__(self, paths, sizes, index_offsets, part_index: int,
                 num_parts: int, batch_records: int = 256,
                 shuffle: bool = False, seed: int = 0, queue_depth: int = 4):
        lib = _load()
        if lib is None:
            raise DMLCError("native core unavailable")
        self._lib = lib
        arr_p = (ctypes.c_char_p * len(paths))(
            *[os.fsencode(p) for p in paths])
        arr_s = (ctypes.c_int64 * len(sizes))(*sizes)
        arr_i = (ctypes.c_int64 * len(index_offsets))(*index_offsets)
        self._h = lib.dmlc_indexed_reader_create(
            arr_p, arr_s, len(paths), arr_i, len(index_offsets),
            part_index, num_parts, batch_records, 1 if shuffle else 0,
            seed, queue_depth)
        if not self._h:
            raise DMLCError(
                "native indexed reader creation failed (out of memory)")
        self._check_error()

    def _check_error(self) -> None:
        err = self._lib.dmlc_indexed_reader_error(self._h)
        if err:
            raise DMLCError(err.decode())

    def next(self):
        """Next batch as ``(payload, offsets)`` numpy views; None at end."""
        if self._h is None:
            return None
        ptr = self._lib.dmlc_indexed_reader_next(self._h)
        if not ptr:
            self._check_error()
            return None
        return _wrap_records(
            self._lib, ctypes.cast(ptr, ctypes.POINTER(_RecordBatchResult)))

    def before_first(self) -> None:
        """Epoch reset; under shuffle the NEXT epoch's permutation is drawn."""
        if self._h is not None:
            self._lib.dmlc_indexed_reader_before_first(self._h)

    def skip(self, epochs: int, records: int) -> None:
        """Native resume: land in epoch `epochs` at record `records` with no
        prefix I/O (missing permutations are drawn by pure rng replay).
        Forward-only — use a fresh reader to revisit an earlier epoch."""
        if self._h is not None:
            self._lib.dmlc_indexed_reader_skip(self._h, epochs, records)
            self._check_error()

    @property
    def bytes_read(self) -> int:
        return (self._lib.dmlc_indexed_reader_bytes_read(self._h)
                if self._h is not None else 0)

    def close(self) -> None:
        if self._h is not None:
            self._lib.dmlc_indexed_reader_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
