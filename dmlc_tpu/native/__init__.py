"""ctypes bindings for the C++ native core, built on demand with g++.

No pybind11 in this image (see repo docs) — the C ABI in native/src/parse.cc
is loaded with ctypes and arrays are wrapped as numpy views that own the
malloc'd buffers via a finalizer (zero copies on the handoff).

Falls back cleanly: ``available()`` is False when the toolchain or build is
missing, and the Python parsers keep working.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import weakref
from typing import Optional

import numpy as np

from dmlc_tpu.utils.check import DMLCError, get_logger

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "src", "parse.cc")
_BUILD_DIR = os.path.join(_REPO_ROOT, "native", "build")
_SO_PATH = os.path.join(_BUILD_DIR, "libdmlc_tpu_native.so")
_ABI_VERSION = 1

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


class _CsrBlockResult(ctypes.Structure):
    _fields_ = [
        ("n_rows", ctypes.c_int64),
        ("nnz", ctypes.c_int64),
        ("offset", ctypes.POINTER(ctypes.c_int64)),
        ("label", ctypes.POINTER(ctypes.c_float)),
        ("weight", ctypes.POINTER(ctypes.c_float)),
        ("qid", ctypes.POINTER(ctypes.c_int64)),
        ("index", ctypes.POINTER(ctypes.c_uint64)),
        ("field", ctypes.POINTER(ctypes.c_uint64)),
        ("value", ctypes.POINTER(ctypes.c_float)),
        ("error", ctypes.c_char_p),
    ]


class _CsvResult(ctypes.Structure):
    _fields_ = [
        ("n_rows", ctypes.c_int64),
        ("n_cols", ctypes.c_int64),
        ("cells", ctypes.POINTER(ctypes.c_float)),
        ("error", ctypes.c_char_p),
    ]


def _build() -> bool:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    # no -march=native: the artifact may outlive the build host (shared FS,
    # copied checkouts) and ISA-specific code would SIGILL with no fallback
    cmd = [
        "g++", "-O3", "-std=c++17", "-fPIC", "-shared", "-pthread",
        "-o", _SO_PATH, _SRC,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    except (OSError, subprocess.TimeoutExpired) as exc:
        get_logger().warning("native build failed to run: %s", exc)
        return False
    if proc.returncode != 0:
        get_logger().warning("native build failed:\n%s", proc.stderr[-2000:])
        return False
    return True


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        if os.environ.get("DMLC_TPU_NO_NATIVE", "0") not in ("", "0"):
            _build_failed = True
            return None
        need_build = not os.path.exists(_SO_PATH) or (
            os.path.exists(_SRC)
            and os.path.getmtime(_SRC) > os.path.getmtime(_SO_PATH)
        )
        if need_build and not _build():
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError as exc:
            # stale/corrupt artifact: rebuild once before giving up
            get_logger().warning("native load failed (%s); rebuilding", exc)
            try:
                os.unlink(_SO_PATH)
            except OSError:
                pass
            if not _build():
                _build_failed = True
                return None
            try:
                lib = ctypes.CDLL(_SO_PATH)
            except OSError as exc2:
                get_logger().warning("native load failed after rebuild: %s", exc2)
                _build_failed = True
                return None
        _declare(lib)
        if lib.dmlc_native_abi_version() != _ABI_VERSION:
            get_logger().warning("native ABI mismatch; rebuilding")
            try:
                os.unlink(_SO_PATH)
                if not _build():
                    _build_failed = True
                    return None
                lib = ctypes.CDLL(_SO_PATH)
                _declare(lib)
                if lib.dmlc_native_abi_version() != _ABI_VERSION:
                    get_logger().warning("native ABI still mismatched after rebuild")
                    _build_failed = True
                    return None
            except OSError as exc:
                get_logger().warning("native ABI rebuild failed: %s", exc)
                _build_failed = True
                return None
        _lib = lib
        return _lib


def _declare(lib: ctypes.CDLL) -> None:
    lib.dmlc_parse_libsvm.restype = ctypes.POINTER(_CsrBlockResult)
    lib.dmlc_parse_libsvm.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, ctypes.c_int]
    lib.dmlc_parse_libfm.restype = ctypes.POINTER(_CsrBlockResult)
    lib.dmlc_parse_libfm.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, ctypes.c_int]
    lib.dmlc_parse_csv.restype = ctypes.POINTER(_CsvResult)
    lib.dmlc_parse_csv.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, ctypes.c_char]
    # void* so finalizers never depend on ctypes class identity (which
    # changes across importlib.reload) — they may fire at interpreter exit
    lib.dmlc_free_block.argtypes = [ctypes.c_void_p]
    lib.dmlc_free_csv.argtypes = [ctypes.c_void_p]
    lib.dmlc_native_abi_version.restype = ctypes.c_int


def available() -> bool:
    return _load() is not None


def default_nthread() -> int:
    """min(user, cores/2) in the spirit of text_parser.h:33-34."""
    env = os.environ.get("DMLC_TPU_PARSE_THREADS")
    if env:
        return max(1, int(env))
    return max(2, (os.cpu_count() or 2) // 2)


def _view(ptr, n, dtype):
    """Zero-copy numpy view over a malloc'd buffer.

    The buffer's lifetime is governed by the _Owner returned alongside the
    views — every consumer (RowBlock carries it in ``hold``) must keep the
    owner referenced for as long as the views live.
    """
    if not ptr or n == 0:
        return None
    arr = np.ctypeslib.as_array(ptr, shape=(n,))
    return arr.view(dtype) if arr.dtype != dtype else arr


class _Owner:
    """Frees the C result when garbage collected."""

    __slots__ = ("__weakref__",)

    def __init__(self, lib, res, free_fn):
        weakref.finalize(self, free_fn, lib, ctypes.cast(res, ctypes.c_void_p).value)


def _free_block(lib, addr):
    lib.dmlc_free_block(addr)


def _free_csv(lib, addr):
    lib.dmlc_free_csv(addr)


def parse_libsvm(chunk: bytes, nthread: int = 0, indexing_mode: int = 0):
    """Parse a libsvm chunk natively; returns dict of numpy arrays or None."""
    lib = _load()
    if lib is None:
        return None
    res = lib.dmlc_parse_libsvm(
        chunk, len(chunk), nthread or default_nthread(), indexing_mode)
    return _wrap_block(lib, res)


def parse_libfm(chunk: bytes, nthread: int = 0, indexing_mode: int = 0):
    lib = _load()
    if lib is None:
        return None
    res = lib.dmlc_parse_libfm(
        chunk, len(chunk), nthread or default_nthread(), indexing_mode)
    return _wrap_block(lib, res)


def _wrap_block(lib, res):
    r = res.contents
    if r.error:
        msg = r.error.decode()
        lib.dmlc_free_block(res)
        raise DMLCError(msg)
    owner = _Owner(lib, res, _free_block)
    n, nnz = r.n_rows, r.nnz
    out = {
        "offset": _view(r.offset, n + 1, np.int64),
        "label": _view(r.label, n, np.float32),
        "weight": _view(r.weight, n, np.float32),
        "qid": _view(r.qid, n, np.int64),
        "index": _view(r.index, nnz, np.uint64),
        "field": _view(r.field, nnz, np.uint64),
        "value": _view(r.value, nnz, np.float32),
        "_owner": owner,
    }
    if n == 0:
        out["offset"] = np.zeros(1, np.int64)
        out["label"] = np.empty(0, np.float32)
    if out["index"] is None:
        out["index"] = np.empty(0, np.uint64)
    return out


def parse_csv(chunk: bytes, delimiter: str = ",", nthread: int = 0):
    """Parse a csv chunk natively -> (cells [n, ncol] float32, owner) or None.

    The caller must keep ``owner`` referenced while using ``cells``.
    """
    lib = _load()
    if lib is None:
        return None
    res = lib.dmlc_parse_csv(
        chunk, len(chunk), nthread or default_nthread(),
        delimiter.encode()[0] if delimiter else b","[0])
    r = res.contents
    if r.error:
        msg = r.error.decode()
        lib.dmlc_free_csv(res)
        raise DMLCError(msg)
    owner = _Owner(lib, res, _free_csv)
    n, c = r.n_rows, r.n_cols
    if n == 0 or c == 0:
        return np.zeros((0, 0), np.float32), owner
    cells = _view(r.cells, n * c, np.float32)
    return cells.reshape(n, c), owner
