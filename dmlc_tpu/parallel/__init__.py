"""Parallelism: mesh/sharding helpers, collectives, distributed bootstrap.

The reference's distributed story is rank bootstrap + input sharding
(SURVEY.md §2.3-2.4); its TPU-native equivalent is a
``jax.sharding.Mesh`` + XLA collectives over ICI, with ``jax.distributed``
as the DCN control plane bootstrapped from the same ``DMLC_*`` env contract
the tracker exports.
"""

from dmlc_tpu.parallel.mesh import (
    make_mesh, data_sharding, replicated, local_batch_to_global, host_shard_info,
)
from dmlc_tpu.parallel.distributed import (
    EnvContract, init_from_env, pod_identity, sync_min,
)

__all__ = [
    "make_mesh", "data_sharding", "replicated", "local_batch_to_global",
    "host_shard_info", "init_from_env", "EnvContract", "pod_identity",
    "sync_min",
]
