"""jax.distributed bootstrap from the DMLC_* env contract.

The reference tracker exports ``DMLC_TRACKER_URI/PORT``, ``DMLC_NUM_WORKER``,
``DMLC_TASK_ID``, ``DMLC_ROLE`` ... to every worker (SURVEY.md §2.2 env
contract; tracker.py:178-184, local.py:21-26). On TPU the data plane is XLA
collectives, so the whole rank-brokering protocol collapses into
``jax.distributed.initialize(coordinator_address, num_processes, process_id)``
— this module performs that mapping so a binary launched by ``dmlc-submit``
(any backend, including ``tpu-pod``) joins the pod with zero extra code.
"""

from __future__ import annotations

import os
from typing import NamedTuple, Optional

from dmlc_tpu.utils.check import DMLCError, get_logger


class EnvContract(NamedTuple):
    """Parsed DMLC_* environment (the de-facto wire API, SURVEY.md §2.2)."""

    tracker_uri: Optional[str]
    tracker_port: Optional[int]
    num_worker: int
    task_id: int
    role: str
    node_host: Optional[str]

    @staticmethod
    def from_env(env=None) -> "EnvContract":
        e = os.environ if env is None else env
        port = e.get("DMLC_TRACKER_PORT")
        return EnvContract(
            tracker_uri=e.get("DMLC_TRACKER_URI"),
            tracker_port=int(port) if port else None,
            num_worker=int(e.get("DMLC_NUM_WORKER", "1")),
            task_id=int(e.get("DMLC_TASK_ID", "0")),
            role=e.get("DMLC_ROLE", "worker"),
            node_host=e.get("DMLC_NODE_HOST"),
        )


_INITIALIZED = False


def init_from_env(
    env=None,
    *,
    coordinator_port_offset: int = 1,
    force: bool = False,
) -> EnvContract:
    """Initialize jax.distributed from the DMLC_* contract.

    Mapping (SURVEY.md §5.8): ``DMLC_TRACKER_URI:PORT+offset`` ->
    coordinator_address, ``DMLC_NUM_WORKER`` -> num_processes,
    ``DMLC_TASK_ID`` -> process_id. Single-worker jobs skip initialization
    (single-host JAX works without a coordinator).

    The coordinator listens next to the tracker port (offset +1) so the two
    control planes (tracker TCP rendezvous, JAX DCN coordination) coexist on
    one head node.
    """
    global _INITIALIZED
    contract = EnvContract.from_env(env)
    if contract.num_worker <= 1:
        return contract
    if _INITIALIZED and not force:
        return contract
    if contract.tracker_uri is None or contract.tracker_port is None:
        raise DMLCError(
            "init_from_env: DMLC_TRACKER_URI/DMLC_TRACKER_PORT not set; "
            "launch through dmlc-submit or set them explicitly"
        )
    import jax

    coordinator = f"{contract.tracker_uri}:{contract.tracker_port + coordinator_port_offset}"
    get_logger().info(
        "jax.distributed.initialize(coordinator=%s, num_processes=%d, process_id=%d)",
        coordinator, contract.num_worker, contract.task_id,
    )
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=contract.num_worker,
        process_id=contract.task_id,
    )
    _INITIALIZED = True
    return contract


def pod_identity(env=None) -> "tuple[int, int]":
    """``(host_id, num_hosts)`` of this process in the pod — the shard
    identity the deterministic epoch planner's ``pod_sharding`` resolves
    (:mod:`dmlc_tpu.data.epoch`, docs/data.md).

    Resolution order mirrors how a pod process learns who it is:

    1. the tracker env contract (``DMLC_TASK_ID`` / ``DMLC_NUM_WORKER``,
       exported by every launcher backend incl. ``tpu-pod``) — available
       before, and without, jax.distributed initialization;
    2. an initialized ``jax.distributed`` runtime
       (``process_index``/``process_count``) — covers processes
       bootstrapped outside the dmlc tracker;
    3. ``(0, 1)`` — single host.
    """
    e = os.environ if env is None else env
    contract = EnvContract.from_env(env)
    if contract.num_worker > 1:
        if e.get("DMLC_TASK_ID") is None:
            # EnvContract defaults task_id to 0 — trusting that here
            # would hand EVERY host shard 0 (fully overlapping "disjoint"
            # shards, most of the corpus never read, silently)
            raise DMLCError(
                "pod_identity: DMLC_NUM_WORKER is set but DMLC_TASK_ID "
                "is not — every host would claim shard 0; launch through "
                "a dmlc-submit backend or export both")
        return contract.task_id, contract.num_worker
    try:
        import jax

        if jax.process_count() > 1:
            return jax.process_index(), jax.process_count()
    except Exception:  # noqa: BLE001 - no jax runtime: single host
        pass
    return 0, 1


def sync_min(value: int) -> int:
    """All-process minimum of a host integer (1 tiny collective).

    The SPMD safety primitive for data-parallel epochs: byte-range shards
    rarely hold identical batch counts, and a process that runs one more
    collective step than its peers deadlocks the pod. Agreeing on
    ``min(local_steps)`` up front keeps every process executing the same
    program the same number of times. Single-process: returns ``value``.
    """
    import jax

    if jax.process_count() == 1:
        return int(value)
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()), ("p",))
    local = np.full((jax.local_device_count(),), int(value), np.int64)
    arr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("p")), local)
    out = jax.jit(jnp.min, out_shardings=NamedSharding(mesh, P()))(arr)
    return int(out)
