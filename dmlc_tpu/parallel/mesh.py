"""Mesh construction + sharding helpers.

The tracker's tree/ring topology maps (tracker.py:186-261) have no socket
analog on TPU: the ICI torus plus XLA collectives replace them. What remains
is (a) building the mesh, (b) placing per-host batches into a global sharded
array — the TPU equivalent of per-rank InputSplit shards feeding one logical
dataset (SURVEY.md §2.3 row 1).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    axes: Optional[Dict[str, int]] = None, *, devices=None
) -> Mesh:
    """Build a Mesh from an axis->size dict, e.g. ``{"data": 4, "model": 2}``.

    Defaults to a 1-D data mesh over all devices. Axis sizes must multiply to
    the device count; pass ``-1`` for one axis to infer it.
    """
    devices = list(devices if devices is not None else jax.devices())
    ndev = len(devices)
    if not axes:
        axes = {"data": ndev}
    names = list(axes.keys())
    sizes = list(axes.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = ndev // known
    total = int(np.prod(sizes))
    if total != ndev:
        raise ValueError(f"mesh axes {dict(zip(names, sizes))} != {ndev} devices")
    dev_array = np.array(devices).reshape(sizes)
    return Mesh(dev_array, axis_names=names)


def data_sharding(mesh: Mesh, *, axis: str = "data", ndim: int = 1) -> NamedSharding:
    """Batch-dim sharding over the data axis, rest replicated."""
    spec = [axis] + [None] * (ndim - 1)
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def host_shard_info(
    num_parts_hint: Optional[int] = None,
) -> Tuple[int, int]:
    """(part_index, num_parts) for this host's InputSplit shard.

    Multi-host: each process reads its own partition
    (``jax.process_index()/process_count()``), the direct analog of per-rank
    ``InputSplit::Create(uri, rank, world)`` (src/io.cc:74-130).
    """
    if num_parts_hint is not None:
        return 0, num_parts_hint
    return jax.process_index(), jax.process_count()


def local_batch_to_global(
    mesh: Mesh, local_arrays, *, axis: str = "data"
) -> Tuple[jax.Array, ...]:
    """Assemble per-process host batches into global sharded jax.Arrays.

    Uses ``jax.make_array_from_process_local_data``: each host contributes its
    InputSplit shard; the result is one logical array sharded over ``axis``
    across the pod — no host ever materializes the global batch.
    """
    out = []
    for arr in local_arrays:
        sharding = NamedSharding(mesh, P(axis, *([None] * (arr.ndim - 1))))
        out.append(jax.make_array_from_process_local_data(sharding, np.asarray(arr)))
    return tuple(out)
