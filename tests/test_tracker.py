"""Tracker tests: topology, protocol integration, backends, CLI.

The reference has zero tracker tests (SURVEY.md §4 gap); these use real
in-process sockets with WorkerClient fakes, the pattern SURVEY recommends.
"""

import os
import subprocess
import sys
import threading

import pytest

from dmlc_tpu.tracker import RabitTracker, WorkerClient
from dmlc_tpu.tracker import tracker as T
from dmlc_tpu.tracker.opts import parse_opts, read_host_file


# ---------------- topology ----------------

@pytest.mark.parametrize("n", [1, 2, 3, 5, 8, 17, 64])
def test_tree_and_ring_invariants(n):
    tree_map, parent_map = T.get_tree(n)
    # parent consistency + symmetry
    for r in range(n):
        if parent_map[r] >= 0:
            assert r in tree_map[parent_map[r]]
            assert parent_map[r] in tree_map[r]
    ring = T.get_ring(tree_map, parent_map)
    # ring covers all nodes exactly once
    seen = [0]
    cur = 0
    for _ in range(n - 1):
        cur = ring[cur][1]
        seen.append(cur)
    assert sorted(seen) == list(range(n))
    # prev/next are inverse
    for r in range(n):
        prev, nxt = ring[r]
        assert ring[nxt][0] == r
        assert ring[prev][1] == r


@pytest.mark.parametrize("n", [2, 4, 9, 16])
def test_link_map_renumbering(n):
    tree_map, parent_map, ring_map = T.get_link_map(n)
    assert sorted(tree_map) == list(range(n))
    # ring walks through all ranks
    cur = 0
    seen = {0}
    for _ in range(n - 1):
        cur = ring_map[cur][1]
        seen.add(cur)
    assert seen == set(range(n))
    for r, neighbors in tree_map.items():
        for x in neighbors:
            assert 0 <= x < n and x != r


# ---------------- protocol integration ----------------

def _run_workers(tracker, n, world_size_from_first=True, jobids=None):
    """Spawn n WorkerClients in threads; return their assignments."""
    results = [None] * n
    errors = []

    def work(i):
        try:
            client = WorkerClient("127.0.0.1", tracker.port,
                                  jobid=(jobids[i] if jobids else "NULL"))
            ws = n if (world_size_from_first) else -1
            results[i] = (client, client.start(world_size=ws))
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=work, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors, errors
    return results


@pytest.mark.parametrize("n", [1, 2, 4, 7])
def test_tracker_assigns_unique_ranks(n):
    tracker = RabitTracker("127.0.0.1", n, port=19000)
    tracker.start(n)
    results = _run_workers(tracker, n)
    ranks = sorted(a.rank for _, a in results)
    assert ranks == list(range(n))
    for _, a in results:
        assert a.world_size == n
        assert a.parent < n
        for x in a.tree_neighbors:
            assert 0 <= x < n and x != a.rank
    # total dialed links == total expected incoming links
    dialed = sum(len(a.connected_peers) for _, a in results)
    incoming = sum(a.num_incoming for _, a in results)
    assert dialed == incoming
    for client, _ in results:
        client.shutdown()
    tracker.join(timeout=30)
    assert tracker.alive() is False
    tracker.close()


def test_tracker_lazy_world_size():
    # tracker started with a wrong count; first worker's world_size wins
    tracker = RabitTracker("127.0.0.1", 999, port=19100)
    tracker.start(999)
    results = _run_workers(tracker, 3)
    assert sorted(a.rank for _, a in results) == [0, 1, 2]
    assert all(a.world_size == 3 for _, a in results)
    for client, _ in results:
        client.shutdown()
    tracker.join(timeout=30)
    tracker.close()


def test_tracker_print_and_jobid_rank_stability():
    tracker = RabitTracker("127.0.0.1", 2, port=19200)
    tracker.start(2)
    results = _run_workers(tracker, 2, jobids=["job-a", "job-b"])
    rank_of = {("job-a" if i == 0 else "job-b"): a.rank
               for i, (_, a) in enumerate(results)}
    probe = WorkerClient("127.0.0.1", tracker.port)
    probe.print_to_tracker("hello from test")
    for client, _ in results:
        client.shutdown()
    tracker.join(timeout=30)
    tracker.close()
    assert sorted(rank_of.values()) == [0, 1]


def test_tracker_multi_round_brokering_accounting():
    """A client that reports nerr (dial failure) in its first brokering
    round and links in round 2 must still settle the peer's wait_accept —
    the final-round-only accounting left the peer in wait_conn forever and
    its shutdown then killed the accept loop (r4 regression test for the
    client's nerr-retry protocol)."""
    tracker = RabitTracker("127.0.0.1", 2, port=19400)
    tracker.start(2)

    # worker A: a real client (connects first -> rank 0, enters wait_conn)
    a = WorkerClient("127.0.0.1", tracker.port)
    a_result = {}

    def run_a():
        a_result["assign"] = a.start()

    ta = threading.Thread(target=run_a, daemon=True)
    ta.start()
    # A must CONNECT first (pending order = arrival order): the tracker
    # then assigns A rank 0 into wait_conn and hands B its address — the
    # scenario this test scripts. A's start() blocks until B also joins,
    # so "A connected" cannot be observed via the client; a short delay
    # before B's hello makes the arrival order deterministic.
    import time as _time

    _time.sleep(0.5)

    # worker B: manual protocol — round 1 reports a dial failure, round 2
    # claims the link succeeded (goodset includes A's rank)
    b = WorkerClient("127.0.0.1", tracker.port)
    port = b._listen()
    conn = b._hello("start", -1, -1)
    b.rank = conn.recv_int()
    conn.recv_int()            # parent
    conn.recv_int()            # world
    num_nn = conn.recv_int()
    neighbors = [conn.recv_int() for _ in range(num_nn)]
    rprev, rnext = conn.recv_int(), conn.recv_int()
    linkset = {r for r in neighbors + [rprev, rnext] if r >= 0}
    # round 1: nothing linked; tracker hands out A's address; fail it
    conn.send_int(0)
    nconn = conn.recv_int()
    conn.recv_int()            # nwait
    for _ in range(nconn):
        conn.recv_str(), conn.recv_int(), conn.recv_int()
    assert nconn >= 1
    conn.send_int(nconn)       # every dial "failed"
    # round 2: claim all links made (protocol trusts the client's goodset)
    conn.send_int(len(linkset))
    for r in linkset:
        conn.send_int(r)
    nconn2 = conn.recv_int()
    conn.recv_int()
    assert nconn2 == 0         # nothing left to hand out
    conn.send_int(0)           # no errors
    conn.send_int(port)
    conn.close()
    ta.join(timeout=30)
    assert not ta.is_alive()

    a.shutdown()
    sh = b._hello("shutdown", b.rank, -1)
    sh.close()
    tracker.join(timeout=30)
    # clean completion: with the stale wait_conn entry the accept loop died
    # on `assert worker.rank not in wait_conn` and never set end_time
    assert tracker.end_time is not None
    tracker.close()
    b.close()
    a.close()


def test_tracker_recover_keeps_rank():
    tracker = RabitTracker("127.0.0.1", 2, port=19300)
    tracker.start(2)
    results = _run_workers(tracker, 2)
    by_rank = {a.rank: client for client, a in results}
    # rank 1 "dies" and recovers: same rank, fresh topology
    by_rank[1].close()
    recovered = WorkerClient("127.0.0.1", tracker.port)
    a1 = recovered.recover(1)
    assert a1.rank == 1 and a1.world_size == 2
    # its peer re-links too (real rabit peers redial on link failure)
    by_rank[0].close()
    relinked = WorkerClient("127.0.0.1", tracker.port)
    a0 = relinked.recover(0)
    assert a0.rank == 0
    recovered.shutdown()
    relinked.shutdown()
    tracker.join(timeout=30)
    tracker.close()


# ---------------- opts + backends ----------------

def test_parse_opts_and_env():
    args = parse_opts([
        "--cluster", "local", "--num-workers", "3",
        "--env", "FOO=bar", "--env", "X=1",
        "--", "python", "train.py", "--lr", "0.1",
    ])
    assert args.cluster == "local"
    assert args.num_workers == 3
    assert args.pass_envs == {"FOO": "bar", "X": "1"}
    assert args.command == ["python", "train.py", "--lr", "0.1"]
    with pytest.raises(SystemExit):
        parse_opts(["--num-workers", "2", "cmd"])  # no cluster
    with pytest.raises(SystemExit):
        parse_opts(["--cluster", "local", "--num-workers", "2",
                    "--env", "BAD", "cmd"])


def test_host_file(tmp_path):
    p = tmp_path / "hosts"
    p.write_text("10.0.0.1\n# comment\n10.0.0.2:2222\n\n")
    assert read_host_file(str(p)) == ["10.0.0.1", "10.0.0.2:2222"]
    from dmlc_tpu.tracker.ssh import parse_host

    assert parse_host("10.0.0.2:2222") == ("10.0.0.2", 2222)
    assert parse_host("10.0.0.1") == ("10.0.0.1", 22)


def test_ssh_command_construction():
    from dmlc_tpu.tracker.ssh import build_remote_command, build_ssh_argv

    remote = build_remote_command(
        ["python", "train.py"], {"DMLC_ROLE": "worker", "DMLC_TASK_ID": "3"},
        "10.0.0.5", "/work")
    assert "export DMLC_ROLE='worker';" in remote
    assert "export DMLC_NODE_HOST='10.0.0.5';" in remote
    assert remote.endswith("cd '/work'; python train.py")
    argv = build_ssh_argv("10.0.0.5", 22, remote)
    assert argv[0] == "ssh" and argv[-1] == remote


def test_slurm_mpi_sge_command_construction():
    from dmlc_tpu.tracker.slurm import build_srun_argv
    from dmlc_tpu.tracker.mpi import build_mpirun_argv, detect_mpi_dialect
    from dmlc_tpu.tracker.sge import build_run_script, build_qsub_argv

    srun = build_srun_argv(["./train"], 2, 8, "job-worker")
    assert srun[:1] == ["srun"] and "--ntasks=8" in srun

    assert detect_mpi_dialect("mpirun (Open MPI) 4.1.2") == "openmpi"
    assert detect_mpi_dialect("HYDRA build details: mpich") == "mpich"
    ompi = build_mpirun_argv(["./train"], 4, {"A": "1"}, "openmpi")
    assert ["-x", "A=1"] == ompi[3:5]
    mpich = build_mpirun_argv(["./train"], 4, {"A": "1"}, "mpich")
    assert ["-env", "A", "1"] == mpich[3:6]

    script = build_run_script(["./train"], {"DMLC_NUM_WORKER": "4"}, "worker")
    assert "export DMLC_TASK_ID=$((SGE_TASK_ID - 1))" in script
    qsub = build_qsub_argv("run.sh", 4, "j", "default", 2)
    assert "-t" in qsub and "1-4" in qsub


def test_kubernetes_manifests():
    from dmlc_tpu.tracker.kubernetes import build_manifests

    args = parse_opts([
        "--cluster", "kubernetes", "--num-workers", "2", "--num-servers", "1",
        "--jobname", "my_job", "--", "python", "train.py"])
    manifests = build_manifests(args, {"DMLC_PS_ROOT_URI": "h",
                                       "DMLC_PS_ROOT_PORT": "9091"})
    kinds = [(m["kind"], m["metadata"]["name"]) for m in manifests]
    assert ("Service", "my-job-scheduler") in kinds
    worker = [m for m in manifests if m["metadata"]["name"] == "my-job-worker"][0]
    assert worker["spec"]["parallelism"] == 2
    envs = {e["name"]: e["value"]
            for e in worker["spec"]["template"]["spec"]["containers"][0]["env"]}
    assert envs["DMLC_ROLE"] == "worker"


def test_tpu_pod_worker_env():
    from dmlc_tpu.tracker.tpu_pod import worker_env

    env = worker_env({"DMLC_TRACKER_URI": "10.0.0.1",
                      "DMLC_TRACKER_PORT": "9091",
                      "DMLC_NUM_WORKER": "4"}, 2)
    assert env["DMLC_TASK_ID"] == "2"
    assert env["DMLC_ROLE"] == "worker"
    assert env["DMLC_JOB_CLUSTER"] == "tpu-pod"
    # init_from_env maps this contract onto the jax coordinator
    from dmlc_tpu.parallel.distributed import EnvContract

    contract = EnvContract.from_env(env)
    assert contract.task_id == 2 and contract.num_worker == 4
    assert contract.tracker_uri == "10.0.0.1"


def test_local_exec_retry(tmp_path):
    from dmlc_tpu.tracker.local import exec_cmd

    marker = tmp_path / "tries"
    cmd = [sys.executable, "-c",
           f"import os,sys; p={str(marker)!r}; "
           "n = int(open(p).read()) if os.path.exists(p) else 0; "
           "open(p, 'w').write(str(n + 1)); sys.exit(0 if n >= 2 else 1)"]
    exec_cmd(cmd, "worker", 0, {}, num_attempt=5)
    assert marker.read_text() == "3"
    with pytest.raises(RuntimeError, match="failed"):
        exec_cmd([sys.executable, "-c", "import sys; sys.exit(1)"],
                 "worker", 0, {}, num_attempt=2)


def test_submit_local_end_to_end(tmp_path):
    """Full dmlc-submit local job: workers rendezvous via the tracker."""
    out_dir = tmp_path
    worker_code = (
        "import os, sys; sys.path.insert(0, os.environ['REPO']);\n"
        "from dmlc_tpu.tracker.client import WorkerClient\n"
        "c = WorkerClient(os.environ['DMLC_TRACKER_URI'],"
        " int(os.environ['DMLC_TRACKER_PORT']))\n"
        "a = c.start()\n"
        "open(os.path.join(os.environ['OUT'],"
        " f'rank_{a.rank}'), 'w').write(os.environ['DMLC_TASK_ID'])\n"
        "c.shutdown()\n"
    )
    from dmlc_tpu.tracker.submit import main

    env_backup = dict(os.environ)
    os.environ["REPO"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    os.environ["OUT"] = str(out_dir)
    try:
        main(["--cluster", "local", "--num-workers", "3", "--host-ip", "127.0.0.1",
              "--", sys.executable, "-c", worker_code])
    finally:
        os.environ.clear()
        os.environ.update(env_backup)
    ranks = sorted(p.name for p in out_dir.glob("rank_*"))
    assert ranks == ["rank_0", "rank_1", "rank_2"]


class TestLauncher:
    def test_unpack_archives_with_alias(self, tmp_path):
        import zipfile

        from dmlc_tpu.tracker.launcher import unpack_archives

        z = tmp_path / "code.zip"
        with zipfile.ZipFile(z, "w") as zf:
            zf.writestr("pkg/mod.py", "X = 1\n")
        dirs = unpack_archives(f"{z}#libs", dest=str(tmp_path))
        assert dirs == [str(tmp_path / "libs")]
        assert (tmp_path / "libs" / "pkg" / "mod.py").read_text() == "X = 1\n"
        # missing archives are skipped, not fatal
        assert unpack_archives(str(tmp_path / "nope.zip")) == []

    def test_build_env_maps_tracker_contract(self):
        from dmlc_tpu.tracker.launcher import build_env

        env = build_env({
            "DMLC_TRACKER_URI": "10.0.0.1", "DMLC_TRACKER_PORT": "9091",
            "DMLC_NUM_WORKER": "8", "DMLC_TASK_ID": "3",
            "DMLC_EXTRA_PYTHONPATH": "/opt/extra",
            "PYTHONPATH": "/base",
        })
        assert env["JAX_COORDINATOR_ADDRESS"] == "10.0.0.1:9091"
        assert env["JAX_NUM_PROCESSES"] == "8"
        assert env["JAX_PROCESS_ID"] == "3"
        assert env["PYTHONPATH"] == "/opt/extra:/base"

    def test_launcher_main_execs_command(self, tmp_path):
        from dmlc_tpu.tracker.launcher import main

        marker = tmp_path / "ran.txt"
        rc = main(["python", "-c",
                   f"open(r'{marker}', 'w').write('ok')"], use_exec=False)
        assert rc == 0
        assert marker.read_text() == "ok"


class TestStartPathWorkerDeath:
    def test_worker_dying_mid_start_brokering_fails_alone(self):
        """A worker that hangs up during start brokering must not take the
        rendezvous down with an unhandled EOF (ADVICE r4 #5); its relaunch
        (same jobid) re-claims the rank via job_map and completes the
        world. Settles applied before the death are rolled back (ADVICE r4
        #1), so the survivor's wait_accept stays exact."""
        import socket as _socket
        import struct
        import threading
        import time as _time

        from dmlc_tpu.tracker.client import WorkerClient
        from dmlc_tpu.tracker.tracker import MAGIC, RabitTracker

        tracker = RabitTracker("127.0.0.1", 2)
        tracker.start()
        a = b = None
        try:
            # half-dead worker: completes the hello for jobid "b" then
            # hangs up — the tracker hits EOF inside its assign_rank
            sock = _socket.create_connection(("127.0.0.1", tracker.port), 5)
            sock.sendall(struct.pack("@i", MAGIC))
            assert struct.unpack("@i", sock.recv(4))[0] == MAGIC
            sock.sendall(struct.pack("@i", -1))       # rank
            sock.sendall(struct.pack("@i", 2))        # world_size
            for s in (b"b", b"start"):
                sock.sendall(struct.pack("@i", len(s)) + s)

            a = WorkerClient("127.0.0.1", tracker.port, jobid="a")
            ra = {}
            ta = threading.Thread(
                target=lambda: ra.setdefault("a", a.start(world_size=2)))
            ta.start()
            _time.sleep(0.3)  # let the batch assignment begin
            sock.close()      # die mid-brokering

            # relaunch of jobid "b": re-claims its rank, links the survivor
            b = WorkerClient("127.0.0.1", tracker.port, jobid="b")
            assn_b = b.start(world_size=2)
            ta.join(10)
            assn_a = ra.get("a")
            assert assn_a is not None and assn_a.world_size == 2
            assert {assn_a.rank, assn_b.rank} == {0, 1}
            a.shutdown()
            b.shutdown()
            tracker.join(5)
        finally:
            if a is not None:
                a.close()
            if b is not None:
                b.close()
            tracker.close()


class TestPodMetrics:
    def test_multiprocess_workers_merge_per_rank_stage_table(self, tmp_path):
        """ISSUE 6 pod aggregation: ≥2 REAL worker processes rendezvous,
        each records telemetry and ships a registry snapshot over the
        `metrics` command; the tracker merges them into the per-rank ×
        per-stage table."""
        import time as _time

        os.environ["DMLC_METRICS_LOG_EVERY"] = "0"
        tracker = RabitTracker("127.0.0.1", 2)
        tracker.start(2)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        worker_code = (
            "import sys, os; sys.path.insert(0, os.environ['REPO'])\n"
            "from dmlc_tpu.tracker.client import WorkerClient\n"
            "from dmlc_tpu.utils import telemetry\n"
            "from dmlc_tpu.io import resilience\n"
            "c = WorkerClient('127.0.0.1', int(os.environ['PORT']))\n"
            "a = c.start(world_size=2)\n"
            "# stage seconds + a scoped resilience event + a span, as a\n"
            "# real pipeline would record them\n"
            "telemetry.REGISTRY.counter(telemetry.STAGE_BUSY_METRIC,\n"
            "    stage='parse', pipeline='p').inc(1.5)\n"
            "telemetry.REGISTRY.counter(telemetry.STAGE_BUSY_METRIC,\n"
            "    stage='read', pipeline='p').inc(0.25 * a.rank)\n"
            "with telemetry.scope('p'):\n"
            "    resilience.record_event('retries', a.rank)\n"
            "telemetry.record_span('parse', 0.0, 1.5)\n"
            "c.report_metrics()\n"
            "c.shutdown()\n"
        )
        env = dict(os.environ, REPO=repo, PORT=str(tracker.port),
                   JAX_PLATFORMS="cpu")
        procs = [subprocess.Popen([sys.executable, "-c", worker_code],
                                  env=env, stdout=subprocess.PIPE,
                                  stderr=subprocess.PIPE, text=True)
                 for _ in range(2)]
        try:
            for p in procs:
                out, err = p.communicate(timeout=60)
                assert p.returncode == 0, err
            tracker.join(timeout=30)
            # a metrics send can race the shutdown accept: wait briefly
            deadline = _time.monotonic() + 5
            while (len(tracker.pod_metrics()) < 2
                   and _time.monotonic() < deadline):
                _time.sleep(0.05)
            pod = tracker.pod_metrics()
            assert sorted(pod) == [0, 1]
            for rank in (0, 1):
                snap = pod[rank]
                assert snap["telemetry_schema_version"] >= 1
                assert snap["stages"]["parse"] == pytest.approx(1.5)
                assert snap["spans"]["parse"] >= 1
            assert pod[1]["stages"]["read"] == pytest.approx(0.25)
            assert pod[1]["resilience"]["retries"] == 1
            table = tracker.format_pod_table()
            lines = table.splitlines()
            assert "rank" in lines[0] and "parse" in lines[0]
            assert any(ln.strip().startswith("0") for ln in lines[1:])
            assert any(ln.strip().startswith("1") for ln in lines[1:])
            assert "3.000" in lines[-1]  # merged parse sum across ranks
        finally:
            os.environ.pop("DMLC_METRICS_LOG_EVERY", None)
            for p in procs:
                if p.poll() is None:
                    p.kill()
            tracker.close()

    def test_heartbeat_thread_with_metrics(self):
        """start_heartbeat(metrics=True): the periodic ping doubles as a
        snapshot feed and still counts for liveness."""
        import time as _time

        tracker = RabitTracker("127.0.0.1", 1, liveness_timeout=5.0)
        tracker.start(1)
        w = WorkerClient("127.0.0.1", tracker.port)
        try:
            a = w.start(world_size=1)
            w.start_heartbeat(interval=0.1, metrics=True)
            deadline = _time.monotonic() + 5
            while (a.rank not in tracker.pod_metrics()
                   and _time.monotonic() < deadline):
                _time.sleep(0.05)
            snap = tracker.pod_metrics().get(a.rank)
            assert snap is not None
            assert snap["telemetry_schema_version"] >= 1
            assert a.rank in tracker.last_seen  # metrics == liveness ping
            w.stop_heartbeat()
            w.shutdown()
            tracker.join(10)
        finally:
            w.close()
            tracker.close()


class TestLiveness:
    def test_silent_worker_flagged_heartbeater_not(self):
        import time as _time

        from dmlc_tpu.tracker.client import WorkerClient
        from dmlc_tpu.tracker.tracker import RabitTracker

        lost = []
        tracker = RabitTracker("127.0.0.1", 2, liveness_timeout=0.6,
                               on_worker_lost=lost.append)
        tracker.start()
        try:
            a = WorkerClient("127.0.0.1", tracker.port, jobid="a")
            b = WorkerClient("127.0.0.1", tracker.port, jobid="b")
            ra = {}
            import threading

            ta = threading.Thread(
                target=lambda: ra.setdefault("a", a.start(world_size=2)))
            ta.start()
            assn_b = b.start(world_size=2)
            ta.join(5)
            assn_a = ra["a"]
            # detection is opt-in per worker: b heartbeats once (enrolling
            # itself) then goes silent; a keeps heartbeating
            a.start_heartbeat(interval=0.2)
            b.heartbeat()
            _time.sleep(1.5)
            assert assn_b.rank in tracker.lost_workers
            assert assn_a.rank not in tracker.lost_workers
            assert lost == [assn_b.rank]
            # b comes back (recover semantics revive liveness)
            b.heartbeat()
            _time.sleep(0.1)
            assert assn_b.rank not in tracker.lost_workers
            a.stop_heartbeat()
            a.shutdown()
            b.shutdown()
            tracker.join(5)
        finally:
            a.close()
            b.close()
            tracker.close()

    def test_never_heartbeating_worker_not_flagged(self):
        # legacy rabit clients send no heartbeats and must never be flagged
        import time as _time

        from dmlc_tpu.tracker.client import WorkerClient
        from dmlc_tpu.tracker.tracker import RabitTracker

        tracker = RabitTracker("127.0.0.1", 1, liveness_timeout=0.3)
        tracker.start()
        try:
            w = WorkerClient("127.0.0.1", tracker.port)
            w.start(world_size=1)
            _time.sleep(1.0)
            assert tracker.lost_workers == set()
            w.shutdown()
            tracker.join(5)
        finally:
            w.close()
            tracker.close()
