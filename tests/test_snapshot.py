"""Device-native snapshot store (ISSUE 9): format (golden-pinned),
geometry/signature self-invalidation, the DeviceIter integration — cold
shadow write, warm zero-convert serving, byte-identical checkpoints
across cache<->snapshot pipeline swaps, plan-ordered epochs, int8
quantization, corruption healing — the bf16 pack_aux losslessness guard,
and the service snapshot frames (wire halving under bf16)."""

import json
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from dmlc_tpu.data import create_parser  # noqa: E402
from dmlc_tpu.data.device import DeviceIter, pack_dense_batches  # noqa: E402
from dmlc_tpu.io import resilience  # noqa: E402
from dmlc_tpu.io.snapshot import (  # noqa: E402
    SNAPSHOT_MAGIC,
    SnapshotIter,
    SnapshotReader,
    SnapshotWriter,
    open_snapshot,
    quantize_int8,
)
from dmlc_tpu.utils.check import DMLCError  # noqa: E402

NUM_COL = 6
BATCH = 64


def _corpus(tmp_path, n=512, name="c.libsvm", bf16_exact=True):
    rng = np.random.default_rng(7)
    path = tmp_path / name
    with open(path, "w") as f:
        for i in range(n):
            label = i % 2 if bf16_exact else 0.1 + 0.01 * i
            feats = " ".join(
                f"{j}:{rng.standard_normal():.6f}" for j in range(NUM_COL))
            f.write(f"{label} {feats}\n")
    return str(path)


def _make_iter(corpus, snap=None, **kw):
    parser = create_parser(corpus, 0, 1, "libsvm", threaded=True,
                           snapshot=snap)
    kw.setdefault("num_col", NUM_COL)
    kw.setdefault("batch_size", BATCH)
    kw.setdefault("layout", "dense")
    kw.setdefault("pack_aux", True)
    return DeviceIter(parser, **kw)


def _drain(it):
    return [np.asarray(b.packed) for b in it]


# ---------------- format ----------------

GEOM = {"v": 1, "batch_size": 4, "num_col": 3, "x_dtype": "float32"}


def _golden_batches():
    """The exact fixture tests/data/snapshot_v1.golden was written from —
    rewriting it must reproduce the committed bytes."""
    xp = np.arange(20, dtype=np.float32).reshape(4, 5)
    q, scale = quantize_int8(xp)
    ell_idx = np.array([[0, 1], [2, 3]], np.int32)
    ell_val = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
    return [
        ("dense_packed", (xp,), 4,
         {"source": {"kind": "split", "chunks": 1,
                     "split": {"kind": "byte", "offset_curr": 64}},
          "skip_rows": 2}),
        ("ell", (ell_idx, ell_val, np.array([1.0, 0.0], np.float32),
                 np.array([1.0, 1.0], np.float32)), 2, None),
        ("dense_packed_q8", (q, scale), 4, None),
    ]


GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "data", "snapshot_v1.golden")


class TestFormat:
    def test_roundtrip_shapes_and_views(self, tmp_path):
        path = str(tmp_path / "s.snapshot")
        w = SnapshotWriter(path, signature={"s": 1}, geometry=GEOM)
        for kind, arrays, rows, resume in _golden_batches():
            w.add_batch(kind, arrays, rows=rows, resume=resume)
        w.finish()
        assert not os.path.exists(path + ".tmp")  # atomic publish
        r = SnapshotReader(path, signature={"s": 1}, geometry=GEOM)
        assert r.num_batches == 3 and r.rows == 10
        for i, (kind, arrays, rows, resume) in enumerate(_golden_batches()):
            got = r.load_batch(i)
            assert got[0] == kind
            assert len(got) == 1 + len(arrays)
            for a, b in zip(got[1:], arrays):
                np.testing.assert_array_equal(a, b)
                assert a.dtype == b.dtype and a.shape == b.shape
                assert not a.flags.writeable  # zero-copy mmap contract
            assert r.batch_rows(i) == rows
            assert r.resume(i) == (json.loads(json.dumps(resume))
                                   if resume is not None else None)
        r.close()

    def test_golden_layout_pinned(self, tmp_path):
        """The v1 layout is frozen: rewriting the golden fixture must be
        byte-identical to the committed file, and the committed file must
        decode exactly — an accidental format change fails both ways."""
        rebuilt = str(tmp_path / "rebuilt.golden")
        w = SnapshotWriter(rebuilt, signature={"pinned": "snapshot-v1"},
                           geometry=GEOM)
        for kind, arrays, rows, resume in _golden_batches():
            w.add_batch(kind, arrays, rows=rows, resume=resume)
        w.finish()
        with open(GOLDEN, "rb") as f:
            want = f.read()
        with open(rebuilt, "rb") as f:
            got = f.read()
        assert got == want, "on-disk snapshot layout drifted from v1"
        r = SnapshotReader(GOLDEN)
        assert r.signature == {"pinned": "snapshot-v1"}
        k, xp = r.load_batch(0)
        assert k == "dense_packed" and xp.shape == (4, 5)
        np.testing.assert_array_equal(
            xp, np.arange(20, dtype=np.float32).reshape(4, 5))
        k8, q, scale = r.load_batch(2)
        assert k8 == "dense_packed_q8" and q.dtype == np.int8
        assert want[:8] == SNAPSHOT_MAGIC and want[-8:] == SNAPSHOT_MAGIC
        r.close()

    @pytest.mark.parametrize("drift", [
        {"batch_size": 8},            # different batch size
        {"x_dtype": "bfloat16"},      # different dtype
        {"num_col": 4},               # different width
    ])
    def test_geometry_mismatch_self_invalidates(self, tmp_path, drift):
        """A snapshot written at a different batch_size/x_dtype/padding
        config must self-invalidate (same signature discipline as the
        block cache), never serve wrong-shaped batches."""
        path = str(tmp_path / "s.snapshot")
        w = SnapshotWriter(path, signature={"s": 1}, geometry=GEOM)
        w.add_batch(*_golden_batches()[0][:2], rows=4)
        w.finish()
        base = resilience.counters_snapshot()
        assert open_snapshot(path, signature={"s": 1},
                             geometry=dict(GEOM, **drift)) is None
        assert not os.path.exists(path)  # stale snapshot dropped
        assert resilience.counters_delta(base)[
            "snapshot_invalidations"] == 1

    def test_signature_mismatch_self_invalidates(self, tmp_path):
        path = str(tmp_path / "s.snapshot")
        w = SnapshotWriter(path, signature={"files": [["a", 1, 2]]},
                           geometry=GEOM)
        w.add_batch(*_golden_batches()[0][:2], rows=4)
        w.finish()
        assert open_snapshot(path, signature={"files": [["a", 1, 3]]},
                             geometry=GEOM) is None
        assert not os.path.exists(path)

    def test_snapshot_iter_orders(self, tmp_path):
        path = str(tmp_path / "s.snapshot")
        w = SnapshotWriter(path, geometry=GEOM)
        for kind, arrays, rows, resume in _golden_batches():
            w.add_batch(kind, arrays, rows=rows, resume=resume)
        w.finish()
        r = SnapshotReader(path, geometry=GEOM)
        it = SnapshotIter(r, order=np.array([2, 0, 1]), start=1)
        first = it.next()
        assert first is not None and first[0][0] == "dense_packed"
        assert first[1] == r.resume(0)  # the stored annotation rides along
        assert it.next()[0][0] == "ell"
        assert it.next() is None
        it.destroy()
        r.close()

    def test_quantize_int8_roundtrip_bound(self):
        rng = np.random.default_rng(3)
        a = rng.standard_normal((32, 7)).astype(np.float32) * 10
        q, scale = quantize_int8(a)
        assert q.dtype == np.int8 and scale.shape == (7,)
        np.testing.assert_allclose(q.astype(np.float32) * scale, a,
                                   atol=float(scale.max()) * 0.51)


# ---------------- DeviceIter integration ----------------

class TestPipeline:
    def test_cold_writes_warm_serves_zero_convert(self, tmp_path):
        corpus = _corpus(tmp_path)
        snap = str(tmp_path / "c.snapshot")
        it = _make_iter(corpus, snap=snap)
        cold = _drain(it)
        assert it.stats()["snapshot_state"] == "cold"
        assert os.path.exists(snap)
        it.close()
        # a FRESH pipeline over the published snapshot serves warm with
        # convert busy EXACTLY zero and a nonzero snapshot_read stage —
        # the acceptance contract: the convert stage is bypassed, not
        # merely overlapped
        it2 = _make_iter(corpus, snap=snap)
        warm = _drain(it2)
        s = it2.stats()
        assert s["snapshot_state"] == "warm"
        assert s["stage_busy"]["convert"] == 0.0
        assert s["stage_busy"]["parse"] == 0.0 and s["stage_busy"][
            "read"] == 0.0
        assert s["stage_busy"]["snapshot_read"] > 0.0
        assert s["stages"]["snapshot_read"] >= 0.0
        it2.close()
        assert len(cold) == len(warm) == -(-512 // BATCH)
        for a, b in zip(cold, warm):
            np.testing.assert_array_equal(a, b)

    def test_same_iterator_flips_warm_on_reset(self, tmp_path):
        corpus = _corpus(tmp_path)
        snap = str(tmp_path / "c.snapshot")
        it = _make_iter(corpus, snap=snap)
        cold = _drain(it)
        it.reset()
        warm = _drain(it)
        assert it.stats()["snapshot_state"] == "warm"
        it.close()
        for a, b in zip(cold, warm):
            np.testing.assert_array_equal(a, b)

    def test_checkpoint_swaps_cache_and_snapshot(self, tmp_path):
        """ACCEPTANCE: mid-epoch checkpoints restore byte-identically
        across cache->snapshot pipeline swaps — a state taken against a
        warm SNAPSHOT pipeline restores into a block-CACHE pipeline (and
        a plain one), and vice versa."""
        corpus = _corpus(tmp_path)
        snap = str(tmp_path / "c.snapshot")
        cache = str(tmp_path / "c.blockcache")
        it = _make_iter(corpus, snap=snap)
        full = _drain(it)
        it.close()
        # warm snapshot pipeline -> 3 batches -> checkpoint
        it_snap = _make_iter(corpus, snap=snap)
        for _ in range(3):
            next(it_snap)
        state = it_snap.state_dict()
        it_snap.close()
        # restore into a block-cache pipeline (no snapshot armed)
        parser = create_parser(corpus, 0, 1, "libsvm", threaded=True,
                               block_cache=cache)
        it_cache = DeviceIter(parser, num_col=NUM_COL, batch_size=BATCH,
                              layout="dense", pack_aux=True)
        it_cache.load_state(state)
        rest = _drain(it_cache)
        it_cache.close()
        assert len(rest) == len(full) - 3
        for a, b in zip(rest, full[3:]):
            np.testing.assert_array_equal(a, b)
        # now the reverse: warm CACHE pipeline state -> snapshot pipeline
        parser = create_parser(corpus, 0, 1, "libsvm", threaded=True,
                               block_cache=cache)
        it_cache2 = DeviceIter(parser, num_col=NUM_COL, batch_size=BATCH,
                               layout="dense", pack_aux=True)
        for _ in range(2):
            next(it_cache2)
        state2 = it_cache2.state_dict()
        it_cache2.close()
        it_snap2 = _make_iter(corpus, snap=snap)
        it_snap2.load_state(state2)
        rest2 = _drain(it_snap2)
        assert it_snap2.stats()["snapshot_state"] == "warm"
        it_snap2.close()
        assert len(rest2) == len(full) - 2
        for a, b in zip(rest2, full[2:]):
            np.testing.assert_array_equal(a, b)

    def test_vanished_snapshot_restores_cold(self, tmp_path):
        corpus = _corpus(tmp_path)
        snap = str(tmp_path / "c.snapshot")
        it = _make_iter(corpus, snap=snap)
        full = _drain(it)
        it.close()
        it2 = _make_iter(corpus, snap=snap)
        for _ in range(2):
            next(it2)
        state = it2.state_dict()
        it2.close()
        os.remove(snap)
        it3 = _make_iter(corpus, snap=snap)
        it3.load_state(state)
        rest = _drain(it3)
        assert it3.stats()["snapshot_state"] == "cold"
        it3.close()
        for a, b in zip(rest, full[2:]):
            np.testing.assert_array_equal(a, b)

    def test_plan_ordered_epochs_deterministic(self, tmp_path):
        """snapshot_shuffle_seed serves the stored batches through the
        epoch planner's permutation over BATCH indices: a pure function
        of (seed, epoch) — same seed reproduces, different seed is a
        different order of the same multiset, epochs draw fresh orders."""
        corpus = _corpus(tmp_path)
        snap = str(tmp_path / "c.snapshot")
        it = _make_iter(corpus, snap=snap)
        seq = _drain(it)
        it.close()
        it_a = _make_iter(corpus, snap=snap, snapshot_shuffle_seed=11)
        shuf_a = _drain(it_a)
        s = it_a.stats()
        assert s["snapshot_seed"] == 11 and s["snapshot_state"] == "warm"
        it_a.reset()
        shuf_a2 = _drain(it_a)  # epoch 1: a fresh permutation
        it_a.close()
        it_b = _make_iter(corpus, snap=snap, snapshot_shuffle_seed=11)
        shuf_b = _drain(it_b)
        it_b.close()
        assert len(shuf_a) == len(seq)
        # same (seed, epoch) -> byte-identical across runs
        for a, b in zip(shuf_a, shuf_b):
            np.testing.assert_array_equal(a, b)
        # permuted vs sequential, and epoch 1 differs from epoch 0
        assert not all(np.array_equal(a, b) for a, b in zip(shuf_a, seq))
        assert not all(np.array_equal(a, b)
                       for a, b in zip(shuf_a, shuf_a2))
        # same multiset of batches
        key = lambda arr: arr.tobytes()  # noqa: E731
        assert sorted(key(a) for a in shuf_a) == sorted(
            key(a) for a in seq)

    def test_plan_mid_epoch_resume_byte_identical(self, tmp_path):
        corpus = _corpus(tmp_path)
        snap = str(tmp_path / "c.snapshot")
        it = _make_iter(corpus, snap=snap)
        _drain(it)
        it.close()
        it1 = _make_iter(corpus, snap=snap, snapshot_shuffle_seed=5)
        shuf = _drain(it1)
        it1.close()
        it2 = _make_iter(corpus, snap=snap, snapshot_shuffle_seed=5)
        for _ in range(3):
            next(it2)
        state = it2.state_dict()
        it2.close()
        assert state["source"]["kind"] == "epoch_plan"
        assert state["source"]["unit"] == "batch"
        # restore into a FRESH pipeline — even one built with a different
        # seed: the state's plan identity is adopted wholesale
        it3 = _make_iter(corpus, snap=snap, snapshot_shuffle_seed=99)
        it3.load_state(state)
        rest = _drain(it3)
        it3.close()
        assert len(rest) == len(shuf) - 3
        for a, b in zip(rest, shuf[3:]):
            np.testing.assert_array_equal(a, b)

    def test_block_plan_state_falls_through_to_source(self, tmp_path):
        """A shuffled BLOCK-cache checkpoint (kind='epoch_plan' over
        blocks, no unit) restored into a snapshot-armed pipeline must
        replay the PERMUTED stream via the source — never be hijacked
        into a count-based sequential warm-snapshot resume."""
        corpus = _corpus(tmp_path)
        cache = str(tmp_path / "c.blockcache")
        snap = str(tmp_path / "c.snapshot")
        # small chunks -> many cache blocks, so mid-epoch checkpoints
        # carry real plan annotations (a 1-block cache degrades them to
        # order-less count states no restore path could disambiguate)
        kw = dict(threaded=True, chunk_bytes=2048)
        # publish the (sequential-order) snapshot and the block cache
        parser = create_parser(corpus, 0, 1, "libsvm", block_cache=cache,
                               snapshot=snap, **kw)
        it = DeviceIter(parser, num_col=NUM_COL, batch_size=BATCH,
                        layout="dense", pack_aux=True)
        _drain(it)
        it.close()

        def shuffled_iter():
            p = create_parser(corpus, 0, 1, "libsvm", block_cache=cache,
                              shuffle_seed=3, shuffle_window=8, **kw)
            return DeviceIter(p, num_col=NUM_COL, batch_size=BATCH,
                              layout="dense", pack_aux=True)

        it_ref = shuffled_iter()
        ref = _drain(it_ref)  # warm epoch 0 in plan order
        it_ref.close()
        it_ck = shuffled_iter()
        for _ in range(2):
            next(it_ck)
        state = it_ck.state_dict()
        it_ck.close()
        assert state["source"]["kind"] == "epoch_plan"
        assert "unit" not in state["source"]  # a BLOCK-plan state
        # restore into a snapshot-armed (sequential) pipeline: the plan
        # state's order only exists at the source — the snapshot must
        # step aside
        parser = create_parser(corpus, 0, 1, "libsvm", block_cache=cache,
                               snapshot=snap, **kw)
        it2 = DeviceIter(parser, num_col=NUM_COL, batch_size=BATCH,
                         layout="dense", pack_aux=True)
        it2.load_state(state)
        rest = _drain(it2)
        it2.close()
        assert len(rest) == len(ref) - 2
        for a, b in zip(rest, ref[2:]):
            np.testing.assert_array_equal(a, b)

    def test_plan_state_rejected_by_block_cache(self, tmp_path):
        """A unit='batch' plan state must not restore into the block
        cache's block stream (wrong positions) — it rejects loudly."""
        corpus = _corpus(tmp_path)
        snap = str(tmp_path / "c.snapshot")
        cache = str(tmp_path / "c.blockcache")
        it = _make_iter(corpus, snap=snap)
        _drain(it)
        it.close()
        it2 = _make_iter(corpus, snap=snap, snapshot_shuffle_seed=5)
        next(it2)
        state = it2.state_dict()
        it2.close()
        parser = create_parser(corpus, 0, 1, "libsvm", threaded=True,
                               block_cache=cache, shuffle_seed=1)
        with pytest.raises(DMLCError, match="unit='batch'"):
            parser.load_state(state["source"])
        parser.close()

    def test_quant_int8_epoch(self, tmp_path):
        corpus = _corpus(tmp_path)
        snap = str(tmp_path / "q.snapshot")
        it = _make_iter(corpus, snap=snap, snapshot_quant="int8")
        cold = _drain(it)
        it.reset()
        warm = _drain(it)
        assert it.stats()["snapshot_state"] == "warm"
        assert it.stats()["stage_busy"]["convert"] > 0.0  # cold converted
        it.close()
        assert len(warm) == len(cold)
        # dequantized batches approximate the originals within the
        # per-column quantization step
        for a, b in zip(cold, warm):
            step = np.abs(a).max(axis=0) / 127.0 + 1e-12
            assert np.all(np.abs(a - b) <= step * 0.51 + 1e-6)
        # quantized store approaches 1/4 the f32 one (at this tiny test
        # batch geometry the 64B segment alignment + per-batch footer
        # entries dominate, so the bound is looser than the asymptote)
        snap32 = str(tmp_path / "f.snapshot")
        it32 = _make_iter(corpus, snap=snap32)
        _drain(it32)
        it32.close()
        ratio = os.path.getsize(snap) / os.path.getsize(snap32)
        assert ratio <= 0.45, ratio

    def test_bf16_snapshot_halves_bytes(self, tmp_path):
        """ACCEPTANCE: bf16 snapshots halve stored/wire bytes vs float32
        (snapshot_wire_bytes_ratio <= 0.55)."""
        corpus = _corpus(tmp_path)
        snap32 = str(tmp_path / "f32.snapshot")
        snap16 = str(tmp_path / "bf16.snapshot")
        it = _make_iter(corpus, snap=snap32)
        _drain(it)
        it.close()
        it16 = _make_iter(corpus, snap=snap16, x_dtype="bfloat16")
        cold16 = _drain(it16)
        it16.reset()
        warm16 = _drain(it16)
        assert it16.stats()["snapshot_state"] == "warm"
        it16.close()
        for a, b in zip(cold16, warm16):
            np.testing.assert_array_equal(a, b)
        ratio = os.path.getsize(snap16) / os.path.getsize(snap32)
        assert ratio <= 0.55, ratio

    def test_corruption_heals_to_cold_byte_identical(self, tmp_path):
        """A corrupt warm batch (bit flip on disk) is a classified fault:
        the snapshot is dropped, the pipeline re-arms COLD at the exact
        delivered batch, and the stream stays byte-identical."""
        corpus = _corpus(tmp_path)
        snap = str(tmp_path / "c.snapshot")
        it = _make_iter(corpus, snap=snap)
        full = _drain(it)
        it.close()
        # flip one byte inside batch 2's span
        r = SnapshotReader(snap)
        entry_pos = r._batches[2]["pos"]
        r.close()
        with open(snap, "r+b") as f:
            f.seek(entry_pos + 8)
            b = f.read(1)
            f.seek(entry_pos + 8)
            f.write(bytes([b[0] ^ 0xFF]))
        base = resilience.counters_snapshot()
        it2 = _make_iter(corpus, snap=snap)
        healed = _drain(it2)
        s = it2.stats()
        it2.close()
        assert len(healed) == len(full)
        for a, b2 in zip(healed, full):
            np.testing.assert_array_equal(a, b2)
        delta = resilience.counters_delta(base)
        assert delta["snapshot_corruptions"] == 1
        assert s["resilience"]["pipeline_restarts"] == 1

    def test_snapshot_rejects_source_plan(self, tmp_path):
        corpus = _corpus(tmp_path)
        snap = str(tmp_path / "c.snapshot")
        cache = str(tmp_path / "c.blockcache")
        with pytest.raises(DMLCError, match="snapshot.*shuffle_seed"):
            create_parser(corpus, 0, 1, "libsvm", threaded=True,
                          snapshot=snap, block_cache=cache, shuffle_seed=3)
        # and at the DeviceIter level for a directly-armed planned source
        parser = create_parser(corpus, 0, 1, "libsvm", threaded=True,
                               block_cache=cache, shuffle_seed=3)
        with pytest.raises(DMLCError, match="source-side epoch plan"):
            DeviceIter(parser, num_col=NUM_COL, batch_size=BATCH,
                       layout="dense", pack_aux=True, snapshot=snap)
        parser.close()

    def test_snapshot_composes_with_block_cache(self, tmp_path):
        """The two-tier story: block cache (parser output) under the
        snapshot (device layout) — the cold snapshot pass reads the warm
        cache, and the warmest tier wins thereafter."""
        corpus = _corpus(tmp_path)
        snap = str(tmp_path / "c.snapshot")
        cache = str(tmp_path / "c.blockcache")
        # epoch 0: parse + publish the block cache (no snapshot)
        parser = create_parser(corpus, 0, 1, "libsvm", threaded=True,
                               block_cache=cache)
        it = DeviceIter(parser, num_col=NUM_COL, batch_size=BATCH,
                        layout="dense", pack_aux=True)
        plain = _drain(it)
        it.close()
        # epoch 1: cache-warm cold-snapshot pass (parses nothing)
        parser = create_parser(corpus, 0, 1, "libsvm", threaded=True,
                               block_cache=cache, snapshot=snap)
        it = DeviceIter(parser, num_col=NUM_COL, batch_size=BATCH,
                        layout="dense", pack_aux=True)
        from_cache = _drain(it)
        s = it.stats()
        assert s["cache_state"] == "warm" and s["snapshot_state"] == "cold"
        it.close()
        # epoch 2: snapshot-warm (neither parser nor cache touched)
        parser = create_parser(corpus, 0, 1, "libsvm", threaded=True,
                               block_cache=cache, snapshot=snap)
        it = DeviceIter(parser, num_col=NUM_COL, batch_size=BATCH,
                        layout="dense", pack_aux=True)
        from_snap = _drain(it)
        s = it.stats()
        assert s["snapshot_state"] == "warm"
        assert s["stage_busy"]["cache_read"] == 0.0
        assert s["stage_busy"]["convert"] == 0.0
        it.close()
        for a, b in zip(plain, from_cache):
            np.testing.assert_array_equal(a, b)
        for a, b in zip(plain, from_snap):
            np.testing.assert_array_equal(a, b)

    def test_ell_snapshot_roundtrip(self, tmp_path):
        corpus = _corpus(tmp_path, n=200)
        snap = str(tmp_path / "e.snapshot")

        def ell_iter():
            parser = create_parser(corpus, 0, 1, "libsvm", threaded=True,
                                   snapshot=snap)
            return DeviceIter(parser, num_col=NUM_COL, batch_size=32,
                              layout="ell", max_nnz=NUM_COL)

        def drain_ell(it):
            return [tuple(np.asarray(x) for x in b) for b in it]

        it = ell_iter()
        cold = drain_ell(it)
        it.close()
        it2 = ell_iter()
        warm = drain_ell(it2)
        assert it2.stats()["snapshot_state"] == "warm"
        assert it2.stats()["stage_busy"]["convert"] == 0.0
        it2.close()
        assert len(cold) == len(warm)
        for ba, bb in zip(cold, warm):
            for a, b in zip(ba, bb):
                np.testing.assert_array_equal(a, b)


# ---------------- bf16 pack_aux losslessness (satellite) ----------------

class TestBf16AuxGuard:
    def test_exact_labels_pass(self, tmp_path):
        corpus = _corpus(tmp_path, n=128, bf16_exact=True)
        it = _make_iter(corpus, x_dtype="bfloat16")
        out = _drain(it)
        it.close()
        assert len(out) == 2

    def test_lossy_labels_raise(self, tmp_path, monkeypatch):
        """Labels that are not bf16-exact must raise a clear error at
        pack time instead of silently corrupting (the old undocumented
        device.py caller promise, now enforced). The guard lives at the
        Python pack site (_pack_dense_parts); the fully-native dense-emit
        path converts inside C++ where the f32 originals never surface —
        pin the Python engine so the guarded path runs."""
        monkeypatch.setenv("DMLC_TPU_NO_NATIVE_READER", "1")
        corpus = _corpus(tmp_path, n=128, bf16_exact=False)
        it = _make_iter(corpus, x_dtype="bfloat16")
        with pytest.raises(DMLCError, match="bf16-exact"):
            _drain(it)
        it.close()

    def test_lossy_labels_fine_without_pack_aux(self, tmp_path):
        corpus = _corpus(tmp_path, n=128, bf16_exact=False)
        it = _make_iter(corpus, x_dtype="bfloat16", pack_aux=False)
        n = sum(1 for _ in it)
        it.close()
        assert n == 2


# ---------------- service snapshot frames ----------------

class TestServiceSnapshot:
    def test_wire_halved_under_bf16(self):
        from dmlc_tpu.native import bf16_dtype
        from dmlc_tpu.service.frame import (
            decode_frame, encode_snapshot_frame, snapshot_from_frame,
        )

        rng = np.random.default_rng(0)
        xp = rng.standard_normal((BATCH, NUM_COL + 2)).astype(np.float32)
        f32 = encode_snapshot_frame("dense_packed", (xp,), rows=BATCH)
        f16 = encode_snapshot_frame(
            "dense_packed", (xp.astype(bf16_dtype()),), rows=BATCH)
        assert len(f16) / len(f32) <= 0.55
        kind, meta, payload = decode_frame(f16)
        got = snapshot_from_frame(meta, payload)
        assert got[0] == "dense_packed"
        assert got[1].dtype == bf16_dtype()
        np.testing.assert_array_equal(
            got[1], xp.astype(bf16_dtype()))

    def test_bf16_frame_decodes_without_jax(self, tmp_path):
        """A host-block service consumer never imports jax/ml_dtypes —
        decoding a bf16 snapshot frame must register the extension dtype
        lazily instead of crashing on np.dtype('bfloat16')."""
        import subprocess
        import sys

        from dmlc_tpu.native import bf16_dtype
        from dmlc_tpu.service.frame import encode_snapshot_frame

        xp = np.arange(24, dtype=np.float32).reshape(4, 6).astype(
            bf16_dtype())
        frame = encode_snapshot_frame("dense_packed", (xp,), rows=4)
        fpath = tmp_path / "frame.bin"
        fpath.write_bytes(frame)
        code = (
            "import sys; sys.path.insert(0, %r)\n"
            "from dmlc_tpu.service.frame import decode_frame, "
            "snapshot_from_frame\n"
            "assert 'jax' not in sys.modules and "
            "'ml_dtypes' not in sys.modules\n"
            "data = open(%r, 'rb').read()\n"
            "kind, meta, payload = decode_frame(data)\n"
            "got = snapshot_from_frame(meta, payload)\n"
            "assert got[0] == 'dense_packed' and got[1].shape == (4, 6)\n"
            "print('ok')\n"
        ) % (os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
             str(fpath))
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        assert out.stdout.strip() == "ok"

    def test_worker_pack_validates_bf16_labels(self, tmp_path):
        """The worker-side snapshot-frame pack applies the same bf16
        losslessness guard as the local pack path — lossy labels surface
        as an error, never silent corruption on the wire."""
        from dmlc_tpu.data.parsers import create_parser as _cp

        corpus = _corpus(tmp_path, n=64, bf16_exact=False)
        parser = _cp(corpus, 0, 1, "libsvm", threaded=False)
        blocks = list(parser)
        parser.close()
        from dmlc_tpu.native import bf16_dtype

        with pytest.raises(DMLCError, match="bf16-exact"):
            list(pack_dense_batches(blocks, 16, NUM_COL,
                                    dtype=bf16_dtype()))
        # exact labels pack clean
        corpus2 = _corpus(tmp_path, n=64, name="e.libsvm",
                          bf16_exact=True)
        parser = _cp(corpus2, 0, 1, "libsvm", threaded=False)
        blocks = list(parser)
        parser.close()
        out = list(pack_dense_batches(blocks, 16, NUM_COL,
                                      dtype=bf16_dtype()))
        assert len(out) == 4

    def test_fleet_serves_packed_batches(self, tmp_path):
        from dmlc_tpu.service import LocalFleet, ServiceParser

        corpus = _corpus(tmp_path, n=300)
        geom = {"batch_size": 32, "num_col": NUM_COL,
                "x_dtype": "bfloat16"}
        fleet = LocalFleet(corpus, 2, num_workers=2,
                           parser={"format": "libsvm"}, snapshot=geom)
        try:
            client = ServiceParser(fleet.address)
            assert client.snapshot == geom
            blocks = []
            while (b := client.next_block()) is not None:
                blocks.append(b)
            client.close()
            assert blocks and all(b.packed and len(b) == 32
                                  for b in blocks)
            assert sum(len(b) for b in blocks) >= 300
            # a DeviceIter over snapshot frames rides the dense_ready
            # fast path: packing work on the trainer is ~zero
            client2 = ServiceParser(fleet.address)
            it = DeviceIter(client2, num_col=NUM_COL, batch_size=32,
                            layout="dense", x_dtype="bfloat16",
                            pack_aux=True)
            n = sum(1 for _ in it)
            assert n == len(blocks)
            it.close()
        finally:
            fleet.close()

    def test_foreign_state_rejected_in_snapshot_mode(self, tmp_path):
        from dmlc_tpu.service import LocalFleet, ServiceParser

        corpus = _corpus(tmp_path, n=64)
        fleet = LocalFleet(corpus, 1, num_workers=1,
                           parser={"format": "libsvm"},
                           snapshot={"batch_size": 16,
                                     "num_col": NUM_COL,
                                     "x_dtype": "float32"})
        try:
            client = ServiceParser(fleet.address)
            with pytest.raises(DMLCError, match="service"):
                client.load_state({"kind": "blocks", "blocks": 3})
            # but (part, batch) service states round-trip
            while client.next_block() is not None:
                pass
            client.close()
        finally:
            fleet.close()
