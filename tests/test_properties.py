"""Property-based tests (hypothesis) for the invariants the whole stack
rests on. The reference tests these with fixed fixtures
(unittest_inputsplit.cc, unittest_serializer.cc, unittest_recordio.cc);
random generation covers the corpus shapes a fixture author doesn't think
of — blank lines, CRLF mixes, missing trailing newline, records embedding
the RecordIO magic, multi-file layouts with empty members.
"""

from __future__ import annotations

import io
import os

import numpy as np
import pytest

# hypothesis is an optional dev dependency: without it these properties
# must SKIP at collection (pytest.importorskip), not error the whole
# tier-1 collection run
hypothesis = pytest.importorskip(
    "hypothesis", reason="property tests need the hypothesis package")
from hypothesis import HealthCheck, given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

from dmlc_tpu.data import create_parser
from dmlc_tpu.io import create_input_split
from dmlc_tpu.io.recordio import _MAGIC_BYTES as MAGIC_BYTES
from dmlc_tpu.io.recordio import RecordIOReader, RecordIOWriter
from dmlc_tpu.utils.serializer import read_obj, write_obj

SETTLE = settings(max_examples=30, deadline=None,
                  suppress_health_check=[HealthCheck.function_scoped_fixture])


def _require_native(parser) -> None:
    """Parity tests are vacuous when the 'native' leg silently fell back
    to the Python engine (e.g. build failure) — skip loudly instead."""
    from dmlc_tpu.data.native_parser import NativeStreamParser

    if not isinstance(parser, NativeStreamParser):
        parser.close()
        pytest.skip("native engine unavailable; parity not exercisable")


def _write_libsvm(path, rows, prec: str = ".5g") -> None:
    """Serialize [(idx, val), ...] feature rows (deduped, sorted) as a
    libsvm corpus — shared by every property that generates one."""
    lines = []
    for i, feats in enumerate(rows):
        feats = sorted({j: v for j, v in feats}.items())
        body = " ".join(f"{j}:{v:{prec}}" for j, v in feats)
        lines.append(f"{i % 2}{' ' if body else ''}{body}")
    path.write_text("\n".join(lines) + "\n")


# ---------------------------------------------------------------------------
# InputSplit partition invariant: looping all parts == one pass, for ANY
# corpus layout (src/io.cc:74-130 byte-range sharding; PR#385/PR#452 edge
# cases are exactly the newline-shape corner this generator explores).

_line_st = st.text(
    alphabet=st.characters(min_codepoint=33, max_codepoint=126),
    min_size=0, max_size=40)


@SETTLE
@given(
    lines=st.lists(_line_st, min_size=1, max_size=60),
    nfiles=st.integers(min_value=1, max_value=3),
    num_parts=st.integers(min_value=1, max_value=5),
    trailing_newline=st.booleans(),
    crlf=st.booleans(),
)
def test_text_split_partition_invariant(tmp_path_factory, lines, nfiles,
                                        num_parts, trailing_newline, crlf):
    d = tmp_path_factory.mktemp("prop")
    sep = "\r\n" if crlf else "\n"
    chunks = [lines[i::nfiles] for i in range(nfiles)]
    paths = []
    for i, chunk in enumerate(chunks):
        p = d / f"part{i}.txt"
        body = sep.join(chunk)
        if chunk and trailing_newline:
            body += sep
        p.write_text(body)
        paths.append(str(p))
    uri = ";".join(paths)
    # records = non-empty lines (the splitter skips blank records the same
    # way the reference's line splitter does)
    expect = [ln for chunk in chunks for ln in chunk if ln]
    if all(os.path.getsize(p) == 0 for p in paths):
        # zero-byte files don't match the URI listing (reference semantics:
        # size-0 members are skipped); an all-empty corpus is a config
        # error, raised loudly
        with pytest.raises(Exception):
            s = create_input_split(uri, 0, num_parts, "text", threaded=False)
            list(s.iter_records())
        return

    got = []
    for part in range(num_parts):
        s = create_input_split(uri, part, num_parts, "text", threaded=False)
        got.extend(bytes(r).decode() for r in s.iter_records())
        s.close()
    # exact ORDER, not just multiset equality: parts looped in order must
    # reproduce the file-major record sequence (partition boundaries move,
    # records never reorder across them)
    assert got == expect


# ---------------------------------------------------------------------------
# RecordIO round-trip: payloads may EMBED the magic (the cflag escaping
# machinery, recordio.cc:17-52) and arbitrary binary bytes.

_payload_st = st.one_of(
    st.binary(min_size=0, max_size=64),
    st.binary(min_size=0, max_size=16).map(lambda b: b + MAGIC_BYTES + b),
    st.just(MAGIC_BYTES * 3),
)


@SETTLE
@given(payloads=st.lists(_payload_st, min_size=1, max_size=24))
def test_recordio_roundtrip_any_payload(payloads):
    buf = io.BytesIO()
    w = RecordIOWriter(buf)
    for p in payloads:
        w.write_record(p)
    buf.seek(0)
    r = RecordIOReader(buf)
    got = []
    while True:
        rec = r.next_record()
        if rec is None:
            break
        got.append(bytes(rec))
    assert got == payloads


# ---------------------------------------------------------------------------
# Indexed-RecordIO shuffle is a PERMUTATION for any record set, partition
# count, and seed: every part-loop covers its shard exactly (no loss, no
# duplication) and the same seed replays the same order
# (indexed_recordio_split.h shuffle semantics).

# threaded as a PYTEST param, not a hypothesis draw: a skip for the
# missing native engine must not abort the python-splitter leg (hypothesis
# treats an in-body skip as skipping the whole test)
@pytest.mark.parametrize("threaded", [False, True])
@SETTLE
@given(
    payloads=st.lists(st.binary(min_size=1, max_size=32),
                      min_size=2, max_size=40),
    num_parts=st.integers(min_value=1, max_value=3),
    seed=st.integers(min_value=0, max_value=2**31 - 1),
)
def test_indexed_recordio_shuffle_is_permutation(tmp_path_factory, threaded,
                                                 payloads, num_parts, seed):
    from dmlc_tpu.io import write_indexed_recordio
    from dmlc_tpu.io.native_recordio import NativeIndexedRecordIOSplit

    d = tmp_path_factory.mktemp("idx")
    data_p, idx_p = d / "d.rec", d / "d.idx"
    with open(data_p, "wb") as df, open(idx_p, "wb") as xf:
        write_indexed_recordio(df, xf, payloads)

    def epoch():
        got = []
        for part in range(num_parts):
            s = create_input_split(str(data_p), part, num_parts,
                                   "indexed_recordio", index_uri=str(idx_p),
                                   shuffle=True, seed=seed,
                                   threaded=threaded)
            if threaded and not isinstance(s, NativeIndexedRecordIOSplit):
                s.close()
                pytest.skip("native indexed reader unavailable")
            got.append([bytes(r) for r in s.iter_records()])
            s.close()
        return got

    a = epoch()
    b = epoch()
    flat_a = [r for part in a for r in part]
    assert sorted(flat_a) == sorted(payloads)  # permutation, whole corpus
    # same seed -> same per-part order on a fresh split (first epoch)
    assert a == b


# ---------------------------------------------------------------------------
# Serializer identity over nested structures incl. ndarrays
# (serializer.h:83-104 typed read/write analog).

_scalar_st = st.one_of(
    st.integers(min_value=-(2**62), max_value=2**62),
    st.floats(allow_nan=False, allow_infinity=False, width=32),
    st.text(max_size=20),
    st.booleans(),
    st.none(),
)
_array_st = st.one_of(
    st.lists(st.integers(-1000, 1000), max_size=8).map(
        lambda v: np.asarray(v, np.int64)),
    st.lists(st.floats(-1e6, 1e6, width=32), max_size=8).map(
        lambda v: np.asarray(v, np.float32)),
)
_obj_st = st.recursive(
    st.one_of(_scalar_st, _array_st),
    lambda inner: st.one_of(
        st.lists(inner, max_size=4),
        st.dictionaries(st.text(max_size=8), inner, max_size=4),
    ),
    max_leaves=12,
)


def _eq(a, b) -> bool:
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        return (isinstance(a, np.ndarray) and isinstance(b, np.ndarray)
                and a.dtype == b.dtype and a.shape == b.shape
                and bool((a == b).all()))
    if isinstance(a, list) and isinstance(b, list):
        return len(a) == len(b) and all(_eq(x, y) for x, y in zip(a, b))
    if isinstance(a, dict) and isinstance(b, dict):
        return (a.keys() == b.keys()
                and all(_eq(a[k], b[k]) for k in a))
    return type(a) is type(b) and a == b


@SETTLE
@given(obj=_obj_st)
def test_serializer_roundtrip_identity(obj):
    buf = io.BytesIO()
    write_obj(buf, obj)
    buf.seek(0)
    back = read_obj(buf)
    assert _eq(obj, back), (obj, back)


# ---------------------------------------------------------------------------
# Checkpoint/resume invariant (SURVEY §5.4): restoring a DeviceIter state
# captured after ANY number of delivered batches reproduces the remainder
# of the uninterrupted stream exactly — for any corpus size, batch size,
# and chunking (block boundaries move; the resumed stream must not care).

@SETTLE
@given(
    n_rows=st.integers(min_value=40, max_value=300),
    batch=st.sampled_from([16, 32, 64]),
    chunk=st.sampled_from([512, 2048, 8192]),
    data=st.data(),
)
def test_device_iter_resume_any_position(tmp_path_factory, n_rows, batch,
                                         chunk, data):
    from dmlc_tpu.data.device import DeviceIter

    d = tmp_path_factory.mktemp("resume")
    p = d / "c.libsvm"
    rng = np.random.default_rng(n_rows)
    lines = []
    for i in range(n_rows):
        feats = " ".join(f"{j}:{rng.normal():.4f}" for j in range(4))
        lines.append(f"{i % 2} {feats}")
    p.write_text("\n".join(lines) + "\n")

    def make():
        parser = create_parser(str(p) + "?engine=python", 0, 1, "libsvm",
                               threaded=False, chunk_bytes=chunk)
        return DeviceIter(parser, num_col=4, batch_size=batch,
                          layout="dense")

    it = make()
    full = [(np.asarray(x), np.asarray(y), np.asarray(w)) for x, y, w in it]
    it.close()
    k = data.draw(st.integers(min_value=0, max_value=len(full)))

    it2 = make()
    for _ in range(k):
        next(it2)
    state = it2.state_dict()
    it2.close()

    it3 = make()
    it3.load_state(state)
    rest = [(np.asarray(x), np.asarray(y), np.asarray(w)) for x, y, w in it3]
    assert len(rest) == len(full) - k
    for (xa, ya, wa), (xb, yb, wb) in zip(rest, full[k:]):
        np.testing.assert_allclose(xa, xb)
        np.testing.assert_allclose(ya, yb)
        np.testing.assert_allclose(wa, wb)
    it3.close()


# ---------------------------------------------------------------------------
# RecordIO splitter partition invariant: random binary payloads (incl.
# magic-embedding, multi-part frames) written through the writer, read
# back through the SPLIT engine over every partitioning — no record lost,
# duplicated, or corrupted (recordio_split.cc aligned-magic scan).

@SETTLE
@given(
    payloads=st.lists(_payload_st, min_size=1, max_size=30),
    num_parts=st.integers(min_value=1, max_value=4),
)
def test_recordio_split_partition_invariant(tmp_path_factory, payloads,
                                            num_parts):
    d = tmp_path_factory.mktemp("recsplit")
    path = d / "r.rec"
    with open(path, "wb") as f:
        w = RecordIOWriter(f)
        for pl in payloads:
            w.write_record(pl)

    got = []
    for part in range(num_parts):
        s = create_input_split(str(path), part, num_parts, "recordio",
                               threaded=False)
        got.extend(bytes(r) for r in s.iter_records())
        s.close()
    assert got == payloads


# ---------------------------------------------------------------------------
# BCOO shape bucketing is a mathematical no-op for ANY corpus: bucketed
# batches densify to exactly the unbucketed ones (padding rows empty,
# padded nnz masked OOB), while every emitted (nse, rows) is quantized.

@SETTLE
@given(
    rows=st.lists(
        st.lists(st.tuples(st.integers(0, 19),
                           st.floats(-10, 10, width=32)),
                 min_size=1, max_size=5),
        min_size=8, max_size=60),
    nnz_bucket=st.sampled_from([8, 32, 128]),
    batch=st.sampled_from([8, 16]),
)
def test_bcoo_bucketing_noop_random_corpora(tmp_path_factory, rows,
                                            nnz_bucket, batch):
    from dmlc_tpu.data.device import DeviceIter

    d = tmp_path_factory.mktemp("bcoo")
    p = d / "c.libsvm"
    _write_libsvm(p, rows)

    def run(bucket):
        parser = create_parser(str(p), 0, 1, "libsvm", threaded=False)
        it = DeviceIter(parser, num_col=20, batch_size=batch, layout="bcoo",
                        nnz_bucket=bucket)
        out = [(np.asarray(m.todense()), np.asarray(y), np.asarray(w),
                int(m.nse)) for m, y, w in it]
        it.close()
        return out

    bucketed = run(nnz_bucket)
    exact = run(0)
    assert len(bucketed) == len(exact)
    for (mb, yb, wb, nse), (me, ye, we, _) in zip(bucketed, exact):
        assert nse % nnz_bucket == 0
        # the ROW dimension is quantized too: every batch (tail included)
        # is padded to batch_size
        assert mb.shape[0] == batch and yb.shape == (batch,)
        np.testing.assert_allclose(mb, me, rtol=1e-6)
        np.testing.assert_allclose(yb, ye)
        np.testing.assert_allclose(wb, we)


# ---------------------------------------------------------------------------
# Parser engine parity: the native C++ scanner and the numpy engine must
# produce identical blocks for ANY valid libsvm corpus (the fixed-fixture
# version lives in test_native_reader.py; this explores row shapes).

@SETTLE
@given(
    rows=st.lists(
        st.lists(
            st.tuples(st.integers(0, 30),
                      st.floats(-100, 100, width=32)),
            min_size=0, max_size=6),
        min_size=1, max_size=40),
)
def test_libsvm_engine_parity_random_corpora(tmp_path_factory, rows):
    d = tmp_path_factory.mktemp("parity")
    p = d / "c.libsvm"
    _write_libsvm(p, rows, prec=".6g")

    def collect(native: bool):
        uri = str(p) + ("" if native else "?engine=python")
        parser = create_parser(uri, 0, 1, "libsvm", threaded=native)
        if native:
            _require_native(parser)
        vals, idxs, labels, counts = [], [], [], []
        for b in parser:
            # featureless blocks may carry None value/index arrays
            vals.append(np.asarray(
                b.value if b.value is not None else [], np.float32))
            idxs.append(np.asarray(
                b.index if b.index is not None else [], np.int64))
            labels.append(np.asarray(b.label))
            counts.append(len(b))
        parser.close()
        return (np.concatenate(vals) if vals else np.zeros(0, np.float32),
                np.concatenate(idxs) if idxs else np.zeros(0, np.int64),
                np.concatenate(labels) if labels else np.zeros(0),
                sum(counts))

    vn, ix_n, yn, n_n = collect(True)
    vp, ix_p, yp, n_p = collect(False)
    assert n_n == n_p == len(rows)
    np.testing.assert_array_equal(ix_n, ix_p)
    np.testing.assert_allclose(vn, vp, rtol=1e-6)
    np.testing.assert_allclose(yn, yp)


@SETTLE
@given(
    cells=st.lists(
        st.lists(st.floats(-1e4, 1e4, width=32), min_size=3, max_size=3),
        min_size=1, max_size=40),
    label_col=st.sampled_from([-1, 0, 1, 2]),
)
def test_csv_engine_parity_random_corpora(tmp_path_factory, cells,
                                          label_col):
    """Native stream CSV (split or cells path, chosen by label_col) vs the
    Python engine, row-for-row, on random numeric tables."""
    d = tmp_path_factory.mktemp("csvparity")
    p = d / "c.csv"
    p.write_text("\n".join(",".join(f"{v:.6g}" for v in row)
                           for row in cells) + "\n")
    base = str(p) + "?format=csv" + (
        f"&label_column={label_col}" if label_col >= 0 else "")

    def collect(native: bool):
        uri = base + ("" if native else "&engine=python")
        parser = create_parser(uri, 0, 1, threaded=native)
        if native:
            _require_native(parser)
        vals, labels = [], []
        for b in parser:
            vals.append(np.asarray(b.value, np.float32))
            labels.append(np.asarray(b.label))
        parser.close()
        return np.concatenate(vals), np.concatenate(labels)

    vn, yn = collect(True)
    vp, yp = collect(False)
    # anchor to the GENERATED corpus: a row-dropping bug shared by both
    # engines must not pass as parity
    assert len(yn) == len(yp) == len(cells)
    np.testing.assert_allclose(vn, vp, rtol=1e-6)
    np.testing.assert_allclose(yn, yp, rtol=1e-6)


@SETTLE
@given(
    rows=st.lists(
        st.lists(st.tuples(st.integers(0, 9), st.integers(0, 500),
                           st.floats(-100, 100, width=32)),
                 min_size=1, max_size=5),
        min_size=1, max_size=40),
)
def test_libfm_engine_parity_random_corpora(tmp_path_factory, rows):
    """Native libfm triple scanner vs the Python engine on random
    field:index:value rows."""
    d = tmp_path_factory.mktemp("fmparity")
    p = d / "c.libfm"
    lines = []
    for i, triples in enumerate(rows):
        triples = sorted({idx: (f, v) for f, idx, v in triples}.items())
        body = " ".join(f"{f}:{idx}:{v:.5g}" for idx, (f, v) in triples)
        lines.append(f"{i % 2} {body}")
    p.write_text("\n".join(lines) + "\n")

    def collect(native: bool):
        uri = str(p) + "?format=libfm" + ("" if native else "&engine=python")
        parser = create_parser(uri, 0, 1, threaded=native)
        if native:
            _require_native(parser)
        vals, idxs, flds, labels = [], [], [], []
        for b in parser:
            vals.append(np.asarray(b.value, np.float32))
            idxs.append(np.asarray(b.index, np.int64))
            flds.append(np.asarray(b.field, np.int64))
            labels.append(np.asarray(b.label))
        parser.close()
        return (np.concatenate(vals), np.concatenate(idxs),
                np.concatenate(flds), np.concatenate(labels))

    vn, ix_n, fn, yn = collect(True)
    vp, ix_p, fp, yp = collect(False)
    assert len(yn) == len(yp) == len(rows)
    np.testing.assert_array_equal(ix_n, ix_p)
    np.testing.assert_array_equal(fn, fp)
    np.testing.assert_allclose(vn, vp, rtol=1e-6)
    np.testing.assert_allclose(yn, yp)
