"""Tier-1 suite for the wire v2 data plane (docs/service.md Wire v2):
the v2 frame golden pin (v1 stays pinned separately), per-segment
compression round-trips (byte-identical raw payloads, dtype break-even
decisions, measured ratio ledger), torn/corrupt v2 frames classifying
retryable, the stream-open version negotiation matrix in both
directions, pipelined fetch failover with exact resilience counters,
the co-located mmap fast path (byte-identity with pins held through a
mid-epoch eviction squeeze), and the knob/autotuner seams
(``service_pipeline_depth``, ``DMLC_TPU_WIRE_COMPRESSION``)."""

from __future__ import annotations

import json
import os
import time

import numpy as np
import pytest

from dmlc_tpu.data.row_block import RowBlock
from dmlc_tpu.io import resilience
from dmlc_tpu.service import LocalFleet, ServiceParser
from dmlc_tpu.service import dispatcher as svc_dispatcher
from dmlc_tpu.service import frame as svc_frame
from dmlc_tpu.service import worker as svc_worker
from dmlc_tpu.utils import knobs as _knobs
from dmlc_tpu.utils import telemetry
from dmlc_tpu.utils.check import DMLCError

from tests.test_service import (  # noqa: F401  (corpus fixture)
    NUM_PARTS,
    PARSER_CFG,
    _assert_blocks_equal,
    _drain,
    _local_blocks,
    corpus,
)

DATA_DIR = os.path.join(os.path.dirname(__file__), "data")
GOLDEN_V2 = os.path.join(DATA_DIR, "service_frame_v2.golden")


# ---------------------------------------------------------------------------
# helpers

def _golden_v2_block() -> tuple:
    """The fixed (block, resume) pair the v2 golden pins — large enough
    that the integer segments clear the compression break-even floor."""
    rows, nnz = 32, 256
    off = np.linspace(0, nnz, rows + 1).astype(np.int64)
    off[-1] = nnz
    block = RowBlock(
        offset=off,
        label=(np.arange(rows, dtype=np.float32) % 2),
        index=(np.arange(nnz, dtype=np.uint64) * 7) % 997,
        value=(np.arange(nnz, dtype=np.float32) * 0.25 - 8.0),
    )
    resume = {"kind": "split",
              "split": {"kind": "byte", "file": 0, "offset": 123},
              "chunks": 7}
    return block, resume


def _golden_v2_frame() -> bytes:
    block, resume = _golden_v2_block()
    v1 = svc_frame.encode_block_frame(block, resume)
    _, meta, payload = svc_frame.decode_frame(v1)
    v2 = svc_frame.encode_block_frame_v2(meta, payload, "zlib")
    assert v2 is not None
    return v2


# ---------------------------------------------------------------------------
# wire format: golden pins and codec round-trips

def test_frame_v2_golden_bytes():
    """The v2 frame encoding is byte-pinned: header (version 2), meta
    normalization (codec / wire map / raw_len keys), zlib output, and
    crc all drift-proof."""
    with open(GOLDEN_V2, "rb") as f:
        want = f.read()
    assert _golden_v2_frame() == want


def test_frame_v2_golden_decodes_to_v1_payload():
    """Decode-of-golden parity: the pinned v2 bytes inflate to the EXACT
    raw v1 segment payload and rebuild the exact block + annotation."""
    block, resume = _golden_v2_block()
    v1 = svc_frame.encode_block_frame(block, resume)
    _, meta1, payload1 = svc_frame.decode_frame(v1)
    with open(GOLDEN_V2, "rb") as f:
        kind, meta2, payload2 = svc_frame.decode_frame(f.read())
    assert kind == svc_frame.KIND_BLOCK
    assert bytes(payload2) == bytes(payload1)
    got = svc_frame.block_from_frame(meta2, payload2)
    np.testing.assert_array_equal(got.offset, block.offset)
    np.testing.assert_array_equal(got.index, block.index)
    np.testing.assert_array_equal(got.value, block.value)
    assert json.dumps(meta2["resume"], sort_keys=True) == \
        json.dumps(resume, sort_keys=True)


def test_frame_v2_identity_reframe_zero_copy():
    """The identity v2 path rewrites ONLY the header version byte: the
    body (meta+payload+crc) is the stored v1 frame's bytes, untouched —
    what lets the worker hand mmap'd spans to a vectored send."""
    block, resume = _golden_v2_block()
    v1 = svc_frame.encode_block_frame(block, resume)
    header, body = svc_frame.reframe_v2(v1)
    assert bytes(body) == v1[svc_frame.HEADER_LEN:]
    frame = bytes(header) + bytes(body)
    kind, meta, payload = svc_frame.decode_frame(frame)
    _, meta1, payload1 = svc_frame.decode_frame(v1)
    assert kind == svc_frame.KIND_BLOCK
    assert bytes(payload) == bytes(payload1)
    assert meta == meta1


def test_compression_break_even_per_dtype():
    """Per-segment dtype decisions: integer segments (offsets/indices)
    compress, float values ship raw, tiny segments never compress —
    and the measured ratio ledger records what each dtype actually did."""
    v2 = _golden_v2_frame()
    _, meta, _ = svc_frame.decode_frame(v2)
    wire = meta["wire"]
    # offset (<i8) and index (<u8) compressed; value/label (<f4) raw
    enc_by_name = {name: bool(enc) for name, (_w, _l, enc) in wire.items()}
    assert enc_by_name["offset"] and enc_by_name["index"]
    assert not enc_by_name["value"] and not enc_by_name["label"]
    ratios = svc_frame.wire_dtype_ratios()
    assert ratios["<i8"] < 1.0 and ratios["<u8"] < 1.0
    assert ratios["<f4"] == 1.0


def test_compression_roundtrip_every_codec_available():
    """Round-trip byte-identity through every codec this process has
    (zstd/lz4 are import-gated — absent modules simply don't register,
    never crash)."""
    block, resume = _golden_v2_block()
    v1 = svc_frame.encode_block_frame(block, resume)
    _, meta, payload = svc_frame.decode_frame(v1)
    assert "zlib" in svc_frame.WIRE_CODECS  # stdlib floor, always there
    for codec in svc_frame.WIRE_CODECS:
        v2 = svc_frame.encode_block_frame_v2(meta, payload, codec)
        assert v2 is not None and len(v2) < len(v1)
        _, m2, p2 = svc_frame.decode_frame(v2)
        assert bytes(p2) == bytes(payload)
        assert m2["codec"] == codec


def test_incompressible_block_encodes_identity():
    """A block whose segments all sit under the break-even floor (or
    don't pay for the codec) returns None: the caller ships the
    reframed v1 bytes instead of a bigger 'compressed' frame."""
    block = RowBlock(
        offset=np.array([0, 2, 3, 5], np.int64),
        label=np.array([1.0, 0.0, 1.0], np.float32),
        index=np.array([1, 5, 7, 0, 3], np.uint64),
        value=np.array([0.5, 1.5, 2.5, -1.0, 4.25], np.float32),
    )
    v1 = svc_frame.encode_block_frame(block, None)
    _, meta, payload = svc_frame.decode_frame(v1)
    assert svc_frame.encode_block_frame_v2(meta, payload, "zlib") is None


def test_torn_and_corrupt_v2_frames_classify_retryable():
    """A truncated v2 frame and a crc byte-flip both raise
    ServiceFrameError, and the shared classifier calls it RETRYABLE —
    the client heals by re-requesting the exact block."""
    v2 = _golden_v2_frame()
    with pytest.raises(svc_frame.ServiceFrameError) as torn:
        svc_frame.decode_frame(v2[: len(v2) // 2])
    assert resilience.classify(torn.value) == resilience.RETRYABLE
    flipped = bytearray(v2)
    flipped[svc_frame.HEADER_LEN + 40] ^= 0xFF
    with pytest.raises(svc_frame.ServiceFrameError) as crc:
        svc_frame.decode_frame(bytes(flipped))
    assert resilience.classify(crc.value) == resilience.RETRYABLE


def test_negotiate_codec_preference_and_fallbacks():
    have = set(svc_frame.WIRE_CODECS)
    # both ends agree on the preferred available codec
    assert svc_frame.negotiate_codec(have) in have
    assert svc_frame.negotiate_codec(["zlib"]) == "zlib"
    # no overlap / unknown peer codecs -> identity, never an error
    assert svc_frame.negotiate_codec([]) is None
    assert svc_frame.negotiate_codec(["snappy", "brotli"]) is None


def test_wire_compression_knob_validated(monkeypatch):
    assert _knobs.wire_compression() == "auto"
    monkeypatch.setenv("DMLC_TPU_WIRE_COMPRESSION", "off")
    assert _knobs.wire_compression() == "off"
    assert _knobs.wire_compression("zlib") == "zlib"
    monkeypatch.setenv("DMLC_TPU_WIRE_COMPRESSION", "gzip9")
    with pytest.raises(DMLCError, match="wire compression"):
        _knobs.wire_compression()


def test_pipeline_depth_knob_row_and_resize(monkeypatch):
    assert _knobs.resolve("service_pipeline_depth") == 4
    monkeypatch.setenv("DMLC_TPU_SERVICE_PIPELINE_DEPTH", "16")
    assert _knobs.resolve("service_pipeline_depth") == 16
    monkeypatch.setenv("DMLC_TPU_SERVICE_PIPELINE_DEPTH", "0")
    with pytest.raises(DMLCError):
        _knobs.resolve("service_pipeline_depth")


# ---------------------------------------------------------------------------
# negotiation matrix (both directions) and the transport end to end

def test_v2_client_v1_worker_falls_back(corpus, monkeypatch):
    """An old worker ignores the v2 offer keys and pushes v1 frames from
    ``start``: the client's handshake peek sees a data frame instead of
    a HELLO, stashes it, and the epoch is byte-identical on the v1
    plane."""
    monkeypatch.setattr(
        svc_worker.ParseWorker, "_serve_stream_v2",
        lambda self, conn, rfile, job, part, accept, host:
            self._serve_stream(conn, job, part, 0))
    local = _local_blocks(corpus)
    fleet = LocalFleet(corpus, NUM_PARTS, num_workers=2,
                       parser=PARSER_CFG)
    try:
        sp = ServiceParser(fleet.address)
        got = _drain(sp)
        assert sp._wire == 1 and sp.fastpath_blocks == 0
        sp.close()
        _assert_blocks_equal(got, local)
    finally:
        fleet.close()


def test_v1_client_v2_worker_serves_v1(corpus):
    """An old client sends no ``wire`` offer: the v2 worker dispatches
    the plain v1 push stream and the epoch is byte-identical."""
    local = _local_blocks(corpus)
    fleet = LocalFleet(corpus, NUM_PARTS, num_workers=2,
                       parser=PARSER_CFG)
    try:
        sp = ServiceParser(fleet.address)
        sp._offer_wire = 1  # the compat escape hatch IS the old client
        got = _drain(sp)
        sp.close()
        _assert_blocks_equal(got, local)
    finally:
        fleet.close()


def test_v2_transport_byte_identical_with_wire_ledger(corpus):
    """The v2 acceptance core: a pipelined, compressed epoch is
    byte-identical to local parsing and the compression-ratio ledger
    (service_wire_bytes_raw/sent, job-labeled) measured a real
    reduction (integer segments compress on this corpus)."""
    local = _local_blocks(corpus)
    fleet = LocalFleet(corpus, NUM_PARTS, num_workers=2,
                       parser=PARSER_CFG)
    try:
        raw0 = telemetry.REGISTRY.counter(
            telemetry.SERVICE_WIRE_RAW_METRIC, job="default").value
        sent0 = telemetry.REGISTRY.counter(
            telemetry.SERVICE_WIRE_SENT_METRIC, job="default").value
        sp = ServiceParser(fleet.address)
        got = _drain(sp)
        sp.close()
        _assert_blocks_equal(got, local)
        raw = telemetry.REGISTRY.counter(
            telemetry.SERVICE_WIRE_RAW_METRIC, job="default").value - raw0
        sent = telemetry.REGISTRY.counter(
            telemetry.SERVICE_WIRE_SENT_METRIC,
            job="default").value - sent0
        assert raw > 0
        assert 0 < sent < raw  # compressed: strictly fewer wire bytes
    finally:
        fleet.close()


def test_wire_compression_off_ships_identity(corpus, monkeypatch):
    """``DMLC_TPU_WIRE_COMPRESSION=off`` pins the negotiated codec to
    identity: the ledger's sent bytes match raw (vectored reframe only),
    and the stream stays byte-identical."""
    monkeypatch.setenv("DMLC_TPU_WIRE_COMPRESSION", "off")
    local = _local_blocks(corpus)
    fleet = LocalFleet(corpus, NUM_PARTS, num_workers=2,
                       parser=PARSER_CFG)
    try:
        raw0 = telemetry.REGISTRY.counter(
            telemetry.SERVICE_WIRE_RAW_METRIC, job="default").value
        sent0 = telemetry.REGISTRY.counter(
            telemetry.SERVICE_WIRE_SENT_METRIC, job="default").value
        sp = ServiceParser(fleet.address)
        got = _drain(sp)
        assert sp._codec is None
        sp.close()
        _assert_blocks_equal(got, local)
        raw = telemetry.REGISTRY.counter(
            telemetry.SERVICE_WIRE_RAW_METRIC, job="default").value - raw0
        sent = telemetry.REGISTRY.counter(
            telemetry.SERVICE_WIRE_SENT_METRIC,
            job="default").value - sent0
        assert raw > 0 and sent == raw
    finally:
        fleet.close()


def test_kill_worker_mid_pipelined_stream_exact_counters(
        corpus, monkeypatch):
    """Failover under a deep in-flight window: a worker killed while the
    client has 8 pipelined fetches outstanding costs EXACTLY one
    service_retries and one service_failovers — the reconnect
    re-negotiates and re-issues the window from the exact block cursor,
    and the epoch stays byte-identical to local parsing."""
    monkeypatch.setenv("DMLC_TPU_SERVICE_PIPELINE_DEPTH", "8")
    local = _local_blocks(corpus, 4)
    fleet = LocalFleet(corpus, 4, num_workers=2, parser=PARSER_CFG)
    try:
        sp = ServiceParser(fleet.address)
        assert sp.pipeline_depth == 8
        base = resilience.counters_snapshot()
        got = [sp.next_block() for _ in range(7)]
        state = sp.state_dict()
        # kill the owner of the LAST part (its frames cannot already sit
        # in the client's TCP buffer), same scheme as the v1 acceptance
        deadline = time.time() + 5.0
        while time.time() < deadline:
            status = svc_dispatcher.request(fleet.address,
                                            {"cmd": "status"})
            if "3" in status["assigned"]:
                break
            time.sleep(0.02)
        victim = next(i for i, w in enumerate(fleet.workers)
                      if w.worker_id == status["assigned"]["3"])
        fleet.kill_worker(victim)
        got.extend(_drain(sp))
        sp.close()
        _assert_blocks_equal(got, local)
        delta = resilience.counters_delta(base)
        assert delta["service_retries"] == 1
        assert delta["service_failovers"] == 1
        assert delta["service_giveups"] == 0
        # mid-epoch checkpoint restores into a fresh pipelined client
        sp2 = ServiceParser(fleet.address)
        sp2.load_state(state)
        rest = _drain(sp2)
        sp2.close()
        _assert_blocks_equal(rest, local[7:])
    finally:
        fleet.close()


def test_resize_pipeline_depth_contract(corpus):
    fleet = LocalFleet(corpus, NUM_PARTS, num_workers=1,
                       parser=PARSER_CFG)
    try:
        sp = ServiceParser(fleet.address)
        assert sp.resize_pipeline_depth(8) is True
        assert sp.pipeline_depth == 8
        assert sp.resize_pipeline_depth(8) is False  # no-op
        assert sp.resize_pipeline_depth(0) is False  # below floor
        assert sp.pipeline_depth == 8
        sp.close()
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# co-located mmap fast path

def test_fastpath_byte_identity_through_eviction_squeeze(
        corpus, tmp_path, monkeypatch):
    """The zero-copy local fast path: a co-located client's second epoch
    serves EVERY block off the published block caches (no TCP), stays
    byte-identical — and a starvation-level byte budget armed mid-epoch
    evicts nothing while the client's reader pin holds. Once the fleet
    and client are gone the same budget pass evicts the artifacts,
    proving the pins were the protection."""
    from dmlc_tpu.store import reset_stores, store_for

    share = str(tmp_path / "share")
    local = _local_blocks(corpus)
    fleet = LocalFleet(corpus, NUM_PARTS, num_workers=2,
                       parser=PARSER_CFG, share_dir=share)
    cached = []
    try:
        sp = ServiceParser(fleet.address)
        _assert_blocks_equal(_drain(sp), local)
        cached = sorted(n for n in os.listdir(share) if ".part" in n)
        assert len(cached) == NUM_PARTS
        # epoch 2: every part is complete and published -> all mmap
        sp.before_first()
        fp0 = sp.fastpath_blocks  # the ledger is cumulative across epochs
        got = [sp.next_block() for _ in range(3)]
        assert sp.fastpath_blocks - fp0 >= 3  # the map is live mid-part
        # mid-epoch eviction squeeze: 1-byte budget + fresh store pass
        monkeypatch.setenv("DMLC_TPU_STORE_BUDGET_BYTES", "1")
        reset_stores()
        st = store_for(os.path.join(share, cached[0]))
        live = [e for e in st.entries() if not e["evicted"]]
        assert sorted(e["path"] for e in live) == cached
        assert all(e["pinned"] for e in live)
        got.extend(_drain(sp))
        _assert_blocks_equal(got, local)
        assert sp.fastpath_blocks - fp0 == len(local)  # zero TCP epoch
        sp.close()
    finally:
        fleet.close()
    # every pin dropped: the same budget pass now evicts the artifacts
    reset_stores()
    store_for(os.path.join(share, cached[0]))
    assert not [n for n in os.listdir(share) if ".part" in n]
    reset_stores()  # do not leak the budget-armed store to later tests


def test_fastpath_checkpoint_restore_exact_block(corpus, tmp_path):
    """A mid-epoch (part, block) checkpoint taken off the fast path
    restores into a FRESH client byte-identically — the fast path keeps
    the same cursor contract as the wire."""
    share = str(tmp_path / "share")
    local = _local_blocks(corpus)
    fleet = LocalFleet(corpus, NUM_PARTS, num_workers=2,
                       parser=PARSER_CFG, share_dir=share)
    try:
        sp = ServiceParser(fleet.address)
        _assert_blocks_equal(_drain(sp), local)  # publish the caches
        sp.before_first()
        first = [sp.next_block() for _ in range(7)]
        state = sp.state_dict()
        sp.close()
        sp2 = ServiceParser(fleet.address)
        sp2.load_state(state)
        rest = _drain(sp2)
        sp2.close()
        _assert_blocks_equal(first + rest, local)
    finally:
        fleet.close()
