"""Byte-order guards: golden LITTLE-ENDIAN byte vectors for every wire
format (VERDICT r3 missing #4 / the s390x CI analog,
/root/reference/scripts/travis/travis_script.sh:62-66).

These assert EMITTED bytes, not round-trips (a round-trip passes on any
host whatever the byte order) — mirroring the reference's endian golden
bytes (/root/reference/test/unittest/unittest_serializer.cc:86-110). On a
big-endian host a native-endian '@' slipping into a pack format, or a raw
``tobytes()`` of a native-order array, fails these exact-byte asserts.
The native core is guarded separately: api.h #errors at COMPILE time on a
big-endian target (its frame loads are memcpy-native by design), so wire
corruption there is impossible rather than detected.
"""

import io
import struct

import numpy as np

from dmlc_tpu.io.recordio import RECORDIO_MAGIC, RecordIOWriter
from dmlc_tpu.utils import serializer


def _emit(fn, *args) -> bytes:
    buf = io.BytesIO()
    fn(buf, *args)
    return buf.getvalue()


class TestSerializerGoldenBytes:
    def test_scalar_wire_bytes(self):
        # one golden vector per fixed-width kind (serializer.h:83-104
        # arithmetic handler, explicit LE on the wire)
        golden = [
            ("int8", -2, b"\xfe"),
            ("uint8", 0xAB, b"\xab"),
            ("int32", 0x01020304, b"\x04\x03\x02\x01"),
            ("uint32", 0xDEADBEEF, b"\xef\xbe\xad\xde"),
            ("int64", 0x0102030405060708, b"\x08\x07\x06\x05\x04\x03\x02\x01"),
            ("uint64", 1, b"\x01\x00\x00\x00\x00\x00\x00\x00"),
            # IEEE-754: 1.0f = 0x3f800000, 1.0 = 0x3ff0000000000000
            ("float32", 1.0, b"\x00\x00\x80\x3f"),
            ("float64", 1.0, b"\x00\x00\x00\x00\x00\x00\xf0\x3f"),
            ("bool", True, b"\x01"),
        ]
        for kind, value, want in golden:
            got = _emit(serializer.write_scalar, value, kind)
            assert got == want, (kind, got.hex(), want.hex())
            # and the reader decodes the golden bytes (not just its own)
            assert serializer.read_scalar(io.BytesIO(want), kind) == value

    def test_length_prefixed_bytes_and_str(self):
        # [u64 LE length][payload] (serializer.h string handler)
        assert _emit(serializer.write_bytes, b"hi") == (
            b"\x02\x00\x00\x00\x00\x00\x00\x00hi")
        assert _emit(serializer.write_str, "A") == (
            b"\x01\x00\x00\x00\x00\x00\x00\x00A")

    def test_ndarray_wire_bytes(self):
        # [dtype str]['<i4'][ndim u32][shape u64...][LE payload]
        arr = np.array([[1, 2]], dtype=np.int32)
        got = _emit(serializer.write_ndarray, arr)
        want = (
            b"\x03\x00\x00\x00\x00\x00\x00\x00<i4"  # dtype tag (u64-len str)
            + b"\x02\x00\x00\x00"                  # ndim = 2 (u32)
            + b"\x01\x00\x00\x00\x00\x00\x00\x00"  # shape[0] = 1
            + b"\x02\x00\x00\x00\x00\x00\x00\x00"  # shape[1] = 2
            + b"\x01\x00\x00\x00\x02\x00\x00\x00"  # data LE
        )
        assert got == want, got.hex()
        back = serializer.read_ndarray(io.BytesIO(want))
        np.testing.assert_array_equal(back, arr)

    def test_obj_tagged_wire_bytes(self):
        # tag u8 + payload; int rides int64 LE
        got = _emit(serializer.write_obj, 3)
        assert got[1:] == b"\x03\x00\x00\x00\x00\x00\x00\x00"
        got = _emit(serializer.write_obj, True)
        assert got[1:] == b"\x01"

    def test_big_endian_input_arrays_normalize(self):
        # a BE-ordered array must serialize to the same LE wire bytes
        arr_be = np.array([1, 2], dtype=">i4")
        arr_le = np.array([1, 2], dtype="<i4")
        assert _emit(serializer.write_ndarray, arr_be)[-8:] == \
            _emit(serializer.write_ndarray, arr_le)[-8:] == \
            b"\x01\x00\x00\x00\x02\x00\x00\x00"


class TestRecordIOGoldenBytes:
    def test_frame_exact_bytes(self):
        # [magic u32 LE][lrec u32 LE][data][pad] — the full 16-byte vector,
        # magic 0xced7230a on the wire as 0a 23 d7 ce (recordio.h:17-45)
        buf = io.BytesIO()
        RecordIOWriter(buf).write_record(b"abcde")
        assert buf.getvalue() == (
            b"\x0a\x23\xd7\xce"      # magic LE
            b"\x05\x00\x00\x00"      # lrec: cflag=0, len=5
            b"abcde"
            b"\x00\x00\x00"          # pad to 4
        )

    def test_escaped_frame_exact_bytes(self):
        # payload == magic: escaped as a 2-part record, the aligned magic
        # cell dropped (cflag 1 = start then 3 = end, both zero-length
        # parts; recordio.h:17-45 cflag semantics)
        buf = io.BytesIO()
        RecordIOWriter(buf).write_record(struct.pack("<I", RECORDIO_MAGIC))
        assert buf.getvalue() == (
            b"\x0a\x23\xd7\xce" + struct.pack("<I", (1 << 29) | 0)
            + b"\x0a\x23\xd7\xce" + struct.pack("<I", (3 << 29) | 0)
        )

    def test_native_extract_reads_le_wire(self):
        # the native reader must interpret the SAME golden bytes (its
        # compile-time guard makes BE hosts unbuildable, so a passing build
        # implies these loads are LE-correct)
        from dmlc_tpu import native

        if not native.available():
            import pytest

            pytest.skip("native core unavailable")
        wire = b"\x0a\x23\xd7\xce\x05\x00\x00\x00abcde\x00\x00\x00"
        payload, offsets = native.recordio_extract(wire)
        assert bytes(payload[offsets[0]:offsets[1]]) == b"abcde"
