"""Determinism property suite for the epoch planner (ISSUE 8).

The contract the shuffle-native warm cache must hold (docs/data.md):

- same ``(seed, epoch)`` => byte-identical stream, across runs and across
  ``parse_workers`` settings (the cache content is engine-invariant, the
  plan is a pure function);
- different seed (or epoch) => different order with the identical
  multiset of rows;
- per-host shards of one epoch are disjoint and their union equals the
  unsharded epoch;
- a mid-epoch checkpoint restores byte-identically into a FRESH
  pipeline, at the parser level and through ``DeviceIter``;
- cold epoch 0 stays sequential while shadow-writing (the documented
  caveat), the plan applies from the first warm epoch;
- a corrupt plan-served block heals by rebuild, stream unbroken.
"""

import os

import numpy as np
import pytest

from dmlc_tpu.data import create_parser
from dmlc_tpu.data.epoch import (
    EpochPlan,
    block_permutation,
    permute_block_rows,
    row_permutation,
    uniform_column_pattern,
)
from dmlc_tpu.data.row_block import RowBlock
from dmlc_tpu.io import faults
from dmlc_tpu.io.resilience import counters_delta, counters_snapshot

N_ROWS = 1200
CHUNK = 4096  # the split layer's minimum chunk hint -> ~8 blocks


def _write_corpus(tmp_path, n=N_ROWS):
    path = tmp_path / "plan.libsvm"
    with open(path, "w") as f:
        for i in range(n):
            # label identifies the row; values identify it redundantly so
            # row-level mixups cannot cancel out in comparisons
            f.write(f"{i} 0:{i}.0 1:{i}.5 2:0.25\n")
    return str(path)


def _rows(parser):
    """Drain to a list of per-row tuples — the byte-comparison unit."""
    out = []
    while (b := parser.next_block()) is not None:
        for i in range(len(b)):
            s, e = int(b.offset[i]), int(b.offset[i + 1])
            out.append((float(b.label[i]), tuple(b.index[s:e].tolist()),
                        tuple(np.asarray(b.value[s:e]).tolist())))
    return out


def _mk(path, cache, **kw):
    kw.setdefault("threaded", False)
    kw.setdefault("chunk_bytes", CHUNK)
    return create_parser(path, 0, 1, "libsvm", block_cache=cache, **kw)


# ---------------- plan unit properties ----------------

class TestPlanUnit:
    def test_block_permutation_pure_function(self):
        a = block_permutation(7, 3, 50)
        assert np.array_equal(a, block_permutation(7, 3, 50))
        assert not np.array_equal(a, block_permutation(7, 4, 50))
        assert not np.array_equal(a, block_permutation(8, 3, 50))
        assert sorted(a.tolist()) == list(range(50))

    def test_row_permutation_windowed_and_independent(self):
        rp = row_permutation(7, 3, 5, rows=10, window=4)
        # each window permutes only its own range
        assert sorted(rp[:4].tolist()) == [0, 1, 2, 3]
        assert sorted(rp[4:8].tolist()) == [4, 5, 6, 7]
        assert sorted(rp[8:].tolist()) == [8, 9]
        # keyed by (seed, epoch, block): computable without predecessors,
        # different blocks draw different orders
        assert np.array_equal(rp, row_permutation(7, 3, 5, 10, 4))
        full_a = row_permutation(7, 3, 5, rows=64, window=64)
        full_b = row_permutation(7, 3, 6, rows=64, window=64)
        assert not np.array_equal(full_a, full_b)
        # window<=1 / degenerate rows = identity
        assert row_permutation(7, 3, 5, rows=10, window=0) is None
        assert row_permutation(7, 3, 5, rows=1, window=8) is None

    def test_shards_partition_the_global_order(self):
        shards = [EpochPlan(7, 2, 23, num_hosts=3, host_id=h).order
                  for h in range(3)]
        union = np.concatenate(shards)
        assert sorted(union.tolist()) == list(range(23))
        assert abs(len(shards[0]) - len(shards[2])) <= 1
        # sequential (seed=None) plan: identity order, still sharded
        seq = EpochPlan(None, 2, 10, num_hosts=2, host_id=1)
        assert seq.order.tolist() == [1, 3, 5, 7, 9]
        assert not seq.permuted

    def test_permute_block_rows_gathers_csr(self):
        blk = RowBlock(offset=np.array([0, 2, 3, 6]),
                       label=np.array([0.0, 1.0, 2.0], np.float32),
                       index=np.array([10, 11, 20, 30, 31, 32], np.uint64),
                       value=np.array([1, 2, 3, 4, 5, 6], np.float32),
                       weight=np.array([.1, .2, .3], np.float32),
                       qid=np.array([5, 6, 7]))
        out = permute_block_rows(blk, np.array([2, 0, 1]))
        assert out.label.tolist() == [2.0, 0.0, 1.0]
        assert out.offset.tolist() == [0, 3, 5, 6]
        assert out.index.tolist() == [30, 31, 32, 10, 11, 20]
        assert out.value.tolist() == [4, 5, 6, 1, 2, 3]
        assert out.weight.tolist() == pytest.approx([.3, .1, .2])
        assert out.qid.tolist() == [7, 5, 6]
        assert not uniform_column_pattern(blk)  # ragged rows

    def test_uniform_column_pattern_skips_id_gathers(self):
        # HIGGS/Criteo-like: every row carries the same column ids, so
        # index is permutation-invariant and passes through un-gathered
        n, k = 4, 3
        blk = RowBlock(
            offset=np.arange(0, (n + 1) * k, k),
            label=np.arange(n, dtype=np.float32),
            index=np.tile(np.array([5, 7, 9], np.uint64), n),
            value=np.arange(n * k, dtype=np.float32))
        assert uniform_column_pattern(blk)
        perm = np.array([3, 1, 0, 2])
        fast = permute_block_rows(blk, perm, uniform_columns=True)
        slow = permute_block_rows(blk, perm, uniform_columns=False)
        assert fast.index is blk.index  # invariant array passed through
        assert np.array_equal(fast.index, slow.index)
        assert np.array_equal(fast.value, slow.value)
        assert np.array_equal(fast.label, slow.label)
        # mixed column ids must fail the detection
        ragged_ids = RowBlock(
            offset=np.arange(0, (n + 1) * k, k),
            label=np.arange(n, dtype=np.float32),
            index=np.arange(n * k, dtype=np.uint64))
        assert not uniform_column_pattern(ragged_ids)


# ---------------- end-to-end determinism ----------------

class TestDeterminism:
    def test_cold_sequential_then_planned_warm_epochs(self, tmp_path):
        path = _write_corpus(tmp_path)
        parser = _mk(path, str(tmp_path / "c.bc"),
                     shuffle_seed=42, shuffle_window=8)
        cold = _rows(parser)
        assert [r[0] for r in cold] == [float(i) for i in range(N_ROWS)], \
            "cold epoch 0 must stay sequential while shadow-writing"
        parser.before_first()
        assert parser._reader.num_blocks > 4  # the plan has blocks to order
        warm1 = _rows(parser)
        parser.before_first()
        warm2 = _rows(parser)
        parser.close()
        assert sorted(warm1) == sorted(cold) and warm1 != cold
        assert sorted(warm2) == sorted(cold) and warm2 != warm1, \
            "each epoch draws a fresh permutation"

    def test_same_seed_epoch_byte_identical_across_runs_and_engines(
            self, tmp_path):
        path = _write_corpus(tmp_path)
        # two caches built by different engines/fan-outs...
        streams = {}
        for tag, kw in (("w1", dict(parse_workers=1)),
                        ("w4", dict(threaded=True, parse_workers=4))):
            cache = str(tmp_path / f"{tag}.bc")
            build = _mk(path, cache, shuffle_seed=9, shuffle_window=16, **kw)
            _rows(build)
            build.close()
            # ...serve a fresh warm pipeline each: epoch 0 plan order
            warm = _mk(path, cache, shuffle_seed=9, shuffle_window=16)
            streams[tag] = _rows(warm)
            warm.close()
        assert streams["w1"] == streams["w4"], \
            "same (seed, epoch) => byte-identical across parse_workers"
        again = _mk(path, str(tmp_path / "w1.bc"),
                    shuffle_seed=9, shuffle_window=16)
        assert _rows(again) == streams["w1"], "and across runs"
        again.close()

    def test_different_seed_different_order_same_multiset(self, tmp_path):
        path = _write_corpus(tmp_path)
        cache = str(tmp_path / "c.bc")
        build = _mk(path, cache)
        base = _rows(build)
        build.close()
        a = _mk(path, cache, shuffle_seed=1, shuffle_window=32)
        b = _mk(path, cache, shuffle_seed=2, shuffle_window=32)
        ra, rb = _rows(a), _rows(b)
        a.close(), b.close()
        assert ra != rb
        assert sorted(ra) == sorted(rb) == sorted(base)

    def test_pod_shards_disjoint_union_equals_epoch(self, tmp_path):
        path = _write_corpus(tmp_path)
        cache = str(tmp_path / "c.bc")
        build = _mk(path, cache)
        _rows(build)
        build.close()
        kw = dict(shuffle_seed=7, shuffle_window=8)
        shards = []
        for h in range(3):
            p = _mk(path, cache, pod_sharding=(h, 3), **kw)
            assert p.plan_state["num_hosts"] == 3
            shards.append(_rows(p))
            p.close()
        full = _mk(path, cache, **kw)
        whole = _rows(full)
        full.close()
        sets = [set(s) for s in shards]
        assert sets[0].isdisjoint(sets[1]) and sets[0].isdisjoint(sets[2]) \
            and sets[1].isdisjoint(sets[2])
        assert sorted(sum(shards, [])) == sorted(whole)
        # row orders inside a block are host-independent: each shard's
        # stream is a subsequence-by-blocks of the global plan's serve
        assert all(s != whole for s in shards)

    def test_sharded_cold_pass_disjoint_too(self, tmp_path):
        path = _write_corpus(tmp_path)
        streams = []
        for h in range(2):
            cache = str(tmp_path / f"h{h}.bc")  # per-host cache files
            p = _mk(path, cache, shuffle_seed=3, pod_sharding=(h, 2))
            streams.append(_rows(p))
            p.close()
        assert set(streams[0]).isdisjoint(streams[1])
        union = sorted(streams[0] + streams[1])
        assert [r[0] for r in union] == [float(i) for i in range(N_ROWS)]

    def test_mid_epoch_resume_byte_identical_fresh_pipeline(self, tmp_path):
        path = _write_corpus(tmp_path)
        cache = str(tmp_path / "c.bc")
        kw = dict(shuffle_seed=5, shuffle_window=8)
        build = _mk(path, cache, **kw)
        _rows(build)
        build.close()
        parser = _mk(path, cache, **kw)
        head = []
        for _ in range(3):
            b = parser.next_block()
            for i in range(len(b)):
                head.append(float(b.label[i]))
        state = parser.state_dict()
        assert state["kind"] == "epoch_plan"
        assert state["seed"] == 5 and state["pos"] == 3  # (seed,epoch,pos)
        tail = _rows(parser)
        parser.close()
        fresh = _mk(path, cache, **kw)
        fresh.load_state(state)
        assert _rows(fresh) == tail
        fresh.close()
        # the state even restores into a pipeline built with DIFFERENT
        # knobs: the annotation's plan identity wins (byte-identity first)
        other = _mk(path, cache, shuffle_seed=99, shuffle_window=2)
        other.load_state(state)
        assert _rows(other) == tail
        other.close()

    def test_deviceiter_checkpoint_restores_plan_stream(self, tmp_path):
        from dmlc_tpu.data.device import DeviceIter

        path = _write_corpus(tmp_path)
        cache = str(tmp_path / "c.bc")
        kw = dict(shuffle_seed=11, shuffle_window=8)
        build = _mk(path, cache, **kw)
        _rows(build)
        build.close()

        def harvest(it, limit=None):
            out = []
            for x, y, w in it:
                out.append(np.asarray(y).tolist())
                if limit and len(out) >= limit:
                    break
            return out

        it = DeviceIter(_mk(path, cache, **kw), num_col=3, batch_size=128,
                        layout="dense")
        head = harvest(it, limit=3)
        state = it.state_dict()
        stats = it.stats()
        assert stats["shuffle_seed"] == 11 and stats["epoch"] == 0
        assert stats["cache_state"] == "warm"
        assert stats["stages"].get("cache_read", 0.0) > 0.0
        tail = harvest(it)
        it.close()
        it2 = DeviceIter(_mk(path, cache, **kw), num_col=3, batch_size=128,
                         layout="dense")
        it2.load_state(state)
        tail2 = harvest(it2)
        it2.close()
        assert tail2 == tail, \
            "mid-epoch DeviceIter restore replays byte-identically"

    def test_cold_state_restores_into_plan_pipeline_sequentially(
            self, tmp_path):
        path = _write_corpus(tmp_path)
        cache = str(tmp_path / "c.bc")
        cold = _mk(path, cache, shuffle_seed=4)
        for _ in range(3):
            cold.next_block()
        state = cold.state_dict()  # a parser-chain split state
        rest_cold = _rows(cold)  # completing the pass publishes the cache
        cold.close()
        assert os.path.exists(cache)
        # restore the cold checkpoint into a warm plan-armed pipeline:
        # the remainder must match the cold stream (sequential), the plan
        # only resuming at the next epoch
        warm = _mk(path, cache, shuffle_seed=4)
        warm.load_state(state)
        assert warm.plan_state["order"] == "sequential"
        assert _rows(warm) == rest_cold
        # ...and the NEXT epoch returns to plan order
        warm.before_first()
        nxt = _rows(warm)
        assert sorted(nxt) == sorted(rest_cold + _head_rows(path, 3))
        assert warm.plan_state["order"] == "plan"
        warm.close()


def _head_rows(path, nblocks):
    """The first ``nblocks`` blocks' rows of a sequential parse."""
    p = create_parser(path, 0, 1, "libsvm", threaded=False,
                      chunk_bytes=CHUNK)
    out = []
    for _ in range(nblocks):
        b = p.next_block()
        for i in range(len(b)):
            s, e = int(b.offset[i]), int(b.offset[i + 1])
            out.append((float(b.label[i]), tuple(b.index[s:e].tolist()),
                        tuple(np.asarray(b.value[s:e]).tolist())))
    p.close()
    return out


# ---------------- resilience + plumbing ----------------

class TestPlanResilience:
    def test_corrupt_plan_block_heals_by_rebuild(self, tmp_path):
        path = _write_corpus(tmp_path)
        cache = str(tmp_path / "c.bc")
        kw = dict(shuffle_seed=6, shuffle_window=8)
        build = _mk(path, cache, **kw)
        _rows(build)
        build.close()
        clean = _mk(path, cache, **kw)
        expect = _rows(clean)
        clean.close()
        before = counters_snapshot()
        with faults.inject("cache_read@3=corrupt"):
            parser = _mk(path, cache, **kw)
            healed = _rows(parser)
            parser.close()
        delta = counters_delta(before)
        assert healed == expect, "stream unbroken through the rebuild"
        assert delta.get("cache_corruptions") == 1
        assert delta.get("cache_rebuilds") == 1

    def test_plan_state_restore_rebuilds_missing_cache(self, tmp_path):
        path = _write_corpus(tmp_path)
        cache = str(tmp_path / "c.bc")
        kw = dict(shuffle_seed=8, shuffle_window=4)
        build = _mk(path, cache, **kw)
        _rows(build)
        build.close()
        parser = _mk(path, cache, **kw)
        for _ in range(2):
            parser.next_block()
        state = parser.state_dict()
        tail = _rows(parser)
        parser.close()
        os.remove(cache)  # the cache vanishes between save and restore
        fresh = _mk(path, cache, **kw)
        fresh.load_state(state)
        assert _rows(fresh) == tail
        fresh.close()
        assert os.path.exists(cache), "restore republished the cache"

    def test_one_cache_serves_every_plan(self, tmp_path):
        # plan knobs are outside the cache signature: arming/armless and
        # different seeds must NOT invalidate (no rebuild between them)
        path = _write_corpus(tmp_path)
        cache = str(tmp_path / "c.bc")
        build = _mk(path, cache)
        _rows(build)
        build.close()
        mtime = os.path.getmtime(cache)
        for kw in (dict(shuffle_seed=1), dict(shuffle_seed=2),
                   dict(shuffle_seed=1, pod_sharding=(0, 2)), {}):
            p = _mk(path, cache, **kw)
            assert p.cache_state == "warm"
            p.next_block()
            p.close()
        assert os.path.getmtime(cache) == mtime


class TestPlumbing:
    def test_plan_requires_block_cache(self, tmp_path):
        from dmlc_tpu.utils.check import DMLCError

        path = _write_corpus(tmp_path)
        with pytest.raises(DMLCError, match="require a block_cache"):
            create_parser(path, 0, 1, "libsvm", shuffle_seed=1)
        with pytest.raises(DMLCError, match="requires shuffle_seed"):
            # a window alone would silently serve sequential epochs
            create_parser(path, 0, 1, "libsvm",
                          block_cache=str(tmp_path / "c.bc"),
                          shuffle_window=4096)
        with pytest.raises(DMLCError, match="double-shard"):
            create_parser(path, 0, 2, "libsvm",
                          block_cache=str(tmp_path / "c.bc"),
                          shuffle_seed=1, pod_sharding=(0, 2))
        with pytest.raises(DMLCError, match="dispatcher owns the dataset's "
                                            "plan"):
            # the service branch must reject, not silently drop, the knobs
            create_parser(path, 0, 1, "libsvm",
                          service="127.0.0.1:1", shuffle_seed=1)

    def test_legacy_seed_stays_out_of_cache_signature(self, tmp_path):
        # the mapped legacy seed must NOT invalidate the cache: one cache
        # serves every seed, and the migration path (shuffle_seed=) must
        # hit the cache a legacy run (shuffle=True, seed=) built
        path = _write_corpus(tmp_path)
        cache = str(tmp_path / "c.bc")
        with pytest.warns(DeprecationWarning):
            legacy = create_parser(path, 0, 1, "libsvm", threaded=False,
                                   chunk_bytes=CHUNK, block_cache=cache,
                                   shuffle=True, seed=1)
        _rows(legacy)
        legacy.close()
        mtime = os.path.getmtime(cache)
        for kw in (dict(shuffle_seed=1, shuffle_window=4096), {}):
            p = _mk(path, cache, **kw)
            assert p.cache_state == "warm", kw
            p.close()
        with pytest.warns(DeprecationWarning):
            legacy2 = create_parser(path, 0, 1, "libsvm", threaded=False,
                                    chunk_bytes=CHUNK, block_cache=cache,
                                    shuffle=True, seed=2)
        assert legacy2.cache_state == "warm"
        legacy2.close()
        assert os.path.getmtime(cache) == mtime

    def test_pod_identity_resolution(self, monkeypatch):
        from dmlc_tpu.parallel.distributed import pod_identity

        monkeypatch.setenv("DMLC_TASK_ID", "2")
        monkeypatch.setenv("DMLC_NUM_WORKER", "4")
        assert pod_identity() == (2, 4)
        monkeypatch.delenv("DMLC_TASK_ID")
        monkeypatch.delenv("DMLC_NUM_WORKER")
        assert pod_identity() == (0, 1)  # single host, no jax pod

    def test_create_row_block_iter_pod_entry_point(self, tmp_path,
                                                   monkeypatch):
        from dmlc_tpu.data import create_row_block_iter

        path = _write_corpus(tmp_path, n=300)
        cache = str(tmp_path / "c.bc")
        build = _mk(path, cache)
        _rows(build)
        build.close()
        monkeypatch.setenv("DMLC_TASK_ID", "1")
        monkeypatch.setenv("DMLC_NUM_WORKER", "2")
        it = create_row_block_iter(path, block_cache=cache, shuffle_seed=3,
                                   pod_sharding=True, threaded=False,
                                   chunk_bytes=CHUNK, silent=True)
        blk = it.next_block()
        full = sum(1 for _ in open(path))
        assert 0 < len(blk) < full, "the iterator drained one disjoint shard"
        it.close()

    def test_dispatcher_ships_plan_to_fleet(self, tmp_path):
        from dmlc_tpu.service import Dispatcher, ServiceParser
        from dmlc_tpu.service import dispatcher as _dispatch

        disp = Dispatcher("dummy.libsvm", 2, parser={"format": "libsvm"},
                          plan={"shuffle_seed": 13, "shuffle_window": 8})
        try:
            cfg = _dispatch.request(disp.address, {"cmd": "config"})
            assert cfg["plan"] == {"shuffle_seed": 13, "shuffle_window": 8}
        finally:
            disp.close()

    def test_fleet_ships_plan_but_serves_parse_order(self, tmp_path):
        from dmlc_tpu.service import LocalFleet, ServiceParser

        path = _write_corpus(tmp_path, n=400)
        fleet = LocalFleet(
            path, 2, num_workers=2,
            parser={"format": "libsvm", "chunk_bytes": CHUNK,
                    "threaded": False,
                    "block_cache": str(tmp_path / "svc.bc")},
            plan={"shuffle_seed": 21, "shuffle_window": 4})
        client = None
        try:
            client = ServiceParser(fleet.address)
            # the plan identity reaches every party...
            assert client.shuffle_seed == 21
            assert all(w.plan.get("shuffle_seed") == 21
                       for w in fleet.workers)
            # ...but the wire stays PARSE-order (the failover-resume
            # byte-identity contract): the stream equals local sequential
            # parsing, never a plan permutation
            got = _rows(client)
            expect = []
            for part in range(2):
                p = create_parser(path, part, 2, "libsvm", threaded=False,
                                  chunk_bytes=CHUNK)
                expect.extend(_rows(p))
                p.close()
            assert got == expect
        finally:
            if client is not None:
                client.close()
            fleet.close()
