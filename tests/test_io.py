"""IO layer tests: URI, filesystems, RecordIO, ThreadedIter, InputSplit.

Follows the reference test strategy (SURVEY.md §4): sharding correctness is
tested by looping every part_index in-process over tempdir/in-memory corpora
(unittest_inputsplit.cc pattern), parse/pipeline failure injection mirrors
unittest_threaditer_exc_handling.cc.
"""

import io
import os
import struct

import pytest

from dmlc_tpu.io import (
    URI, URISpec, MemoryFileSystem, RecordIOChunkReader, RecordIOReader,
    RecordIOWriter, RECORDIO_MAGIC, ThreadedIter, create_input_split,
    get_filesystem, open_stream,
)
from dmlc_tpu.io.input_split import LineSplitter, ShuffledInputSplit
from dmlc_tpu.utils.check import DMLCError


# ---------------- URI ----------------

def test_uri_parse():
    u = URI("hdfs://namenode:9000/path/file.txt")
    assert u.protocol == "hdfs://"
    assert u.host == "namenode:9000"
    assert u.name == "/path/file.txt"
    local = URI("/tmp/x.txt")
    assert local.protocol == "file://" and local.name == "/tmp/x.txt"


def test_urispec():
    s = URISpec("s3://b/key?format=libsvm&clabel=0#cachefile", 2, 4)
    assert s.uri == "s3://b/key"
    assert s.args == {"format": "libsvm", "clabel": "0"}
    assert s.cache_file == "cachefile.split4.part2"
    s1 = URISpec("path#cache", 0, 1)
    assert s1.cache_file == "cache"  # single part: no suffix (uri_spec.h:50)
    s2 = URISpec("plain/path")
    assert s2.cache_file is None and s2.args == {}
    with pytest.raises(DMLCError):
        URISpec("a#b#c")


# ---------------- filesystems ----------------

def test_local_fs(tmp_path):
    p = tmp_path / "data.txt"
    p.write_bytes(b"hello")
    fs = get_filesystem(str(p))
    info = fs.get_path_info(URI(str(p)))
    assert info.size == 5 and info.type == "file"
    listing = fs.list_directory(URI(str(tmp_path)))
    assert any(i.path.name.endswith("data.txt") for i in listing)
    with open_stream(str(p)) as f:
        assert f.read() == b"hello"
    assert open_stream(str(tmp_path / "missing.txt"), "r", allow_null=True) is None
    with pytest.raises(DMLCError):
        open_stream(str(tmp_path / "missing.txt"))


def test_mem_fs():
    MemoryFileSystem.reset()
    with open_stream("mem://bucket/a.txt", "w") as f:
        f.write(b"abc")
    with open_stream("mem://bucket/sub/b.txt", "w") as f:
        f.write(b"defg")
    fs = get_filesystem("mem://bucket/a.txt")
    assert fs.get_path_info(URI("mem://bucket/a.txt")).size == 3
    names = {i.path.raw for i in fs.list_directory(URI("mem://bucket"))}
    assert "mem://bucket/a.txt" in names
    rec = fs.list_directory_recursive(URI("mem://bucket"))
    assert sum(i.size for i in rec) == 7
    with open_stream("mem://bucket/a.txt") as f:
        assert f.read() == b"abc"


def test_unknown_protocol():
    with pytest.raises(DMLCError):
        get_filesystem("zz://x/y")


# ---------------- recordio ----------------

def test_recordio_roundtrip():
    buf = io.BytesIO()
    writer = RecordIOWriter(buf)
    records = [b"hello", b"", b"world!!", b"x" * 1000]
    for r in records:
        writer.write_record(r)
    buf.seek(0)
    out = list(RecordIOReader(buf))
    assert out == records


def test_recordio_golden_layout():
    # format spec recordio.h:17-45: [magic][lrec][data][pad]
    buf = io.BytesIO()
    RecordIOWriter(buf).write_record(b"abcde")
    raw = buf.getvalue()
    magic, lrec = struct.unpack_from("<II", raw, 0)
    assert magic == RECORDIO_MAGIC == 0xCED7230A
    assert lrec >> 29 == 0 and lrec & ((1 << 29) - 1) == 5
    assert raw[8:13] == b"abcde" and raw[13:16] == b"\x00\x00\x00"
    assert len(raw) == 16


def test_recordio_magic_escape():
    # payload containing the magic at an aligned cell must be escaped
    magic_bytes = struct.pack("<I", RECORDIO_MAGIC)
    payloads = [
        magic_bytes,                        # exactly magic
        b"abcd" + magic_bytes + b"efgh",    # aligned mid-payload
        magic_bytes * 3,                    # consecutive magics
        b"ab" + magic_bytes + b"cd",        # UNaligned: no escape needed
    ]
    buf = io.BytesIO()
    writer = RecordIOWriter(buf)
    for p in payloads:
        writer.write_record(p)
    assert writer.except_counter >= 5
    buf.seek(0)
    assert list(RecordIOReader(buf)) == payloads


def test_recordio_chunk_reader_parts():
    buf = io.BytesIO()
    writer = RecordIOWriter(buf)
    records = [f"rec{i}".encode() * (i % 7 + 1) for i in range(100)]
    for r in records:
        writer.write_record(r)
    chunk = buf.getvalue()
    for nparts in (1, 2, 3, 8):
        got = []
        for part in range(nparts):
            got.extend(bytes(r) for r in RecordIOChunkReader(chunk, part, nparts))
        assert got == records, f"nparts={nparts}"


# ---------------- ThreadedIter ----------------

def test_threaded_iter_order_and_recycle():
    it = ThreadedIter.from_factory(lambda: iter(range(100)), max_capacity=4)
    got = []
    while True:
        v = it.next()
        if v is None:
            break
        got.append(v)
        it.recycle(v)
    assert got == list(range(100))
    it.destroy()


def test_threaded_iter_before_first():
    it = ThreadedIter.from_factory(lambda: iter(range(10)), max_capacity=2)
    assert it.next() == 0
    assert it.next() == 1
    it.before_first()  # epoch reset mid-stream (threadediter.h:210-235)
    got = list(it)
    assert got == list(range(10))
    it.before_first()
    assert list(it) == list(range(10))
    it.destroy()


def test_threaded_iter_stall_watchdog(monkeypatch):
    """DMLC_PIPELINE_STALL_TIMEOUT: a live-but-wedged producer (hung device
    transfer, dead tunnel) raises a diagnosable error instead of blocking
    the consumer forever. Off by default."""
    import threading as _threading

    release = _threading.Event()

    def gen():
        yield 1
        release.wait(30)  # wedge until the test releases us
        yield 2

    it = ThreadedIter.from_factory(lambda: gen(), max_capacity=1)
    assert it.next() == 1
    monkeypatch.setenv("DMLC_PIPELINE_STALL_TIMEOUT", "0.3")
    with pytest.raises(DMLCError, match="pipeline stalled.*alive but blocked"):
        it.next()
    # un-wedge: with the watchdog off again the stream continues normally
    monkeypatch.delenv("DMLC_PIPELINE_STALL_TIMEOUT")
    release.set()
    assert it.next() == 2
    it.destroy()


def test_threaded_iter_exception_propagation():
    # mirror unittest_threaditer_exc_handling.cc:25-60
    def gen():
        for i in range(50):
            if i == 20:
                raise DMLCError("injected producer failure")
            yield i

    it = ThreadedIter.from_factory(gen, max_capacity=4)
    got = []
    with pytest.raises(DMLCError, match="injected"):
        while True:
            v = it.next()
            if v is None:
                break
            got.append(v)
    assert got == list(range(20))
    it.destroy()


def test_threaded_iter_exception_in_before_first():
    state = {"n": 0}

    def factory():
        state["n"] += 1
        if state["n"] == 2:
            raise ValueError("reset failure")
        return iter(range(3))

    it = ThreadedIter.from_factory(factory, max_capacity=2)
    assert list(it) == [0, 1, 2]
    with pytest.raises(ValueError, match="reset failure"):
        it.before_first()
    it.destroy()


# ---------------- InputSplit: line ----------------

def _write_corpus(tmp_path, contents):
    paths = []
    for i, data in enumerate(contents):
        p = tmp_path / f"part{i:02d}.txt"
        p.write_bytes(data)
        paths.append(str(p))
    return ";".join(paths)


def _collect_all_parts(uri, num_parts, type_="text", threaded=False, **kw):
    per_part = []
    for part in range(num_parts):
        split = create_input_split(uri, part, num_parts, type_, threaded=threaded, **kw)
        per_part.append([bytes(r) for r in split.iter_records()])
        split.close()
    return per_part


LINES = [f"line-{i:04d} value:{i * 3}".encode() for i in range(500)]


@pytest.mark.parametrize("num_parts", [1, 2, 3, 5, 8])
def test_line_split_no_loss_no_dup(tmp_path, num_parts):
    # 3 files, all ending with newline
    third = len(LINES) // 3
    contents = [
        b"\n".join(LINES[:third]) + b"\n",
        b"\n".join(LINES[third:2 * third]) + b"\n",
        b"\n".join(LINES[2 * third:]) + b"\n",
    ]
    uri = _write_corpus(tmp_path, contents)
    parts = _collect_all_parts(uri, num_parts)
    merged = [r for p in parts for r in p]
    assert merged == LINES, f"num_parts={num_parts}"


@pytest.mark.parametrize("num_parts", [1, 2, 4, 7])
def test_line_split_noeol_files(tmp_path, num_parts):
    # files WITHOUT trailing newline: the PR#385/PR#452 cases
    third = len(LINES) // 3
    contents = [
        b"\n".join(LINES[:third]),            # NOEOL
        b"\n".join(LINES[third:2 * third]),   # NOEOL
        b"\n".join(LINES[2 * third:]),        # NOEOL
    ]
    uri = _write_corpus(tmp_path, contents)
    parts = _collect_all_parts(uri, num_parts)
    merged = [r for p in parts for r in p]
    assert merged == LINES, f"num_parts={num_parts}"


def test_line_split_crlf_and_blank_lines(tmp_path):
    data = b"a\r\nb\n\n\nc\r\rd\ne"
    p = tmp_path / "f.txt"
    p.write_bytes(data)
    parts = _collect_all_parts(str(p), 1)
    assert parts[0] == [b"a", b"b", b"c", b"d", b"e"]


def test_line_split_record_larger_than_chunk(tmp_path):
    # force the buffer-doubling path (Chunk::Load, input_split_base.cc:260-277)
    big = b"x" * 5000
    data = b"\n".join([b"small", big, b"tail"]) + b"\n"
    p = tmp_path / "f.txt"
    p.write_bytes(data)
    for num_parts in (1, 2):
        got = []
        for part in range(num_parts):
            split = create_input_split(
                str(p), part, num_parts, "text", threaded=False, chunk_bytes=64
            )
            got.extend(bytes(r) for r in split.iter_records())
            split.close()
        assert got == [b"small", big, b"tail"]


def test_line_split_before_first_epoch(tmp_path):
    uri = _write_corpus(tmp_path, [b"\n".join(LINES[:50]) + b"\n"])
    split = create_input_split(uri, 0, 1, "text", threaded=False)
    first = [bytes(r) for r in split.iter_records()]
    split.before_first()
    second = [bytes(r) for r in split.iter_records()]
    assert first == second == LINES[:50]
    split.close()


def test_line_split_on_memfs():
    MemoryFileSystem.reset()
    with open_stream("mem://c/a.txt", "w") as f:
        f.write(b"\n".join(LINES[:100]))
    with open_stream("mem://c/b.txt", "w") as f:
        f.write(b"\n".join(LINES[100:200]))
    uri = "mem://c/a.txt;mem://c/b.txt"
    parts = _collect_all_parts(uri, 3)
    merged = [r for p in parts for r in p]
    assert merged == LINES[:200]


def test_line_split_directory_expansion(tmp_path):
    d = tmp_path / "corpus"
    d.mkdir()
    (d / "a.txt").write_bytes(b"1\n2\n")
    (d / "b.txt").write_bytes(b"3\n4\n")
    parts = _collect_all_parts(str(d), 1)
    assert parts[0] == [b"1", b"2", b"3", b"4"]


def test_line_split_regex_expansion(tmp_path):
    (tmp_path / "data-0.txt").write_bytes(b"a\n")
    (tmp_path / "data-1.txt").write_bytes(b"b\n")
    (tmp_path / "other.log").write_bytes(b"z\n")
    pattern = str(tmp_path / "data-.*\\.txt")
    parts = _collect_all_parts(pattern, 1)
    assert parts[0] == [b"a", b"b"]


def test_threaded_input_split_matches(tmp_path):
    uri = _write_corpus(tmp_path, [b"\n".join(LINES) + b"\n"])
    for num_parts in (1, 3):
        got = []
        for part in range(num_parts):
            split = create_input_split(uri, part, num_parts, "text", threaded=True)
            got.extend(bytes(r) for r in split.iter_records())
            split.close()
        assert got == LINES


def test_threaded_input_split_epoch_reset(tmp_path):
    uri = _write_corpus(tmp_path, [b"\n".join(LINES[:30]) + b"\n"])
    split = create_input_split(uri, 0, 1, "text", threaded=True)
    a = [bytes(r) for r in split.iter_records()]
    split.before_first()
    b = [bytes(r) for r in split.iter_records()]
    assert a == b == LINES[:30]
    split.close()


# ---------------- InputSplit: recordio ----------------

def _write_rec_files(tmp_path, records, nfiles):
    per = (len(records) + nfiles - 1) // nfiles
    paths = []
    for i in range(nfiles):
        p = tmp_path / f"data{i}.rec"
        with open(p, "wb") as f:
            w = RecordIOWriter(f)
            for r in records[i * per:(i + 1) * per]:
                w.write_record(r)
        paths.append(str(p))
    return ";".join(paths)


@pytest.mark.parametrize("num_parts", [1, 2, 5])
def test_recordio_split(tmp_path, num_parts):
    magic_bytes = struct.pack("<I", RECORDIO_MAGIC)
    records = [os.urandom(i % 50 + 1) for i in range(200)]
    records[17] = magic_bytes + b"embedded"      # escape path exercised
    records[42] = b"abcd" + magic_bytes
    uri = _write_rec_files(tmp_path, records, 3)
    parts = _collect_all_parts(uri, num_parts, "recordio")
    merged = [r for p in parts for r in p]
    assert merged == records, f"num_parts={num_parts}"


def test_recordio_split_small_chunks(tmp_path):
    records = [os.urandom(40) for _ in range(100)]
    uri = _write_rec_files(tmp_path, records, 1)
    split = create_input_split(uri, 0, 1, "recordio", threaded=False, chunk_bytes=64)
    got = [bytes(r) for r in split.iter_records()]
    assert got == records
    split.close()


# ---------------- InputSplit: indexed recordio ----------------

def _write_indexed(tmp_path, records):
    data_p = tmp_path / "data.rec"
    idx_p = tmp_path / "data.idx"
    with open(data_p, "wb") as df, open(idx_p, "wb") as xf:
        from dmlc_tpu.io import write_indexed_recordio

        write_indexed_recordio(df, xf, records)
    return str(data_p), str(idx_p)


@pytest.mark.parametrize("num_parts", [1, 2, 4])
def test_indexed_recordio_split(tmp_path, num_parts):
    records = [f"sample-{i:03d}".encode() * (i % 5 + 1) for i in range(103)]
    data_uri, idx_uri = _write_indexed(tmp_path, records)
    got = []
    for part in range(num_parts):
        split = create_input_split(
            data_uri, part, num_parts, "indexed_recordio",
            index_uri=idx_uri, threaded=False,
        )
        got.extend(bytes(r) for r in split.iter_records())
        split.close()
    assert got == records


def test_indexed_recordio_shuffle(tmp_path):
    records = [f"r{i:03d}".encode() for i in range(64)]
    data_uri, idx_uri = _write_indexed(tmp_path, records)
    split = create_input_split(
        data_uri, 0, 1, "indexed_recordio",
        index_uri=idx_uri, shuffle=True, seed=7, threaded=False,
    )
    epoch1 = [bytes(r) for r in split.iter_records()]
    split.before_first()
    epoch2 = [bytes(r) for r in split.iter_records()]
    split.close()
    assert sorted(epoch1) == sorted(records)  # coverage
    assert sorted(epoch2) == sorted(records)
    assert epoch1 != records                  # actually shuffled
    assert epoch1 != epoch2                   # reshuffled each epoch

    # determinism under the same seed
    split_b = create_input_split(
        data_uri, 0, 1, "indexed_recordio",
        index_uri=idx_uri, shuffle=True, seed=7, threaded=False,
    )
    assert [bytes(r) for r in split_b.iter_records()] == epoch1
    split_b.close()


def test_indexed_recordio_batches(tmp_path):
    records = [os.urandom(16) for _ in range(40)]
    data_uri, idx_uri = _write_indexed(tmp_path, records)
    split = create_input_split(
        data_uri, 0, 1, "indexed_recordio",
        index_uri=idx_uri, batch_size=7, threaded=False,
    )
    # batch api returns whole-record chunks of <= batch_size records
    total = []
    nchunks = 0
    while True:
        chunk = split.next_chunk()
        if chunk is None:
            break
        nchunks += 1
        total.extend(bytes(r) for r in split.records_in_chunk(chunk))
    split.close()
    assert total == records
    assert nchunks == (40 + 6) // 7


# ---------------- shuffled chunk split ----------------

def test_shuffled_input_split_coverage(tmp_path):
    uri = _write_corpus(tmp_path, [b"\n".join(LINES) + b"\n"])
    got = []
    for part in range(2):
        split = create_input_split(
            uri, part, 2, "text", num_shuffle_parts=4, seed=3, threaded=False
        )
        got.extend(bytes(r) for r in split.iter_records())
        split.close()
    assert sorted(got) == sorted(LINES)
    assert got != LINES  # order was shuffled at chunk level


# ---------------- partition edge cases ----------------

def test_more_parts_than_records(tmp_path):
    p = tmp_path / "tiny.txt"
    p.write_bytes(b"only-one-line\n")
    parts = _collect_all_parts(str(p), 8)
    merged = [r for pt in parts for r in pt]
    assert merged == [b"only-one-line"]


def test_empty_files_skipped(tmp_path):
    (tmp_path / "a.txt").write_bytes(b"x\n")
    (tmp_path / "empty.txt").write_bytes(b"")
    uri = str(tmp_path / "a.txt") + ";" + str(tmp_path / "empty.txt")
    parts = _collect_all_parts(uri, 2)
    merged = [r for pt in parts for r in pt]
    assert merged == [b"x"]


# ---------------- reset_partition reuse (regression: review findings) ----------------

def test_reset_partition_reuse_no_stale_state(tmp_path):
    # one split object reused across partitions, including empty ones
    p = tmp_path / "r.txt"
    p.write_bytes(b"a\nb\nc\n")
    split = create_input_split(str(p), 0, 1, "text", threaded=False)
    assert bytes(split.next_record()) == b"a"  # mid-iteration
    split.reset_partition(7, 8)  # empty byte range
    assert split.next_record() is None
    split.reset_partition(0, 1)
    assert [bytes(r) for r in split.iter_records()] == [b"a", b"b", b"c"]
    split.close()


def test_indexed_reset_partition_empty_after_use(tmp_path):
    records = [f"r{i}".encode() for i in range(8)]
    data_uri, idx_uri = _write_indexed(tmp_path, records)
    split = create_input_split(
        data_uri, 0, 1, "indexed_recordio", index_uri=idx_uri,
        shuffle=True, seed=1, threaded=False,
    )
    assert split.next_record() is not None  # partially consumed
    split.reset_partition(10, 16)  # out-of-range -> empty
    assert split.next_record() is None
    split.reset_partition(0, 1)
    assert sorted(bytes(r) for r in split.iter_records()) == sorted(records)
    split.close()


def test_single_file_split_chunk_then_record():
    import dmlc_tpu.io.input_split as isp
    import tempfile, os as _os
    with tempfile.NamedTemporaryFile("wb", suffix=".txt", delete=False) as f:
        f.write(b"x\ny\n")
        path = f.name
    try:
        s = isp.SingleFileSplit(path)
        chunk = s.next_chunk()
        assert bytes(chunk) == b"x\ny\n"
        assert s.next_record() is None  # chunk consumed the stream
        s.before_first()
        assert bytes(s.next_record()) == b"x"
    finally:
        _os.unlink(path)


def test_single_file_split_streams_in_bounded_chunks():
    """A file larger than chunk_bytes is served in multiple record-aligned
    chunks with no dropped/duplicated/split records (single_file_split.h
    buffers incrementally; slurping the whole file would OOM on stdin)."""
    import dmlc_tpu.io.input_split as isp
    import tempfile, os as _os
    lines = [f"row-{i:05d}" for i in range(500)]
    with tempfile.NamedTemporaryFile("wb", suffix=".txt", delete=False) as f:
        f.write(("\n".join(lines) + "\n").encode())
        path = f.name
    try:
        s = isp.SingleFileSplit(path, chunk_bytes=4096)
        got = []
        while (rec := s.next_record()) is not None:
            got.append(bytes(rec).decode())
        assert got == lines
        # chunk interface: multiple chunks, all record-aligned, re-parseable
        s.before_first()
        chunks = []
        while (c := s.next_chunk()) is not None:
            assert len(c) <= 8192
            chunks.append(bytes(c))
        assert len(chunks) > 1
        reparsed = b"".join(chunks).decode().splitlines()
        assert reparsed == lines
        s.close()
    finally:
        _os.unlink(path)


def test_memfile_double_close():
    MemoryFileSystem.reset()
    f = open_stream("mem://b/x.txt", "w")
    f.write(b"hi")
    f.close()
    f.close()  # idempotent
    with open_stream("mem://b/x.txt") as g:
        assert g.read() == b"hi"


# ---------------- chunk-boundary regression pins (ISSUE 14 satellite) ----
#
# CRLF line endings and a final record with no trailing newline, at EXACT
# partition boundaries: the stream engine (LineSplitter) and the
# zero-copy mmap engine (MmapLineSplit) must deliver identical record
# streams for every (partition, chunk-budget) combination — including
# boundaries that land between the '\r' and '\n' of a CRLF pair and a
# partition whose final record is unterminated. An exhaustive sweep
# (every nparts up to len(corpus)+1 places a raw boundary at every byte)
# verified the current handling correct; these tests pin it so the SIMD
# batch path — whose chunk/tail handling is new code over the same
# splits — can never silently regress it.

_BOUNDARY_CORPORA = {
    "lf_term": b"a 1:1\nbb 2:2\nccc 3:3\nd 4:4\n",
    "lf_noterm": b"a 1:1\nbb 2:2\nccc 3:3\nd 4:4",
    "crlf_term": b"a 1:1\r\nbb 2:2\r\nccc 3:3\r\nd 4:4\r\n",
    "crlf_noterm": b"a 1:1\r\nbb 2:2\r\nccc 3:3\r\nd 4:4",
    "cr_only_noterm": b"a 1:1\rbb 2:2\rccc 3:3\rd 4:4",
    "blank_runs": b"a 1:1\n\n\r\n\nbb 2:2\r\n\r\nccc 3:3",
}


def _split_records(split):
    out = []
    while (r := split.next_record()) is not None:
        out.append(bytes(r))
    return out


@pytest.mark.parametrize("name", sorted(_BOUNDARY_CORPORA))
def test_mmap_split_boundary_parity_exhaustive(tmp_path, name):
    """Every partition boundary position x several chunk budgets:
    MmapLineSplit records == LineSplitter records, and the union over
    all parts is exactly the corpus's lines (nothing lost or doubled at
    a CRLF straddle or an unterminated tail)."""
    import re

    from dmlc_tpu.io.filesystem import get_filesystem
    from dmlc_tpu.io.input_split import MmapLineSplit

    data = _BOUNDARY_CORPORA[name]
    p = tmp_path / f"{name}.txt"
    p.write_bytes(data)
    fs = get_filesystem(str(p))
    want_lines = [l for l in re.split(rb"[\r\n]+", data) if l]
    for nparts in range(1, len(data) + 2):
        union = []
        for part in range(nparts):
            per_engine = {}
            for label, cls in (("stream", LineSplitter),
                               ("mmap", MmapLineSplit)):
                for cb in (1, 3, 7, len(data), 4096):
                    s = cls(fs, str(p))
                    s._chunk_bytes = cb
                    s.reset_partition(part, nparts)
                    recs = _split_records(s)
                    s.close()
                    prev = per_engine.setdefault(label, recs)
                    assert recs == prev, (name, nparts, part, label, cb)
            assert per_engine["mmap"] == per_engine["stream"], (
                name, nparts, part)
            union.extend(per_engine["mmap"])
        assert union == want_lines, (name, nparts)


def test_mmap_split_unterminated_tail_resume(tmp_path):
    """Checkpoint/restore across the unterminated-final-record chunk:
    states taken after every chunk (including the tail) restore
    byte-identically into a fresh MmapLineSplit AND cross-engine from a
    LineSplitter state."""
    from dmlc_tpu.io.filesystem import get_filesystem
    from dmlc_tpu.io.input_split import MmapLineSplit

    data = _BOUNDARY_CORPORA["crlf_noterm"]
    p = tmp_path / "resume.txt"
    p.write_bytes(data)
    fs = get_filesystem(str(p))

    def chunks_from(split):
        out = []
        while (c := split.next_chunk()) is not None:
            out.append(bytes(c))
        return out

    base = MmapLineSplit(fs, str(p))
    base._chunk_bytes = 8
    base.reset_partition(0, 1)
    full = chunks_from(base)
    base.close()
    assert len(full) >= 2  # the sweep must cross the unterminated tail
    for k in range(len(full) + 1):
        for src_cls in (MmapLineSplit, LineSplitter):
            s = src_cls(fs, str(p))
            s._chunk_bytes = 8
            s.reset_partition(0, 1)
            for _ in range(k):
                s.next_chunk()
            state = s.state_dict()
            s.close()
            r = MmapLineSplit(fs, str(p))
            r._chunk_bytes = 8
            r.reset_partition(0, 1)
            r.load_state(state)
            tail = b"".join(chunks_from(r))
            r.close()
            # chunk grouping may differ across engines on the appended
            # final newline; the delivered BYTES must not
            want = b"".join(full[k:])
            assert tail.replace(b"\n", b"").replace(b"\r", b"") == \
                want.replace(b"\n", b"").replace(b"\r", b""), (src_cls, k)
