"""Always-on pipeline telemetry (ISSUE 6): the span tracer + metrics
registry core, Chrome-trace export validated structurally against
``DeviceIter.stats()``, per-pipeline counter isolation between two
concurrent iterators, the structured stall diagnostic, pod-snapshot
merging, and the lint-metrics gate.
"""

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from dmlc_tpu.data import create_parser
from dmlc_tpu.data.device import DeviceIter
from dmlc_tpu.io import faults, resilience
from dmlc_tpu.io.resilience import RetryPolicy
from dmlc_tpu.io.threaded_iter import OrderedWorkerPool, ThreadedIter
from dmlc_tpu.utils import telemetry
from dmlc_tpu.utils.check import DMLCError
from dmlc_tpu.utils.timer import StageMeter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _isolated(monkeypatch):
    monkeypatch.delenv("DMLC_TPU_TRACE", raising=False)
    monkeypatch.delenv("DMLC_FAULT_PLAN", raising=False)
    monkeypatch.delenv("DMLC_PIPELINE_STALL_TIMEOUT", raising=False)
    faults.reset()
    resilience.reset_counters()
    yield
    faults.reset()
    telemetry.set_scope(None)


def _libsvm_text(n=300, d=6, seed=0):
    rng = np.random.default_rng(seed)
    lines = []
    for i in range(n):
        feats = " ".join(f"{j}:{rng.normal():.5f}" for j in range(d))
        lines.append(f"{i % 2} {feats}")
    return ("\n".join(lines) + "\n").encode()


def _write(tmp_path, name, data):
    p = tmp_path / name
    p.write_bytes(data)
    return str(p)


# ---------------- registry core ----------------

class TestRegistry:
    def test_counter_gauge_histogram_info(self):
        reg = telemetry.MetricsRegistry()
        c = reg.counter("c", stage="parse")
        c.inc()
        c.inc(2.5)
        assert reg.counter("c", stage="parse") is c  # get-or-create
        assert c.value == pytest.approx(3.5)
        reg.gauge("g", x="1").set(7)
        assert reg.gauge("g", x="1").value == 7.0
        h = reg.histogram("h")
        h.observe(1.0)
        h.observe(3.0)
        assert h.value == {"count": 2, "sum": 4.0, "min": 1.0, "max": 3.0}
        reg.info("i", k="v").set({"a": 1})
        assert reg.info("i", k="v").value == {"a": 1}

    def test_label_scoping_and_sums(self):
        reg = telemetry.MetricsRegistry()
        reg.counter("ev", event="retries", pipeline="a").inc(2)
        reg.counter("ev", event="retries", pipeline="b").inc(3)
        reg.counter("ev", event="fatal", pipeline="a").inc(1)
        assert reg.sum("ev") == 6.0
        assert reg.sum("ev", pipeline="a") == 3.0
        assert reg.sum_by("ev", "event") == {"retries": 5.0, "fatal": 1.0}
        assert reg.sum_by("ev", "event", pipeline="b") == {"retries": 3.0}
        rows = reg.snapshot(name="ev", pipeline="a")
        assert {tuple(sorted(r["labels"].items())) for r in rows} == {
            (("event", "fatal"), ("pipeline", "a")),
            (("event", "retries"), ("pipeline", "a")),
        }
        reg.clear("ev")
        assert reg.sum("ev") == 0.0

    def test_concurrent_increments_are_exact(self):
        reg = telemetry.MetricsRegistry()
        c = reg.counter("n")

        def work():
            for _ in range(5000):
                c.inc()

        threads = [threading.Thread(target=work) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == 20000.0

    def test_stage_meter_is_registry_backed(self):
        meter = StageMeter("read", "parse", metric="test_stage_seconds")
        meter.add("read", 0.25)
        meter.add("parse", 1.0)
        meter.add("extra", 0.5)  # dynamic stage, same behavior as before
        assert meter.seconds() == {"read": 0.25, "parse": 1.0, "extra": 0.5}
        assert meter.total() == pytest.approx(1.75)
        # the same numbers are visible through the registry — stats() and
        # the pod snapshot read ONE set of books
        assert telemetry.REGISTRY.sum(
            "test_stage_seconds", pipeline=meter.scope, stage="parse") == 1.0
        # independent meters never alias (auto-unique scope)
        other = StageMeter("read", metric="test_stage_seconds")
        other.add("read", 9.0)
        assert meter.seconds()["read"] == 0.25


# ---------------- scoping ----------------

class TestScoping:
    def test_scope_context_restores(self):
        assert telemetry.current_scope() is None
        with telemetry.scope("p1"):
            assert telemetry.current_scope() == "p1"
            with telemetry.scope("p2"):
                assert telemetry.current_scope() == "p2"
            assert telemetry.current_scope() == "p1"
        assert telemetry.current_scope() is None

    def test_scoped_target_inherits_creator_scope(self):
        seen = {}
        with telemetry.scope("creator"):
            target = telemetry.scoped_target(
                lambda: seen.setdefault("scope", telemetry.current_scope()))
        t = threading.Thread(target=target)
        t.start()
        t.join()
        assert seen["scope"] == "creator"

    def test_record_event_scoping_and_compat_api(self):
        resilience.record_event("retries")
        with telemetry.scope("pipe-a"):
            resilience.record_event("retries", 2)
        snap = resilience.counters_snapshot()
        assert snap["retries"] == 3  # process-wide: byte-compatible view
        assert set(resilience._Counters._KEYS) <= set(snap)
        assert resilience.counters_snapshot("pipe-a")["retries"] == 2
        assert resilience.counters_snapshot("")["retries"] == 1
        delta = resilience.counters_delta(
            {"retries": 1}, pipeline="pipe-a")
        assert delta["retries"] == 1
        resilience.reset_counters()
        assert resilience.counters_snapshot()["retries"] == 0

    def test_threaded_iter_producer_inherits_scope(self):
        seen = []

        def gen():
            seen.append(telemetry.current_scope())
            yield 1

        with telemetry.scope("owner"):
            it = ThreadedIter.from_factory(gen, max_capacity=2)
        assert it.next() == 1
        it.destroy()
        assert seen == ["owner"]

    def test_scope_adoption_on_first_pull(self):
        """A thread primitive built OUTSIDE any scope (e.g. the threaded
        input split, constructed with the parser before its DeviceIter
        exists) adopts the first scoped consumer's label mid-run."""
        events = []

        def gen():
            for i in range(20):
                events.append(telemetry.current_scope())
                yield i

        it = ThreadedIter.from_factory(gen, max_capacity=2)  # unscoped
        with telemetry.scope("late-owner"):
            out = [it.next() for _ in range(20)]
        it.destroy()
        assert out == list(range(20))
        # production after the first pull runs under the adopted label
        # (the eager prefetch before it may legitimately be unscoped)
        assert events[-1] == "late-owner"
        assert set(events) <= {None, "late-owner"}

    def test_threaded_split_scope_captured_at_construction(self, tmp_path):
        """Thread primitives the parser chain built BEFORE the DeviceIter
        existed (the threaded input split starts prefetching at parser
        construction) are stamped with the pipeline label AT ITERATOR
        CONSTRUCTION — not on the first pull — so even the initial
        prefetch window is scoped (the old adoption-window caveat is
        gone from docs/observability.md)."""
        from dmlc_tpu.data.device import DeviceIter
        from dmlc_tpu.data.parsers import create_parser

        p = tmp_path / "c.libsvm"
        p.write_text("".join(f"{i % 2} 0:1.0 1:2.0\n" for i in range(200)))
        parser = create_parser(str(p) + "?engine=python", 0, 1, "libsvm",
                               threaded=True, parse_workers=1)
        # the parse-ahead chain was built outside any scope: find its
        # primitives and prove they are unscoped now, scoped after init
        prims = []
        stack = [parser]
        while stack:
            obj = stack.pop()
            if obj is None:
                continue
            if hasattr(obj, "adopt_scope"):
                prims.append(obj)
            stack.extend(getattr(obj, n, None)
                         for n in ("source", "base", "_base", "_iter"))
        assert prims, "no thread primitive found in the parser chain"
        assert all(prim._scope is None for prim in prims)
        it = DeviceIter(parser, num_col=2, batch_size=32, layout="dense")
        assert all(prim._scope == it.pipeline_label for prim in prims)
        # and an already-scoped primitive is never re-labeled
        prims[0].adopt_scope("someone-else")
        assert prims[0]._scope == it.pipeline_label
        it.close()

    def test_worker_pool_workers_inherit_scope(self):
        seen = set()

        def work(item):
            seen.add(telemetry.current_scope())
            return item

        with telemetry.scope("owner"):
            pool = OrderedWorkerPool(lambda: iter(range(6)), work,
                                     num_workers=2)
        assert [pool.next() for _ in range(6)] == list(range(6))
        pool.destroy()
        assert seen == {"owner"}


# ---------------- span tracer ----------------

class TestSpanTracer:
    def test_ring_bounded_counts_preserved(self, monkeypatch):
        monkeypatch.setenv("DMLC_TPU_TRACE_RING_SPANS", "64")
        ring = telemetry._SpanRing(1, "t", 64)
        for i in range(200):
            ring.record("parse", i, 1, None, None)
        assert len(ring.snapshot()) == 64
        assert ring.total == 200
        assert ring.counts["parse"] == 200
        # oldest-first and the oldest retained is #136
        assert ring.snapshot()[0][1] == 136
        ring.clear()
        assert ring.snapshot() == [] and ring.total == 0

    def test_record_span_carries_scope_and_labels(self):
        telemetry.reset_spans()
        with telemetry.scope("pipe-z"):
            telemetry.record_span("convert", 10.0, 0.25, rows=7)
        rows = telemetry.spans_snapshot(pipeline="pipe-z")
        assert len(rows) == 1
        s = rows[0]
        assert s["name"] == "convert"
        assert s["start_ns"] == 10_000_000_000
        assert s["dur_ns"] == 250_000_000
        assert s["labels"] == {"rows": 7}
        assert telemetry.span_counts().get("convert", 0) >= 1

    def test_chrome_export_structure(self, tmp_path):
        telemetry.reset_spans()
        telemetry.record_span("read", 1.0, 0.5)
        with telemetry.scope("pipe-q"):
            telemetry.record_span("parse", 1.5, 0.25)
        out = str(tmp_path / "trace.json")
        n = telemetry.export_chrome_trace(out)
        assert n == 2
        doc = json.loads(open(out).read())
        assert isinstance(doc["traceEvents"], list)
        assert doc["displayTimeUnit"] == "ms"
        assert doc["otherData"]["telemetry_schema_version"] == \
            telemetry.SCHEMA_VERSION
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert {e["name"] for e in xs} == {"read", "parse"}
        for e in xs:
            assert set(e) >= {"name", "cat", "ph", "pid", "tid", "ts", "dur"}
            assert e["ts"] >= 0 and e["dur"] >= 0
        parse = next(e for e in xs if e["name"] == "parse")
        assert parse["args"]["pipeline"] == "pipe-q"
        assert parse["dur"] == pytest.approx(250_000.0)  # us
        # metadata events name the process/threads (Perfetto niceties)
        assert any(e["ph"] == "M" and e["name"] == "process_name"
                   for e in doc["traceEvents"])

    def test_trace_mode_parsing(self, monkeypatch):
        assert telemetry.trace_mode() == ("off", None)
        monkeypatch.setenv("DMLC_TPU_TRACE", "0")
        assert telemetry.trace_mode() == ("off", None)
        monkeypatch.setenv("DMLC_TPU_TRACE", "1")
        assert telemetry.trace_mode() == ("annotate", None)
        monkeypatch.setenv("DMLC_TPU_TRACE", "chrome:/tmp/x.json")
        assert telemetry.trace_mode() == ("chrome", "/tmp/x.json")


# ---------------- the acceptance contract: trace vs stats() ----------------

def _close(span_sum, ref, what):
    tol = max(0.10 * max(ref, span_sum), 0.02)
    assert abs(span_sum - ref) <= tol, (
        f"{what}: span sum {span_sum:.4f}s vs stats {ref:.4f}s "
        f"(tolerance {tol:.4f}s)")


class TestTraceMatchesStats:
    def test_chrome_trace_covers_all_stages_and_matches_attribution(
            self, tmp_path, monkeypatch):
        """DMLC_TPU_TRACE=chrome:<path> on a cold+warm epoch pair writes a
        well-formed Chrome trace: all six stage names present, and the
        per-stage span sums reconcile with the stats() attribution within
        10% (the acceptance bar — spans and stage counters are fed from
        the same code sites, so disagreement means a bookkeeping hole)."""
        monkeypatch.setenv("DMLC_TPU_NO_NATIVE_READER", "1")
        trace_path = str(tmp_path / "ingest.trace.json")
        monkeypatch.setenv("DMLC_TPU_TRACE", f"chrome:{trace_path}")
        telemetry.reset_spans()
        path = _write(tmp_path, "corpus.libsvm", _libsvm_text(n=2000))
        cache = str(tmp_path / "corpus.blockcache")
        parser = create_parser(path, 0, 1, "libsvm", threaded=False,
                               block_cache=cache, chunk_bytes=8192)
        it = DeviceIter(parser, num_col=6, batch_size=256, layout="dense",
                        prefetch=2, convert_ahead=2, convert_workers=1,
                        transfer_sample=1, pack_aux=True)
        batches = 0
        for _ in it:          # cold epoch: read/parse (+ shadow write)
            batches += 1
        it.reset()
        for _ in it:          # warm epoch: cache_read
            batches += 1
        stats = it.stats()
        assert stats["cache_state"] == "warm"
        it.close()            # chrome mode -> dump on close

        doc = json.loads(open(trace_path).read())
        events = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        for e in events:
            assert set(e) >= {"name", "ph", "ts", "dur", "pid", "tid"}
            assert e["ts"] >= 0 and e["dur"] >= 0
        names = {e["name"] for e in events}
        assert set(telemetry.STAGES) <= names, names

        # this pipeline's spans only (another test's pipeline may share
        # the process), per-stage sums in seconds
        mine = [e for e in events
                if e.get("args", {}).get("pipeline") == stats["pipeline"]]
        sums = {}
        for e in mine:
            sums[e["name"]] = sums.get(e["name"], 0.0) + e["dur"] / 1e6

        # per-batch spans really are per batch: one dispatch per batch
        # delivered, one sampled transfer probe per batch (sample=1)
        ndisp = sum(1 for e in mine if e["name"] == "dispatch")
        assert ndisp == batches
        assert sum(1 for e in mine if e["name"] == "transfer") == \
            stats["transfer_samples"]

        busy = stats["stage_busy"]
        _close(sums.get("read", 0.0), busy["read"], "read")
        _close(sums.get("cache_read", 0.0), busy["cache_read"], "cache_read")
        _close(sums.get("convert", 0.0), busy["convert"], "convert")
        _close(sums.get("dispatch", 0.0), busy["dispatch"], "dispatch")
        _close(sums.get("transfer", 0.0), stats["stages"]["transfer"],
               "transfer")
        # busy 'parse' is measured around the whole supply pull, which in
        # a cold cache epoch includes the shadow-write — the write's own
        # spans account for that share, so parse reconciles NET of them
        _close(sums.get("parse", 0.0),
               max(0.0, busy["parse"] - sums.get("cache_write", 0.0)),
               "parse")

    def test_dump_trace_without_env(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DMLC_TPU_NO_NATIVE_READER", "1")
        telemetry.reset_spans()
        path = _write(tmp_path, "c.libsvm", _libsvm_text(n=200))
        parser = create_parser(path, 0, 1, "libsvm", threaded=False,
                               chunk_bytes=4096)
        it = DeviceIter(parser, num_col=6, batch_size=64, layout="dense",
                        convert_workers=1, transfer_sample=0)
        for _ in it:
            pass
        out = str(tmp_path / "direct.json")
        n = it.dump_trace(out)
        it.close()
        assert n > 0
        doc = json.loads(open(out).read())
        assert {"read", "parse", "convert", "dispatch"} <= {
            e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"}


# ---------------- cross-pipeline isolation (satellite 1) ----------------

class TestPipelineIsolation:
    @staticmethod
    def _make(path, cache):
        parser = create_parser(path, 0, 1, "libsvm", threaded=False,
                               block_cache=cache, chunk_bytes=4096)
        return DeviceIter(parser, num_col=6, batch_size=128, layout="dense",
                          convert_workers=1, transfer_sample=0)

    def test_two_concurrent_iterators_keep_disjoint_counters(
            self, tmp_path, monkeypatch):
        """ISSUE 6 satellite: a fault injected into pipeline A's warm
        cache must show up in A's stats()['resilience'] ONLY — before the
        scoped registry, both iterators diffed the same process-wide
        totals and saw each other's events."""
        monkeypatch.setenv("DMLC_TPU_NO_NATIVE_READER", "1")
        pa = _write(tmp_path, "corpus_a.libsvm", _libsvm_text(seed=0))
        pb = _write(tmp_path, "corpus_b.libsvm", _libsvm_text(seed=1))
        cache_a = str(tmp_path / "a.blockcache")
        cache_b = str(tmp_path / "b.blockcache")
        it_a = self._make(pa, cache_a)
        it_b = self._make(pb, cache_b)
        try:
            for _ in it_a:  # cold: publish both caches
                pass
            for _ in it_b:
                pass
            it_a.reset()
            it_b.reset()
            # warm epochs INTERLEAVED while the corruption fault targets
            # only pipeline A's cache file
            with faults.inject("cache_read~a.blockcache@1=corrupt"):
                done_a = done_b = False
                while not (done_a and done_b):
                    if not done_a:
                        try:
                            next(it_a)
                        except StopIteration:
                            done_a = True
                    if not done_b:
                        try:
                            next(it_b)
                        except StopIteration:
                            done_b = True
            res_a = it_a.stats()["resilience"]
            res_b = it_b.stats()["resilience"]
            assert res_a["cache_corruptions"] == 1
            assert res_a["cache_rebuilds"] == 1
            # B saw NOTHING of A's fault — the contamination fix
            assert res_b["cache_corruptions"] == 0
            assert res_b["cache_rebuilds"] == 0
            assert all(v == 0 for v in res_b.values()), res_b
            # process-wide totals still aggregate both pipelines
            assert resilience.counters_snapshot()["cache_corruptions"] == 1
        finally:
            it_a.close()
            it_b.close()

    def test_stats_carries_pipeline_label(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DMLC_TPU_NO_NATIVE_READER", "1")
        path = _write(tmp_path, "c.libsvm", _libsvm_text(n=50))
        parser = create_parser(path, 0, 1, "libsvm", threaded=False)
        it = DeviceIter(parser, num_col=6, batch_size=32,
                        pipeline_label="train-input")
        try:
            next(iter(it))
            assert it.stats()["pipeline"] == "train-input"
        finally:
            it.close()


# ---------------- structured stall diagnostic (satellite 3) ----------------

class TestStallDiagnostic:
    def test_threaded_iter_publishes_structured_dict(self, monkeypatch):
        monkeypatch.setenv("DMLC_PIPELINE_STALL_TIMEOUT", "0.3")
        gate = threading.Event()

        def produce(cell):
            gate.wait(30)
            return False, None

        it = ThreadedIter(produce,
                          restart_policy=RetryPolicy(max_attempts=4))
        with pytest.raises(DMLCError, match="pipeline stalled"):
            it.next()
        gate.set()
        it.destroy()
        diag = telemetry.REGISTRY.info(
            telemetry.STALL_METRIC, component="ThreadedIter",
            label="", pipeline="").value
        assert diag is not None
        assert diag["component"] == "ThreadedIter"
        assert diag["timeout_seconds"] == pytest.approx(0.3)
        assert diag["producer_alive"] is True
        assert diag["queue_len"] == 0
        assert diag["last_producer_error"] is None
        assert diag["restart_budget"] == {
            "enabled": True, "used": 0, "limit": 3}

    def test_worker_pool_publishes_structured_dict(self, monkeypatch):
        monkeypatch.setenv("DMLC_PIPELINE_STALL_TIMEOUT", "0.3")
        gate = threading.Event()

        def work(item):
            gate.wait(30)
            return item

        pool = OrderedWorkerPool(lambda: iter(range(4)), work,
                                 num_workers=2, counter_label="parse")
        with pytest.raises(DMLCError, match="pipeline stalled"):
            pool.next()
        gate.set()
        pool.destroy()
        diag = telemetry.REGISTRY.info(
            telemetry.STALL_METRIC, component="OrderedWorkerPool",
            label="parse", pipeline="").value
        assert diag is not None
        assert diag["label"] == "parse"
        assert diag["workers"] == 2
        assert diag["waiting_for"] == 0
        assert diag["pulled"] >= 1
        assert diag["restart_budget"]["enabled"] is False
        assert diag["last_producer_error"] is None

    def test_stall_dict_carries_producer_error_and_budget_use(
            self, monkeypatch):
        monkeypatch.setenv("DMLC_PIPELINE_STALL_TIMEOUT", "0.3")
        gate = threading.Event()
        state = {"first": True}

        def gen():
            if state["first"]:
                state["first"] = False
                raise TimeoutError("flaky source")
            gate.wait(30)
            yield 1

        it = ThreadedIter.from_factory(
            gen, restart_policy=RetryPolicy(max_attempts=3,
                                            base_delay=0.001))
        with pytest.raises(DMLCError, match="pipeline stalled"):
            it.next()
        gate.set()
        it.destroy()
        diag = telemetry.REGISTRY.info(
            telemetry.STALL_METRIC, component="ThreadedIter",
            label="", pipeline="").value
        assert "TimeoutError" in diag["last_producer_error"]
        assert diag["restart_budget"] == {
            "enabled": True, "used": 1, "limit": 2}


# ---------------- pod snapshot + merge ----------------

class TestPodAggregation:
    def test_pod_snapshot_shape(self):
        telemetry.REGISTRY.counter(
            telemetry.STAGE_BUSY_METRIC, stage="parse",
            pipeline="snap-test").inc(2.0)
        with telemetry.scope("snap-test"):
            resilience.record_event("retries")
        telemetry.record_span("parse", 0.0, 0.5)
        snap = telemetry.pod_snapshot()
        assert snap["telemetry_schema_version"] == telemetry.SCHEMA_VERSION
        assert snap["stages"]["parse"] >= 2.0  # summed ACROSS pipelines
        assert snap["resilience"]["retries"] >= 1
        assert snap["spans"]["parse"] >= 1
        json.dumps(snap)  # must be wire-serializable

    def test_format_pod_table_merges_ranks(self):
        v = telemetry.SCHEMA_VERSION
        table = telemetry.format_pod_table({
            1: {"telemetry_schema_version": v,
                "stages": {"read": 0.5, "parse": 2.0},
                "resilience": {"retries": 2}},
            0: {"telemetry_schema_version": v,
                "stages": {"parse": 1.0, "transfer": 0.25},
                "resilience": {}},
        })
        lines = table.splitlines()
        assert lines[0].split()[:2] == ["rank", "read"]
        for stage in telemetry.STAGES:
            assert stage in lines[0]
        r0 = next(ln for ln in lines if ln.strip().startswith("0"))
        r1 = next(ln for ln in lines if ln.strip().startswith("1"))
        assert "1.000" in r0 and "2.000" in r1
        assert "{'retries': 2}" in r1
        assert "3.000" in lines[-1]  # parse sum row

    def test_format_pod_table_refuses_cross_schema(self):
        table = telemetry.format_pod_table({
            0: {"telemetry_schema_version": telemetry.SCHEMA_VERSION,
                "stages": {"parse": 1.0}},
            1: {"telemetry_schema_version": 999, "stages": {"parse": 9.0}},
        })
        assert "not merged" in table
        assert "9.000" not in table


# ---------------- lint-metrics gate (satellite 5) ----------------

class TestLintMetrics:
    def _scan(self):
        sys.path.insert(0, os.path.join(REPO, "bin"))
        try:
            import lint_metrics
        finally:
            sys.path.pop(0)
        return lint_metrics.scan_source

    def test_flags_adhoc_bookkeeping(self):
        scan = self._scan()
        bad = (
            "def f():\n"
            "    t0 = time.monotonic()\n"
            "    COUNTERS.bump('retries')\n"
            "    # time.monotonic() in a comment is fine\n"
        )
        offenders = scan(bad)
        assert [ln for ln, _ in offenders] == [2, 3]

    def test_sanctioned_calls_pass(self):
        scan = self._scan()
        good = (
            "def f():\n"
            "    t0 = get_time()\n"
            "    _resilience.record_event('retries')\n"
            "    telemetry.record_span('parse', t0, get_time() - t0)\n"
        )
        assert scan(good) == []

    def test_repo_is_clean(self):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bin", "lint_metrics.py"),
             REPO],
            capture_output=True, text=True)
        assert proc.returncode == 0, proc.stderr
