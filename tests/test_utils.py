"""Tests for the utils layer (registry, params, config, serializer, check).

Mirrors the reference unit-test coverage of unittest_param.cc,
unittest_config.cc, unittest_serializer.cc, unittest_env.cc.
"""

import io

import numpy as np
import pytest

from dmlc_tpu.utils import (
    Config, DMLCError, Parameter, Registry, check, check_eq, check_lt,
)
from dmlc_tpu.utils.params import field, get_env, set_env
from dmlc_tpu.utils import serializer as ser


# ---------------- check ----------------

def test_check_raises():
    check(True)
    with pytest.raises(DMLCError):
        check(False, "boom")
    check_eq(1, 1)
    with pytest.raises(DMLCError):
        check_eq(1, 2)
    with pytest.raises(DMLCError):
        check_lt(3, 2)


# ---------------- registry ----------------

def test_registry_register_find_alias():
    reg = Registry.get("test_reg_1")

    @reg.register("foo", description="a foo")
    def make_foo():
        return "foo!"

    assert reg.find("foo").body() == "foo!"
    assert reg.find("bar") is None
    reg.add_alias("foo", "foo2")
    assert reg.create("foo2") == "foo!"
    with pytest.raises(DMLCError):
        reg.lookup("nope")
    with pytest.raises(DMLCError):
        @reg.register("foo")
        def make_foo_again():
            return None
    assert "foo" in reg.list_names()


# ---------------- params ----------------

class MyParam(Parameter):
    size = field(int, default=100, lower_bound=0, help="a size")
    name = field(str, default="x")
    ratio = field(float, default=0.5, lower_bound=0.0, upper_bound=1.0)
    kind = field(str, default="a", enum=["a", "b"])
    num_hidden = field(int, default=0, aliases=["nhidden"])


def test_param_defaults_and_init():
    p = MyParam()
    assert p.size == 100 and p.name == "x"
    unknown = p.init({"size": "7", "junk": "1"}, allow_unknown=True)
    assert p.size == 7
    assert unknown == {"junk": "1"}
    with pytest.raises(DMLCError):
        p.init({"junk": "1"})  # unknown not allowed


def test_param_range_enum_alias():
    p = MyParam()
    with pytest.raises(DMLCError):
        p.init({"size": "-1"})
    with pytest.raises(DMLCError):
        p.init({"ratio": "1.5"})
    with pytest.raises(DMLCError):
        p.init({"kind": "c"})
    p.init({"nhidden": "32"})  # alias, like DMLC_DECLARE_ALIAS (parameter.cc:30)
    assert p.num_hidden == 32


def test_param_required_and_json():
    class Req(Parameter):
        must = field(int)

    with pytest.raises(DMLCError):
        Req()
    r = Req(must=3)
    assert r.must == 3

    p = MyParam(size=9)
    text = p.save_json()
    q = MyParam()
    q.load_json(text)
    assert q.size == 9
    assert "size" in MyParam.doc()


def test_env_access(monkeypatch):
    monkeypatch.setenv("DMLC_TEST_KEY", "42")
    assert get_env("DMLC_TEST_KEY", int, 0) == 42
    assert get_env("DMLC_TEST_MISSING", int, 7) == 7
    set_env("DMLC_TEST_KEY2", 5)
    assert get_env("DMLC_TEST_KEY2", int, 0) == 5
    monkeypatch.setenv("DMLC_TEST_BOOL", "true")
    assert get_env("DMLC_TEST_BOOL", bool, False) is True


# ---------------- config ----------------

def test_config_basic():
    cfg = Config('a = 1\nb = "hello # not comment" # real comment\nc=2.5\n')
    assert cfg.get("a") == "1"
    assert cfg.get("b") == "hello # not comment"
    assert cfg.get("c") == "2.5"
    assert "a" in cfg and "zz" not in cfg


def test_config_override_and_multi():
    cfg = Config("k = 1\nk = 2\n")
    assert cfg.get("k") == "2"
    assert cfg.get_all("k") == ["2"]  # single-value mode: last wins

    mcfg = Config("k = 1\nk = 2\n", multi_value=True)
    assert mcfg.get_all("k") == ["1", "2"]


def test_config_escaped_quote_and_proto():
    cfg = Config('s = "say \\"hi\\""\nn = 3\n')
    assert cfg.get("s") == 'say "hi"'
    proto = cfg.to_proto_string()
    assert 'n : 3' in proto and 's : "say "hi""' in proto


def test_config_errors():
    with pytest.raises(DMLCError):
        Config("a = ")
    with pytest.raises(DMLCError):
        Config('a = "unterminated')


# ---------------- serializer ----------------

def test_scalar_roundtrip_little_endian():
    buf = io.BytesIO()
    ser.write_scalar(buf, 0x01020304, "uint32")
    # wire bytes are little-endian regardless of host (endian.h:39 analog)
    assert buf.getvalue() == b"\x04\x03\x02\x01"
    buf.seek(0)
    assert ser.read_scalar(buf, "uint32") == 0x01020304


def test_obj_roundtrip():
    obj = {
        "a": 1, "b": 2.5, "c": "hey", "d": [1, 2, [3, "x"]],
        "e": None, "f": True, "g": b"\x00\x01",
        "arr": np.arange(6, dtype=np.float32).reshape(2, 3),
    }
    buf = io.BytesIO()
    ser.write_obj(buf, obj)
    buf.seek(0)
    out = ser.read_obj(buf)
    assert out["a"] == 1 and out["f"] is True and out["c"] == "hey"
    np.testing.assert_array_equal(out["arr"], obj["arr"])
    assert out["d"] == [1, 2, [3, "x"]]


def test_ndarray_dtype_preserved():
    for dtype in (np.uint64, np.int32, np.float64, np.uint8):
        arr = np.array([1, 2, 3], dtype=dtype)
        buf = io.BytesIO()
        ser.write_ndarray(buf, arr)
        buf.seek(0)
        out = ser.read_ndarray(buf)
        assert out.dtype == np.dtype(dtype)
        np.testing.assert_array_equal(out, arr)


def test_truncated_stream_raises():
    buf = io.BytesIO(b"\x01\x02")
    with pytest.raises(DMLCError):
        ser.read_scalar(buf, "uint64")
