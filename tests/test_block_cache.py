"""Parse-once columnar block cache (ISSUE 5): on-disk format (golden-
pinned), cold-vs-warm byte-identical parity across formats, checkpoint/
resume mid-warm-epoch, corruption healing with exact resilience counters,
and the hardened chunk cache (CRC frames + versioned header) underneath.
"""

import json
import os
import struct

import numpy as np
import pytest

from dmlc_tpu.data import BlockCacheIter, create_parser, create_row_block_iter
from dmlc_tpu.data.device import DeviceIter
from dmlc_tpu.data.row_block import RowBlock
from dmlc_tpu.io import faults, resilience
from dmlc_tpu.io.block_cache import (
    BLOCK_CACHE_MAGIC,
    BlockCacheReader,
    BlockCacheWriter,
    open_block_cache,
    source_signature,
)
from dmlc_tpu.io.cached_split import CHUNK_CACHE_MAGIC
from dmlc_tpu.io.input_split import create_input_split
from dmlc_tpu.io.uri import URISpec
from dmlc_tpu.utils.check import CacheCorruptionError, DMLCError


@pytest.fixture(autouse=True)
def _isolated(monkeypatch):
    monkeypatch.delenv("DMLC_TPU_BLOCK_CACHE", raising=False)
    monkeypatch.delenv("DMLC_FAULT_PLAN", raising=False)
    faults.reset()
    resilience.reset_counters()
    yield
    faults.reset()


# ---------------- corpora ----------------

def _libsvm_text(n=300, d=6, qid=False, weight=False, seed=0):
    rng = np.random.default_rng(seed)
    lines = []
    for i in range(n):
        label = f"{i % 2}:{rng.random():.3f}" if weight else f"{i % 2}"
        q = f" qid:{i // 10}" if qid else ""
        feats = " ".join(f"{j}:{rng.normal():.5f}" for j in range(d))
        lines.append(f"{label}{q} {feats}")
    return ("\n".join(lines) + "\n").encode()


def _libfm_text(n=300, d=5, seed=1):
    rng = np.random.default_rng(seed)
    lines = []
    for i in range(n):
        feats = " ".join(f"{j % 3}:{j}:{rng.normal():.5f}" for j in range(d))
        lines.append(f"{i % 2} {feats}")
    return ("\n".join(lines) + "\n").encode()


def _csv_text(n=300, d=5, seed=2):
    rng = np.random.default_rng(seed)
    lines = []
    for i in range(n):
        cells = ",".join(f"{rng.normal():.5f}" for _ in range(d))
        lines.append(f"{i % 2},{cells}")
    return ("\n".join(lines) + "\n").encode()


def _write(tmp_path, name, data):
    p = tmp_path / name
    p.write_bytes(data)
    return str(p)


def _drain_arrays(parser):
    """Concatenated epoch output, every array a RowBlock carries, in
    delivery order — the byte-identity comparator."""
    out = {}

    def add(key, arr):
        if arr is not None:
            out.setdefault(key, []).append(np.asarray(arr))

    while (b := parser.next_block()) is not None:
        add("label", b.label)
        add("index", b.index)
        add("value", b.value)
        add("weight", b.weight)
        add("qid", b.qid)
        add("field", b.field)
        add("nnz", np.diff(np.asarray(b.offset)))
    return {k: np.concatenate(v) for k, v in out.items()}


def _assert_same(a, b):
    assert set(a) == set(b), (sorted(a), sorted(b))
    for k in a:
        np.testing.assert_array_equal(a[k], b[k], err_msg=k)


def _golden_blocks():
    """The exact fixture tests/data/blockcache_v1.golden was written
    from — rewriting it must reproduce the committed bytes."""
    return [
        (dict(
            offset=np.array([0, 2, 3], np.int64),
            label=np.array([1.0, 0.0], np.float32),
            weight=np.array([0.5, 2.0], np.float32),
            qid=np.array([1, 2], np.int64),
            field=np.array([0, 1, 2], np.uint64),
            index=np.array([3, 7, 9], np.uint64),
            value=np.array([0.25, -1.5, 3.0], np.float32),
        ), 2, 10, {"kind": "split", "chunks": 1,
                   "split": {"kind": "byte", "offset_curr": 64}}),
        (dict(
            offset=np.array([0, 1], np.int64),
            label=np.array([1.0], np.float32),
            index=np.array([0], np.uint32),
        ), 1, 1, None),
    ]


GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "data", "blockcache_v1.golden")


# ---------------- format ----------------

class TestFormat:
    def test_roundtrip_zero_copy(self, tmp_path):
        path = str(tmp_path / "c.blockcache")
        w = BlockCacheWriter(path, signature={"s": 1})
        for segments, rows, num_col, resume in _golden_blocks():
            w.add_block(segments, rows=rows, num_col=num_col, resume=resume)
        w.finish()
        assert not os.path.exists(path + ".tmp")  # atomic publish
        r = BlockCacheReader(path, signature={"s": 1})
        assert r.num_blocks == 2 and r.num_col == 10 and r.rows == 3
        for i, (segments, rows, _, resume) in enumerate(_golden_blocks()):
            got = r.load_segments(i)
            assert set(got) == {k for k, v in segments.items()
                                if v is not None}
            for name, arr in segments.items():
                if arr is None:
                    continue
                np.testing.assert_array_equal(got[name], arr)
                assert got[name].dtype == arr.dtype
                # mmap-backed views are read-only (zero-copy contract)
                assert not got[name].flags.writeable
            assert r.block_rows(i) == rows
            assert r.resume(i) == (json.loads(json.dumps(resume))
                                   if resume is not None else None)
        blk = RowBlock.from_segments(r.load_segments(0), hold=r.hold)
        assert len(blk) == 2 and blk.num_nonzero == 3

    def test_golden_layout_pinned(self, tmp_path):
        """The v1 layout is frozen: rewriting the golden fixture must be
        byte-identical to the committed file, and the committed file must
        decode exactly — an accidental format change fails both ways."""
        rebuilt = str(tmp_path / "rebuilt.golden")
        w = BlockCacheWriter(rebuilt,
                             signature={"pinned": "blockcache-v1-golden"})
        for segments, rows, num_col, resume in _golden_blocks():
            w.add_block(segments, rows=rows, num_col=num_col, resume=resume)
        w.finish()
        with open(GOLDEN, "rb") as f:
            want = f.read()
        with open(rebuilt, "rb") as f:
            got = f.read()
        assert got == want, "on-disk block-cache layout drifted from v1"
        r = BlockCacheReader(GOLDEN)
        assert r.signature == {"pinned": "blockcache-v1-golden"}
        seg0 = r.load_segments(0)
        np.testing.assert_array_equal(seg0["value"],
                                      np.array([0.25, -1.5, 3.0], np.float32))
        np.testing.assert_array_equal(seg0["qid"], np.array([1, 2], np.int64))
        seg1 = r.load_segments(1)
        assert seg1["index"].dtype == np.dtype(np.uint32)
        assert want[:8] == BLOCK_CACHE_MAGIC and want[-8:] == BLOCK_CACHE_MAGIC

    def test_signature_mismatch_self_invalidates(self, tmp_path):
        path = str(tmp_path / "c.blockcache")
        w = BlockCacheWriter(path, signature={"files": [["a", 1, 2]]})
        w.add_block(_golden_blocks()[1][0], rows=1, num_col=1)
        w.finish()
        base = resilience.counters_snapshot()
        assert open_block_cache(path, {"files": [["a", 1, 3]]}) is None
        assert not os.path.exists(path)  # stale cache dropped
        assert resilience.counters_delta(base)["cache_invalidations"] == 1
        # matching signature on a fresh cache opens fine
        w = BlockCacheWriter(path, signature={"files": [["a", 1, 3]]})
        w.add_block(_golden_blocks()[1][0], rows=1, num_col=1)
        w.finish()
        r = open_block_cache(path, {"files": [["a", 1, 3]]})
        assert r is not None and r.num_blocks == 1

    def test_truncated_cache_invalidates(self, tmp_path):
        path = str(tmp_path / "c.blockcache")
        w = BlockCacheWriter(path, signature={})
        w.add_block(_golden_blocks()[1][0], rows=1, num_col=1)
        w.finish()
        data = open(path, "rb").read()
        with open(path, "wb") as f:
            f.write(data[:-10])  # tail magic gone
        assert open_block_cache(path) is None
        assert not os.path.exists(path)

    def test_crc_detects_bit_flip(self, tmp_path):
        path = str(tmp_path / "c.blockcache")
        w = BlockCacheWriter(path, signature={})
        w.add_block(_golden_blocks()[0][0], rows=2, num_col=10)
        w.finish()
        data = bytearray(open(path, "rb").read())
        data[70] ^= 0xFF  # inside block 0's first segment
        with open(path, "wb") as f:
            f.write(bytes(data))
        r = BlockCacheReader(path)  # footer is intact: open succeeds
        with pytest.raises(CacheCorruptionError):
            r.load_segments(0)
        assert resilience.classify(CacheCorruptionError("x")) == "retryable"

    def test_abort_drops_tmp(self, tmp_path):
        path = str(tmp_path / "c.blockcache")
        w = BlockCacheWriter(path, signature={})
        w.add_block(_golden_blocks()[1][0], rows=1, num_col=1)
        w.abort()
        assert not os.path.exists(path) and not os.path.exists(path + ".tmp")


# ---------------- cold/warm parity ----------------

class TestColdWarmParity:
    @pytest.mark.parametrize("fmt,data,uri_args", [
        ("libsvm", _libsvm_text(), ""),
        ("libsvm", _libsvm_text(qid=True), ""),
        ("libsvm", _libsvm_text(weight=True), ""),
        ("libfm", _libfm_text(), ""),
        ("csv", _csv_text(), "?label_column=0"),
    ])
    def test_cold_warm_byte_identical(self, tmp_path, fmt, data, uri_args):
        path = _write(tmp_path, f"corpus.{fmt}", data)
        cache = str(tmp_path / "c.blockcache")
        uri = path + uri_args
        ref = create_parser(uri, 0, 1, fmt, chunk_bytes=4096)
        want = _drain_arrays(ref)
        ref.close()
        parser = create_parser(uri, 0, 1, fmt, chunk_bytes=4096,
                               block_cache=cache)
        assert parser.cache_state == "cold"
        _assert_same(_drain_arrays(parser), want)   # cold epoch: tee-through
        assert os.path.exists(cache)                # published at stream end
        parser.before_first()
        assert parser.cache_state == "warm"
        _assert_same(_drain_arrays(parser), want)   # warm epoch: from mmap
        parser.close()
        # a FRESH warm pass never constructs the parser chain
        def boom():
            raise AssertionError("parser factory invoked on a warm pass")
        sig = source_signature(path, 0, 1, format=fmt,
                               args=dict(URISpec(uri).args),
                               index_dtype="<u8", chunk_bytes=4096,
                               split={})
        warm = BlockCacheIter(boom, cache, signature=sig)
        assert warm.cache_state == "warm"
        _assert_same(_drain_arrays(warm), want)
        warm.close()

    def test_multi_partition_parity(self, tmp_path):
        path = _write(tmp_path, "corpus.libsvm", _libsvm_text(n=400))
        cache = str(tmp_path / "c.blockcache")
        for part in (0, 1):
            ref = create_parser(path, part, 2, "libsvm", chunk_bytes=2048)
            want = _drain_arrays(ref)
            ref.close()
            parser = create_parser(path, part, 2, "libsvm",
                                   chunk_bytes=2048, block_cache=cache)
            _assert_same(_drain_arrays(parser), want)
            parser.before_first()
            assert parser.cache_state == "warm"
            _assert_same(_drain_arrays(parser), want)
            parser.close()
            # partition-qualified cache files never collide
            assert os.path.exists(f"{cache}.split2.part{part}")

    def test_source_drift_invalidates(self, tmp_path):
        path = _write(tmp_path, "corpus.libsvm", _libsvm_text(n=100))
        cache = str(tmp_path / "c.blockcache")
        parser = create_parser(path, 0, 1, "libsvm", block_cache=cache)
        _drain_arrays(parser)
        parser.close()
        # rewrite the corpus: size+mtime drift must force a re-parse
        data2 = _libsvm_text(n=120, seed=5)
        _write(tmp_path, "corpus.libsvm", data2)
        ref = create_parser(path, 0, 1, "libsvm")
        want = _drain_arrays(ref)
        ref.close()
        base = resilience.counters_snapshot()
        parser = create_parser(path, 0, 1, "libsvm", block_cache=cache)
        assert parser.cache_state == "cold"  # stale cache self-invalidated
        _assert_same(_drain_arrays(parser), want)
        parser.before_first()
        assert parser.cache_state == "warm"  # rebuilt for the new source
        _assert_same(_drain_arrays(parser), want)
        parser.close()
        assert resilience.counters_delta(base)["cache_invalidations"] == 1

    def test_chunk_bytes_drift_invalidates(self, tmp_path):
        """Block grouping config is part of the signature: the heal and
        count-based resume paths skip re-parsed blocks by INDEX, which is
        only sound when re-parse grouping matches the cached grouping — a
        cache built under one chunk_bytes must not serve warm under
        another."""
        path = _write(tmp_path, "corpus.libsvm", _libsvm_text(n=600))
        cache = str(tmp_path / "c.blockcache")
        parser = create_parser(path, 0, 1, "libsvm", chunk_bytes=2048,
                               block_cache=cache)
        _drain_arrays(parser)
        parser.close()
        ref = create_parser(path, 0, 1, "libsvm", chunk_bytes=8192)
        want = _drain_arrays(ref)
        ref.close()
        base = resilience.counters_snapshot()
        parser = create_parser(path, 0, 1, "libsvm", chunk_bytes=8192,
                               block_cache=cache)
        assert parser.cache_state == "cold"  # grouping drift: invalidated
        # ...and a corruption mid-warm under the REBUILT grouping heals
        # into a byte-identical stream (the index-skip is sound again)
        _drain_arrays(parser)
        parser.before_first()
        assert parser.cache_state == "warm"
        with faults.inject("cache_read@2=corrupt"):
            _assert_same(_drain_arrays(parser), want)
        parser.close()
        assert resilience.counters_delta(base)["cache_invalidations"] == 1

    def test_shuffle_maps_to_plan_with_deprecation(self, tmp_path):
        # the old hard rejection is gone: legacy shuffle decorator args +
        # block_cache now map onto the shuffle-native epoch plan with a
        # one-release DeprecationWarning (docs/data.md)
        path = _write(tmp_path, "corpus.libsvm", _libsvm_text(n=50))
        with pytest.warns(DeprecationWarning, match="epoch plan"):
            parser = create_parser(path, 0, 1, "libsvm", num_shuffle_parts=2,
                                   seed=9, block_cache=str(tmp_path / "c.bc"))
        try:
            assert parser.plan_state is not None
            assert parser.plan_state["shuffle_seed"] == 9
        finally:
            parser.close()

    def test_uri_suffix_and_env_dir(self, tmp_path, monkeypatch):
        path = _write(tmp_path, "corpus.libsvm", _libsvm_text(n=100))
        # `#blockcache=<path>` suffix, mirroring `#cachefile`
        spec = URISpec(f"{path}?format=libsvm#blockcache=/x/c.bc")
        assert spec.block_cache == "/x/c.bc" and spec.cache_file is None
        assert spec.args == {"format": "libsvm"}
        cache = str(tmp_path / "via_uri.blockcache")
        parser = create_parser(f"{path}#blockcache={cache}", 0, 1, "libsvm")
        _drain_arrays(parser)
        parser.close()
        assert os.path.exists(cache)
        # DMLC_TPU_BLOCK_CACHE directory: auto-named per URI+args
        env_dir = tmp_path / "bc_dir"
        monkeypatch.setenv("DMLC_TPU_BLOCK_CACHE", str(env_dir))
        parser = create_parser(path, 0, 1, "libsvm")
        assert parser.cache_state == "cold"
        _drain_arrays(parser)
        parser.close()
        named = [f for f in os.listdir(env_dir) if f.endswith(".blockcache")]
        assert len(named) == 1
        parser = create_parser(path, 0, 1, "libsvm")
        assert parser.cache_state == "warm"
        parser.close()

    def test_create_row_block_iter_block_cache(self, tmp_path):
        path = _write(tmp_path, "corpus.libsvm", _libsvm_text(n=150))
        cache = str(tmp_path / "c.blockcache")
        it = create_row_block_iter(path, 0, 1, "libsvm", silent=True,
                                   block_cache=cache)
        blk_cold = it.next_block()
        assert it.next_block() is None and os.path.exists(cache)
        it2 = create_row_block_iter(path, 0, 1, "libsvm", silent=True,
                                    block_cache=cache)
        blk_warm = it2.next_block()
        np.testing.assert_array_equal(blk_cold.label, blk_warm.label)
        np.testing.assert_array_equal(blk_cold.index, blk_warm.index)
        np.testing.assert_array_equal(blk_cold.value, blk_warm.value)


# ---------------- DeviceIter integration ----------------

def _device_batches(it, limit=None):
    out = []
    for b in it:
        out.append(np.asarray(b[0]))
        if limit and len(out) >= limit:
            break
    return out


class TestDeviceIter:
    def test_cache_state_and_stage(self, tmp_path):
        path = _write(tmp_path, "corpus.libsvm", _libsvm_text(n=600))
        cache = str(tmp_path / "c.blockcache")
        parser = create_parser(path, 0, 1, "libsvm", chunk_bytes=4096,
                               block_cache=cache)
        it = DeviceIter(parser, num_col=6, batch_size=128, layout="dense",
                        prefetch=2)
        cold = _device_batches(it)
        stats = it.stats()
        assert stats["cache_state"] == "cold"
        assert "cache_read" in stats["stages"]
        it.reset()
        warm = _device_batches(it)
        stats = it.stats()
        assert stats["cache_state"] == "warm"
        assert stats["stage_busy"]["cache_read"] > 0.0
        assert len(cold) == len(warm)
        for a, b in zip(cold, warm):
            np.testing.assert_array_equal(a, b)
        it.close()

    def test_checkpoint_resume_mid_warm_epoch(self, tmp_path):
        path = _write(tmp_path, "corpus.libsvm", _libsvm_text(n=900))
        cache = str(tmp_path / "c.blockcache")
        uri = path + "?engine=python"  # annotated blocks: byte-exact states

        def make_iter():
            parser = create_parser(uri, 0, 1, "libsvm", chunk_bytes=2048,
                                   block_cache=cache)
            return DeviceIter(parser, num_col=6, batch_size=128,
                              layout="dense", prefetch=2, pack_aux=False)

        it = make_iter()
        _device_batches(it)            # cold epoch publishes the cache
        it.reset()
        warm_all = _device_batches(it)  # uninterrupted warm reference
        it.reset()
        _device_batches(it, limit=2)
        state = it.state_dict()
        assert state["kind"] == "source"  # byte-exact, identically to cold
        it.close()
        it2 = make_iter()
        assert it2.source.cache_state == "warm"
        it2.load_state(state)
        tail = _device_batches(it2)
        assert len(tail) == len(warm_all) - 2
        for a, b in zip(tail, warm_all[2:]):
            np.testing.assert_array_equal(a, b)
        it2.close()

    def test_cold_state_restores_into_warm_pipeline(self, tmp_path):
        path = _write(tmp_path, "corpus.libsvm", _libsvm_text(n=900))
        cache = str(tmp_path / "c.blockcache")
        uri = path + "?engine=python"

        def make_iter():
            parser = create_parser(uri, 0, 1, "libsvm", chunk_bytes=2048,
                                   block_cache=cache)
            return DeviceIter(parser, num_col=6, batch_size=128,
                              layout="dense", prefetch=2, pack_aux=False)

        it = make_iter()
        head = _device_batches(it, limit=2)
        cold_state = it.state_dict()     # taken mid-COLD-epoch
        rest = _device_batches(it)       # finish the epoch: cache publishes
        it.close()
        it2 = make_iter()                # fresh pipeline is warm now
        assert it2.source.cache_state == "warm"
        it2.load_state(cold_state)       # cold state restores warm
        tail = _device_batches(it2)
        assert len(tail) == len(rest)
        for a, b in zip(tail, rest):
            np.testing.assert_array_equal(a, b)
        it2.close()


# ---------------- corruption healing ----------------

class TestCorruptionHeals:
    def test_fault_plan_corrupt_segment_heals(self, tmp_path):
        path = _write(tmp_path, "corpus.libsvm", _libsvm_text(n=600))
        cache = str(tmp_path / "c.blockcache")
        parser = create_parser(path, 0, 1, "libsvm", chunk_bytes=2048,
                               block_cache=cache)
        want = _drain_arrays(parser)
        parser.before_first()
        assert parser.cache_state == "warm"
        base = resilience.counters_snapshot()
        with faults.inject("cache_read@2=corrupt") as plan:
            healed = _drain_arrays(parser)
        assert plan.fired() == 1
        _assert_same(healed, want)  # byte-identical through the heal
        delta = {k: v for k, v in resilience.counters_delta(base).items()
                 if v}
        assert delta == {"cache_corruptions": 1, "cache_rebuilds": 1}
        # the heal REWROTE the cache: the next epoch is warm and clean
        parser.before_first()
        assert parser.cache_state == "warm"
        _assert_same(_drain_arrays(parser), want)
        parser.close()

    def test_on_disk_bit_flip_heals(self, tmp_path):
        path = _write(tmp_path, "corpus.libsvm", _libsvm_text(n=600))
        cache = str(tmp_path / "c.blockcache")
        parser = create_parser(path, 0, 1, "libsvm", chunk_bytes=2048,
                               block_cache=cache)
        want = _drain_arrays(parser)
        parser.close()
        data = bytearray(open(cache, "rb").read())
        data[80] ^= 0x55  # inside the first block's segments
        with open(cache, "wb") as f:
            f.write(bytes(data))
        base = resilience.counters_snapshot()
        parser = create_parser(path, 0, 1, "libsvm", chunk_bytes=2048,
                               block_cache=cache)
        assert parser.cache_state == "warm"  # footer intact: opens warm
        _assert_same(_drain_arrays(parser), want)
        delta = resilience.counters_delta(base)
        assert delta["cache_corruptions"] == 1
        assert delta["cache_rebuilds"] == 1
        parser.close()


# ---------------- chunk-cache hardening (CachedInputSplit) ----------------

def _records(split):
    out = []
    while (rec := split.next_record()) is not None:
        out.append(bytes(rec))
    return out


class TestChunkCacheCrc:
    def test_crc_framed_roundtrip(self, tmp_path):
        path = _write(tmp_path, "corpus.txt",
                      b"".join(b"line %d\n" % i for i in range(500)))
        cache = str(tmp_path / "chunks.cache")
        split = create_input_split(f"{path}#{cache}", 0, 1, "text",
                                   chunk_bytes=4096)
        want = _records(split)
        split.close()
        assert open(cache, "rb").read(8) == CHUNK_CACHE_MAGIC
        split = create_input_split(f"{path}#{cache}", 0, 1, "text",
                                   chunk_bytes=4096)
        assert _records(split) == want
        split.close()

    def test_legacy_headerless_cache_invalidates_cleanly(self, tmp_path):
        path = _write(tmp_path, "corpus.txt",
                      b"".join(b"line %d\n" % i for i in range(200)))
        cache = str(tmp_path / "chunks.cache")
        # fabricate a v0 cache: raw [u64 size][bytes] frames, no header
        payload = b"not the real corpus\n"
        with open(cache, "wb") as f:
            f.write(struct.pack("<Q", len(payload)))
            f.write(payload)
        base = resilience.counters_snapshot()
        split = create_input_split(f"{path}#{cache}", 0, 1, "text",
                                   chunk_bytes=4096)
        recs = _records(split)
        split.close()
        # the legacy cache was dropped and rebuilt from SOURCE, not served
        assert recs[0] == b"line 0" and len(recs) == 200
        assert resilience.counters_delta(base)["cache_invalidations"] == 1
        assert open(cache, "rb").read(8) == CHUNK_CACHE_MAGIC

    def test_frame_corruption_heals_via_reread(self, tmp_path):
        path = _write(tmp_path, "corpus.txt",
                      b"".join(b"line %d\n" % i for i in range(2000)))
        cache = str(tmp_path / "chunks.cache")
        split = create_input_split(f"{path}#{cache}", 0, 1, "text",
                                   chunk_bytes=2048)
        want = _records(split)
        split.close()
        data = bytearray(open(cache, "rb").read())
        data[len(data) // 2] ^= 0xFF  # flip a byte mid-file
        with open(cache, "wb") as f:
            f.write(bytes(data))
        base = resilience.counters_snapshot()
        split = create_input_split(f"{path}#{cache}", 0, 1, "text",
                                   chunk_bytes=2048)
        healed = _records(split)
        split.close()
        assert healed == want  # unbroken record stream through the heal
        delta = resilience.counters_delta(base)
        assert delta["cache_corruptions"] == 1
        assert delta["cache_rebuilds"] == 1
        # the cache was rewritten: a third pass is clean
        base = resilience.counters_snapshot()
        split = create_input_split(f"{path}#{cache}", 0, 1, "text",
                                   chunk_bytes=2048)
        assert _records(split) == want
        split.close()
        assert resilience.counters_delta(base)["cache_corruptions"] == 0

    def test_heal_resumes_by_bytes_across_chunk_bytes_drift(self, tmp_path):
        """The heal skips BYTES, not frames: a cache built under one
        chunk_bytes must heal correctly when the split is reopened with
        another (frame groupings differ, the byte stream does not)."""
        path = _write(tmp_path, "corpus.txt",
                      b"".join(b"line %d\n" % i for i in range(2000)))
        cache = str(tmp_path / "chunks.cache")
        split = create_input_split(f"{path}#{cache}", 0, 1, "text",
                                   chunk_bytes=2048)
        want = _records(split)
        split.close()
        base = resilience.counters_snapshot()
        with faults.inject("cache_read@3=corrupt"):
            split = create_input_split(f"{path}#{cache}", 0, 1, "text",
                                       chunk_bytes=8192)  # drifted grouping
            healed = _records(split)
            split.close()
        assert healed == want  # record stream unbroken despite the drift
        assert resilience.counters_delta(base)["cache_corruptions"] == 1

    def test_fault_plan_injects_chunk_cache_corruption(self, tmp_path):
        path = _write(tmp_path, "corpus.txt",
                      b"".join(b"line %d\n" % i for i in range(1000)))
        cache = str(tmp_path / "chunks.cache")
        split = create_input_split(f"{path}#{cache}", 0, 1, "text",
                                   chunk_bytes=2048)
        want = _records(split)
        split.close()
        base = resilience.counters_snapshot()
        with faults.inject("cache_read@2=corrupt"):
            split = create_input_split(f"{path}#{cache}", 0, 1, "text",
                                       chunk_bytes=2048)
            healed = _records(split)
            split.close()
        assert healed == want
        assert resilience.counters_delta(base)["cache_corruptions"] == 1


# ---------------- guard rails ----------------

class TestGuards:
    def test_reset_partition_rejected(self, tmp_path):
        path = _write(tmp_path, "corpus.libsvm", _libsvm_text(n=50))
        parser = create_parser(path, 0, 1, "libsvm",
                               block_cache=str(tmp_path / "c.bc"))
        with pytest.raises(DMLCError):
            parser.reset_partition(1, 2)
        parser.close()

    def test_empty_blockcache_fragment_rejected(self):
        with pytest.raises(DMLCError):
            URISpec("path#blockcache=")

    def test_corrupt_error_class_in_fault_grammar(self):
        plan = faults.FaultPlan("cache_read@1=corrupt")
        err = plan.check("cache_read", "/some/cache")
        assert isinstance(err, CacheCorruptionError)
